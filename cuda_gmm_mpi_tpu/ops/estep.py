"""E-step: per-event cluster log-densities, posteriors, log-likelihood.

TPU-native redesign of the reference's hottest kernels ``estep1``
(``gaussian_kernel.cu:383-444``) and ``estep2`` (``:446-512``). The reference
computes the Mahalanobis quadratic form with a serial D x D loop per (event,
cluster) thread; here the whole E-step is expressed as dense matmuls that XLA
tiles onto the MXU:

  expanded mode (default; data is globally centered at fit() time):
    q[n,k] = (x xT)[n] . Rinv[k] - 2 (Rinv[k] mu[k]) . x[n] + mu[k].Rinv[k].mu[k]
    -> one (B, D^2) @ (D^2, K) matmul + one (B, D) @ (D, K) matmul
  centered mode (reference-shaped, for validation):
    q[n,k] = (x-mu_k)T Rinv_k (x-mu_k) staged explicitly.

  logp[n,k]   = -0.5*q + constant[k] + ln(pi[k])      (estep1, :442)
  logZ[n]     = logsumexp_k logp[n,k]                 (estep2, :483-494)
  w[n,k]      = exp(logp - logZ)                      (estep2, :499-502)
  loglik      = sum_n logZ[n]                         (estep2, :495)

Inactive (masked) clusters get logp = -inf, which makes them exactly inert in
the log-sum-exp -- the mask-based replacement for the reference's compaction.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -jnp.inf


def _precision(name: str):
    return {
        "highest": lax.Precision.HIGHEST,
        "high": lax.Precision.HIGH,
        "default": lax.Precision.DEFAULT,
    }[name]


def log_densities(
    state,
    x: jax.Array,
    *,
    diag_only: bool = False,
    quad_mode: str = "expanded",
    matmul_precision: str = "highest",
    xouter: jax.Array | None = None,
) -> jax.Array:
    """Unnormalized log posteriors: [B, K] = -0.5*q + constant + ln(pi).

    Matches estep1's output (gaussian_kernel.cu:442), vectorized over clusters.
    ``xouter`` optionally supplies the precomputed [B, D*D] flattened outer
    products so the fused E+M pass computes them once per chunk.
    """
    prec = _precision(matmul_precision)
    mu, Rinv, = state.means, state.Rinv
    B, D = x.shape
    K = mu.shape[0]

    if diag_only:
        # q = sum_d (x_d - mu_d)^2 * a_d, a = diag(Rinv)
        # (estep1 DIAG_ONLY branch, gaussian_kernel.cu:430-433)
        a = jnp.diagonal(Rinv, axis1=-2, axis2=-1)  # [K, D]
        x2 = x * x
        q = (
            jnp.einsum("nd,kd->nk", x2, a, precision=prec)
            - 2.0 * jnp.einsum("nd,kd->nk", x, a * mu, precision=prec)
            + jnp.sum(a * mu * mu, axis=-1)[None, :]
        )
    elif quad_mode == "expanded":
        # xx^T flattened once per chunk; shared with the M-step accumulator.
        if xouter is None:
            xouter = (x[:, :, None] * x[:, None, :]).reshape(B, D * D)
        b = jnp.einsum("kde,ke->kd", Rinv, mu, precision=prec)  # Rinv mu
        c = jnp.sum(b * mu, axis=-1)  # mu^T Rinv mu
        q = (
            jnp.einsum("nf,kf->nk", xouter, Rinv.reshape(K, D * D), precision=prec)
            - 2.0 * jnp.einsum("nd,kd->nk", x, b, precision=prec)
            + c[None, :]
        )
    elif quad_mode == "centered":
        xc = x[:, None, :] - mu[None, :, :]  # [B, K, D]
        q = jnp.einsum("nkd,kde,nke->nk", xc, Rinv, xc, precision=prec)
    else:
        raise ValueError(f"unknown quad_mode {quad_mode!r}")

    logp = -0.5 * q + state.constant[None, :] + jnp.log(state.pi)[None, :]
    return jnp.where(state.active[None, :], logp, NEG_INF)


def posteriors(
    state,
    x: jax.Array,
    *,
    diag_only: bool = False,
    quad_mode: str = "expanded",
    matmul_precision: str = "highest",
    xouter: jax.Array | None = None,
    cluster_axis: str | None = None,
):
    """(w [B,K], logZ [B]): normalized responsibilities and per-event evidence.

    estep2 semantics (gaussian_kernel.cu:481-502): max-shifted log-sum-exp, then
    w = exp(logp - logZ).

    When ``cluster_axis`` names a mesh axis the cluster dimension is sharded
    across devices (the cross-device generalization of the reference's
    per-cluster grid parallelism, SURVEY.md SS5.7): the log-sum-exp becomes a
    two-stage collective -- ``pmax`` of the per-shard maxima, then ``psum`` of
    the shifted exponential sums -- and the returned ``w`` covers only the
    local cluster shard while ``logZ`` is identical on every shard.
    """
    logp = log_densities(
        state, x, diag_only=diag_only, quad_mode=quad_mode,
        matmul_precision=matmul_precision, xouter=xouter,
    )
    m = jnp.max(logp, axis=1, keepdims=True)
    if cluster_axis is not None:
        m = lax.pmax(m, cluster_axis)
    # All-inactive is impossible (>=1 active cluster globally), but a single
    # SHARD can be all-inactive: guard the -inf max.
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    expd = jnp.exp(logp - m)
    denom = jnp.sum(expd, axis=1, keepdims=True)
    if cluster_axis is not None:
        denom = lax.psum(denom, cluster_axis)
    logZ = (m + jnp.log(denom))[:, 0]
    w = expd / denom
    return w, logZ
