"""E-step: per-event cluster log-densities, posteriors, log-likelihood.

TPU-native redesign of the reference's hottest kernels ``estep1``
(``gaussian_kernel.cu:383-444``) and ``estep2`` (``:446-512``). The reference
computes the Mahalanobis quadratic form with a serial D x D loop per (event,
cluster) thread; here the whole E-step is expressed as dense matmuls that XLA
tiles onto the MXU:

  expanded mode (default; data is globally centered at fit() time):
    q[n,k] = (x xT)[n] . Rinv[k] - 2 (Rinv[k] mu[k]) . x[n] + mu[k].Rinv[k].mu[k]
    -> one (B, D^2) @ (D^2, K) matmul + one (B, D) @ (D, K) matmul
  centered mode (reference-shaped, for validation):
    q[n,k] = (x-mu_k)T Rinv_k (x-mu_k) staged explicitly.

  logp[n,k]   = -0.5*q + constant[k] + ln(pi[k])      (estep1, :442)
  logZ[n]     = logsumexp_k logp[n,k]                 (estep2, :483-494)
  w[n,k]      = exp(logp - logZ)                      (estep2, :499-502)
  loglik      = sum_n logZ[n]                         (estep2, :495)

Inactive (masked) clusters get logp = -inf, which makes them exactly inert in
the log-sum-exp -- the mask-based replacement for the reference's compaction.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG_INF = -jnp.inf


def _precision(name: str):
    return {
        "highest": lax.Precision.HIGHEST,
        "high": lax.Precision.HIGH,
        "default": lax.Precision.DEFAULT,
    }[name]


@lru_cache(maxsize=None)
def _tri(D: int):
    """Static upper-triangle index machinery for symmetric packing.

    Returns (iu0, iu1, fullmap): row/col indices of the D(D+1)/2 upper-triangle
    entries, and a [D*D] map from full (i, j) position to packed index (used to
    expand a packed symmetric matrix with one gather).
    """
    iu0, iu1 = np.triu_indices(D)
    fullmap = np.zeros((D, D), dtype=np.int32)
    fullmap[iu0, iu1] = np.arange(iu0.size, dtype=np.int32)
    fullmap = np.maximum(fullmap, fullmap.T).reshape(-1)
    return iu0, iu1, fullmap


def expand_features(x: jax.Array) -> jax.Array:
    """[B, D] events -> [B, D*D] flattened outer products x x^T.

    THE single definition of the expanded feature layout: the E-step quad
    matmul, the M2 accumulation, and em_while_loop's precompute_features
    hoist all consume exactly this expression, and the hoist's bit-identity
    guarantee depends on every site computing it identically.
    """
    B, D = x.shape
    return (x[:, :, None] * x[:, None, :]).reshape(B, D * D)


def pack_features(x: jax.Array) -> jax.Array:
    """[B, D] events -> [B, D(D+1)/2] upper-triangle products x_i * x_j (i<=j).

    The packed replacement for the flattened outer products in ``expanded``
    mode: since Rinv and the M2 accumulator are symmetric, the lower triangle
    of x xT carries no information -- dropping it cuts the two dominant MXU
    contractions (q and M2, SURVEY.md SS3.3) from D^2 to D(D+1)/2 columns
    (~0.52x the MACs at D=24/32).

    Built from D broadcast-multiplied row slices (x_i * x[i:]) rather than a
    [B, F] gather -- gathers on the minor axis are slow on TPU; slices and
    concat lower to pure layout ops. The concat order (rows of the upper
    triangle) matches ``np.triu_indices`` exactly.
    """
    D = x.shape[-1]
    return jnp.concatenate(
        [x[:, i:] * x[:, i:i + 1] for i in range(D)], axis=1)


def pack_sym_weighted(A: jax.Array) -> jax.Array:
    """[K, D, D] symmetric -> [K, D(D+1)/2] with off-diagonal entries doubled.

    Packs Rinv so that packed_features . packed_Rinv reproduces the full
    quadratic form: sum_ij x_i x_j Rinv_ij = sum_{i<=j} c_ij x_i x_j Rinv_ij
    with c = 1 on the diagonal and 2 off it.
    """
    iu0, iu1, _ = _tri(A.shape[-1])
    coef = jnp.asarray(np.where(iu0 == iu1, 1.0, 2.0), A.dtype)
    return A[:, iu0, iu1] * coef


def unpack_sym(P: jax.Array, D: int) -> jax.Array:
    """[K, D(D+1)/2] packed upper triangle -> [K, D, D] symmetric (one gather).

    Used to expand the packed M2 accumulator; both mirror entries come from
    the same packed value, so the result is exactly symmetric.
    """
    _, _, fullmap = _tri(D)
    return P[:, fullmap].reshape(P.shape[0], D, D)


def log_densities(
    state,
    x: jax.Array,
    *,
    diag_only: bool = False,
    quad_mode: str = "expanded",
    matmul_precision: str = "highest",
    xouter: jax.Array | None = None,
) -> jax.Array:
    """Unnormalized log posteriors: [B, K] = -0.5*q + constant + ln(pi).

    Matches estep1's output (gaussian_kernel.cu:442), vectorized over clusters.
    ``xouter`` optionally supplies the precomputed per-event quadratic
    features so the fused E+M pass computes them once per chunk; its packing
    must match ``quad_mode`` -- [B, D*D] flattened outer products for
    ``expanded``, [B, D(D+1)/2] upper-triangle products for ``packed``.
    """
    prec = _precision(matmul_precision)
    mu, Rinv, = state.means, state.Rinv
    B, D = x.shape
    K = mu.shape[0]

    if diag_only:
        # q = sum_d (x_d - mu_d)^2 * a_d, a = diag(Rinv)
        # (estep1 DIAG_ONLY branch, gaussian_kernel.cu:430-433)
        a = jnp.diagonal(Rinv, axis1=-2, axis2=-1)  # [K, D]
        x2 = x * x
        q = (
            jnp.einsum("nd,kd->nk", x2, a, precision=prec)
            - 2.0 * jnp.einsum("nd,kd->nk", x, a * mu, precision=prec)
            + jnp.sum(a * mu * mu, axis=-1)[None, :]
        )
    elif quad_mode in ("expanded", "packed"):
        # Features shared with the M-step accumulator, computed once per chunk:
        # full flattened xx^T (expanded) or its upper triangle (packed; the
        # symmetric-half saving on the dominant contraction).
        if xouter is None:
            xouter = (pack_features(x) if quad_mode == "packed"
                      else expand_features(x))
        A = (
            pack_sym_weighted(Rinv) if quad_mode == "packed"
            else Rinv.reshape(K, D * D)
        )
        b = jnp.einsum("kde,ke->kd", Rinv, mu, precision=prec)  # Rinv mu
        c = jnp.sum(b * mu, axis=-1)  # mu^T Rinv mu
        q = (
            jnp.einsum("nf,kf->nk", xouter, A, precision=prec)
            - 2.0 * jnp.einsum("nd,kd->nk", x, b, precision=prec)
            + c[None, :]
        )
    elif quad_mode == "centered":
        xc = x[:, None, :] - mu[None, :, :]  # [B, K, D]
        q = jnp.einsum("nkd,kde,nke->nk", xc, Rinv, xc, precision=prec)
    else:
        raise ValueError(f"unknown quad_mode {quad_mode!r}")

    logp = -0.5 * q + state.constant[None, :] + jnp.log(state.pi)[None, :]
    return jnp.where(state.active[None, :], logp, NEG_INF)


def posteriors(
    state,
    x: jax.Array,
    *,
    diag_only: bool = False,
    quad_mode: str = "expanded",
    matmul_precision: str = "highest",
    xouter: jax.Array | None = None,
    cluster_axis: str | None = None,
    with_sanitized: bool = False,
):
    """(w [B,K], logZ [B]): normalized responsibilities and per-event evidence.

    estep2 semantics (gaussian_kernel.cu:481-502): max-shifted log-sum-exp, then
    w = exp(logp - logZ).

    When ``cluster_axis`` names a mesh axis the cluster dimension is sharded
    across devices (the cross-device generalization of the reference's
    per-cluster grid parallelism, SURVEY.md SS5.7): the log-sum-exp becomes a
    two-stage collective -- ``pmax`` of the per-shard maxima, then ``psum`` of
    the shifted exponential sums -- and the returned ``w`` covers only the
    local cluster shard while ``logZ`` is identical on every shard.

    ``with_sanitized`` additionally returns the COUNT of rows whose
    log-sum-exp max had to be sanitized (int32 scalar, third element).
    The max is taken AFTER the cross-shard ``pmax``, so a legitimately
    all-inactive single shard never counts; a non-finite global max means
    the densities themselves went non-finite (NaN parameters, overflow) --
    the poisoning the health bitmask exists to surface
    (``health.SANITIZED_LANES``; the pre-containment code zeroed these
    lanes silently).
    """
    logp = log_densities(
        state, x, diag_only=diag_only, quad_mode=quad_mode,
        matmul_precision=matmul_precision, xouter=xouter,
    )
    m_local = jnp.max(logp, axis=1, keepdims=True)
    m = m_local
    if cluster_axis is not None:
        m = lax.pmax(m, cluster_axis)
    # All-inactive is impossible (>=1 active cluster globally), but a single
    # SHARD can be all-inactive: guard the -inf max. Post-pmax the guard
    # only ever fires on genuinely poisoned lanes -- counted when asked.
    bad = ~jnp.isfinite(m)
    if with_sanitized and cluster_axis is not None:
        # XLA's all-reduce max is allowed to DROP NaN (CPU does): a
        # poisoned shard's NaN max can come back finite from the pmax, so
        # the count must look at the pre-collective local maxima too. A
        # local -inf is the legitimate all-inactive-shard value and never
        # counts; NaN/+inf locals are poison, psum-OR'd across shards so
        # every shard reports the single-device run's exact row count.
        poison_local = jnp.isnan(m_local) | (m_local == jnp.inf)
        bad_rows = bad | (lax.psum(poison_local.astype(jnp.int32),
                                   cluster_axis) > 0)
    else:
        bad_rows = bad
    m = jnp.where(bad, 0.0, m)
    expd = jnp.exp(logp - m)
    denom = jnp.sum(expd, axis=1, keepdims=True)
    if cluster_axis is not None:
        denom = lax.psum(denom, cluster_axis)
    logZ = (m + jnp.log(denom))[:, 0]
    w = expd / denom
    if with_sanitized:
        return w, logZ, jnp.sum(bad_rows, dtype=jnp.int32)
    return w, logZ
