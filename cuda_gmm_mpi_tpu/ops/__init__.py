"""Numeric ops: the TPU-native equivalents of the reference's device kernels.

Layer L1/L2 of SURVEY.md's layer map -- log-densities and posteriors (estep),
sufficient statistics and parameter updates (mstep), Cholesky-based constants
(constants), seeding, merge machinery (merge), and the scalar formulas.
"""

from .constants import chol_inverse_logdet, compute_constants, LOG_2PI
from .estep import log_densities, posteriors
from .formulas import convergence_epsilon, free_params_per_cluster, rissanen_score
from .mstep import SuffStats, accumulate_stats, apply_mstep, chunk_stats, zeros_stats
from .seeding import seed_clusters, seed_means_indices

__all__ = [
    "chol_inverse_logdet", "compute_constants", "LOG_2PI",
    "log_densities", "posteriors",
    "convergence_epsilon", "free_params_per_cluster", "rissanen_score",
    "SuffStats", "accumulate_stats", "apply_mstep", "chunk_stats", "zeros_stats",
    "seed_clusters", "seed_means_indices",
]
