"""M-step: sufficient-statistic accumulation and parameter update.

TPU-native redesign of ``mstep_N`` (``gaussian_kernel.cu:551-577``),
``mstep_means`` (``:522-545``) and ``mstep_covariance1`` (``:605-677``). The
reference launches three kernels that each re-read the memberships array and
sum per-shard; the host then allreduces and divides (``gaussian.cu:541-687``).
Here a single fused pass per event-chunk produces all statistics at once --
the posteriors ``w`` are computed inline (never materialized at N x K) and the
covariance accumulation reuses the chunk's flattened outer products as one
``(K, B) @ (B, D^2)`` MXU matmul:

  Nk  = sum_n w[n,k]                       (mstep_N)
  M1  = sum_n w[n,k] x[n]                  (mstep_means; division deferred)
  M2  = sum_n w[n,k] x[n] x[n]^T           (mstep_covariance1's sums, with the
        per-cluster centering folded out: sum w (x-mu')(x-mu')^T = M2 - Nk mu'mu'^T
        exactly, since mu' = M1/Nk is the same new mean the reference uses)

The update (``apply_mstep``) reproduces the reference's host-side division and
guards:
  means = M1/Nk if Nk > 0.5 else 0                       (gaussian.cu:614-618)
  cov_sums zeroed when Nk < 1                            (gaussian_kernel.cu:658-668)
  R     = (cov_sum + avgvar*I) / Nk if Nk > 0.5 else I   (gaussian.cu:663-679;
          avgvar diagonal loading gaussian_kernel.cu:673-675 -- the reference
          adds avgvar once **per GPU shard** before the global sum; we add it
          exactly once, i.e. the single-GPU semantics, making results
          device-count-invariant)
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .estep import (
    posteriors, _precision, expand_features, pack_features, unpack_sym,
)
from .constants import compute_constants


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SuffStats:
    """Per-shard (or global, after psum) EM sufficient statistics.

    loglik: scalar sum of per-event log-evidence (estep2's likelihood output)
    Nk:     [K]   soft counts
    M1:     [K,D] weighted event sums
    M2:     [K,D,D] weighted outer-product sums (or [K,D] diagonal when
            diag_only -- the DIAG_ONLY path never forms off-diagonals,
            mirroring gaussian_kernel.cu:621-628)
    sanitized: int32 scalar -- E-step lanes whose log-sum-exp max was
            non-finite and had to be sanitized (health.SANITIZED_LANES;
            previously zeroed silently). Rides the stats pytree so it
            accumulates through the chunk scan, the streaming block adds,
            and the cross-device psum exactly like the statistics it
            taints -- each shard counts disjoint events, so the reduced
            count equals the single-device run's.
    """

    loglik: jax.Array
    Nk: jax.Array
    M1: jax.Array
    M2: jax.Array
    # Defaulted so pre-containment constructor call sites (and tests that
    # build stats by hand) stay valid; the zero default means "nothing
    # sanitized", which is exactly what a hand-built stats object asserts.
    sanitized: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.zeros((), jnp.int32))

    def __add__(self, other: "SuffStats") -> "SuffStats":
        return SuffStats(
            self.loglik + other.loglik,
            self.Nk + other.Nk,
            self.M1 + other.M1,
            self.M2 + other.M2,
            self.sanitized + other.sanitized,
        )


def zeros_stats(K: int, D: int, dtype, diag_only: bool = False) -> SuffStats:
    m2_shape = (K, D) if diag_only else (K, D, D)
    return SuffStats(
        loglik=jnp.zeros((), dtype),
        Nk=jnp.zeros((K,), dtype),
        M1=jnp.zeros((K, D), dtype),
        M2=jnp.zeros(m2_shape, dtype),
        sanitized=jnp.zeros((), jnp.int32),
    )


def chunk_stats(
    state,
    x: jax.Array,
    wts: Optional[jax.Array] = None,
    *,
    diag_only: bool = False,
    quad_mode: str = "expanded",
    matmul_precision: str = "highest",
    cluster_axis: str | None = None,
    xouter: Optional[jax.Array] = None,
) -> SuffStats:
    """Fused E+M statistics for one chunk of events.

    ``wts`` is a [B] row of nonnegative per-event weights: 1/0 when it is
    the padding validity mask (the TPU-native replacement for the
    reference's 16-aligned block splits, gaussian_kernel.cu:367-381: we pad
    to a static chunk grid and mask instead), or arbitrary multiplicities
    under ``sample_weight`` -- every statistic (loglik, Nk, M1, M2) scales
    per event, so it is NOT a binary mask contract.
    """
    B, D = x.shape
    K = state.means.shape[0]
    prec = _precision(matmul_precision)

    # ``xouter`` may arrive precomputed (em_while_loop's
    # precompute_features: the [B, F] features are data-only, so hoisting
    # them out of the EM loop trades HBM residency for the per-iteration
    # rebuild); it is built here otherwise.
    if xouter is None:
        if not diag_only and quad_mode == "packed":
            xouter = pack_features(x)
        elif not diag_only and quad_mode == "expanded":
            xouter = expand_features(x)

    w, logZ, sanitized = posteriors(
        state, x, diag_only=diag_only, quad_mode=quad_mode,
        matmul_precision=matmul_precision, xouter=xouter,
        cluster_axis=cluster_axis, with_sanitized=True,
    )
    if wts is not None:
        w = w * wts[:, None]
        logZ = logZ * wts

    loglik = jnp.sum(logZ)
    Nk = jnp.sum(w, axis=0)
    M1 = jnp.einsum("nk,nd->kd", w, x, precision=prec)
    if diag_only:
        M2 = jnp.einsum("nk,nd->kd", w, x * x, precision=prec)
    elif quad_mode == "packed":
        # Accumulate only the upper triangle (xouter holds the packed
        # features, built above), then mirror with one static gather --
        # exact symmetry by construction.
        M2 = unpack_sym(jnp.einsum("nk,nt->kt", w, xouter, precision=prec), D)
    else:
        if xouter is None:
            xouter = expand_features(x)
        M2 = jnp.einsum("nk,nf->kf", w, xouter, precision=prec).reshape(K, D, D)
    return SuffStats(loglik=loglik, Nk=Nk, M1=M1, M2=M2,
                     sanitized=sanitized)


def accumulate_stats(
    state,
    data_chunks: jax.Array,
    wts_chunks: Optional[jax.Array] = None,
    *,
    diag_only: bool = False,
    quad_mode: str = "expanded",
    matmul_precision: str = "highest",
    cluster_axis: str | None = None,
    feats_chunks: Optional[jax.Array] = None,
) -> SuffStats:
    """Scan the fused E+M pass over [num_chunks, B, D] event chunks.

    The scan keeps the working set to one chunk's intermediates -- the
    TPU-native analog of the reference streaming events through a fixed grid of
    thread blocks -- and means the N x K posterior matrix never exists in HBM.

    ``feats_chunks`` optionally carries precomputed [num_chunks, B, F]
    outer-product features (loop-invariant across EM iterations; see
    em_while_loop's precompute_features).
    """
    num_chunks, B, D = data_chunks.shape
    K = state.means.shape[0]

    def body(acc, inp):
        x, wts, feats = inp
        s = chunk_stats(
            state, x, wts, diag_only=diag_only, quad_mode=quad_mode,
            matmul_precision=matmul_precision, cluster_axis=cluster_axis,
            xouter=feats,
        )
        return acc + s, None

    if wts_chunks is None:
        wts_chunks = jnp.ones(data_chunks.shape[:2], data_chunks.dtype)
    init = zeros_stats(K, D, data_chunks.dtype, diag_only=diag_only)
    acc, _ = lax.scan(body, init, (data_chunks, wts_chunks, feats_chunks))
    return acc


def apply_mstep(state, stats: SuffStats, *, diag_only: bool = False,
                cluster_axis: str | None = None,
                covariance_type: str | None = None):
    """Parameter update from (globally reduced) sufficient statistics.

    Reproduces the reference's host-side division/guard sequence and the
    subsequent constants_kernel (gaussian.cu:611-701). Returns the new state
    with N, means, R, Rinv, constant, pi updated.

    ``covariance_type`` extends the reference's two families (full /
    DIAG_ONLY) with the other two standard GMM constraints:
      'full'      per-cluster D x D            (reference default)
      'diag'      per-cluster diagonal         (reference DIAG_ONLY; requires
                  diag_only=True -- same E-step/statistics path)
      'spherical' per-cluster sigma^2 I (the diag update with the MLE tie
                  var_k = mean_d var_kd; requires diag_only=True)
      'tied'      one shared D x D covariance: the Nk-weighted pool of the
                  per-cluster MLE covariances (full-path statistics; when the
                  cluster axis is sharded the pool is a psum over it)
    None resolves to 'diag'/'full' from ``diag_only``.
    """
    if covariance_type is None:
        covariance_type = "diag" if diag_only else "full"
    dtype = state.R.dtype
    K, D = state.means.shape
    Nk = stats.Nk
    nonempty = Nk > 0.5  # gaussian.cu:614,664

    means = jnp.where(nonempty[:, None], stats.M1 / jnp.maximum(Nk, 1e-30)[:, None], 0.0)

    if diag_only:
        cov_sum = stats.M2 - Nk[:, None] * means * means  # [K, D] diagonal
        cov_sum = jnp.where((Nk >= 1.0)[:, None], cov_sum, 0.0)  # gaussian_kernel.cu:658-668
        cov_sum = cov_sum + state.avgvar[:, None]  # diagonal loading (:673-675)
        var = jnp.where(nonempty[:, None], cov_sum / jnp.maximum(Nk, 1e-30)[:, None], 1.0)
        if covariance_type == "spherical":
            # MLE under sigma^2 I: the mean of the per-dim variances. Empty
            # clusters stay at var == 1 (the mean of ones).
            var = jnp.mean(var, axis=1, keepdims=True) + jnp.zeros_like(var)
        R = jnp.zeros((K, D, D), dtype).at[:, jnp.arange(D), jnp.arange(D)].set(var)
    else:
        mmT = means[:, :, None] * means[:, None, :]
        cov_sum = stats.M2 - Nk[:, None, None] * mmT
        cov_sum = jnp.where((Nk >= 1.0)[:, None, None], cov_sum, 0.0)
        eye = jnp.eye(D, dtype=dtype)
        if covariance_type == "tied":
            # Shared-covariance MLE: pool the centered scatter over clusters
            # and divide by the pooled count; diagonal loading applied once.
            # Inactive/empty clusters contribute zero, with the SAME Nk >= 1
            # threshold masking both the scatter (zeroed above) and the count
            # -- a cluster in the (0.5, 1) dead zone must not dilute the
            # pool it contributed nothing to. Cluster-sharded meshes pool
            # with a psum.
            counted = state.active & (Nk >= 1.0)
            pool = jnp.sum(
                jnp.where(state.active[:, None, None], cov_sum, 0.0), axis=0)
            cnt = jnp.sum(jnp.where(counted, Nk, 0.0))
            if cluster_axis is not None:
                pool = lax.psum(pool, cluster_axis)
                cnt = lax.psum(cnt, cluster_axis)
            avg = jnp.max(jnp.where(state.active, state.avgvar, 0.0))
            if cluster_axis is not None:
                avg = lax.pmax(avg, cluster_axis)
            # All-clusters-empty: identity fallback, the tied analog of the
            # per-cluster reset (gaussian.cu:669-678).
            shared = jnp.where(
                cnt >= 1.0, (pool + avg * eye) / jnp.maximum(cnt, 1e-30), eye)
            # K identical copies feed the batched constants/Cholesky below;
            # the redundant K x D^3/3 factorization work is ~1e-6 of one
            # E-step at any supported shape, and keeping the state contract
            # uniform ([K, D, D] everywhere) is worth far more than removing
            # it.
            R = jnp.broadcast_to(shared[None], (K, D, D))
        else:
            cov_sum = cov_sum + state.avgvar[:, None, None] * eye[None]
            R = jnp.where(
                nonempty[:, None, None],
                cov_sum / jnp.maximum(Nk, 1e-30)[:, None, None],
                eye[None],
            )  # empty clusters -> identity (gaussian.cu:669-678)

    # Inactive clusters keep inert placeholder params.
    act = state.active
    new_state = state.replace(
        N=jnp.where(act, Nk, 0.0).astype(dtype),
        means=jnp.where(act[:, None], means, 0.0).astype(dtype),
        R=jnp.where(act[:, None, None], R, jnp.eye(D, dtype=dtype)[None]).astype(dtype),
    )
    return compute_constants(new_state, diag_only=diag_only,
                             cluster_axis=cluster_axis)
