"""Scalar formulas: convergence epsilon and the Rissanen/MDL score.

Straight functional ports of the reference's two closed-form expressions:

  epsilon  = (1 + D + 0.5*(D+1)*D) * ln(N*D) * 0.01      (gaussian.cu:458)
  rissanen = -loglik
             + 0.5 * (K*(1 + D + 0.5*(D+1)*D) - 1) * ln(N*D)   (gaussian.cu:826)

The inner factor is the per-cluster free-parameter count (1 weight + D mean
components + D(D+1)/2 covariance entries).
"""

from __future__ import annotations

import math


def free_params_per_cluster(num_dimensions: int,
                            diag_only: bool = False) -> float:
    d = num_dimensions
    cov = float(d) if diag_only else 0.5 * (d + 1) * d
    return 1.0 + d + cov


def n_free_params(num_clusters, num_dimensions: int,
                  diag_only: bool = False,
                  covariance_type: str | None = None):
    """Total free parameters of a K-component model: K per-cluster counts
    minus the weight-simplex constraint (the ``-1`` in gaussian.cu:826).

    Note: the reference's Rissanen formula always uses the FULL-covariance
    per-cluster count, even in its DIAG_ONLY build -- ``rissanen_score``
    reproduces that; information-criterion APIs that should count what the
    model actually estimates pass ``diag_only`` / ``covariance_type``
    ('spherical' = one variance per cluster; 'tied' = one shared D(D+1)/2
    covariance across clusters).
    """
    k, d = num_clusters, num_dimensions
    if covariance_type is None:
        covariance_type = "diag" if diag_only else "full"
    if covariance_type == "tied":
        return k * (1.0 + d) + 0.5 * (d + 1) * d - 1.0
    cov = {"full": 0.5 * (d + 1) * d, "diag": float(d),
           "spherical": 1.0}[covariance_type]
    return k * (1.0 + d + cov) - 1.0


def convergence_epsilon(
    num_events: int, num_dimensions: int, scale: float = 0.01
) -> float:
    return (
        free_params_per_cluster(num_dimensions)
        * math.log(float(num_events) * num_dimensions)
        * scale
    )


def rissanen_score(
    loglik: float, num_clusters: int, num_events: int, num_dimensions: int
) -> float:
    # Always the full-covariance parameter count (reference behavior even
    # under DIAG_ONLY; see n_free_params).
    return -loglik + 0.5 * n_free_params(
        num_clusters, num_dimensions
    ) * math.log(float(num_events) * num_dimensions)


def model_score(
    loglik,
    num_clusters,
    num_events: int,
    num_dimensions: int,
    criterion: str = "rissanen",
    covariance_type: str | None = None,
):
    """Order-selection score for one K (lower is better); trace-safe.

    'rissanen' is the reference's MDL formula exactly (gaussian.cu:826,
    full-covariance parameter count even under DIAG_ONLY). 'bic'
    (-2 loglik + p ln N), 'aic' (-2 loglik + 2p), and 'aicc' (AIC with the
    Hurvich-Tsai small-sample correction) are upgrades that count the
    parameters the model actually estimates (family-aware via
    ``covariance_type``) and use the conventional sample count N rather
    than the reference's N*D. All four are plain arithmetic in
    ``num_clusters`` plus a static log, so the fused on-device sweep can
    trace them with K dynamic.
    """
    if criterion == "rissanen":
        return rissanen_score(loglik, num_clusters, num_events,
                              num_dimensions)
    p = n_free_params(num_clusters, num_dimensions,
                      covariance_type=covariance_type)
    if criterion == "bic":
        return -2.0 * loglik + p * math.log(float(num_events))
    if criterion == "aic":
        return -2.0 * loglik + 2.0 * p
    if criterion == "aicc":
        # Small-sample correction (Hurvich & Tsai); diverges as p -> n-1,
        # which is the correct behavior (such models are unsupportable).
        # max(d0, 0)+eps spelled branch-free via abs() so the fused sweep
        # can trace this with K dynamic (Python max / np.maximum both
        # choke on tracers).
        n = float(num_events)
        d0 = n - p - 1.0
        denom = 0.5 * (d0 + abs(d0)) + 1e-12
        return -2.0 * loglik + 2.0 * p + 2.0 * p * (p + 1.0) / denom
    raise ValueError(f"unknown criterion: {criterion!r}")
