"""Scalar formulas: convergence epsilon and the Rissanen/MDL score.

Straight functional ports of the reference's two closed-form expressions:

  epsilon  = (1 + D + 0.5*(D+1)*D) * ln(N*D) * 0.01      (gaussian.cu:458)
  rissanen = -loglik
             + 0.5 * (K*(1 + D + 0.5*(D+1)*D) - 1) * ln(N*D)   (gaussian.cu:826)

The inner factor is the per-cluster free-parameter count (1 weight + D mean
components + D(D+1)/2 covariance entries).
"""

from __future__ import annotations

import math


def free_params_per_cluster(num_dimensions: int) -> float:
    d = num_dimensions
    return 1.0 + d + 0.5 * (d + 1) * d


def convergence_epsilon(
    num_events: int, num_dimensions: int, scale: float = 0.01
) -> float:
    return (
        free_params_per_cluster(num_dimensions)
        * math.log(float(num_events) * num_dimensions)
        * scale
    )


def rissanen_score(
    loglik: float, num_clusters: int, num_events: int, num_dimensions: int
) -> float:
    return -loglik + 0.5 * (
        num_clusters * free_params_per_cluster(num_dimensions) - 1.0
    ) * math.log(float(num_events) * num_dimensions)
