"""Pallas/Mosaic TPU kernels -- EXPERIMENTAL alternates to the XLA path.

STATUS (settled round 5, on round-3 hardware data -- see docs/PERF.md
"routing decision"): the production path is jnp/XLA everywhere; these
kernels are kept as measured-and-lost research artifacts plus the
starting point for any future VMEM-resident-features attempt. The round-3
matched-precision study showed the kernel's earlier wins were an artifact
of Mosaic lowering precision-unannotated dots at DEFAULT (bf16); at
honest precision XLA met or beat the kernel at every measured shape. The
one untested hope -- that in-kernel feature materialization beats XLA's
xouter HBM traffic at the north star -- is what the hardware session's
``kernel_north`` step measures; a win there is the only thing that should
flip ``should_use_pallas``.

``should_use_pallas`` decides kernel-vs-jnp per config: 'auto' resolves
to the jnp/XLA path everywhere. The kernels stay available under
``use_pallas='always'`` (fp32; all precisions -- 'high' is a manual 3-dot
bf16_3x decomposition since Mosaic rejects native Precision.HIGH),
correct and parity-tested: the single-shard fused E+M kernel (full +
diagonal covariance) and the two-pass cluster-sharded variant (per-shard
LSE in-kernel, pmax/psum outside -- the cross-device generalization of
estep1's per-cluster grid axis, ``gaussian_kernel.cu:383``; diagonal
covariance only). ``make_stats_fn`` binds the config's covariance mode,
tile size, precision, and mesh axis into the ``stats_fn`` hook consumed
by ``em_while_loop``.
"""

from __future__ import annotations

import functools

from .fused_stats import fused_stats_pallas, fused_stats_pallas_sharded


def should_use_pallas(config, cluster_sharded: bool = False) -> bool:
    if config.use_pallas != "always":
        # 'auto' resolves to the jnp/XLA path everywhere. The round-3
        # matched-precision study (docs/PERF.md) showed the kernel's earlier
        # measured wins were an artifact of Mosaic lowering its precision-
        # unannotated dots at DEFAULT (bf16) while the jnp path ran true
        # fp32; with precision now plumbed through both paths, XLA meets or
        # beats the kernel at every measured shape. The kernel stays
        # available ('always') and tested.
        return False
    if config.dtype != "float32":
        return False
    if cluster_sharded and not config.diag_only:
        # Full covariance is matmul-bound: the 2-pass sharded kernel would
        # evaluate the (B, D^2) @ (D^2, K) contraction twice, while the jnp
        # collective-LSE path does it once at the XLA roofline.
        return False
    return True


def make_stats_fn(config, cluster_sharded: bool = False,
                  cluster_axis: str | None = None):
    """stats_fn hook bound to the config, or None for the jnp path."""
    if not should_use_pallas(config, cluster_sharded):
        return None
    import jax

    # Mosaic compiles on TPU only; on any other backend run the kernel in
    # interpret mode so use_pallas='always' works (slowly) everywhere --
    # the same code path the kernel test suite exercises.
    interpret = jax.default_backend() != "tpu"
    if cluster_sharded:
        from ...parallel.mesh import CLUSTER_AXIS

        return functools.partial(
            fused_stats_pallas_sharded,
            cluster_axis=cluster_axis or CLUSTER_AXIS,
            diag_only=config.diag_only,
            block_b=config.pallas_block_b,
            precision=config.matmul_precision,
            interpret=interpret,
        )
    return functools.partial(
        fused_stats_pallas,
        diag_only=config.diag_only,
        block_b=config.pallas_block_b,
        precision=config.matmul_precision,
        interpret=interpret,
    )


__all__ = ["fused_stats_pallas", "fused_stats_pallas_sharded",
           "make_stats_fn", "should_use_pallas"]
