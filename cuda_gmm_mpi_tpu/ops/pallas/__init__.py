"""Pallas/Mosaic TPU kernels -- the ``estep_backend='pallas'`` hot path.

STATUS (round 6): the fused kernel family now covers every in-memory hot
path -- the single-shard fused E+M statistics kernel (full + diagonal
covariance), its BATCHED sibling with a leading restart axis (grid over
restarts x event tiles; the PR-5 batched-restart driver and the
shard_map(vmap) sharded variant both ride it), the fused M-step parameter
epilogue (Nk/M1/M2 -> N/means/covariance in VMEM, 'full'/'diag'
families), and the two-pass cluster-sharded variant (per-shard LSE
in-kernel, pmax/psum outside; diagonal covariance only). With backend
'pallas' a full EM iteration is ONE kernel round-trip over the events:
no HBM [N, D^2] feature materialization and no separate XLA M-step
dispatch on the statistics.

Routing: ``resolve_estep_backend`` maps the config to the backend that
will actually run -- 'pallas' (TPU), 'pallas-interpret' (any other
platform: Mosaic compiles on TPU only, interpret mode keeps the kernel
path tier-1-testable), or 'jnp' with a reason string. 'auto' still
resolves to the jnp/XLA path everywhere: the round-3 matched-precision
study (docs/PERF.md) showed the UNBATCHED kernel's earlier wins were a
precision artifact, and that routing decision stands until the batched
fused iteration is re-measured on hardware (``bench.py --envelope`` is
the measurement). The resolved backend + reason are emitted as
``em_backend`` / ``em_backend_reason`` on the telemetry stream, so a
silent fallback is observable (docs/OBSERVABILITY.md).

All precisions are supported in-kernel ('high' is a manual 3-dot bf16_3x
decomposition, since Mosaic rejects native Precision.HIGH).
``make_stats_fn`` / ``make_batched_stats_fn`` / ``make_mstep_fn`` bind
the config's covariance mode, tile size, precision, and mesh axis into
the hooks consumed by ``em_while_loop`` / ``em_while_loop_batched``.
"""

from __future__ import annotations

import functools

from .fused_stats import (
    fused_mstep_pallas,
    fused_stats_pallas,
    fused_stats_pallas_batched,
    fused_stats_pallas_sharded,
)

AUTO_REASON = ("estep_backend=auto routes to the XLA path (round-3 "
               "matched-precision routing decision, docs/PERF.md)")


def resolve_estep_backend(config, cluster_sharded: bool = False):
    """(backend, reason) the E-step/statistics path will actually run.

    backend is 'pallas' | 'pallas-interpret' | 'jnp'. The pair is what
    the telemetry stream records as ``em_backend``/``em_backend_reason``
    -- a fallback away from a requested kernel always carries its cause.
    """
    mode = getattr(config, "estep_backend", "auto")
    if mode == "jnp":
        return "jnp", "estep_backend=jnp (explicit)"
    if mode == "auto":
        return "jnp", AUTO_REASON
    # mode == 'pallas': hard request, honored unless structurally impossible.
    if config.dtype != "float32":
        return "jnp", f"kernel is float32-only (dtype={config.dtype})"
    if cluster_sharded and not config.diag_only:
        # Full covariance is matmul-bound: the 2-pass sharded kernel would
        # evaluate the (B, D^2) @ (D^2, K) contraction twice, while the jnp
        # collective-LSE path does it once at the XLA roofline.
        return "jnp", ("cluster-sharded full covariance stays on the jnp "
                       "collective-LSE path (the 2-pass kernel would double "
                       "the dominant contraction)")
    import jax

    if jax.default_backend() == "tpu":
        return "pallas", "estep_backend=pallas"
    return "pallas-interpret", ("estep_backend=pallas on a non-TPU "
                                "platform: Mosaic compiles on TPU only; "
                                "running the kernel in interpret mode")


def should_use_pallas(config, cluster_sharded: bool = False) -> bool:
    backend, _ = resolve_estep_backend(config, cluster_sharded)
    return backend != "jnp"


def _interpret(backend: str) -> bool:
    return backend == "pallas-interpret"


def make_stats_fn(config, cluster_sharded: bool = False,
                  cluster_axis: str | None = None):
    """stats_fn hook bound to the config, or None for the jnp path."""
    backend, _ = resolve_estep_backend(config, cluster_sharded)
    if backend == "jnp":
        return None
    if cluster_sharded:
        from ...parallel.mesh import CLUSTER_AXIS

        return functools.partial(
            fused_stats_pallas_sharded,
            cluster_axis=cluster_axis or CLUSTER_AXIS,
            diag_only=config.diag_only,
            block_b=config.pallas_block_b,
            precision=config.matmul_precision,
            interpret=_interpret(backend),
        )
    return functools.partial(
        fused_stats_pallas,
        diag_only=config.diag_only,
        block_b=config.pallas_block_b,
        precision=config.matmul_precision,
        interpret=_interpret(backend),
    )


def make_batched_stats_fn(config, cluster_sharded: bool = False):
    """Batched (leading restart axis) stats_fn hook, or None.

    None routes ``run_em_batched`` through the vmapped jnp loop: the
    cluster-sharded 2-pass kernel has no batched variant (the restart
    vmap of the jnp path handles that layout), and any jnp-resolved
    backend batches through vmap by construction.
    """
    backend, _ = resolve_estep_backend(config, cluster_sharded)
    if backend == "jnp" or cluster_sharded:
        return None
    return functools.partial(
        fused_stats_pallas_batched,
        diag_only=config.diag_only,
        block_b=config.pallas_block_b,
        precision=config.matmul_precision,
        interpret=_interpret(backend),
    )


def make_mstep_fn(config, cluster_sharded: bool = False,
                  batched: bool = False):
    """mstep_fn hook (fused M-step epilogue + constants), or None.

    Covers the reference's two covariance families ('full'/'diag');
    'spherical'/'tied' keep the jnp ``apply_mstep`` (their cross-cluster
    ties have no per-cluster kernel formulation worth writing), as do
    cluster-sharded meshes (the pi denominator and tied-pool psums live
    in the jnp update).
    """
    backend, _ = resolve_estep_backend(config, cluster_sharded)
    if backend == "jnp" or cluster_sharded:
        return None
    cov = config.covariance_type
    if cov not in ("full", "diag"):
        return None
    import jax

    from ..constants import compute_constants

    diag_only = config.diag_only
    interpret = _interpret(backend)
    constants = functools.partial(compute_constants, diag_only=diag_only)
    if batched:
        constants = jax.vmap(constants)

    def mstep(state, stats):
        return constants(fused_mstep_pallas(
            state, stats, diag_only=diag_only, interpret=interpret))

    return mstep


__all__ = ["fused_stats_pallas", "fused_stats_pallas_batched",
           "fused_stats_pallas_sharded", "fused_mstep_pallas",
           "make_stats_fn", "make_batched_stats_fn", "make_mstep_fn",
           "resolve_estep_backend", "should_use_pallas"]
