"""Pallas/Mosaic TPU kernels -- the hand-tuned hot path (SURVEY L2).

``should_use_pallas`` decides kernel-vs-jnp per config/platform: the Pallas
fused E+M kernels need a TPU (or interpret mode for tests) and float32. Full
and diagonal covariance are both kernelized. On cluster-sharded meshes the
two-pass kernel (per-shard LSE in-kernel, pmax/psum outside -- the
cross-device generalization of estep1's per-cluster grid axis,
``gaussian_kernel.cu:383``) is used for DIAGONAL covariance, where the
kernel's HBM savings dominate; full covariance there stays on the jnp path,
whose single logp evaluation beats the kernel's two matmul passes (the
matmul-bound regime where XLA already sits at the roofline, docs/PERF.md).
``make_stats_fn`` binds the config's covariance mode, tile size, and mesh
axis into the ``stats_fn`` hook consumed by ``em_while_loop``.
"""

from __future__ import annotations

import functools

import jax

from .fused_stats import fused_stats_pallas, fused_stats_pallas_sharded


def should_use_pallas(config, cluster_sharded: bool = False) -> bool:
    if config.use_pallas == "never":
        return False
    if config.dtype != "float32":
        return False
    if cluster_sharded and not config.diag_only:
        # Full covariance is matmul-bound: the 2-pass sharded kernel would
        # evaluate the (B, D^2) @ (D^2, K) contraction twice, while the jnp
        # collective-LSE path does it once at the XLA roofline.
        return False
    if config.use_pallas == "always":
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def make_stats_fn(config, cluster_sharded: bool = False,
                  cluster_axis: str | None = None):
    """stats_fn hook bound to the config, or None for the jnp path."""
    if not should_use_pallas(config, cluster_sharded):
        return None
    if cluster_sharded:
        from ...parallel.mesh import CLUSTER_AXIS

        return functools.partial(
            fused_stats_pallas_sharded,
            cluster_axis=cluster_axis or CLUSTER_AXIS,
            diag_only=config.diag_only,
            block_b=config.pallas_block_b,
        )
    return functools.partial(
        fused_stats_pallas,
        diag_only=config.diag_only,
        block_b=config.pallas_block_b,
    )


__all__ = ["fused_stats_pallas", "fused_stats_pallas_sharded",
           "make_stats_fn", "should_use_pallas"]
