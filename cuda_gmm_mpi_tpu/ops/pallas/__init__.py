"""Pallas/Mosaic TPU kernels -- the hand-tuned hot path (SURVEY L2).

``should_use_pallas`` decides kernel-vs-jnp per config/platform: the Pallas
fused E+M kernel needs a TPU (or interpret mode for tests), float32, full
covariance, the expanded quadratic form, and an unsharded cluster axis.
"""

from __future__ import annotations

import jax

from .fused_stats import fused_stats_pallas


def should_use_pallas(config, cluster_sharded: bool = False) -> bool:
    if config.use_pallas == "never":
        return False
    if config.diag_only or cluster_sharded or config.dtype != "float32":
        return False
    if config.use_pallas == "always":
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


__all__ = ["fused_stats_pallas", "should_use_pallas"]
