"""Pallas/Mosaic TPU kernels -- the hand-tuned hot path (SURVEY L2).

``should_use_pallas`` decides kernel-vs-jnp per config/platform: the Pallas
fused E+M kernel needs a TPU (or interpret mode for tests), float32, the
expanded quadratic form, and an unsharded cluster axis. Full and diagonal
covariance are both kernelized. ``make_stats_fn`` binds the config's
covariance mode and tile size into the ``stats_fn`` hook consumed by
``em_while_loop``.
"""

from __future__ import annotations

import functools

import jax

from .fused_stats import fused_stats_pallas


def should_use_pallas(config, cluster_sharded: bool = False) -> bool:
    if config.use_pallas == "never":
        return False
    if cluster_sharded or config.dtype != "float32":
        return False
    if config.use_pallas == "always":
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def make_stats_fn(config, cluster_sharded: bool = False):
    """stats_fn hook bound to the config, or None for the jnp path."""
    if not should_use_pallas(config, cluster_sharded):
        return None
    return functools.partial(
        fused_stats_pallas,
        diag_only=config.diag_only,
        block_b=config.pallas_block_b,
    )


__all__ = ["fused_stats_pallas", "make_stats_fn", "should_use_pallas"]
