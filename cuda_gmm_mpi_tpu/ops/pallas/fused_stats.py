"""Pallas TPU kernel: fused E-step + M-step sufficient statistics.

This is the TPU-native replacement for the reference's entire kernel sequence
``estep1 -> estep2 -> mstep_N -> mstep_means -> mstep_covariance1``
(``gaussian_kernel.cu:383-677``), which makes 5 passes over HBM-resident data
and a full N x K memberships array. Here ONE kernel makes ONE pass over the
events; everything else lives in VMEM:

  per event-tile [B_t, D]:
    x2   = flattened outer products x x^T            (VMEM only -- the jnp
           path materializes this [N, D^2] in HBM; eliminating that traffic
           is the kernel's whole point)
    q    = x2 @ A^T - 2 x @ h^T + (folded into g)    (MXU)
    logp = -0.5 q + g                                (g = constant + ln pi
           - 0.5 mu^T Rinv mu, -inf for masked clusters)
    logZ = max-shifted log-sum-exp over K            (VPU, = estep2)
    w    = exp(logp - logZ) * event_mask             (never leaves VMEM)
    ll  += sum logZ;  Nk += sum w;  M1 += w^T x;  M2 += w^T x2   (MXU)

Diagonal-covariance mode (the reference's DIAG_ONLY compile path,
``gaussian_kernel.cu:215-223,430-433,621-628``) uses x2 = x*x ([B_t, D]) and
[K, D] diagonal precision coefficients instead of the flattened outer
products -- same kernel structure, D x cheaper contractions.

Stats accumulate in VMEM scratch across the sequential TPU grid and are
written once on the last tile. ``fused_stats_pallas`` requires an unsharded
cluster axis; ``fused_stats_pallas_sharded`` (below) is the two-pass
cluster-sharded variant.

``fused_stats_pallas_batched`` adds a leading RESTART axis: the grid
becomes (restarts x event tiles), per-restart parameter blocks ride the
restart axis while the event tiles are shared (R restarts read the data
once), and per-lane freeze-out masks fold into the event mask. Together
with ``fused_mstep_pallas`` -- the M-step parameter epilogue
(Nk/M1/M2 -> N/means/covariance with the empty-cluster guards and
variance floor, in VMEM, 'full'/'diag' families) -- a full EM iteration
for a whole restart batch is a single kernel round-trip: no HBM [N, D^2]
features, no [R, N, K] posteriors, no separate XLA M-step dispatch on
the statistics (only the K-sized Cholesky/constants stay on XLA).

Precision: 'highest' and 'default' map to Mosaic's native MXU modes.
'high' (bf16_3x) is NOT accepted by Mosaic's dot lowering -- the kernel
implements it MANUALLY as the standard 3-dot decomposition (split each fp32
operand into a bf16 high part and a bf16 residual; a.b ~= ah.bh + ah.bl +
al.bh, accumulated in fp32). This is the same arithmetic XLA emits for
``lax.Precision.HIGH``, so the kernel can run the bench's chosen precision
with zero xouter HBM traffic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..estep import _precision
from ..mstep import SuffStats

NEG_LARGE = -1e30  # stand-in for -inf: exp() underflows to 0, avoids inf-inf


def _kdot(a, b, dims, precision: str):
    """dot_general with fp32 accumulation; 'high' = manual 3-dot bf16_3x.

    Mosaic rejects lax.Precision.HIGH inside kernels, so bf16_3x is spelled
    out: ah.bh + ah.bl + al.bh where xh = bf16(x), xl = bf16(x - xh). The
    dropped al.bl term is O(2^-16) relative -- identical to XLA's HIGH.
    """
    if precision == "high":
        f32 = jnp.float32
        ah = a.astype(jnp.bfloat16)
        al = (a - ah.astype(f32)).astype(jnp.bfloat16)
        bh = b.astype(jnp.bfloat16)
        bl = (b - bh.astype(f32)).astype(jnp.bfloat16)
        d = functools.partial(
            jax.lax.dot_general, dimension_numbers=dims,
            preferred_element_type=f32,
            precision=jax.lax.Precision.DEFAULT,
        )
        return d(ah, bh) + d(ah, bl) + d(al, bh)
    return jax.lax.dot_general(
        a, b, dims, preferred_element_type=jnp.float32,
        precision=_precision(precision),
    )


_NT = (((1,), (0,)), ((), ()))  # [M, C] . [C, N] -> [M, N] (natural layout)
_TT = (((0,), (0,)), ((), ()))  # [C, M] . [C, N] -> [M, N] (event reduce)


def _fused_stats_kernel(x_ref, wt_ref, A_ref, h_ref, g_ref,
                        ll_ref, nk_ref, m1_ref, m2_ref,
                        ll_acc, nk_acc, m1_acc, m2_acc,
                        *, diag: bool, precision):
    i = pl.program_id(0)
    n_tiles = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        ll_acc[:] = jnp.zeros_like(ll_acc)
        nk_acc[:] = jnp.zeros_like(nk_acc)
        m1_acc[:] = jnp.zeros_like(m1_acc)
        m2_acc[:] = jnp.zeros_like(m2_acc)

    x = x_ref[:]                      # [B_t, D]
    wt = wt_ref[:]                    # [B_t, 1]
    bt, d = x.shape

    if diag:
        x2 = x * x                    # [B_t, D]
    else:
        # Flattened outer products, built in VMEM: [B_t, D*D]. Constructed as
        # a lane-concat of D broadcast-scaled copies (x2[:, j*D+i] = x_i*x_j);
        # Mosaic rejects the natural [B,D,D]->[B,D*D] reshape on hardware
        # (sublane/lane repacking), while slice+broadcast+concat lowers fine.
        x2 = jnp.concatenate([x * x[:, j:j + 1] for j in range(d)], axis=1)

    # Quadratic form as two MXU contractions (estep1's double D-loop per
    # thread becomes one (B_t, D^2) @ (D^2, K) matmul; (B_t, D) @ (D, K)
    # under DIAG_ONLY). A and h arrive pre-transposed ([F, K] / [D, K]) so
    # the dots are in natural layout -- no per-tile operand transposes.
    q = _kdot(x2, A_ref[:], _NT, precision)   # [B_t, K]
    q = q - 2.0 * _kdot(x, h_ref[:], _NT, precision)
    logp = -0.5 * q + g_ref[:]        # [B_t, K]; g broadcasts from [1, K]

    # estep2: max-shifted log-sum-exp + normalized responsibilities.
    m = jnp.max(logp, axis=1, keepdims=True)
    m = jnp.maximum(m, NEG_LARGE)     # all-masked guard
    e = jnp.exp(logp - m)
    s = jnp.sum(e, axis=1, keepdims=True)
    logz = (m + jnp.log(s)) * wt      # padded events contribute 0
    w = (e / s) * wt

    # Full-block (1,1) write: Mosaic rejects scalar stores to VMEM refs.
    ll_acc[:] = ll_acc[:] + jnp.sum(logz).reshape(1, 1)
    nk_acc[:] += jnp.sum(w, axis=0, keepdims=True)          # [1, K]
    m1_acc[:] += _kdot(w, x, _TT, precision)                # [K, D]
    m2_acc[:] += _kdot(w, x2, _TT, precision)               # [K, D*D] | [K, D]

    @pl.when(i == n_tiles - 1)
    def _flush():
        ll_ref[:] = ll_acc[:]
        nk_ref[:] = nk_acc[:]
        m1_ref[:] = m1_acc[:]
        m2_ref[:] = m2_acc[:]


@functools.partial(jax.jit,
                   static_argnames=("block_b", "diag", "interpret",
                                   "precision"))
def _fused_stats_call(x, wt, A, h, g, *, block_b: int, diag: bool,
                      interpret: bool, precision: str = "highest"):
    n, d = x.shape
    k = A.shape[1]  # A arrives transposed: [F, K]
    f = A.shape[0]  # D*D (full) or D (diag)
    grid = n // block_b
    f32 = jnp.float32
    out_shapes = (
        jax.ShapeDtypeStruct((1, 1), f32),
        jax.ShapeDtypeStruct((1, k), f32),
        jax.ShapeDtypeStruct((k, d), f32),
        jax.ShapeDtypeStruct((k, f), f32),
    )
    rep = lambda *_: (0, 0)  # accumulator outputs: same block every step
    kernel = functools.partial(_fused_stats_kernel, diag=diag,
                               precision=precision)
    ll, nk, m1, m2 = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block_b, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((f, k), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((d, k), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), rep, memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, 1), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((k, f), rep, memory_space=pltpu.VMEM),
        ),
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((1, 1), f32),
            pltpu.VMEM((1, k), f32),
            pltpu.VMEM((k, d), f32),
            pltpu.VMEM((k, f), f32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * n * k * f,
            bytes_accessed=n * d * 4 + k * f * 8,
            transcendentals=2 * n,
        ),
        interpret=interpret,
    )(x, wt, A, h, g)
    return ll, nk, m1, m2


def _logp_tile(x, A_ref, h_ref, g_ref, diag: bool, precision):
    """Per-tile unnormalized log posteriors [B_t, K] (shared by both passes)."""
    bt, d = x.shape
    if diag:
        x2 = x * x                    # [B_t, D]
    else:
        # Flattened outer products, built in VMEM (see _fused_stats_kernel).
        x2 = jnp.concatenate([x * x[:, j:j + 1] for j in range(d)], axis=1)
    q = _kdot(x2, A_ref[:], _NT, precision)   # [B_t, K]; A is [F, K]
    q = q - 2.0 * _kdot(x, h_ref[:], _NT, precision)
    return -0.5 * q + g_ref[:], x2    # g broadcasts from [1, K]


def _local_lse_kernel(x_ref, A_ref, h_ref, g_ref, m_ref, s_ref, *, diag: bool, precision):
    """Pass 1 of the cluster-sharded kernel: per-event LOCAL max and shifted
    exponential sum over this shard's clusters.

    The cross-shard combination (pmax of maxima, psum of rescaled sums --
    estep2's log-sum-exp generalized across devices, the collective analog of
    gaussian_kernel.cu:483-494) happens OUTSIDE the kernel in the shard_map
    body; only [B, 1]-shaped per-event scalars ever leave VMEM.
    """
    logp, _ = _logp_tile(x_ref[:], A_ref, h_ref, g_ref, diag, precision)
    m = jnp.max(logp, axis=1, keepdims=True)      # [B_t, 1]; NEG_LARGE if the
    e = jnp.exp(logp - m)                         # whole shard is masked (then
    s = jnp.sum(e, axis=1, keepdims=True)         # exp(m - M) == 0 outside)
    m_ref[:] = m
    s_ref[:] = s


def _stats_logz_kernel(x_ref, wt_ref, logz_ref, A_ref, h_ref, g_ref,
                       ll_ref, nk_ref, m1_ref, m2_ref,
                       ll_acc, nk_acc, m1_acc, m2_acc,
                       *, diag: bool, precision):
    """Pass 2 of the cluster-sharded kernel: responsibilities from the GLOBAL
    per-event evidence (logz) and the same fused M-step accumulation as the
    single-shard kernel."""
    i = pl.program_id(0)
    n_tiles = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        ll_acc[:] = jnp.zeros_like(ll_acc)
        nk_acc[:] = jnp.zeros_like(nk_acc)
        m1_acc[:] = jnp.zeros_like(m1_acc)
        m2_acc[:] = jnp.zeros_like(m2_acc)

    x = x_ref[:]
    wt = wt_ref[:]                    # [B_t, 1]
    logz = logz_ref[:]                # [B_t, 1], replicated across shards
    logp, x2 = _logp_tile(x, A_ref, h_ref, g_ref, diag, precision)

    # w = exp(logp - logZ): all-masked shards give exp(NEG_LARGE - logz) == 0.
    w = jnp.exp(logp - logz) * wt

    # loglik = sum logZ over valid events -- identical on every cluster shard
    # (it is NOT psum'd over the cluster axis, matching the jnp path).
    ll_acc[:] = ll_acc[:] + jnp.sum(logz * wt).reshape(1, 1)
    nk_acc[:] += jnp.sum(w, axis=0, keepdims=True)          # [1, K]
    m1_acc[:] += _kdot(w, x, _TT, precision)                # [K, D]
    m2_acc[:] += _kdot(w, x2, _TT, precision)               # [K, D*D] | [K, D]

    @pl.when(i == n_tiles - 1)
    def _flush():
        ll_ref[:] = ll_acc[:]
        nk_ref[:] = nk_acc[:]
        m1_ref[:] = m1_acc[:]
        m2_ref[:] = m2_acc[:]


@functools.partial(jax.jit, static_argnames=("block_b", "diag", "interpret",
                                             "precision"))
def _local_lse_call(x, A, h, g, *, block_b: int, diag: bool, interpret: bool,
                    precision: str = "highest"):
    n, d = x.shape
    k = A.shape[1]  # A arrives transposed: [F, K]
    f = A.shape[0]
    grid = n // block_b
    f32 = jnp.float32
    kernel = functools.partial(_local_lse_kernel, diag=diag,
                               precision=precision)
    row = lambda i: (i, 0)
    rep = lambda *_: (0, 0)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block_b, d), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((f, k), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((d, k), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), rep, memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((block_b, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 1), row, memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, 1), f32),
            jax.ShapeDtypeStruct((n, 1), f32),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * n * k * f,
            bytes_accessed=n * d * 4 + k * f * 4 + n * 8,
            transcendentals=n,
        ),
        interpret=interpret,
    )(x, A, h, g)


@functools.partial(jax.jit, static_argnames=("block_b", "diag", "interpret",
                                             "precision"))
def _stats_logz_call(x, wt, logz, A, h, g, *, block_b: int, diag: bool,
                     interpret: bool, precision: str = "highest"):
    n, d = x.shape
    k = A.shape[1]  # A arrives transposed: [F, K]
    f = A.shape[0]
    grid = n // block_b
    f32 = jnp.float32
    out_shapes = (
        jax.ShapeDtypeStruct((1, 1), f32),
        jax.ShapeDtypeStruct((1, k), f32),
        jax.ShapeDtypeStruct((k, d), f32),
        jax.ShapeDtypeStruct((k, f), f32),
    )
    row = lambda i: (i, 0)
    rep = lambda *_: (0, 0)
    kernel = functools.partial(_stats_logz_kernel, diag=diag,
                               precision=precision)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block_b, d), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((f, k), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((d, k), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), rep, memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, 1), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((k, f), rep, memory_space=pltpu.VMEM),
        ),
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((1, 1), f32),
            pltpu.VMEM((1, k), f32),
            pltpu.VMEM((k, d), f32),
            pltpu.VMEM((k, f), f32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * n * k * f,
            bytes_accessed=n * d * 4 + k * f * 8 + n * 8,
            transcendentals=n,
        ),
        interpret=interpret,
    )(x, wt, logz, A, h, g)


def fused_stats_pallas_sharded(
    state,
    data_chunks: jax.Array,
    wts_chunks: jax.Array | None,
    *,
    cluster_axis: str,
    diag_only: bool = False,
    block_b: int = 512,
    interpret: bool = False,
    precision: str = "highest",
) -> SuffStats:
    """Cluster-sharded SuffStats: two Pallas passes + collective LSE between.

    The cross-device generalization of the reference's per-cluster grid axis
    (estep1's blockIdx.y, ``gaussian_kernel.cu:383``): each device holds a
    K/cluster_size shard of the model and ALL of its data shard's events.
    Pass 1 computes each shard's per-event (max, shifted-sum); a pmax+psum
    pair combines them into the global per-event evidence logZ; pass 2 forms
    the globally-normalized responsibilities and accumulates this shard's
    M-step statistics. Only [N, 1] per-event scalars cross HBM between
    passes -- the [N, K] posteriors still never exist.

    Must be called inside ``shard_map`` with ``cluster_axis`` a live mesh
    axis name (parallel/sharded_em.py binds it).
    """
    c, b, d = data_chunks.shape
    K = state.means.shape[0]
    x, wt, A, h, g = _prep_inputs(state, data_chunks, wts_chunks, block_b,
                                  diag_only)
    m, s = _local_lse_call(x, A, h, g, block_b=block_b, diag=diag_only,
                           interpret=interpret, precision=precision)
    # Collective log-sum-exp across cluster shards (outside the kernel):
    # logZ = M + log(sum_shards exp(m_s - M) * s_s). An all-masked shard has
    # m_s == NEG_LARGE, so exp(m_s - M) underflows to exactly 0.
    M = jax.lax.pmax(m, cluster_axis)
    S = jax.lax.psum(jnp.exp(m - M) * s, cluster_axis)
    logz = M + jnp.log(S)
    ll, nk, m1, m2 = _stats_logz_call(
        x, wt, logz, A, h, g, block_b=block_b, diag=diag_only,
        interpret=interpret, precision=precision,
    )
    dt = data_chunks.dtype
    return SuffStats(
        loglik=ll[0, 0].astype(dt),
        Nk=nk[0].astype(dt),
        M1=m1.astype(dt),
        M2=(m2 if diag_only else m2.reshape(K, d, d)).astype(dt),
        # The kernel's masked-lane trick (NEG_LARGE, not -inf) never
        # produces a non-finite log-sum-exp max, so it has no lanes to
        # sanitize; the health count is structurally zero here.
        sanitized=jnp.zeros((), jnp.int32),
    )


def _prep_events(data_chunks, wts_chunks, block_b):
    """Flatten chunks to tile-padded [N, D] events + [N, 1] weights.

    Padding uses weight 0 via wt (wt rows carry arbitrary nonnegative
    per-event weights, not just the 0/1 mask), so padded tiles contribute
    exactly nothing to any statistic.
    """
    c, b, d = data_chunks.shape
    n = c * b
    x = data_chunks.reshape(n, d).astype(jnp.float32)
    if wts_chunks is None:
        wt = jnp.ones((n, 1), jnp.float32)
    else:
        wt = wts_chunks.reshape(n, 1).astype(jnp.float32)
    pad = (-n) % block_b
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, d), x.dtype)])
        wt = jnp.concatenate([wt, jnp.zeros((pad, 1), wt.dtype)])
    return x, wt


def _prep_params(state, d, diag_only):
    """Per-cluster linear/constant terms (A [F, K], h [D, K], g [1, K]) for
    logp = -0.5 (x2.A - 2 x.h) + g. A and h are emitted PRE-TRANSPOSED so
    every kernel dot runs in natural [M, C] . [C, N] layout (the transpose
    happens once per iteration here, not once per event tile). vmap-safe,
    so the batched entry point maps it over a leading restart axis."""
    K = state.means.shape[0]
    Rinv = state.Rinv.astype(jnp.float32)
    mu = state.means.astype(jnp.float32)
    if diag_only:
        a = jnp.diagonal(Rinv, axis1=-2, axis2=-1)  # [K, D]
        A = a
        h = a * mu
    else:
        A = Rinv.reshape(K, d * d)
        h = jnp.einsum("kde,ke->kd", Rinv, mu)
    g = (
        -0.5 * jnp.sum(h * mu, axis=-1)
        + state.constant.astype(jnp.float32)
        + jnp.log(jnp.maximum(state.pi.astype(jnp.float32), 1e-37))
    )
    g = jnp.where(state.active, g, NEG_LARGE)[None, :]  # [1, K]
    return A.T, h.T, g


def _prep_inputs(state, data_chunks, wts_chunks, block_b, diag_only):
    """Events + per-cluster terms for the unbatched kernels (see the
    two halves above)."""
    d = data_chunks.shape[-1]
    x, wt = _prep_events(data_chunks, wts_chunks, block_b)
    A, h, g = _prep_params(state, d, diag_only)
    return x, wt, A, h, g


def _fused_stats_batched_kernel(x_ref, wt_ref, lane_ref, A_ref, h_ref, g_ref,
                                ll_ref, nk_ref, m1_ref, m2_ref,
                                ll_acc, nk_acc, m1_acc, m2_acc,
                                *, diag: bool, precision):
    """Batched fused E+M statistics: grid (restarts, event tiles).

    Identical tile math to ``_fused_stats_kernel``; the leading grid axis
    selects one restart's (A, h, g) parameter blocks while the EVENT tiles
    (x, wt) are shared -- R restarts read the data once. The per-lane
    freeze-out mask arrives as ``lane_ref`` ([1, 1] per restart) and is
    folded into the event weight, so a frozen lane's statistics (and
    loglik) come out exactly zero without touching the event stream.
    The accumulators live in VMEM scratch shared across the sequential
    grid: re-initialized on each restart's first tile, flushed to that
    restart's output block on its last.
    """
    j = pl.program_id(1)
    n_tiles = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        ll_acc[:] = jnp.zeros_like(ll_acc)
        nk_acc[:] = jnp.zeros_like(nk_acc)
        m1_acc[:] = jnp.zeros_like(m1_acc)
        m2_acc[:] = jnp.zeros_like(m2_acc)

    x = x_ref[:]                          # [B_t, D] (shared across restarts)
    wt = wt_ref[:] * lane_ref[0, 0]       # [B_t, 1]; frozen lane -> all-zero
    bt, d = x.shape

    if diag:
        x2 = x * x
    else:
        # Flattened outer products in VMEM (see _fused_stats_kernel).
        x2 = jnp.concatenate([x * x[:, j2:j2 + 1] for j2 in range(d)], axis=1)

    q = _kdot(x2, A_ref[0], _NT, precision)       # [B_t, K]
    q = q - 2.0 * _kdot(x, h_ref[0], _NT, precision)
    logp = -0.5 * q + g_ref[0]            # g broadcasts from [1, K]

    m = jnp.max(logp, axis=1, keepdims=True)
    m = jnp.maximum(m, NEG_LARGE)
    e = jnp.exp(logp - m)
    s = jnp.sum(e, axis=1, keepdims=True)
    logz = (m + jnp.log(s)) * wt
    w = (e / s) * wt

    ll_acc[:] = ll_acc[:] + jnp.sum(logz).reshape(1, 1)
    nk_acc[:] += jnp.sum(w, axis=0, keepdims=True)          # [1, K]
    m1_acc[:] += _kdot(w, x, _TT, precision)                # [K, D]
    m2_acc[:] += _kdot(w, x2, _TT, precision)               # [K, D*D] | [K, D]

    @pl.when(j == n_tiles - 1)
    def _flush():
        ll_ref[...] = ll_acc[:][None]
        nk_ref[...] = nk_acc[:][None]
        m1_ref[...] = m1_acc[:][None]
        m2_ref[...] = m2_acc[:][None]


@functools.partial(jax.jit,
                   static_argnames=("block_b", "diag", "interpret",
                                    "precision"))
def _fused_stats_batched_call(x, wt, lanes, A, h, g, *, block_b: int,
                              diag: bool, interpret: bool,
                              precision: str = "highest"):
    n, d = x.shape
    r = A.shape[0]
    f, k = A.shape[1], A.shape[2]  # A arrives transposed per lane: [R, F, K]
    grid = (r, n // block_b)
    f32 = jnp.float32
    out_shapes = (
        jax.ShapeDtypeStruct((r, 1, 1), f32),
        jax.ShapeDtypeStruct((r, 1, k), f32),
        jax.ShapeDtypeStruct((r, k, d), f32),
        jax.ShapeDtypeStruct((r, k, f), f32),
    )
    ev = lambda r_, j_: (j_, 0)       # event tiles: shared across restarts
    lane = lambda r_, j_: (r_, 0)     # per-restart freeze-out scalar
    par = lambda r_, j_: (r_, 0, 0)   # per-restart parameter / output block
    kernel = functools.partial(_fused_stats_batched_kernel, diag=diag,
                               precision=precision)
    ll, nk, m1, m2 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, d), ev, memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 1), ev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lane, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, f, k), par, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d, k), par, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, k), par, memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, 1), par, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, k), par, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k, d), par, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k, f), par, memory_space=pltpu.VMEM),
        ),
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((1, 1), f32),
            pltpu.VMEM((1, k), f32),
            pltpu.VMEM((k, d), f32),
            pltpu.VMEM((k, f), f32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * r * n * k * f,
            bytes_accessed=r * n * d * 4 + r * k * f * 8,
            transcendentals=2 * r * n,
        ),
        interpret=interpret,
    )(x, wt, lanes, A, h, g)
    return ll, nk, m1, m2


def fused_stats_pallas_batched(
    states,
    data_chunks: jax.Array,
    wts_chunks: jax.Array | None,
    *,
    lane_mask: jax.Array | None = None,
    diag_only: bool = False,
    block_b: int = 512,
    interpret: bool = False,
    precision: str = "highest",
) -> SuffStats:
    """SuffStats for a BATCH of restarts in one kernel launch.

    ``states`` is a GMMState whose every leaf carries a leading restart
    axis R (the ``run_em_batched`` layout); ``data_chunks``/``wts_chunks``
    are SHARED across restarts -- the kernel reads each event tile once
    per restart from the same HBM buffer (no [R, N, D] replication).
    Returns SuffStats with batched leaves: loglik [R], Nk [R, K],
    M1 [R, K, D], M2 [R, K, D, D] (or [R, K, D] diagonal).

    ``lane_mask`` ([R], 0/1) zeroes a frozen restart's statistics in-kernel
    (folded into the event weight); None means all lanes live. The batched
    EM loop's select-based freeze-out discards frozen lanes' outputs
    anyway, so the mask is an arithmetic guarantee, not a speed knob.
    """
    c, b, d = data_chunks.shape
    R, K = states.means.shape[0], states.means.shape[1]
    x, wt = _prep_events(data_chunks, wts_chunks, block_b)
    A, h, g = jax.vmap(
        functools.partial(_prep_params, d=d, diag_only=diag_only))(states)
    if lane_mask is None:
        lanes = jnp.ones((R, 1), jnp.float32)
    else:
        lanes = lane_mask.astype(jnp.float32).reshape(R, 1)
    ll, nk, m1, m2 = _fused_stats_batched_call(
        x, wt, lanes, A, h, g, block_b=block_b, diag=diag_only,
        interpret=interpret, precision=precision,
    )
    dt = data_chunks.dtype
    return SuffStats(
        loglik=ll[:, 0, 0].astype(dt),
        Nk=nk[:, 0].astype(dt),
        M1=m1.astype(dt),
        M2=(m2 if diag_only else m2.reshape(R, K, d, d)).astype(dt),
        # Masked lanes use NEG_LARGE (finite) in-kernel: nothing to
        # sanitize per lane (same contract as the unbatched kernel).
        sanitized=jnp.zeros((R,), jnp.int32),
    )


# ---------------------------------------------------------------------------
# Fused M-step epilogue: Nk/M1/M2 -> N/means/covariance in VMEM.
# ---------------------------------------------------------------------------


def _mstep_math(nk, m1, m2, avgvar, act, diag: bool):
    """apply_mstep's division/guard/variance-floor sequence on K-major
    operands (nk/avgvar/act arrive as [K, 1] columns so every op is a
    lane-broadcast, never a transpose). Shared by the unbatched and
    batched kernels; expressions mirror ops/mstep.apply_mstep term for
    term so interpret mode is bit-identical to the jnp update."""
    k, d = m1.shape
    nonempty = nk > 0.5                                 # gaussian.cu:614,664
    mean = jnp.where(nonempty, m1 / jnp.maximum(nk, 1e-30), 0.0)
    if diag:
        cov = m2 - nk * mean * mean                     # [K, D] diagonal
        cov = jnp.where(nk >= 1.0, cov, 0.0)            # kernel.cu:658-668
        cov = cov + avgvar                              # loading (:673-675)
        out = jnp.where(nonempty, cov / jnp.maximum(nk, 1e-30), 1.0)
        fallback = 1.0                                  # identity diagonal
    else:
        # Flattened mean outer products, same lane-concat layout as the
        # statistics kernel's x2 (column j*D+i = mean_i * mean_j).
        mm = jnp.concatenate([mean * mean[:, j:j + 1] for j in range(d)],
                             axis=1)                    # [K, D*D]
        f_idx = jax.lax.broadcasted_iota(jnp.int32, (k, d * d), 1)
        eye = (f_idx % (d + 1) == 0).astype(m2.dtype)   # flattened identity
        cov = m2 - nk * mm
        cov = jnp.where(nk >= 1.0, cov, 0.0)
        cov = cov + avgvar * eye
        out = jnp.where(nonempty, cov / jnp.maximum(nk, 1e-30), eye)
        fallback = eye
    # Inactive clusters keep inert placeholder params (apply_mstep's
    # trailing active-mask).
    live = act > 0.5
    return (jnp.where(live, nk, 0.0),
            jnp.where(live, mean, 0.0),
            jnp.where(live, out, fallback))


def _mstep_kernel(nk_ref, m1_ref, m2_ref, av_ref, act_ref,
                  n_ref, mean_ref, cov_ref, *, diag: bool):
    n, mean, cov = _mstep_math(nk_ref[:], m1_ref[:], m2_ref[:],
                               av_ref[:], act_ref[:], diag)
    n_ref[:] = n
    mean_ref[:] = mean
    cov_ref[:] = cov


def _mstep_batched_kernel(nk_ref, m1_ref, m2_ref, av_ref, act_ref,
                          n_ref, mean_ref, cov_ref, *, diag: bool):
    n, mean, cov = _mstep_math(nk_ref[0], m1_ref[0], m2_ref[0],
                               av_ref[0], act_ref[0], diag)
    n_ref[...] = n[None]
    mean_ref[...] = mean[None]
    cov_ref[...] = cov[None]


@functools.partial(jax.jit, static_argnames=("diag", "interpret"))
def _mstep_call(nk, m1, m2, av, act, *, diag: bool, interpret: bool):
    k, d = m1.shape
    f = m2.shape[1]
    f32 = jnp.float32
    full = lambda *_: tuple(0 for _ in range(2))
    spec2 = lambda shape: pl.BlockSpec(shape, full, memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_mstep_kernel, diag=diag),
        grid=(1,),
        in_specs=[spec2((k, 1)), spec2((k, d)), spec2((k, f)),
                  spec2((k, 1)), spec2((k, 1))],
        out_specs=(spec2((k, 1)), spec2((k, d)), spec2((k, f))),
        out_shape=(
            jax.ShapeDtypeStruct((k, 1), f32),
            jax.ShapeDtypeStruct((k, d), f32),
            jax.ShapeDtypeStruct((k, f), f32),
        ),
        interpret=interpret,
    )(nk, m1, m2, av, act)


@functools.partial(jax.jit, static_argnames=("diag", "interpret"))
def _mstep_batched_call(nk, m1, m2, av, act, *, diag: bool, interpret: bool):
    r, k, d = m1.shape
    f = m2.shape[2]
    f32 = jnp.float32
    par = lambda r_: (r_, 0, 0)
    spec3 = lambda shape: pl.BlockSpec((1,) + shape, par,
                                       memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_mstep_batched_kernel, diag=diag),
        grid=(r,),
        in_specs=[spec3((k, 1)), spec3((k, d)), spec3((k, f)),
                  spec3((k, 1)), spec3((k, 1))],
        out_specs=(spec3((k, 1)), spec3((k, d)), spec3((k, f))),
        out_shape=(
            jax.ShapeDtypeStruct((r, k, 1), f32),
            jax.ShapeDtypeStruct((r, k, d), f32),
            jax.ShapeDtypeStruct((r, k, f), f32),
        ),
        interpret=interpret,
    )(nk, m1, m2, av, act)


def fused_mstep_pallas(state, stats: SuffStats, *, diag_only: bool = False,
                       interpret: bool = False):
    """M-step parameter update via the fused epilogue kernel.

    Drop-in for the division/guard/variance-floor half of
    ``ops.mstep.apply_mstep`` ('full' and 'diag' covariance families; the
    caller runs ``compute_constants`` on the result exactly as apply_mstep
    does). The sufficient statistics never round-trip through an XLA
    M-step dispatch: the kernel reads Nk/M1/M2 and writes the new
    N/means/covariance directly. Accepts plain or restart-batched
    (leading-R) states/stats and dispatches to the matching kernel.
    """
    batched = stats.M1.ndim == 3
    f32 = jnp.float32
    K, D = state.means.shape[-2], state.means.shape[-1]
    nk = stats.Nk.astype(f32)[..., None]
    av = state.avgvar.astype(f32)[..., None]
    act = state.active.astype(f32)[..., None]
    m1 = stats.M1.astype(f32)
    m2 = (stats.M2 if diag_only
          else stats.M2.reshape(stats.M2.shape[:-2] + (D * D,))).astype(f32)
    call = _mstep_batched_call if batched else _mstep_call
    n, mean, cov = call(nk, m1, m2, av, act, diag=diag_only,
                        interpret=interpret)
    dtype = state.R.dtype
    if diag_only:
        idx = jnp.arange(D)
        R = (jnp.zeros(cov.shape[:-1] + (D, D), dtype)
             .at[..., idx, idx].set(cov))
    else:
        R = cov.reshape(cov.shape[:-1] + (D, D))
    return state.replace(
        N=n[..., 0].astype(dtype),
        means=mean.astype(dtype),
        R=R.astype(dtype),
    )


def fused_stats_pallas(
    state,
    data_chunks: jax.Array,
    wts_chunks: jax.Array | None,
    *,
    diag_only: bool = False,
    block_b: int = 512,
    interpret: bool = False,
    precision: str = "highest",
) -> SuffStats:
    """SuffStats for all chunks via the fused Pallas kernel.

    Drop-in for ``accumulate_stats`` (unsharded cluster axis; full or diagonal
    covariance). ``data_chunks`` is the [C, B, D] chunk array; it is viewed
    flat and gridded into ``block_b``-event tiles.
    """
    c, b, d = data_chunks.shape
    K = state.means.shape[0]
    x, wt, A, h, g = _prep_inputs(state, data_chunks, wts_chunks, block_b,
                                  diag_only)
    ll, nk, m1, m2 = _fused_stats_call(
        x, wt, A, h, g, block_b=block_b, diag=diag_only, interpret=interpret,
        precision=precision,
    )
    dt = data_chunks.dtype
    return SuffStats(
        loglik=ll[0, 0].astype(dt),
        Nk=nk[0].astype(dt),
        M1=m1.astype(dt),
        M2=(m2 if diag_only else m2.reshape(K, d, d)).astype(dt),
        # Masked lanes use NEG_LARGE (finite) in-kernel: nothing to
        # sanitize, count structurally zero (see the sharded variant).
        sanitized=jnp.zeros((), jnp.int32),
    )
