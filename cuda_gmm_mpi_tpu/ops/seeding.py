"""Initial GMM state from data.

TPU-native equivalent of the reference's two-stage seeding: the device
``seed_clusters`` kernel (``gaussian_kernel.cu:269-328``) followed by the host
``seed_clusters`` override (``gaussian.cu:108-123``) that re-seeds the means from
the FULL dataset (the device kernel only saw the master GPU's shard,
``gaussian.cu:392``). The net effective initial state, reproduced here in one
functional step:

  means[c]  = data[floor(c * seed)], seed = (N_events-1)/(K-1)  (host override,
              gaussian.cu:110-121; evenly spaced events across the full data)
  R         = identity                                   (gaussian_kernel.cu:316-320)
  pi        = 1/K                                        (:323)
  N         = N_events / K                               (:324)
  avgvar    = mean_d(Var_d) / COVARIANCE_DYNAMIC_RANGE   (:325, averageVariance :71-102)
  constant  = -D/2 ln(2*pi)  (constants_kernel on R=I: log|I| = 0)

Deviation: the reference computes avgvar from the master GPU's event shard only;
we use the full dataset (identical in single-process runs, and strictly more
correct distributed).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..state import GMMState
from .constants import compute_constants


def seed_means_indices(num_events: int, num_clusters: int) -> jnp.ndarray:
    """Evenly spaced event indices, matching gaussian.cu:110-120 float math."""
    if num_clusters > 1:
        seed = (num_events - 1.0) / (num_clusters - 1.0)
    else:
        seed = 0.0
    # float32 multiply then truncate, like the reference's (int)(c*seed)
    idx = (jnp.arange(num_clusters, dtype=jnp.float32) * jnp.float32(seed)).astype(
        jnp.int32
    )
    return jnp.clip(idx, 0, num_events - 1)


def kmeanspp_pool(num_events: int, seed: int = 0, max_sample: int = 200_000):
    """Deterministic candidate-pool indices for k-means++ and the RNG to
    continue with (split out so per-host loaders can fetch the pool rows
    from a file instead of holding the full dataset)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    if num_events > max_sample:
        pool = rng.choice(num_events, size=max_sample, replace=False)
    else:
        pool = np.arange(num_events)
    return pool, rng


def kmeanspp_from_pool(x_pool, num_clusters: int, rng):
    """k-means++ (D^2-weighted) selection over a candidate matrix; returns
    indices INTO THE POOL. ``rng`` continues the stream from
    ``kmeanspp_pool`` so results are deterministic given the seed."""
    import numpy as np

    x = x_pool.astype(np.float64)
    first = int(rng.integers(x.shape[0]))
    chosen = [first]
    d2 = ((x - x[first]) ** 2).sum(axis=1)
    for _ in range(1, num_clusters):
        total = d2.sum()
        if total <= 0:  # fewer distinct points than clusters: reuse
            chosen.append(int(rng.integers(x.shape[0])))
            continue
        nxt = int(rng.choice(x.shape[0], p=d2 / total))
        chosen.append(nxt)
        d2 = np.minimum(d2, ((x - x[nxt]) ** 2).sum(axis=1))
    return np.asarray(chosen)


def kmeanspp_indices(data, num_clusters: int, seed: int = 0,
                     max_sample: int = 200_000):
    """k-means++ (D^2-weighted) seeding indices -- capability upgrade over
    the reference's evenly-spaced rows (absent there; opt-in via
    ``GMMConfig.seed_method='kmeans++'``).

    Runs on a deterministic subsample of at most ``max_sample`` events so
    seeding stays O(K * max_sample * D) at any N; returns indices into the
    FULL data array.
    """
    pool, rng = kmeanspp_pool(data.shape[0], seed=seed, max_sample=max_sample)
    chosen = kmeanspp_from_pool(data[pool], num_clusters, rng)
    return pool[chosen]


def seed_clusters_host(
    data,
    num_clusters: int,
    num_clusters_padded: int | None = None,
    covariance_dynamic_range: float = 1e3,
    dtype=None,
    seed_method: str = "even",
    seed: int = 0,
) -> GMMState:
    """Host-side seeding from a NumPy array -- avoids shipping the full dataset
    to device a second time (the chunked copy is the only device-resident one).

    Only K gathered rows and two global moments are needed; moments are
    computed in float64 on host for accuracy. ``seed_method``: 'even' = the
    reference's evenly-spaced rows (default); 'kmeans++' = D^2-weighted
    sampling (upgrade, deterministic given ``seed``).
    """
    import numpy as np

    n_events, _ = data.shape
    dtype = dtype or data.dtype
    if seed_method == "kmeans++":
        idx = kmeanspp_indices(data, num_clusters, seed=seed)
    elif seed_method == "even":
        if num_clusters > 1:
            step = (n_events - 1.0) / (num_clusters - 1.0)
        else:
            step = 0.0
        idx = (np.arange(num_clusters, dtype=np.float32)
               * np.float32(step)).astype(np.int64)
    else:
        raise ValueError(f"unknown seed_method {seed_method!r}")
    means = np.ascontiguousarray(data[np.clip(idx, 0, n_events - 1)])
    mean64 = data.mean(axis=0, dtype=np.float64)
    var = (data.astype(np.float64) ** 2).mean(axis=0) - mean64 * mean64
    return _build_seed_state(
        jnp.asarray(means, dtype), n_events, num_clusters,
        num_clusters_padded or num_clusters,
        jnp.asarray(var.mean() / covariance_dynamic_range, dtype),
        jnp.dtype(dtype),
    )


def seed_state_from_parts(
    means_rows,
    n_events: int,
    data_var_mean: float,
    num_clusters: int,
    num_clusters_padded: int | None = None,
    covariance_dynamic_range: float = 1e3,
    dtype=None,
) -> GMMState:
    """Initial state from precomputed pieces: the K seed rows and the global
    per-dim-variance mean.

    The multi-host seeding entry point: each host fetches the seed rows from
    the input file (``io.read_rows``) and the variance comes from a cross-host
    moment reduction (``parallel.distributed.global_moments``) -- no host ever
    needs the full dataset. Identical inputs on every host produce the
    identical replicated state.
    """
    import numpy as np

    means_rows = np.ascontiguousarray(means_rows)
    dtype = dtype or means_rows.dtype
    return _build_seed_state(
        jnp.asarray(means_rows, dtype), n_events, num_clusters,
        num_clusters_padded or num_clusters,
        jnp.asarray(data_var_mean / covariance_dynamic_range, dtype),
        jnp.dtype(dtype),
    )


def seed_states_batched(
    means_rows_batch,
    n_events: int,
    data_var_mean: float,
    num_clusters: int,
    num_clusters_padded: int | None = None,
    covariance_dynamic_range: float = 1e3,
    dtype=None,
):
    """Batched seeding: the state build vmapped over a leading restart axis.

    ``means_rows_batch`` is [R, K, D] -- one restart's seed rows per lane,
    already shifted into fit coordinates (the per-restart ROW SELECTION
    stays on host so the kmeans++ RNG streams are bit-identical to the
    sequential path's; only the state construction -- identity R, uniform
    pi, avgvar floor, and the per-cluster Cholesky constants -- batches).
    Returns a GMMState whose every leaf has the leading restart axis, the
    seed-state contract of the batched restart driver
    (``GMMModel.run_em_batched``).
    """
    import numpy as np

    means_rows_batch = np.ascontiguousarray(means_rows_batch)
    dtype = jnp.dtype(dtype or means_rows_batch.dtype)
    avgvar = jnp.asarray(
        data_var_mean / covariance_dynamic_range, dtype)
    Kp = num_clusters_padded or num_clusters
    build = jax.vmap(
        lambda rows: _build_seed_state(rows, n_events, num_clusters, Kp,
                                       avgvar, dtype))
    return build(jnp.asarray(means_rows_batch, dtype))


def seed_clusters(
    data: jax.Array,
    num_clusters: int,
    num_clusters_padded: int | None = None,
    covariance_dynamic_range: float = 1e3,
    data_mean: jax.Array | None = None,
    data_var_mean: jax.Array | None = None,
) -> GMMState:
    """Build the initial state (padded to ``num_clusters_padded``, extra slots
    inactive).

    ``data_mean`` / ``data_var_mean`` optionally supply precomputed global
    moments (used by the sharded path where ``data`` is only this host's shard).
    """
    n_events, D = data.shape
    K = num_clusters
    Kp = num_clusters_padded or K
    dtype = data.dtype

    if data_var_mean is None:
        if data_mean is None:
            data_mean = jnp.mean(data, axis=0)
        # E[x^2] - E[x]^2 per dimension, averaged over dimensions
        # (averageVariance, gaussian_kernel.cu:79-99)
        var = jnp.mean(data * data, axis=0) - data_mean * data_mean
        data_var_mean = jnp.mean(var)
    avgvar_val = data_var_mean / jnp.asarray(covariance_dynamic_range, dtype)

    idx = seed_means_indices(n_events, K)
    means_active = data[idx]  # [K, D]
    return _build_seed_state(means_active, n_events, K, Kp, avgvar_val, dtype)


def _build_seed_state(means_active, n_events, K, Kp, avgvar_val, dtype):
    D = means_active.shape[-1]
    means = jnp.zeros((Kp, D), dtype).at[:K].set(means_active)
    active = jnp.arange(Kp) < K
    eye = jnp.broadcast_to(jnp.eye(D, dtype=dtype), (Kp, D, D))
    state = GMMState(
        N=jnp.where(active, n_events / K, 0.0).astype(dtype),
        pi=jnp.where(active, 1.0 / K, 0.0).astype(dtype),
        constant=jnp.zeros((Kp,), dtype),
        avgvar=jnp.where(active, avgvar_val, 0.0).astype(dtype),
        means=means,
        R=eye,
        Rinv=eye,
        active=active,
    )
    # constants_kernel after seeding (gaussian.cu:404)
    return compute_constants(state)
