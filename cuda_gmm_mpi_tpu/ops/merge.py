"""Cluster-merge machinery for the Rissanen/MDL model-order search.

TPU-native redesign of the reference's host-side L5 layer:
``cluster_distance`` (``gaussian.cu:1203-1208``), ``add_clusters``
(``:1210-1253``), ``copy_cluster`` compaction (``:1255-1263``) and the
empty-cluster elimination + exhaustive O(K^2) pair scan in main
(``:865-907``). The reference runs this serially on the rank-0 host with an
O(D^3) LU inversion per candidate pair; here the whole pair scan is a batched
device computation (scan over rows of merged covariances, batched Cholesky
log-dets) and "compaction" is a mask update -- no shapes change, nothing
recompiles, nothing leaves the device except the final argmin pair.

Merge formulas (add_clusters, gaussian.cu:1213-1252), for clusters i, j:
  wt1   = N_i / (N_i + N_j)
  mu_m  = wt1*mu_i + wt2*mu_j
  R_m   = wt1*(R_i + (mu_m-mu_i)(mu_m-mu_i)^T) + wt2*(R_j + (mu_m-mu_j)(mu_m-mu_j)^T)
  pi_m  = pi_i + pi_j          (not renormalized -- reference semantics)
  N_m   = N_i + N_j
  const_m = -D/2 ln(2 pi) - 1/2 ln|R_m|    (ln, not the host log10 of
            invert_matrix.cpp:61 -- we standardize on natural log)
  distance(i,j) = N_i*const_i + N_j*const_j - N_m*const_m   (:1207)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .constants import LOG_2PI, chol_inverse_logdet, chol_logdet


def eliminate_empty(state):
    """Mask off active clusters with N < 0.5 (gaussian.cu:865-874)."""
    return state.replace(active=state.active & (state.N >= 0.5))


def _merged_cov_row(state, i):
    """Merged covariance of cluster i with every cluster j: [K, D, D]."""
    N_i, N_j = state.N[i], state.N
    mu_i, mu_j = state.means[i], state.means
    denom = jnp.maximum(N_i + N_j, 1e-30)
    wt1 = (N_i / denom)[..., None]
    wt2 = 1.0 - wt1
    mu_m = wt1 * mu_i[None, :] + wt2 * mu_j  # [K, D]
    d1 = mu_m - mu_i[None, :]
    d2 = mu_m - mu_j
    R_m = wt1[..., None] * (state.R[i][None] + d1[:, :, None] * d1[:, None, :]) + \
        wt2[..., None] * (state.R + d2[:, :, None] * d2[:, None, :])
    return mu_m, R_m


def pairwise_merge_distances(state, diag_only: bool = False,
                             row_block: int = 32):
    """Full [K, K] merge-cost matrix; +inf on invalid pairs.

    Valid pairs are (i, j) with i < j in slot order and both active -- the same
    enumeration order as the reference's compacted c1 < c2 scan
    (gaussian.cu:882-894), so first-minimum tie-breaking matches.

    Memory/throughput: rows are processed ``row_block`` at a time (lax.map
    over blocks, vmap within), so the peak live intermediate is
    [row_block, K, D, D] merged covariances -- never the full [K, K, D, D]
    (at the MAX_CLUSTERS=512, D=32 extreme that would be 1 GB in fp32) --
    while each step still batches row_block*K Cholesky factorizations
    (row-at-a-time serialized K tiny-matrix launches, the dominant cost of
    the reduce step at large K).
    """
    K, D = state.means.shape
    dtype = state.R.dtype

    def row(i):
        _, R_m = _merged_cov_row(state, i)
        # log-det only: the scan never consumes the candidates' inverses
        # (merge_pair recomputes the winner's Rinv once).
        log_det, ok = chol_logdet(R_m, diag_only=diag_only)
        const_m = (-D * 0.5) * LOG_2PI - 0.5 * log_det
        N_m = state.N[i] + state.N
        dist = (
            state.N[i] * state.constant[i]
            + state.N * state.constant
            - N_m * const_m
        )
        j = jnp.arange(K)
        valid = ok & state.active & state.active[i] & (j > i)
        return jnp.where(valid, dist, jnp.inf).astype(dtype)

    bs = max(1, min(row_block, K))
    pad = (-K) % bs
    rows = jnp.arange(K)
    if pad:
        # Pad the index range to a whole number of blocks by recomputing row
        # 0; the padded output rows carry no marker and are dropped ONLY by
        # the [:K] slice below.
        rows = jnp.concatenate([rows, jnp.zeros((pad,), rows.dtype)])
    blocks = lax.map(jax.vmap(row), rows.reshape(-1, bs))
    return blocks.reshape(-1, K)[:K]


def argmin_pair(dist: jax.Array):
    """First (row-major) minimum of the [K, K] distance matrix -> (i, j)."""
    K = dist.shape[0]
    flat = jnp.ravel(dist)
    idx = jnp.argmin(flat)  # first occurrence on ties, like the strict < scan
    return idx // K, idx % K


def merge_pair(state, i, j, diag_only: bool = False):
    """Merge cluster j into slot i and deactivate j.

    Equivalent to add_clusters + copy_cluster compaction (gaussian.cu:899-907):
    with masks, writing the merged cluster into slot i and masking slot j
    preserves exactly the compacted relative order. Rinv and constant of the
    merged cluster are recomputed here (the reference's add_clusters calls
    invert_cpu at :1247 because the next K's initial E-step consumes Rinv
    directly, with no intervening constants kernel).
    """
    K, D = state.means.shape
    N_i, N_j = state.N[i], state.N[j]
    denom = jnp.maximum(N_i + N_j, 1e-30)
    wt1 = N_i / denom
    wt2 = 1.0 - wt1
    mu_m = wt1 * state.means[i] + wt2 * state.means[j]
    d1 = mu_m - state.means[i]
    d2 = mu_m - state.means[j]
    R_m = wt1 * (state.R[i] + d1[:, None] * d1[None, :]) + \
        wt2 * (state.R[j] + d2[:, None] * d2[None, :])

    Rinv_m, log_det, ok = chol_inverse_logdet(R_m[None], diag_only=diag_only)
    eye = jnp.eye(D, dtype=state.R.dtype)
    R_m = jnp.where(ok[0], R_m, eye)
    Rinv_m = jnp.where(ok[0], Rinv_m[0], eye)
    const_m = (-D * 0.5) * LOG_2PI - 0.5 * jnp.where(ok[0], log_det[0], 0.0)

    return state.replace(
        N=state.N.at[i].set(N_i + N_j).at[j].set(0.0),
        pi=state.pi.at[i].set(state.pi[i] + state.pi[j]),
        constant=state.constant.at[i].set(const_m.astype(state.constant.dtype)),
        avgvar=state.avgvar,  # same for all clusters (gaussian.cu:1252)
        means=state.means.at[i].set(mu_m),
        R=state.R.at[i].set(R_m),
        Rinv=state.Rinv.at[i].set(Rinv_m),
        active=state.active.at[j].set(False),
    )


def eliminate_and_reduce(state, diag_only: bool = False):
    """Fused empty-elimination + pair scan + merge, one device dispatch.

    Returns ``(new_state, k_active_after_elim, min_distance, pair)``. Exists
    so the sweep driver can fetch all its per-K decision scalars in ONE host
    sync -- on a remote-TPU link every blocking transfer costs a round trip,
    and the reference-shaped loop (eliminate, count, scan, merge as separate
    host steps, gaussian.cu:857-907) would pay it 3-4 times per K.

    ``pair`` is the merged pair as an int32 [2] of COMPACTION-STABLE
    indices: each slot index is remapped to its rank among the
    post-elimination active slots, i.e. the position the cluster holds in
    the compacted layout (state.compact / compact_to preserve that order).
    Raw padded-slot indices would go stale the moment the sweep rebuckets
    the state to a narrower width; these stay valid, and match the
    reference's compacted c1 < c2 scan coordinates (gaussian.cu:882-894).
    """
    state = eliminate_empty(state)
    k_active = state.num_active()
    new_state, (i, j), min_d = reduce_order_step(state, diag_only=diag_only)
    # A merge with < 2 active clusters is impossible; reduce_order_step
    # already returns the state unchanged in that case (all-inf distances).
    rank = jnp.cumsum(state.active.astype(jnp.int32)) - 1
    pair = jnp.stack([rank[i], rank[j]]).astype(jnp.int32)
    return new_state, k_active, min_d, pair


def reduce_order_step(state, diag_only: bool = False):
    """One full order-reduction step: pair scan + merge of the closest pair.

    Returns ``(new_state, (i, j), min_distance)``. If no valid pair exists
    (``min_distance`` is +inf -- e.g. every merged covariance failed its
    factorization) the state is returned UNCHANGED; callers must check the
    distance before decrementing K. Caller is responsible for empty-cluster
    elimination first, matching the reference's sequencing (gaussian.cu:865-907).
    """
    dist = pairwise_merge_distances(state, diag_only=diag_only)
    i, j = argmin_pair(dist)
    merged = merge_pair(state, i, j, diag_only=diag_only)
    min_d = dist[i, j]
    valid = jnp.isfinite(min_d)
    out = jax.tree_util.tree_map(
        lambda a, b: jnp.where(valid, a, b), merged, state
    )
    return out, (i, j), min_d
