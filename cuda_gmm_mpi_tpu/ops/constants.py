"""Per-cluster derived quantities: Rinv, log|R|, the Gaussian log-constant, pi.

TPU-native equivalent of the reference's ``constants_kernel``
(``gaussian_kernel.cu:250-259``) and its helpers ``compute_constants``
(``:196-243``), ``invert`` (``:107-169``) and ``compute_pi`` (``:172-193``).

Design deviations (documented per SURVEY.md SS2.3):
- Inversion/log-det use a batched **Cholesky** factorization instead of the
  reference's unpivoted LU: R is symmetric and, thanks to the avgvar diagonal
  loading (gaussian_kernel.cu:673-675), positive definite. Cholesky is the
  right primitive on TPU (one `lax.linalg` call batched over K, no per-element
  control flow) and is strictly more numerically robust here.
- Natural log everywhere. The reference uses ln on device
  (gaussian_kernel.cu:139) but log10 on the host merge path
  (invert_matrix.cpp:61); we standardize on ln.
- Clusters whose covariance is not positive definite (Cholesky produces
  non-finite entries) are reset to the identity covariance, mirroring the
  reference's empty-cluster identity reset (gaussian.cu:669-678).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

LOG_2PI = math.log(2.0 * math.pi)


def _chol_ok(R: jax.Array):
    """Batched Cholesky factor + per-matrix PD flag (NaN rows = not PD)."""
    L = jax.lax.linalg.cholesky(R)
    ok = jnp.all(jnp.isfinite(L.reshape(L.shape[0], -1)), axis=-1)
    return L, ok


def _logdet_from_chol(L: jax.Array, ok: jax.Array):
    diag = jnp.abs(jnp.diagonal(L, axis1=-2, axis2=-1))
    diag = jnp.where(ok[:, None], diag, 1.0)  # failed rows -> log_det 0
    return 2.0 * jnp.sum(jnp.log(diag), axis=-1)


def chol_logdet(R: jax.Array, diag_only: bool = False):
    """Batched log-determinant + PD check WITHOUT the inverse.

    The merge pair scan (ops/merge.py::pairwise_merge_distances) evaluates
    O(K^2) candidate covariances but consumes only each one's log|R| for the
    merged constant -- computing the inverse there (two triangular solves +
    a [D,D]x[D,D] product per candidate) was pure waste. Returns
    ``(log_det [K], ok [K])``. Single source of truth for the log-det/PD
    semantics; chol_inverse_logdet builds on the same helpers.
    """
    if diag_only:
        d = jnp.diagonal(R, axis1=-2, axis2=-1)  # [K, D]
        ok = jnp.all(d > 0, axis=-1)
        return jnp.sum(jnp.log(jnp.where(d > 0, d, 1.0)), axis=-1), ok
    L, ok = _chol_ok(R)
    return _logdet_from_chol(L, ok), ok


def chol_inverse_logdet(R: jax.Array, diag_only: bool = False):
    """Batched inverse + log-determinant of covariance matrices.

    Args:
      R: [K, D, D] symmetric positive-definite covariance matrices.
      diag_only: treat R as diagonal (DIAG_ONLY fast path,
        gaussian_kernel.cu:215-223: reciprocal diagonal + log of diagonal
        product).

    Returns:
      (Rinv [K,D,D], log_det [K], ok [K] bool) -- ``ok`` is False where the
      factorization failed (non-PD input); callers reset those clusters.
    """
    K, D, _ = R.shape
    if diag_only:
        d = jnp.diagonal(R, axis1=-2, axis2=-1)  # [K, D]
        log_det, ok = chol_logdet(R, diag_only=True)
        safe = jnp.where(d > 0, d, 1.0)
        Rinv = jnp.zeros_like(R)
        Rinv = Rinv.at[..., jnp.arange(D), jnp.arange(D)].set(1.0 / safe)
        return Rinv, log_det, ok

    L, ok = _chol_ok(R)
    log_det = _logdet_from_chol(L, ok)
    eyeK = jnp.broadcast_to(jnp.eye(D, dtype=R.dtype), R.shape)
    L_safe = jnp.where(ok[:, None, None], L, eyeK)
    # Rinv = L^-T L^-1 via two batched triangular solves against I.
    Linv = jax.lax.linalg.triangular_solve(
        L_safe, eyeK, left_side=True, lower=True
    )
    Rinv = jnp.einsum("kji,kjl->kil", Linv, Linv)  # L^-T @ L^-1
    return Rinv, log_det, ok


def compute_constants(state, diag_only: bool = False,
                      cluster_axis: str | None = None):
    """Recompute Rinv, constant, and pi from R and N.

    Mirrors constants_kernel (gaussian_kernel.cu:250-259):
      constant[c] = -D/2 * ln(2*pi) - 1/2 * ln|R_c|   (:241)
      pi[c]       = N[c] / sum(N)   with a 1e-10 floor when N[c] < 0.5
                    (compute_pi, :184-189; the reference's pi[threadIdx.x]
                    indexing quirk is equivalent to pi[c] for K <= blockDim and
                    is implemented here with the intended pi[c] semantics)

    Non-PD covariances are reset to identity before the constant is computed.
    Inactive clusters keep pi's floor value but are masked out of the E-step
    entirely, so their values are inert.
    """
    D = state.num_dimensions
    Rinv, log_det, ok = chol_inverse_logdet(state.R, diag_only=diag_only)
    eyeK = jnp.broadcast_to(jnp.eye(D, dtype=state.R.dtype), state.R.shape)
    R = jnp.where(ok[:, None, None], state.R, eyeK)
    Rinv = jnp.where(ok[:, None, None], Rinv, eyeK)
    log_det = jnp.where(ok, log_det, 0.0)
    constant = (-D * 0.5) * LOG_2PI - 0.5 * log_det

    n_total = jnp.sum(jnp.where(state.active, state.N, 0.0))
    if cluster_axis is not None:
        # K is sharded across this mesh axis: pi's denominator is the global
        # soft count (the reference's sum over all clusters, compute_pi,
        # gaussian_kernel.cu:175-180).
        n_total = jax.lax.psum(n_total, cluster_axis)
    pi = jnp.where(state.N < 0.5, 1e-10, state.N / jnp.maximum(n_total, 1e-30))
    return state.replace(R=R, Rinv=Rinv, constant=constant.astype(state.R.dtype),
                         pi=pi.astype(state.R.dtype))
