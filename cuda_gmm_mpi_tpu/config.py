"""Runtime configuration for the TPU-native GMM framework.

Every compile-time ``#define`` in the reference's ``gaussian.h:10-60`` is promoted
to a runtime field here (the reference requires a recompile to change any of them,
``README.txt:48-57``). Field-by-field provenance:

- ``max_clusters``           <- MAX_CLUSTERS            (gaussian.h:10)
- ``covariance_dynamic_range`` <- COVARIANCE_DYNAMIC_RANGE (gaussian.h:12)
- ``diag_only``              <- DIAG_ONLY               (gaussian.h:23)
- ``min_iters``/``max_iters`` <- MIN_ITERS/MAX_ITERS    (gaussian.h:26-27)
- ``enable_debug``/``enable_print``/``enable_output``
                             <- ENABLE_DEBUG/PRINT/OUTPUT (gaussian.h:31-38)
- ``device``                 <- DEVICE                  (gaussian.h:19) -- here a
  JAX platform name ('tpu'/'cpu'/'gpu') instead of a CUDA ordinal, plus the
  north-star ``--device=tpu`` flag from BASELINE.json.

The CUDA launch-geometry knobs (NUM_BLOCKS, NUM_THREADS_*) have no TPU meaning;
their TPU-native analog is ``chunk_size`` (events per fused E+M pass, which bounds
the on-chip working set the way the reference's grid split over 16 blocks bounded
per-block work, gaussian_kernel.cu:367-381).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class GMMConfig:
    """Configuration for GMM-EM fitting and the model-order search."""

    # --- algorithm semantics (reference parity) ---
    max_clusters: int = 512
    covariance_dynamic_range: float = 1e3
    diag_only: bool = False
    # Covariance family: 'full' (reference default) | 'diag' (reference
    # DIAG_ONLY; equivalent to diag_only=True) | 'spherical' (sigma^2 I per
    # cluster; diagonal statistics path) | 'tied' (one shared D x D
    # covariance; full statistics path). The two extra families are a
    # capability upgrade over the reference's two compile-time modes; the
    # order-search merge machinery scores merges with the unconstrained
    # pooled covariance and EM re-imposes the constraint each K.
    covariance_type: str = "full"
    min_iters: int = 100
    max_iters: int = 100
    # Model-order selection criterion: 'rissanen' = the reference's MDL
    # score exactly (gaussian.cu:826); 'bic'/'aic' count family-correct
    # free parameters and use the conventional sample count N (upgrade).
    criterion: str = "rissanen"
    # Convergence threshold scale: epsilon = nparams_per_cluster * ln(N*D) * scale
    # (gaussian.cu:458). Runtime-tunable here.
    epsilon_scale: float = 0.01

    # --- numerics (TPU-native policy) ---
    # The reference mixes natural log (device invert, gaussian_kernel.cu:139) and
    # log10 (host invert_cpu, invert_matrix.cpp:61) for log-determinants. We use
    # natural log everywhere (documented deviation; SURVEY.md SS2.3).
    dtype: str = "float32"
    # Matmul precision for the fused E/M contractions: 'highest' keeps true fp32
    # accumulate on the MXU; 'default' allows bf16 passes.
    matmul_precision: str = "highest"
    # Events per fused E+M chunk (lax.scan step). Bounds the (chunk, K, D) and
    # (chunk, D*D) intermediates in VMEM/HBM.
    chunk_size: int = 65536
    # Quadratic-form evaluation: 'expanded' = x Rinv x^T - 2 b x + c as pure
    # matmuls (fastest on MXU; data is centered at fit() time to keep it
    # well-conditioned); 'centered' = explicit (x-mu) staging (most stable).
    quad_mode: str = "expanded"
    # Center data at fit() time (shift-equivariant; outputs are shifted back).
    center_data: bool = True
    # Pallas fused kernel for the E+M pass (EXPERIMENTAL; docs/PERF.md
    # round-5 routing decision): 'always' forces it, 'auto' resolves to
    # the XLA path everywhere -- at matched precision XLA met or beat the
    # kernel at every measured shape. All precisions are supported
    # in-kernel ('high' is a manual 3-dot bf16_3x decomposition, since
    # Mosaic rejects native Precision.HIGH). Legacy spelling of
    # ``estep_backend`` below; the two are kept coherent in __post_init__
    # ('always' == 'pallas', 'never' == 'jnp').
    use_pallas: str = "auto"  # 'auto' | 'always' | 'never'
    # E-step/statistics backend (docs/PERF.md "Fused EM iteration"):
    # 'pallas' runs the fused E+M kernel -- batched ([R, N, D] restart
    # axis) and unbatched, with the M-step parameter update fused as a
    # kernel epilogue on 'full'/'diag' covariance -- so one EM iteration
    # is a single kernel round-trip over the events; off-TPU it executes
    # in interpret mode (slow, tier-1-testable). 'jnp' pins the XLA path.
    # 'auto' currently resolves to 'jnp' everywhere (the round-3 matched-
    # precision routing decision stands until the batched kernel is
    # re-measured on hardware; bench.py --envelope is the measurement).
    # The backend that actually ran is emitted as ``em_backend`` on the
    # telemetry stream (docs/OBSERVABILITY.md).
    estep_backend: str = "auto"  # 'auto' | 'pallas' | 'jnp'
    # Hoist the [N, F] outer-product features out of the EM loop: built
    # once per run and held in HBM, replacing every iteration's feature
    # rebuild+write with a read. F depends on the quad layout: D*D floats
    # per event under 'expanded' (2.3 GB at 1M x 24), D(D+1)/2 under
    # 'packed' (~48% less HBM at D=24 -- the symmetric upper triangle
    # carries the full information). The XLA-path candidate for the
    # measured xouter-traffic bottleneck (docs/PERF.md); results are
    # bit-identical to the unhoisted run OF THE SAME LAYOUT (each layout
    # hoists exactly the expression its inline path computes).
    # Full-covariance in-memory paths only.
    precompute_features: bool = False
    # Events per Pallas grid tile (the kernel's VMEM working set is
    # ~ block_b * D^2 floats for the outer products).
    pallas_block_b: int = 512  # best measured tile on v5e (docs/PERF.md)
    # Run the ENTIRE model-order sweep as one jitted device program (zero
    # host syncs between dispatch and final result), on plain or sharded
    # (any mesh layout) models. Opt-in fast path. Composes with per-K
    # checkpointing AND profiling via ordered io_callback emission (plain
    # model, single-controller; profile attribution is coarse -- whole-K
    # spans land in e_step); other combinations fall back to the
    # host-driven sweep with a warning.
    fused_sweep: bool = False
    # Cluster-width bucketing for the HOST-DRIVEN model-order sweep:
    # 'pow2' (default) recompacts the state to the smallest power-of-two
    # padded width >= the active count whenever a merge crosses a bucket
    # boundary, so EM at k active clusters pays matmuls at width ~k instead
    # of the full starting K0 (~2x sweep-level FLOPs/HBM traffic for at
    # most ceil(log2 K0) + 1 compiled EM widths; docs/PERF.md). 'off'
    # keeps the single fixed width (one compile, reference-shaped).
    # The fused whole-sweep program is fixed-width by design and ignores
    # this (models/fused_sweep.py documents the trade); multi-controller
    # sweeps also stay fixed-width.
    sweep_k_buckets: str = "pow2"

    # Out-of-core mode: event chunks stay in HOST memory and stream through
    # the device one chunk per E+M pass, so N is bounded by host RAM rather
    # than HBM. Trades the single-jit EM loop for per-chunk dispatches --
    # only worth it when the data genuinely exceeds device memory
    # (models/streaming.py). Single-process, single-device.
    stream_events: bool = False
    # Out-of-core ingestion (io/pipeline.py; docs/PERF.md "Pipelined
    # ingestion"): 'resident' materializes this rank's event slice in host
    # RAM before streaming (the classic path); 'pipelined' never does -- a
    # bounded-queue background reader pulls per-block byte ranges from the
    # source file and decodes/screens them on a worker thread while the
    # device computes the previous block, so peak host memory is
    # O(ingest_queue_depth x block), never O(N). Requires stream_events
    # and a file-backed source; results are bit-identical to 'resident'
    # (same chunk grid, same block-sequential addition order).
    ingest: str = "resident"  # 'resident' | 'pipelined'
    # Blocks the background reader may run ahead of the device: the
    # bounded prefetch queue's capacity, and therefore the peak resident
    # block count of 'pipelined' mode.
    ingest_queue_depth: int = 4

    # --- EM update schedule (models/streaming.py) ---
    # 'full' = the reference's batch EM: one M-step per full-data pass.
    # 'minibatch' = stepwise EM (Cappe & Moulines 2009): each step reads
    # the NEXT minibatch of streamed blocks, rescales its sufficient
    # statistics to full-data size, folds them into a decayed running
    # estimate with gamma_t = (t + minibatch_t0) ** -minibatch_alpha, and
    # applies the M-step -- convergence no longer costs a full data pass
    # per iteration. min/max_iters count minibatch STEPS in this mode; the
    # reported final loglik is still one full-data evaluation pass.
    # Requires stream_events (it is the streaming block loop's schedule).
    em_mode: str = "full"  # 'full' | 'minibatch'
    # Events per stepwise-EM minibatch, rounded UP to whole streamed
    # blocks (chunk_size x local data shards). 0 = one block per step.
    minibatch_size: int = 0
    # Stepwise decay knobs: gamma_t = (t + t0) ** -alpha. alpha must lie
    # in (0.5, 1] (the Robbins-Monro square-summability condition).
    minibatch_t0: float = 2.0
    minibatch_alpha: float = 0.7

    # --- platform / parallelism ---
    device: Optional[str] = None  # None = JAX default platform
    # Mesh shape over (event axis, cluster axis). None = all local devices on the
    # event ('data') axis, cluster axis unsharded.
    mesh_shape: Optional[Tuple[int, int]] = None

    # --- output / logging (reference: compile-time, default off; here runtime,
    # output on by default since a clustering tool that writes nothing is only
    # useful for benchmarking) ---
    enable_debug: bool = False
    enable_print: bool = False
    enable_output: bool = True

    # Retained sweep-checkpoint steps (newest + fallbacks; utils/checkpoint
    # prunes older ones after each durable save). >= 1.
    checkpoint_keep: int = 2
    # Bounded retry (with exponential jittered backoff) for checkpoint
    # writes: a transient EIO on a network filesystem must not kill an
    # hours-long sweep -- least of all from inside the fused sweep's
    # ordered io_callback, where an exception aborts the device program.
    # 0 disables retrying (first failure is final).
    checkpoint_retries: int = 3

    # --- preemption-safe execution (supervisor.py; docs/ROBUSTNESS.md
    # "Run lifecycle") ---
    # Wall-clock budget in seconds: the run supervisor treats reaching it
    # like a SIGTERM -- cooperative stop at the next poll point, emergency
    # intra-K checkpoint, exit 75 (EX_TEMPFAIL). Front-runs a batch
    # scheduler's hard kill limit with a clean, resumable exit. None = no
    # deadline. Only observed while a supervisor is active (the CLI always
    # activates one; library callers use supervisor.use()).
    max_runtime_s: Optional[float] = None
    # EM iterations per supervised segment: with a supervisor active AND
    # checkpointing on, the jitted EM loop runs in host-polled segments of
    # this many iterations so SIGTERM/deadline are observed mid-K (each
    # boundary re-runs one E-step -- ~1/poll_iters overhead; results stay
    # bit-identical to the single-dispatch loop). Unsupervised runs keep
    # the zero-sync single dispatch.
    preempt_poll_iters: int = 25
    # Checkpoint resume policy: 'auto' (default) resumes from the newest
    # step -- including an intra-K emergency sub-step, restarting inside
    # the interrupted fit; 'never' ignores existing checkpoints (fresh
    # sweep; new checkpoints are still written).
    resume: str = "auto"
    # Cross-host liveness watchdog timeout (multi-controller runs with a
    # supervisor + checkpoint_dir): a peer whose heartbeat on the shared
    # checkpoint filesystem goes stale beyond this raises PeerLostError
    # with a local emergency checkpoint instead of hanging forever in the
    # next collective. 0 disables the watchdog.
    peer_timeout_s: float = 60.0
    # Elastic multi-host recovery (parallel/elastic.py;
    # docs/DISTRIBUTED.md "Elastic recovery"): on PeerLostError the
    # surviving hosts rendezvous on the checkpoint filesystem, seal a
    # generation-stamped shrunken membership, recompute host_chunk_bounds
    # over the survivors, restore the newest checkpoint, and refit --
    # instead of exiting 75 and waiting for an external full-world
    # restart. Requires checkpoint_dir (the rendezvous medium). Off by
    # default: the exit-75 contract is unchanged unless opted into.
    elastic: bool = False
    # Smallest world elastic recovery may shrink to; a loss that would go
    # below this gives up and exits 75 as today. >= 1.
    min_hosts: int = 1
    # Shrink attempts before elastic recovery gives up (each loss event
    # consumes one; repeated losses of different peers each retry). >= 1.
    elastic_max_retries: int = 2
    # First-attempt pause before the rendezvous (doubles per attempt):
    # lets a transient filesystem blip or a slow-but-alive peer settle
    # before the world is resealed without it. >= 0.
    elastic_backoff_s: float = 0.5

    # --- numerical fault containment (health.py; docs/ROBUSTNESS.md) ---
    # Health detection (the in-loop bitmask) is ALWAYS on -- it is a
    # handful of elementwise ops per EM iteration against the loop's
    # matmuls. ``recovery`` selects what a FATAL flag (non-finite
    # loglik/params) does to the run:
    #   'retry' (default): roll back to the K's input state and climb the
    #     deterministic escalation ladder -- sanitize + raise the variance
    #     floor -> quad_mode='centered' -> matmul_precision='highest' --
    #     failing loudly (NumericalFaultError + diagnostic bundle) only
    #     when the ladder is exhausted. The fused whole-sweep program
    #     recovers by falling back to the host-driven sweep (a single
    #     device program has no per-K host intervention point).
    #   'off': detect and raise immediately. Either way a poisoned model
    #     is never silently returned (the reference's failure mode).
    recovery: str = "retry"
    # Escalation rungs attempted per fault before giving up (<= 3 rungs
    # exist; smaller values truncate the ladder).
    max_recovery_attempts: int = 3
    # Variance-floor multiplier per recovery attempt: attempt i retries
    # with avgvar * boost**i (the runtime analog of lowering
    # COVARIANCE_DYNAMIC_RANGE, gaussian.h:12).
    recovery_boost: float = 10.0
    # Reseed empty clusters from worst-fit events at a target-K fit
    # instead of letting elimination shrink the model below the requested
    # K. Off = reference semantics (empties are eliminated).
    recovery_reseed_empty: bool = False
    # Loglik-regression tolerance, in units of the convergence epsilon: a
    # drop beyond scale*epsilon between EM iterations raises the (non-
    # fatal) loglik_regression health flag.
    health_regression_scale: float = 10.0

    # --- aux subsystems ---
    profile: bool = False
    # Run-scoped telemetry sink: a JSONL path that receives the
    # schema-versioned event stream (run_start / em_iter / em_done / merge /
    # chunk_flush / heartbeat / run_summary -- docs/OBSERVABILITY.md) for
    # every execution path. None (default) = off; the legacy stderr lines
    # (metrics_line, --profile) are unaffected either way. Multi-host runs
    # write one coherent stream from process 0 with rank-tagged records.
    metrics_file: Optional[str] = None
    # Live observability plane (stream rev v2.1; docs/OBSERVABILITY.md
    # "Live metrics endpoint"): serve a Prometheus/OpenMetrics `/metrics`
    # endpoint on this localhost port for the duration of the run, start
    # the periodic resource sampler (memory heartbeats), and emit trace
    # spans + a fit-scoped trace_id on the stream. 0 = OS-assigned
    # ephemeral port (tests). None (default) = fully off: the stream is
    # byte-identical to a pre-v2.1 run.
    metrics_port: Optional[int] = None
    # Training drift envelope (stream rev v2.4; telemetry/sketch.py,
    # docs/OBSERVABILITY.md "Drift detection"): at fit end, one extra
    # streamed pass over the (already device-resident) training data
    # through the final parameters sketches the per-event score
    # distribution + per-cluster responsibility occupancy; the envelope
    # rides GMMResult/run_summary and is persisted as envelope.json on
    # registry export -- the reference distribution serve-time drift is
    # measured against. Observational: envelope failures never fail a
    # fit. False = skip the pass (envelope.json can be backfilled later
    # with `gmm drift --rebuild-envelope`).
    envelope: bool = True
    # Profile-guided autotuning (docs/PERF.md "Autotuning"; tuning/):
    #   'off' (default): every knob runs exactly as set -- streams and
    #     results stay byte-identical to pre-tuner behavior.
    #   'db': resolve unset tunable knobs (chunk_size, estep_backend,
    #     sweep_k_buckets, restart_batch_size, fleet_mode) from the
    #     nearest recorded profile in the tuning database, falling back
    #     to the static cost model; knobs whose value differs from the
    #     dataclass default are treated as user-pinned and never touched.
    #   'probe': like 'db', but missing rows are measured first by a
    #     bounded microprobe (2-3 real EM iterations per candidate) and
    #     written back to the database.
    # Every resolved decision is emitted as a `tune` telemetry event
    # (schema rev v2.5) when a recorder is active.
    autotune: str = "off"
    # Tuning database path. None = GMM_TUNING_DB or
    # ~/.cache/gmm/tuning.json (tuning.db.default_db_path).
    tuning_db: Optional[str] = None
    checkpoint_dir: Optional[str] = None
    seed: int = 0  # RNG seed for any randomized paths (reference is deterministic)
    # Initial means: 'even' = the reference's evenly-spaced event rows
    # (gaussian.cu:108-123); 'kmeans++' = D^2-weighted sampling (upgrade,
    # deterministic given ``seed``).
    seed_method: str = "even"
    # Independent restarts (sklearn's n_init): fit n_init times with
    # kmeans++ seeds seed, seed+1, ... and keep the best Rissanen score.
    # 1 = reference behavior (single deterministic init). Restarts share the
    # compiled executables (no recompilation), and the chunked event data is
    # prepared and uploaded ONCE -- restarts reuse the device-resident
    # arrays (order_search._fit_with_restarts' per-model data cache); only
    # seeding and the EM itself repeat per restart.
    n_init: int = 1
    # Restarts per batched-EM dispatch (models/restarts.py): the n_init
    # restarts are vmapped over a leading restart axis and run as ONE
    # compiled EM program per batch -- [R, B, K] E-step matmuls with R x
    # the arithmetic intensity at zero extra host->device cost (the
    # restart cache uploads the data once). None (default) auto-sizes the
    # batch from a psutil-free host-memory heuristic (the [R, B, K]
    # posterior buffer is the constraint; GMM_RESTART_MEM_BYTES overrides
    # the budget, GMM_RESTART_BATCH_SIZE the size itself). 1 = the
    # sequential path (one fit per init -- the degenerate case; selects
    # the identical winner at the same seeds). Streaming and fused-sweep
    # restarts always run sequentially.
    restart_batch_size: Optional[int] = None
    # --- multi-tenancy fleet fits (tenancy/; docs/TENANCY.md) ---
    # Per-group EM dispatch mode for `fit_fleet` / `gmm fleet`:
    #   'scan' (default): the tenant lanes of one packed group run as a
    #     lax.map over the UNBATCHED EM loop inside one compiled program
    #     -- one dispatch per group, and every lane's arithmetic is the
    #     exact HLO of its solo fit, so per-tenant results are
    #     BIT-IDENTICAL to solo fits of the same tenants (the fleet
    #     parity contract, tests/test_tenancy.py).
    #   'vmap': the lanes vmap over a leading tenant axis -- [T, B, K]
    #     batched matmuls (the restart-batching shape, maximal MXU feed)
    #     at reduction-order tolerance instead of bit-parity (batched
    #     dot_general associates differently than T solo matmuls).
    fleet_mode: str = "scan"
    # Tenants per packed-group EM dispatch. None = every tenant of a
    # (N-bucket, K-bucket) group rides one dispatch; smaller values split
    # groups (memory bound: one group holds T x the padded chunk grid on
    # device).
    fleet_group_size: Optional[int] = None
    # Numerical-sanitizer analog (SURVEY SS5.2: the reference has no race
    # detection / sanitizers; JAX's functional model removes data races, and
    # this enables the remaining useful check -- trap NaN/Inf at the op that
    # produced it).
    debug_nans: bool = False
    # Reject NaN/Inf event rows at load (one cheap host pass per slice). The
    # reference's atof-based reader admits them silently and they poison
    # every statistic; opt out with --no-validate-input for raw-speed runs.
    validate_input: bool = True

    def __post_init__(self):
        if self.min_iters > self.max_iters:
            raise ValueError(
                f"min_iters ({self.min_iters}) must be <= max_iters ({self.max_iters})"
            )
        if self.max_clusters < 1:
            raise ValueError("max_clusters must be >= 1")
        if self.metrics_port is not None and not (
                0 <= self.metrics_port <= 65535):
            raise ValueError(
                f"metrics_port must be in [0, 65535], got {self.metrics_port}")
        if self.autotune not in ("off", "db", "probe"):
            raise ValueError(
                f"unknown autotune mode: {self.autotune!r} "
                "(expected 'off', 'db' or 'probe')")
        if self.quad_mode not in ("expanded", "packed", "centered"):
            raise ValueError(f"unknown quad_mode: {self.quad_mode!r}")
        if self.covariance_type not in ("full", "diag", "spherical", "tied"):
            raise ValueError(
                f"unknown covariance_type: {self.covariance_type!r}")
        if self.criterion not in ("rissanen", "bic", "aic", "aicc"):
            raise ValueError(f"unknown criterion: {self.criterion!r}")
        # diag_only (the reference's DIAG_ONLY flag) and covariance_type are
        # one setting: keep them coherent whichever way the user spells it.
        if self.diag_only and self.covariance_type == "full":
            object.__setattr__(self, "covariance_type", "diag")
        elif self.covariance_type in ("diag", "spherical"):
            object.__setattr__(self, "diag_only", True)
        elif self.diag_only and self.covariance_type == "tied":
            raise ValueError(
                "covariance_type='tied' needs full-covariance statistics; "
                "it cannot combine with diag_only=True")
        if self.use_pallas not in ("auto", "always", "never"):
            raise ValueError(f"unknown use_pallas: {self.use_pallas!r}")
        if self.estep_backend not in ("auto", "pallas", "jnp"):
            raise ValueError(
                f"unknown estep_backend: {self.estep_backend!r} "
                "(expected 'auto', 'pallas' or 'jnp')")
        # use_pallas is the legacy spelling of estep_backend: keep them
        # coherent whichever way the caller set it (explicit contradictions
        # fail loudly rather than silently preferring one).
        if self.estep_backend == "auto":
            if self.use_pallas == "always":
                object.__setattr__(self, "estep_backend", "pallas")
            elif self.use_pallas == "never":
                object.__setattr__(self, "estep_backend", "jnp")
        elif ((self.estep_backend == "pallas"
               and self.use_pallas == "never")
              or (self.estep_backend == "jnp"
                  and self.use_pallas == "always")):
            raise ValueError(
                f"estep_backend={self.estep_backend!r} contradicts "
                f"use_pallas={self.use_pallas!r} -- drop one flag")
        elif self.estep_backend == "pallas":
            object.__setattr__(self, "use_pallas", "always")
        elif self.estep_backend == "jnp":
            object.__setattr__(self, "use_pallas", "never")
        if (self.stream_events and self.mesh_shape is not None
                and self.mesh_shape[1] != 1):
            raise ValueError(
                "stream_events shards events over local devices; the "
                "cluster mesh axis must be 1 (use mesh_shape=(S, 1))")
        if self.stream_events and self.use_pallas == "always":
            raise ValueError(
                "stream_events streams per-chunk through the jnp path; "
                "use_pallas='always' cannot be honored -- drop one flag")
        if self.sweep_k_buckets not in ("pow2", "off"):
            raise ValueError(
                f"unknown sweep_k_buckets: {self.sweep_k_buckets!r} "
                "(expected 'pow2' or 'off')")
        if self.precompute_features:
            if self.diag_only:
                raise ValueError(
                    "precompute_features is a full-covariance optimization "
                    "(diag builds no [N, F] features)")
            if self.quad_mode == "centered":
                raise ValueError(
                    "precompute_features requires quad_mode='expanded' or "
                    "'packed' (the 'centered' staging has no loop-invariant "
                    "feature matrix to hoist)")
            if self.use_pallas == "always":
                raise ValueError(
                    "precompute_features is the XLA-path feature hoist; "
                    "the Pallas kernel builds features in VMEM -- drop one "
                    "flag")
            if self.stream_events:
                raise ValueError(
                    "precompute_features holds all features in device "
                    "memory; stream_events exists because the data does "
                    "not fit there -- drop one flag")
        if self.ingest not in ("resident", "pipelined"):
            raise ValueError(
                f"unknown ingest: {self.ingest!r} "
                "(expected 'resident' or 'pipelined')")
        if self.ingest == "pipelined" and not self.stream_events:
            raise ValueError(
                "ingest='pipelined' feeds the streaming block loop; it "
                "requires stream_events=True")
        if self.ingest_queue_depth < 1:
            raise ValueError("ingest_queue_depth must be >= 1")
        if self.em_mode not in ("full", "minibatch"):
            raise ValueError(
                f"unknown em_mode: {self.em_mode!r} "
                "(expected 'full' or 'minibatch')")
        if self.em_mode == "minibatch" and not self.stream_events:
            raise ValueError(
                "em_mode='minibatch' is the streaming stepwise driver; it "
                "requires stream_events=True")
        if not 0.5 < self.minibatch_alpha <= 1.0:
            raise ValueError(
                f"minibatch_alpha must lie in (0.5, 1], got "
                f"{self.minibatch_alpha}")
        if self.minibatch_t0 < 0:
            raise ValueError("minibatch_t0 must be >= 0")
        if self.minibatch_size < 0:
            raise ValueError(
                "minibatch_size must be >= 0 (0 = one block per step)")
        if self.seed_method not in ("even", "kmeans++"):
            raise ValueError(f"unknown seed_method: {self.seed_method!r}")
        if self.checkpoint_keep < 1:
            raise ValueError("checkpoint_keep must be >= 1")
        if self.max_runtime_s is not None and self.max_runtime_s <= 0:
            raise ValueError("max_runtime_s must be > 0 (or None)")
        if self.preempt_poll_iters < 1:
            raise ValueError("preempt_poll_iters must be >= 1")
        if self.resume not in ("auto", "never"):
            raise ValueError(
                f"unknown resume: {self.resume!r} "
                "(expected 'auto' or 'never')")
        if self.peer_timeout_s < 0:
            raise ValueError("peer_timeout_s must be >= 0 (0 disables)")
        if self.elastic and not self.checkpoint_dir:
            raise ValueError(
                "elastic recovery requires checkpoint_dir: the checkpoint "
                "filesystem is the survivors' rendezvous medium and the "
                "resume source")
        if self.min_hosts < 1:
            raise ValueError("min_hosts must be >= 1")
        if self.elastic_max_retries < 1:
            raise ValueError("elastic_max_retries must be >= 1")
        if self.elastic_backoff_s < 0:
            raise ValueError("elastic_backoff_s must be >= 0")
        if self.recovery not in ("retry", "off"):
            raise ValueError(
                f"unknown recovery: {self.recovery!r} "
                "(expected 'retry' or 'off')")
        if self.max_recovery_attempts < 0:
            raise ValueError("max_recovery_attempts must be >= 0")
        if self.checkpoint_retries < 0:
            raise ValueError("checkpoint_retries must be >= 0")
        if self.recovery_boost < 1.0:
            raise ValueError("recovery_boost must be >= 1")
        if self.health_regression_scale <= 0:
            raise ValueError("health_regression_scale must be > 0")
        if self.chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        if self.pallas_block_b < 1:
            raise ValueError("pallas_block_b must be >= 1")
        if self.n_init < 1:
            raise ValueError("n_init must be >= 1")
        if self.restart_batch_size is not None and self.restart_batch_size < 1:
            raise ValueError("restart_batch_size must be >= 1 (or None for "
                             "the host-memory auto cap)")
        if self.fleet_mode not in ("scan", "vmap"):
            raise ValueError(
                f"unknown fleet_mode: {self.fleet_mode!r} "
                "(expected 'scan' or 'vmap')")
        if self.fleet_group_size is not None and self.fleet_group_size < 1:
            raise ValueError("fleet_group_size must be >= 1 (or None for "
                             "whole-group dispatches)")


DEFAULT_CONFIG = GMMConfig()
