"""Compile & cost introspection: the CompileWatch (stream rev v2.2).

The reference binary's only performance signal is a wall-clock printf per
EM phase (gaussian.cu:967); PR 13's live plane made the counters visible
but still could not say WHERE compile time and memory go -- a retrace
storm or a silent recompile shows up only as a slower wall. This module
closes that gap with three instruments, all inert unless a
:class:`CompileWatch` is active (the ``watch()`` context, entered by
``fit_gmm``/``serve_main`` only when a recorder is already active -- so
no-recorder runs stay byte-identical to pre-v2.2):

* **XLA compile observation** -- one process-global ``jax.monitoring``
  event-duration listener (registered lazily and exactly once;
  jax.monitoring has no unregister, so the listener is permanent and
  forwards to the CURRENT watch, a no-op when none is active) counts
  every ``backend_compile`` with its wall seconds, tagged with the
  active span/phase, and emits a ``compile`` telemetry event for
  compiles the executable caches did not expect.

* **Executable cost introspection** -- the memoized executable caches
  (``models/gmm.py`` ``_em_*_executable`` variants via
  :class:`ProfiledExecutable`, ``serving/executor.py`` AOT builds via
  :func:`site_compile`) time their lower+compile and pull
  ``compiled.cost_analysis()`` (flops, bytes accessed) and
  ``memory_analysis()`` (argument/output/temp/generated-code bytes)
  where the backend provides them, stamped into enriched ``compile``
  events and rolled up into ``run_summary.profile``.

* **Device memory watermarks** -- :func:`wm_begin`/:func:`wm_end` (and
  the lexical :func:`watermark`) capture device ``memory_stats()`` peak
  deltas attributed to span boundaries (``sweep`` / ``em_k`` /
  ``serve_dispatch``); inert where the backend reports no stats (CPU).

The watch feeds the metrics registry under ``compiles`` /
``compile_seconds`` / ``hbm_peak_bytes``, which the OpenMetrics exporter
renders as ``gmm_compiles_total`` / ``gmm_compile_seconds_total`` /
``gmm_hbm_peak_bytes`` with no exporter-side wiring.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from . import recorder as _recorder
from . import spans as _spans

# The per-XLA-compile signal: fired once per backend compilation (jit
# tracing fires its own jaxpr events; this one is the actual compile).
_XLA_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_register_lock = threading.Lock()
_current: Optional["CompileWatch"] = None
_listener_registered = False


class _SiteState(threading.local):
    """Per-thread instrumentation state: ``depth`` > 0 while inside an
    instrumented site compile (so the XLA listener does not double-emit
    the event the site is about to emit enriched), and ``tag`` the
    active phase label for listener attribution when no trace span is
    open (metrics-file-only runs have no span stack)."""

    def __init__(self):
        self.depth = 0
        self.tag: Optional[str] = None


_tls = _SiteState()


def active() -> Optional["CompileWatch"]:
    """The process-global active watch (None = all instruments inert)."""
    return _current


def _on_event_duration(event: str, duration, **kwargs) -> None:
    watch = _current
    if watch is None or event != _XLA_COMPILE_EVENT:
        return
    try:
        watch._observe_xla(float(duration))
    except Exception:
        # Observability must never take the run down: a broken listener
        # degrades to missing compile records, not a failed fit.
        pass


def _ensure_listener() -> None:
    # jax.monitoring listeners cannot be unregistered (jax 0.4 API), so
    # one permanent forwarder is registered on first watch activation;
    # it reads the mutable _current ref and is a no-op between watches.
    global _listener_registered
    if _listener_registered:
        return
    with _register_lock:
        if _listener_registered:
            return
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(
                _on_event_duration)
        except Exception:
            # No jax.monitoring: XLA totals degrade to site-only numbers.
            pass
        _listener_registered = True


def compiled_analyses(compiled) -> Tuple[Optional[dict], Optional[dict]]:
    """(cost, memory) introspection of one compiled executable.

    ``cost``: {flops, bytes_accessed} from ``cost_analysis()`` (dict or
    one-element list depending on jax version). ``memory``:
    {argument_bytes, output_bytes, temp_bytes, generated_code_bytes}
    from ``memory_analysis()``. Either side is None where the backend
    does not provide it -- both calls are best-effort by contract.
    """
    cost = None
    try:
        c = compiled.cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0] if c else None
        if isinstance(c, dict):
            cost = {}
            if c.get("flops") is not None:
                cost["flops"] = float(c["flops"])
            if c.get("bytes accessed") is not None:
                cost["bytes_accessed"] = float(c["bytes accessed"])
            cost = cost or None
    except Exception:
        pass
    memory = None
    try:
        m = compiled.memory_analysis()
        if m is not None:
            memory = {}
            for attr, name in (
                    ("argument_size_in_bytes", "argument_bytes"),
                    ("output_size_in_bytes", "output_bytes"),
                    ("temp_size_in_bytes", "temp_bytes"),
                    ("generated_code_size_in_bytes",
                     "generated_code_bytes")):
                v = getattr(m, attr, None)
                if v is not None:
                    memory[name] = int(v)
            memory = memory or None
    except Exception:
        pass
    return cost, memory


class CompileWatch:
    """Accumulating compile/cost/memory observations for one run.

    Thread-safe: EM dispatch, serve tick loops, and io_callback threads
    all report here. ``snapshot()`` is the ``run_summary.profile``
    payload (and serve_summary's); per-observation detail lands on the
    stream as ``compile`` events through the ambient recorder.
    """

    def __init__(self, recorder: Optional[Any] = None):
        self._recorder = recorder
        self._lock = threading.Lock()
        # Shadowed outer watch (set by watch(); _register_lock-guarded).
        self._prev: Optional["CompileWatch"] = None
        # ``compile`` records observed before the owning loop wrote the
        # stream head (run_start lands AFTER the prologue jit compiles
        # in _prepare_fit; serve AOT warmup precedes the first serve
        # event): buffered here and flushed behind the head so the
        # stream-ordering contract (run_start first) holds.
        self._pending: list = []
        # Instrumented executable-cache compiles (the acceptance target:
        # these must match the caches' own counters).
        self.compiles = 0
        self.compile_seconds = 0.0
        # Every backend compile jax.monitoring saw (site compiles
        # included; the superset catches retraces the caches missed).
        self.xla_compiles = 0
        self.xla_seconds = 0.0
        self.by_phase: Dict[str, Dict[str, float]] = {}
        self.sites: Dict[str, Dict[str, float]] = {}
        self.cost: Dict[str, float] = {}
        self.memory: Dict[str, int] = {}       # max over compiles
        self.watermarks: Dict[str, Dict[str, int]] = {}
        self.hbm_peak_bytes: Optional[int] = None

    def _rec(self):
        rec = self._recorder
        return rec if rec is not None else _recorder.current()

    def _emit_compile(self, rec, fields: Dict[str, Any]) -> None:
        """Emit one ``compile`` record, buffering ahead of the stream head.

        Until the recorder has written its first record (``run_start`` /
        the first serve event), compile observations queue in
        ``_pending``; once the head exists they flush in observation
        order before the new record. ``flush()`` (called from
        ``snapshot()`` and watch exit) drains stragglers so buffered
        records still precede ``run_summary``.
        """
        with self._lock:
            if not getattr(rec, "emitted", True):
                self._pending.append(fields)
                return
            pending, self._pending = self._pending, []
        for f in pending:
            rec.emit("compile", **f)
        rec.emit("compile", **fields)

    def flush(self, force: bool = False) -> None:
        """Drain buffered ``compile`` records once the stream is open.

        A no-op while the recorder has still written nothing: records
        that cannot yet be ordered behind the stream head are held
        rather than emitted ahead of ``run_start``. ``force`` (watch
        exit) writes them regardless -- a watch whose stream never grew
        a head (library users recording only compiles) still delivers
        its observations, and a fit that died before ``run_start``
        leaves its compiles on the stream for forensics.
        """
        rec = self._rec()
        if not rec.active or not (force or getattr(rec, "emitted", True)):
            return
        with self._lock:
            pending, self._pending = self._pending, []
        for f in pending:
            rec.emit("compile", **f)

    def _tag(self) -> Optional[str]:
        return _spans.current_span_name() or _tls.tag

    def _fold_phase(self, tag: Optional[str], seconds: float) -> None:
        if not tag:
            return
        slot = self.by_phase.setdefault(tag, {"compiles": 0,
                                              "seconds": 0.0})
        slot["compiles"] += 1
        slot["seconds"] = round(slot["seconds"] + seconds, 6)

    def _observe_xla(self, seconds: float) -> None:
        tag = self._tag()
        in_site = _tls.depth > 0
        with self._lock:
            self.xla_compiles += 1
            self.xla_seconds += seconds
            if not in_site:
                # Site compiles fold their own (more precise, analysis-
                # enriched) observation; only unexpected compiles land
                # in the phase table and registry from the listener.
                self._fold_phase(tag, seconds)
        rec = self._rec()
        if in_site or not rec.active:
            return
        rec.metrics.count("compiles")
        rec.metrics.count("compile_seconds", round(seconds, 6))
        self._emit_compile(rec, dict(
            source="xla", seconds=round(seconds, 6),
            **({"phase": tag} if tag else {})))

    def observe_site(self, site: str, seconds: float,
                     cost: Optional[dict] = None,
                     memory: Optional[dict] = None, **fields) -> None:
        """Fold one instrumented lower+compile (and emit its event)."""
        tag = self._tag()
        with self._lock:
            self.compiles += 1
            self.compile_seconds += seconds
            slot = self.sites.setdefault(site, {"compiles": 0,
                                                "seconds": 0.0})
            slot["compiles"] += 1
            slot["seconds"] = round(slot["seconds"] + seconds, 6)
            self._fold_phase(tag, seconds)
            for k, v in (cost or {}).items():
                self.cost[k] = self.cost.get(k, 0.0) + float(v)
            for k, v in (memory or {}).items():
                self.memory[k] = max(self.memory.get(k, 0), int(v))
        rec = self._rec()
        if not rec.active:
            return
        rec.metrics.count("compiles")
        rec.metrics.count("compile_seconds", round(seconds, 6))
        self._emit_compile(rec, dict(
            source="aot", site=site, seconds=round(seconds, 6),
            **({"phase": tag} if tag else {}),
            **(cost or {}), **(memory or {}), **fields))

    def observe_watermark(self, name: str, before: Optional[dict],
                          after: Optional[dict]) -> None:
        """Fold one span boundary's device memory_stats() delta."""
        if not after:
            return
        peak = after.get("peak_bytes_in_use")
        in_use = after.get("bytes_in_use")
        base = (before or {}).get("bytes_in_use")
        with self._lock:
            w = self.watermarks.setdefault(
                name, {"sections": 0, "peak_bytes": 0, "delta_bytes": 0})
            w["sections"] += 1
            if peak is not None:
                w["peak_bytes"] = max(w["peak_bytes"], int(peak))
                self.hbm_peak_bytes = max(self.hbm_peak_bytes or 0,
                                          int(peak))
            if in_use is not None and base is not None:
                w["delta_bytes"] = max(w["delta_bytes"],
                                       int(in_use) - int(base))
            hbm = self.hbm_peak_bytes
        rec = self._rec()
        if rec.active and hbm is not None:
            rec.metrics.gauge("hbm_peak_bytes", hbm)

    def snapshot(self) -> dict:
        """The ``run_summary.profile`` payload (empty sections omitted)."""
        # Summary construction precedes the summary record: draining the
        # buffer here puts any still-pending compile records on the
        # stream BEFORE run_summary/serve_summary closes it.
        self.flush()
        with self._lock:
            out: Dict[str, Any] = {
                "compiles": int(self.compiles),
                "compile_seconds": round(self.compile_seconds, 6),
                "xla_compiles": int(self.xla_compiles),
                "xla_compile_seconds": round(self.xla_seconds, 6),
            }
            if self.sites:
                out["sites"] = {k: dict(v) for k, v in self.sites.items()}
            if self.by_phase:
                out["by_phase"] = {k: dict(v)
                                   for k, v in self.by_phase.items()}
            if self.cost:
                out["cost"] = dict(self.cost)
            if self.memory:
                out["memory"] = dict(self.memory)
            if self.watermarks:
                out["watermarks"] = {k: dict(v)
                                     for k, v in self.watermarks.items()}
            if self.hbm_peak_bytes is not None:
                out["hbm_peak_bytes"] = int(self.hbm_peak_bytes)
            return out


@contextlib.contextmanager
def watch(recorder: Optional[Any] = None):
    """Activate a :class:`CompileWatch` for the enclosed run.

    Process-global (compiles arrive from io_callback and serve tick
    threads, not just the caller's); nested activation shadows the
    outer watch and restores it on exit. Activation and restore run
    under ``_register_lock``, and each watch remembers the one it
    shadowed: concurrent watches from different threads (a fit in one
    thread while ``gmm serve`` runs in another) exit in ANY order
    without a later-exiting context resurrecting an already-exited
    watch -- an out-of-order exit splices itself out of the shadow
    chain instead of blindly restoring its predecessor. Callers gate
    activation on an active recorder so no-recorder runs never enter
    here.
    """
    global _current
    _ensure_listener()
    w = CompileWatch(recorder)
    with _register_lock:
        w._prev = _current
        _current = w
    # A sweep that raised through its wm_begin/wm_end pair leaves a
    # stale tag on this thread; a fresh watch must not inherit it.
    _tls.tag = None
    try:
        yield w
    finally:
        with _register_lock:
            if _current is w:
                _current = w._prev
            else:
                node = _current
                while node is not None and node._prev is not w:
                    node = node._prev
                if node is not None:
                    node._prev = w._prev
            w._prev = None
        # Stragglers observed after the last snapshot() still land on
        # the stream; on the fit/serve paths the buffer drained before
        # run_summary/serve_summary, so a forced flush here only ever
        # writes to head-less streams (compile-only library use,
        # pre-run_start fatalities).
        w.flush(force=True)


def site_compile(site: str, build: Callable[[], Any], **fields):
    """Run ``build`` (a lower+compile) under the active watch.

    No watch: calls ``build`` directly -- zero added work on the
    uninstrumented path. With a watch: times the build, suppresses the
    XLA listener's duplicate event for its duration, pulls the cost /
    memory analyses off the compiled result, and folds one enriched
    ``compile`` observation. Returns whatever ``build`` returns.
    """
    watch_ = _current
    if watch_ is None:
        return build()
    _tls.depth += 1
    t0 = time.perf_counter()
    try:
        compiled = build()
    finally:
        _tls.depth -= 1
    seconds = time.perf_counter() - t0
    try:
        cost, memory = compiled_analyses(compiled)
        watch_.observe_site(site, seconds, cost, memory, **fields)
    except Exception:
        pass
    return compiled


def _arg_signature(args) -> Optional[tuple]:
    """Hashable shape/dtype signature of one positional call.

    Array leaves key by (shape, dtype, weak_type) -- VALUES stay out of
    the key, so the dynamic scalar args (epsilon, min/max iters) reuse
    one executable across values exactly like jit's own cache. Python
    scalars key by type.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype),
                        bool(getattr(leaf, "weak_type", False))))
        else:
            sig.append(type(leaf))
    return (treedef, tuple(sig))


class ProfiledExecutable:
    """Transparent cost-introspection proxy over a memoized jit callable.

    With no watch active every call falls straight through to the
    wrapped ``jax.jit`` function -- same dispatch path, byte-identical
    results. With a watch, calls route through an explicit
    ``lower(*args).compile()`` per argument signature (mirroring jit's
    shape-keyed cache, so a bucketed sweep still compiles once per
    distinct padded width): the compile is timed, its cost / memory
    analyses are captured, and warm calls dispatch the compiled object
    directly. Any AOT failure (exotic shardings, backend quirks) falls
    back to the plain jit call -- introspection degrades, results never
    change.
    """

    def __init__(self, fn, site: str):
        self._fn = fn
        self._site = site
        self._aot: Dict[tuple, Any] = {}

    def __getattr__(self, name):
        # lower(), clear_cache(), ... pass through to the jit callable.
        return getattr(self._fn, name)

    @property
    def aot_compiles(self) -> int:
        """Distinct signatures compiled under a watch (tests)."""
        return len(self._aot)

    def __call__(self, *args, **kwargs):
        if _current is None or kwargs:
            return self._fn(*args, **kwargs)
        try:
            key = _arg_signature(args)
        except Exception:
            return self._fn(*args)
        compiled = self._aot.get(key)
        if compiled is None:
            try:
                compiled = site_compile(
                    self._site,
                    lambda: self._fn.lower(*args).compile())
            except Exception:
                return self._fn(*args)
            self._aot[key] = compiled
        try:
            return compiled(*args)
        except (TypeError, ValueError):
            # Aval mismatch beyond the signature (committed-device or
            # sharding drift): rejected before execution, so re-running
            # through jit is safe.
            return self._fn(*args)


# -- watermarks ----------------------------------------------------------

def wm_begin(name: str) -> Optional[tuple]:
    """Open a watermark section at a span boundary.

    Returns an opaque handle for :func:`wm_end` (None-safe when no watch
    is active, so call sites need no gate). Also tags the thread's phase
    label for XLA-listener attribution -- metrics-file-only runs have no
    trace spans to read the phase from.
    """
    if _current is None:
        return None
    prev_tag, _tls.tag = _tls.tag, name
    return (name, _recorder.memory_stats(), prev_tag)


def wm_end(handle: Optional[tuple]) -> None:
    """Close a :func:`wm_begin` section: restore the phase tag and fold
    the device memory delta (inert where memory_stats() is None)."""
    if handle is None:
        return
    name, before, prev_tag = handle
    _tls.tag = prev_tag
    watch_ = _current
    if watch_ is None:
        return
    try:
        watch_.observe_watermark(name, before, _recorder.memory_stats())
    except Exception:
        pass


@contextlib.contextmanager
def watermark(name: str):
    """Lexical watermark section (the ``with``-friendly wm_begin/wm_end)."""
    handle = wm_begin(name)
    try:
        yield
    finally:
        wm_end(handle)
