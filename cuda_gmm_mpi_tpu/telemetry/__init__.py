"""Run-scoped observability: event stream, metrics registry, reporting.

The structured replacement for the reference's printf telemetry + cudaEvent
phase timers (SURVEY.md SS5.1/5.5): every execution path -- in-memory,
streaming, sharded-mesh, multi-controller, fused-sweep -- emits the same
schema-versioned JSONL record stream through one :class:`RunRecorder`,
and ``gmm report`` / ``bench.py`` consume it instead of scraping stdout.

Layering: ``schema`` is the wire contract, ``registry`` the numeric
aggregates, ``recorder`` the event bus + ambient-activation plumbing,
``report`` the offline renderer. ``utils.profiling.PhaseTimer`` and
``utils.logging_.metrics_line`` are thin adapters over this package.
"""

from .recorder import (RunRecorder, current, memory_stats, read_stream, use,
                       write_line)
from .registry import MetricsRegistry
from .report import render_phase_table, render_report, report_main
from .schema import (EVENT_FIELDS, SCHEMA_VERSION, validate_record,
                     validate_stream)

__all__ = [
    "RunRecorder", "MetricsRegistry", "current", "use", "write_line",
    "read_stream", "memory_stats",
    "render_phase_table", "render_report", "report_main",
    "EVENT_FIELDS", "SCHEMA_VERSION", "validate_record", "validate_stream",
]
