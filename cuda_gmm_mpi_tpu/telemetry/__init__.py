"""Run-scoped observability: event stream, metrics registry, reporting.

The structured replacement for the reference's printf telemetry + cudaEvent
phase timers (SURVEY.md SS5.1/5.5): every execution path -- in-memory,
streaming, sharded-mesh, multi-controller, fused-sweep -- emits the same
schema-versioned JSONL record stream through one :class:`RunRecorder`,
and ``gmm report`` / ``bench.py`` consume it instead of scraping stdout.

Layering: ``schema`` is the wire contract, ``registry`` the numeric
aggregates, ``recorder`` the event bus + ambient-activation plumbing,
``report`` the offline renderer (plus the ``--follow`` live tailer),
``exporter`` the live OpenMetrics endpoint + resource sampler, and
``spans`` the trace-span emission (rev v2.1 live plane).
``profiling`` the compile & cost introspection watch (rev v2.2), and
``diff`` the cross-run regression analytics behind ``gmm diff`` /
``gmm runs``, and ``timeline`` the Perfetto/Chrome trace export with
cross-stream clock alignment behind ``gmm timeline`` (rev v2.3).
``utils.profiling.PhaseTimer`` and ``utils.logging_.metrics_line`` are
thin adapters over this package.
"""

from .diff import diff_main, runs_main, summarize_run
from .exporter import (MetricsExporter, ResourceSampler, current_exporter,
                       host_rss_bytes, live_plane, render_openmetrics)
from .profiling import CompileWatch, ProfiledExecutable, site_compile, watch
from .recorder import (RunRecorder, current, memory_stats, read_stream, use,
                       write_line)
from .registry import MetricsRegistry
from .report import (StreamTailer, follow_stream, render_follow,
                     render_phase_table, render_report, report_main)
from .schema import (EVENT_FIELDS, SCHEMA_VERSION, validate_record,
                     validate_stream)
from .spans import build_span_tree, mint_trace_id, span
from .spans import trace as trace_spans
from .timeline import (build_timeline, fit_alignment, summarize_trace,
                       timeline_main, validate_trace)

__all__ = [
    "RunRecorder", "MetricsRegistry", "current", "use", "write_line",
    "read_stream", "memory_stats",
    "render_phase_table", "render_report", "report_main",
    "StreamTailer", "follow_stream", "render_follow",
    "EVENT_FIELDS", "SCHEMA_VERSION", "validate_record", "validate_stream",
    "MetricsExporter", "ResourceSampler", "current_exporter",
    "host_rss_bytes", "live_plane", "render_openmetrics",
    "build_span_tree", "mint_trace_id", "span", "trace_spans",
    "CompileWatch", "ProfiledExecutable", "site_compile", "watch",
    "diff_main", "runs_main", "summarize_run",
    "build_timeline", "fit_alignment", "summarize_trace",
    "timeline_main", "validate_trace",
]
