"""Cross-run regression analytics: ``gmm diff`` and ``gmm runs``.

Stream rev v2.2. BENCH_r01..r05 regressions were caught by a human
reading JSON files side by side; this module makes the comparison a CI
primitive instead. :func:`summarize_run` flattens one run -- a JSONL
stream, a directory of per-rank streams, or a ``bench.py`` JSON record
-- into a flat metric dict (per-phase walls from the span tree, iters/s,
compile count/seconds from the CompileWatch profile, health counters,
ingest prefetch waits, serve latency percentiles); :func:`diff_runs`
compares two of them under ``--fail-on 'metric>threshold%'`` specs.

Exit-code contract (CI-friendly, documented in docs/API.md):

* ``gmm diff``: 0 = clean (no spec tripped), 1 = at least one named
  regression, 2 = usage error / unreadable target.
* ``gmm runs``: 0 = listed (even when empty), 2 = unreadable directory.

The default specs are count-shaped ("must not increase at all"):
compile counts, health counters, serve errors/sheds. Wall-clock metrics
are never failed on by default -- two byte-identical runs still jitter
in wall time, and a flaky gate is worse than none -- so time-shaped
thresholds are opt-in via ``--fail-on``.

``gmm report --json`` emits the same rollup, so scripts consume one
shape everywhere.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from .recorder import read_stream
from .spans import build_span_tree

# Run-identity fields folded into the config fingerprint: same
# fingerprint = comparable runs (a diff across fingerprints still
# renders, with a loud note).
_FINGERPRINT_FIELDS = (
    "platform", "num_events", "num_dimensions", "start_k", "target_k",
    "epsilon", "dtype", "criterion", "covariance_type", "chunk_size",
    "fused_sweep", "n_init", "em_backend",
)

# Count-shaped metrics that must not increase between comparable runs.
# ``tune.regressions`` counts autotune decisions whose measured wall/iter
# came in >20% over the recorded profile that chose them (stale tuning-DB
# rows page instead of silently pessimizing; docs/PERF.md "Autotuning").
DEFAULT_FAIL_ON = (
    "compiles>0",
    "xla_compiles>0",
    "health_fatal>0",
    "health_recoveries>0",
    "health_io_retries>0",
    "serve.errors>0",
    "serve.shed>0",
    "serve.deadline_expired>0",
    "tune.regressions>0",
    # Closed-loop lifecycle (rev v2.6): a promotion that had to be
    # rolled back, or a candidate/attempt that had to be quarantined,
    # is a regression even though serving survived it by design.
    "lifecycle.rollbacks>0",
    "lifecycle.quarantines>0",
    # Network front end (rev v2.7): a 5xx answered to a client, a worker
    # process crash, or a request that exhausted the pool's sibling
    # retry is a regression even when the tier absorbed it.
    "http.errors_5xx>0",
    "http.worker_crashes>0",
    "http.retries_exhausted>0",
    # Device-resident routes (rev v2.8): warm serve traffic must score
    # against pinned device state -- any request that had to stage its
    # model host-side fell off the resident fast path (a reload released
    # the pin, or an unpinned version was addressed explicitly).
    "serve.host_staging>0",
)

#: a tuned run this much slower than its own recorded profile regresses.
TUNE_REGRESSION_TOLERANCE = 1.20


def _num(value) -> Optional[float]:
    if isinstance(value, bool):
        return float(value)
    if isinstance(value, (int, float)):
        f = float(value)
        return f if f == f else None  # NaN drops out
    return None


def _flatten(obj, prefix: str, out: Dict[str, float]) -> None:
    """Dotted-path flatten of one JSON object's numeric leaves."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            _flatten(v, f"{prefix}.{k}" if prefix else str(k), out)
        return
    v = _num(obj)
    if v is not None and prefix:
        out[prefix] = v


def _fingerprint(run_start: dict) -> str:
    ident = {k: run_start.get(k) for k in _FINGERPRINT_FIELDS
             if run_start.get(k) is not None}
    blob = json.dumps(ident, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:10]


def summarize_run(records: List[dict]) -> dict:
    """One decoded stream -> the flat cross-run rollup.

    ``{"kind": "stream", "run_id", "fingerprint", "backend", "platform",
    "metrics": {name: float}}`` -- the shape both ``gmm diff`` and
    ``gmm report --json`` emit.
    """
    metrics: Dict[str, float] = {}
    info: Dict[str, Any] = {"kind": "stream", "run_id": None,
                            "fingerprint": None, "backend": None,
                            "platform": None}

    starts = [r for r in records if r.get("event") == "run_start"]
    if starts:
        s = starts[0]
        info["run_id"] = s.get("run_id")
        info["platform"] = s.get("platform")
        info["backend"] = s.get("em_backend") or s.get("platform")
        info["fingerprint"] = _fingerprint(s)

    # Per-phase walls from the span tree (total time per span name; a
    # bucketed sweep sums its em_k spans).
    for root in build_span_tree(records):
        stack = [root]
        while stack:
            node = stack.pop()
            sp = node["span"]
            name = str(sp.get("name"))
            dur = _num(sp.get("duration_s"))
            if dur is not None:
                key = f"span.{name}_s"
                metrics[key] = round(metrics.get(key, 0.0) + dur, 6)
            stack.extend(node["children"])

    n_compile_events = 0
    tune_events: List[dict] = []
    lifecycle_seen = False
    serve_seen = False
    for r in records:
        ev = r.get("event")
        if ev == "compile":
            n_compile_events += 1
        elif ev == "lifecycle":
            # One count per state-machine phase (rev v2.6). ``retrain``
            # counts PUBLISHED candidates only -- scheduled/retry edges
            # are progress, not outcomes.
            lifecycle_seen = True
            phase = str(r.get("phase"))
            dst = {"retrain": "lifecycle.retrains",
                   "canary": "lifecycle.canaries",
                   "promote": "lifecycle.promotes",
                   "watch": "lifecycle.watches",
                   "rollback": "lifecycle.rollbacks",
                   "quarantine": "lifecycle.quarantines"}.get(phase)
            if dst is None:
                continue
            outcome = r.get("outcome")
            if phase == "retrain" and outcome != "published":
                continue
            if phase == "promote" and outcome != "promoted":
                continue
            metrics[dst] = metrics.get(dst, 0.0) + 1
        elif ev == "registry_torn":
            metrics["registry.torn"] = (
                metrics.get("registry.torn", 0.0) + 1)
        elif ev == "tune":
            tune_events.append(r)
        elif ev == "ingest_summary":
            for src, dst in (("prefetch_wait_s", "ingest.prefetch_wait_s"),
                             ("blocks_read", "ingest.blocks_read"),
                             ("bytes", "ingest.bytes")):
                v = _num(r.get(src))
                if v is not None:
                    metrics[dst] = round(metrics.get(dst, 0.0) + v, 6)
        elif ev == "serve_summary":
            serve_seen = True
            for src, dst in (("requests", "serve.requests"),
                             ("batches", "serve.batches"),
                             ("rows", "serve.rows"),
                             ("errors", "serve.errors"),
                             ("qps", "serve.qps"),
                             ("wall_s", "serve.wall_s"),
                             ("shed", "serve.shed"),
                             ("deadline_expired", "serve.deadline_expired"),
                             ("reloads", "serve.reloads"),
                             ("stacked_batches", "serve.stacked_batches")):
                v = _num(r.get(src))
                if v is not None:
                    metrics[dst] = v
            lat = r.get("latency_ms") or {}
            for q in ("p50", "p99", "mean", "max"):
                v = _num(lat.get(q))
                if v is not None:
                    metrics[f"serve.{q}_ms"] = v
            ex = r.get("executor") or {}
            v = _num(ex.get("compiles"))
            if v is not None:
                metrics["serve.compiles"] = v
            v = _num(ex.get("host_stagings"))
            if v is not None:
                metrics["serve.host_staging"] = v
            if info["run_id"] is None:
                info["run_id"] = r.get("run_id")
            self_prof = r.get("profile")
            if isinstance(self_prof, dict):
                _fold_profile(self_prof, metrics)
            # HTTP front-end rollup (rev v2.7): flatten the ``http``
            # dict so its counters gate like any other serve metric.
            http = r.get("http")
            if isinstance(http, dict):
                for k, raw in http.items():
                    v = _num(raw)
                    if v is not None:
                        metrics[f"http.{k}"] = v
        elif ev == "fleet_summary":
            for src in ("tenants", "dropped", "groups", "wall_s"):
                v = _num(r.get(src))
                if v is not None:
                    metrics[f"fleet.{src}"] = v
    if n_compile_events:
        metrics["compile_events"] = float(n_compile_events)
    if lifecycle_seen or serve_seen:
        # Explicit zeros so the count gates (lifecycle.rollbacks>0,
        # lifecycle.quarantines>0) compare against a baseline serve run
        # that simply had no lifecycle trouble, instead of evaporating
        # when one side lacks the metric.
        for key in ("lifecycle.rollbacks", "lifecycle.quarantines"):
            metrics.setdefault(key, 0.0)
    if serve_seen:
        # Same explicit-zero contract for the HTTP gates: a serve run
        # with the front end off (or one that simply saw no trouble)
        # reads 0, so baselines stay comparable across http on/off.
        for key in ("http.errors_5xx", "http.worker_crashes",
                    "http.retries_exhausted", "serve.host_staging"):
            metrics.setdefault(key, 0.0)

    summaries = [r for r in records if r.get("event") == "run_summary"]
    if summaries:
        s = summaries[-1]
        for src in ("wall_s", "total_iters", "score", "ideal_k"):
            v = _num(s.get(src))
            if v is not None:
                metrics[src] = v
        wall = _num(s.get("wall_s"))
        iters = _num(s.get("total_iters"))
        if wall and iters is not None and wall > 0:
            metrics["iters_per_s"] = round(iters / wall, 3)
        comp = s.get("compile") or {}
        # Pre-v2.5 streams only: the derived first-vs-warm estimate was
        # deleted once CompileWatch's measured compile_seconds (folded
        # from ``profile`` below) covered every run.
        v = _num(comp.get("est_compile_s"))
        if v is not None:
            metrics["est_compile_s"] = v
        prof = s.get("profile")
        if isinstance(prof, dict):
            _fold_profile(prof, metrics)
        phases = (s.get("phase_profile") or {}).get("seconds") or {}
        for name, sec in phases.items():
            v = _num(sec)
            if v is not None:
                metrics[f"phase.{name}_s"] = v
        health = s.get("health") or {}
        metrics["health_fatal"] = float(bool(health.get("fatal")))
        for src, dst in (("recoveries", "health_recoveries"),
                         ("io_retries", "health_io_retries")):
            v = _num(health.get(src))
            if v is not None:
                metrics[dst] = v
        counters = health.get("counters") or {}
        flagged = sum(v for v in counters.values()
                      if isinstance(v, (int, float)))
        metrics["health_flagged"] = float(flagged)
        if info["run_id"] is None:
            info["run_id"] = s.get("run_id")

    if tune_events:
        # Autotune audit (rev v2.5): how many knobs the resolver touched,
        # and how many of its MEASURED predictions (db/probe rows carry a
        # wall/iter; static predictions are too coarse to gate on) the
        # run's actual wall/iter blew through by >20%.
        metrics["tune.decisions"] = float(len(tune_events))
        wall = metrics.get("wall_s")
        iters = metrics.get("total_iters")
        measured = (wall / iters if wall and iters else None)
        regressions = 0
        for t in tune_events:
            pred = _num(t.get("predicted_s"))
            if pred is None or pred <= 0 \
                    or t.get("source") not in ("db", "probe"):
                continue
            if measured is not None \
                    and measured > TUNE_REGRESSION_TOLERANCE * pred:
                regressions += 1
        metrics["tune.regressions"] = float(regressions)

    info["metrics"] = metrics
    return info


def _fold_profile(prof: dict, metrics: Dict[str, float]) -> None:
    """run_summary/serve_summary ``profile`` -> flat compile metrics."""
    for src in ("compiles", "compile_seconds", "xla_compiles",
                "xla_compile_seconds", "hbm_peak_bytes"):
        v = _num(prof.get(src))
        if v is not None:
            metrics[src] = v
    for name, slot in (prof.get("sites") or {}).items():
        for field in ("compiles", "seconds"):
            v = _num((slot or {}).get(field))
            if v is not None:
                metrics[f"compile.{name}.{field}"] = v
    cost = prof.get("cost") or {}
    for field in ("flops", "bytes_accessed"):
        v = _num(cost.get(field))
        if v is not None:
            metrics[f"cost.{field}"] = v


def summarize_bench(record: dict) -> dict:
    """One ``bench.py`` JSON record -> the same rollup shape."""
    metrics: Dict[str, float] = {}
    _flatten(record, "", metrics)
    return {"kind": "bench",
            "run_id": record.get("run_id"),
            "fingerprint": None,
            "backend": record.get("backend") or record.get("platform"),
            "platform": record.get("platform"),
            "metrics": metrics}


def stream_files(path: str) -> List[str]:
    """The stream files behind one run target: the file itself, or every
    ``*.jsonl`` in a directory of per-rank streams. Shared by ``gmm
    diff``, ``gmm runs``, and ``gmm timeline`` (telemetry/timeline.py),
    which all accept the same target grammar."""
    if os.path.isdir(path):
        return sorted(os.path.join(path, f) for f in os.listdir(path)
                      if f.endswith(".jsonl"))
    return [path]


_stream_files = stream_files  # historical private name (pre-v2.3 callers)


def load_target(path: str) -> dict:
    """One diff target -> rollup. A directory merges its per-rank
    ``*.jsonl`` streams; a file is a JSONL stream when its records carry
    ``event``, otherwise the last JSON object wins (a captured bench
    line). Raises OSError/ValueError on unreadable input."""
    files = _stream_files(path)
    if not files:
        raise ValueError(f"{path}: no *.jsonl streams in directory")
    records: List[dict] = []
    for f in files:
        records.extend(r for r in read_stream(f) if isinstance(r, dict))
    if not records:
        raise ValueError(f"{path}: no records")
    if any("event" in r for r in records):
        return summarize_run(records)
    return summarize_bench(records[-1])


# -- fail-on specs -------------------------------------------------------

class FailSpec:
    """One ``metric>threshold[%]`` (or ``metric<...``: lower-is-worse,
    e.g. throughput) regression gate."""

    def __init__(self, raw: str):
        self.raw = raw.strip()
        op_idx = max(self.raw.find(">"), self.raw.find("<"))
        if op_idx <= 0 or op_idx == len(self.raw) - 1:
            raise ValueError(
                f"bad --fail-on spec {raw!r} (want 'metric>threshold' "
                f"or 'metric>threshold%')")
        self.metric = self.raw[:op_idx].strip()
        self.op = self.raw[op_idx]
        thr = self.raw[op_idx + 1:].strip()
        self.relative = thr.endswith("%")
        try:
            self.threshold = float(thr[:-1] if self.relative else thr)
        except ValueError:
            raise ValueError(f"bad --fail-on threshold in {raw!r}")

    def check(self, a: Optional[float],
              b: Optional[float]) -> Optional[str]:
        """A regression message, or None (clean / not comparable)."""
        if a is None or b is None:
            return None
        delta = (b - a) if self.op == ">" else (a - b)
        if self.relative:
            if a == 0:
                exceeded = delta > 0 and self.threshold >= 0
                pct = None
            else:
                pct = delta / abs(a) * 100.0
                exceeded = pct > self.threshold
            if not exceeded:
                return None
            how = (f"{pct:+.1f}%" if pct is not None
                   else "from zero")
            return (f"{self.metric}: {a:g} -> {b:g} ({how}, limit "
                    f"{self.op}{self.threshold:g}%)")
        if delta <= self.threshold:
            return None
        return (f"{self.metric}: {a:g} -> {b:g} ({delta:+g}, limit "
                f"{self.op}{self.threshold:g})")


def diff_runs(a: dict, b: dict,
              specs: List[FailSpec]) -> Tuple[List[str], List[str]]:
    """(regressions, notes) of rollup ``b`` against baseline ``a``."""
    regressions: List[str] = []
    notes: List[str] = []
    am, bm = a.get("metrics") or {}, b.get("metrics") or {}
    if (a.get("fingerprint") and b.get("fingerprint")
            and a["fingerprint"] != b["fingerprint"]):
        notes.append(
            f"config fingerprints differ ({a['fingerprint']} vs "
            f"{b['fingerprint']}): comparing anyway")
    for spec in specs:
        msg = spec.check(am.get(spec.metric), bm.get(spec.metric))
        if msg is not None:
            regressions.append(msg)
    return regressions, notes


def _render_table(a: dict, b: dict, show_all: bool) -> List[str]:
    am, bm = a.get("metrics") or {}, b.get("metrics") or {}
    shared = sorted(set(am) & set(bm))
    lines = [f"  {'metric':<28} {'A':>14} {'B':>14} {'delta':>12}"]
    for name in shared:
        va, vb = am[name], bm[name]
        if not show_all and va == vb == 0:
            continue
        delta = vb - va
        pct = f" ({delta / abs(va) * 100.0:+.1f}%)" if va else ""
        lines.append(f"  {name:<28} {va:>14g} {vb:>14g} "
                     f"{delta:>+12g}{pct}")
    only_a = sorted(set(am) - set(bm))
    only_b = sorted(set(bm) - set(am))
    if only_a:
        lines.append(f"  (only in A: {', '.join(only_a[:8])}"
                     f"{' ...' if len(only_a) > 8 else ''})")
    if only_b:
        lines.append(f"  (only in B: {', '.join(only_b[:8])}"
                     f"{' ...' if len(only_b) > 8 else ''})")
    return lines


def diff_main(argv=None) -> int:
    """``gmm diff A B``: exit 0 clean / 1 named regressions / 2 usage."""
    parser = argparse.ArgumentParser(
        prog="gmm diff",
        description="Compare two runs (JSONL streams, per-rank stream "
                    "directories, or bench JSON records) and gate on "
                    "regressions.")
    parser.add_argument("a", help="baseline run (stream/dir/bench JSON)")
    parser.add_argument("b", help="candidate run to judge against A")
    parser.add_argument("--fail-on", action="append", default=[],
                        metavar="SPEC",
                        help="regression gate, e.g. 'wall_s>10%%' "
                             "(relative) or 'serve.p99_ms>5' (absolute); "
                             "'<' flips direction for lower-is-worse "
                             "metrics like iters_per_s. Repeatable; adds "
                             "to the default count gates.")
    parser.add_argument("--no-default-gates", action="store_true",
                        help="drop the built-in compile/health/serve "
                             "count gates; only --fail-on specs apply")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable result on stdout")
    parser.add_argument("--all", action="store_true",
                        help="show all shared metrics, including 0 -> 0")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0
    try:
        specs = [] if args.no_default_gates else \
            [FailSpec(s) for s in DEFAULT_FAIL_ON]
        specs.extend(FailSpec(s) for s in args.fail_on)
    except ValueError as e:
        print(f"gmm diff: {e}")
        return 2
    try:
        a = load_target(args.a)
        b = load_target(args.b)
    except (OSError, ValueError) as e:
        print(f"gmm diff: {e}")
        return 2
    regressions, notes = diff_runs(a, b, specs)
    if args.json:
        print(json.dumps({
            "a": a, "b": b,
            "fail_on": [s.raw for s in specs],
            "regressions": regressions, "notes": notes,
            "clean": not regressions,
        }, sort_keys=True))
        return 1 if regressions else 0
    print(f"gmm diff: A={args.a} (run {a.get('run_id') or '?'})  "
          f"B={args.b} (run {b.get('run_id') or '?'})")
    for note in notes:
        print(f"note: {note}")
    for line in _render_table(a, b, args.all):
        print(line)
    if regressions:
        for msg in regressions:
            print(f"REGRESSION {msg}")
        print(f"{len(regressions)} regression(s)")
        return 1
    shared = len(set(a.get("metrics") or {}) & set(b.get("metrics") or {}))
    print(f"clean: no regressions ({shared} shared metrics, "
          f"{len(specs)} gates)")
    return 0


# -- gmm runs ------------------------------------------------------------

def _health_word(metrics: Dict[str, float]) -> str:
    if metrics.get("health_fatal"):
        return "FATAL"
    flagged = metrics.get("health_flagged") or 0
    recov = metrics.get("health_recoveries") or 0
    if flagged or recov:
        return f"{int(flagged)} flagged/{int(recov)} recovered"
    return "ok"


def runs_main(argv=None) -> int:
    """``gmm runs DIR``: index historical runs so diff targets are
    discoverable. Exit 0 (even when empty) / 2 unreadable directory."""
    parser = argparse.ArgumentParser(
        prog="gmm runs",
        description="List historical runs (one row per *.jsonl stream "
                    "in DIR): run id, config fingerprint, backend, "
                    "wall, iters/s, health.")
    parser.add_argument("dir", help="directory of *.jsonl run streams")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable rows on stdout")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0
    if not os.path.isdir(args.dir):
        print(f"gmm runs: {args.dir}: not a directory")
        return 2
    rows = []
    for f in _stream_files(args.dir):
        try:
            rollup = summarize_run(
                [r for r in read_stream(f) if isinstance(r, dict)])
        except (OSError, ValueError):
            continue  # non-stream jsonl in the same directory
        m = rollup.get("metrics") or {}
        rows.append({
            "file": os.path.basename(f),
            "run_id": rollup.get("run_id"),
            "fingerprint": rollup.get("fingerprint"),
            "backend": rollup.get("backend"),
            "wall_s": m.get("wall_s"),
            "iters_per_s": m.get("iters_per_s"),
            "health": _health_word(m),
        })
    if args.json:
        print(json.dumps({"dir": args.dir, "runs": rows},
                         sort_keys=True))
        return 0
    if not rows:
        print(f"gmm runs: no run streams in {args.dir}")
        return 0
    print(f"  {'run_id':<14} {'config':<12} {'backend':<10} "
          f"{'wall_s':>10} {'iters/s':>10}  {'health':<24} file")
    for r in rows:
        wall = f"{r['wall_s']:.3f}" if r["wall_s"] is not None else "-"
        ips = (f"{r['iters_per_s']:.1f}"
               if r["iters_per_s"] is not None else "-")
        print(f"  {str(r['run_id'] or '?'):<14} "
              f"{str(r['fingerprint'] or '?'):<12} "
              f"{str(r['backend'] or '?'):<10} {wall:>10} {ips:>10}  "
              f"{r['health']:<24} {r['file']}")
    return 0
