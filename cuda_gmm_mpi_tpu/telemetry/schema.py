"""Event schema for the run-scoped telemetry stream (docs/OBSERVABILITY.md).

The reference's observability surface is printf: per-phase cudaEvent totals
(``gaussian.cu:967``) and ad-hoc status prints scattered through ``main``.
This module is the contract that replaces it -- every record a
:class:`~cuda_gmm_mpi_tpu.telemetry.RunRecorder` emits is one JSON object
per line, stamped with a schema version, and validates against the field
tables below. ``bench.py``, ``gmm report``, and the regression tests all
consume the stream through this contract, never by scraping stdout.

Versioning: ``SCHEMA_VERSION`` bumps only on breaking changes (a removed or
retyped required field). Adding optional fields is always allowed -- readers
must ignore unknown fields.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Tuple

SCHEMA_VERSION = 1

# Stamped on every record by the recorder. Rev v2.1 additions to the
# envelope are OPTIONAL (not listed here -- old fixtures must keep
# validating): ``mono_s``, the process-monotonic emission time
# (time.perf_counter()), which report/--follow prefer over wall-clock
# ``ts`` deltas for durations (``ts`` can jump under NTP slew -- the
# clock-skew bug class the PR-11 watchdog fix addressed); and
# ``trace_id``, the fit/request-scoped trace identity joining a record
# to its span tree (telemetry/spans.py).
#
# Rev v2.3 optional envelope additions: ``clock``, an atomically-sampled
# {"wall", "mono"} pair carried by the stream head (run_start / a serve
# stream's first record) and every heartbeat -- the cross-stream
# alignment anchor ``gmm timeline`` uses to merge multi-rank and
# fit+serve streams onto one timebase; the head also carries ``clock0``,
# the recorder-construction pair, so a heartbeat-free stream still holds
# two anchors for drift estimation. ``validate_record`` checks the
# pair's shape wherever it appears.
COMMON_FIELDS = ("event", "schema", "ts", "run_id", "process")

# The v2.3 clock-pair shape shared by ``clock`` and ``clock0``.
CLOCK_FIELDS = ("wall", "mono")

# event -> ((required fields), (optional well-known fields)). Optional
# fields are documented for readers; unknown extras are always legal.
EVENT_FIELDS: Dict[str, Tuple[Tuple[str, ...], Tuple[str, ...]]] = {
    # One per fit (per init when n_init > 1): the run's identity card.
    # ``em_backend`` (stream rev v1.5) names the E-step/statistics backend
    # that ACTUALLY ran -- pallas / pallas-interpret / jnp / custom -- and
    # ``em_backend_reason`` why (resolve_estep_backend): a silent jnp
    # fallback away from a requested kernel is observable in the stream,
    # not indistinguishable from the kernel path.
    "run_start": (
        ("platform", "num_events", "num_dimensions", "start_k", "epsilon"),
        ("target_k", "process_count", "device_count", "local_device_count",
         "mesh", "path", "dtype", "chunk_size", "covariance_type",
         "criterion", "fused_sweep", "stream_events", "n_init", "init",
         "restart_batch_size", "memory_stats", "em_backend",
         "em_backend_reason"),
    ),
    # One per EM iteration of each K (host-driven sweeps; the fused
    # whole-sweep device program emits per-K records only).
    "em_iter": (
        ("k", "iter", "loglik", "delta", "epsilon", "wall_s", "timing"),
        (),
    ),
    # One per completed K of the model-order sweep.
    "em_done": (
        ("k", "loglik", "score", "criterion", "iters", "seconds"),
        (),
    ),
    # One per closest-pair merge between Ks. ``pair`` (optional) is the
    # merged clusters' positions in the compacted (post-elimination)
    # ordering -- stable across bucket recompaction, unlike padded-slot
    # indices (ops/merge.eliminate_and_reduce).
    "merge": (
        ("k_active", "next_k", "min_distance"),
        ("pair",),
    ),
    # One per bucket recompaction of the host-driven sweep (sweep_k_buckets):
    # the state was rebuilt from padded width ``from_width`` down to
    # ``to_width`` with ``k_active`` clusters live.
    "rebucket": (
        ("k_active", "from_width", "to_width"),
        (),
    ),
    # Streaming (out-of-core) path: one per host->device block flush.
    # ``prefetch_wait_s``/``compute_s`` (rev v1.9) split the block's host
    # wall: time blocked on ingestion (0.0 when the chunks are host-
    # resident) vs. time in the statistics dispatch -- the pipelined-
    # ingestion overlap win is directly observable per block.
    "chunk_flush": (
        ("iter", "block", "chunks", "bytes"),
        ("k", "prefetch_wait_s", "compute_s"),
    ),
    # Pipelined ingestion lifecycle (rev v1.9; io/pipeline.py): one
    # ingest_start per fit with a lazy block source -- the rank's file
    # source, row range, and bounded-queue depth.
    "ingest_start": (
        ("source", "rows", "queue_depth"),
        ("row_start", "row_stop", "blocks", "chunk_size", "mode"),
    ),
    # ...and one ingest_summary when the source closes: blocks served,
    # peak resident block count (the O(queue_depth x block) memory claim,
    # measured), cumulative prefetch wait, and bytes range-read.
    "ingest_summary": (
        ("blocks_read", "peak_resident_blocks"),
        ("prefetch_wait_s", "bytes", "queue_depth"),
    ),
    # Rate-limited liveness marker for long phases. The resource sampler
    # (rev v2.1; telemetry/exporter.py, --metrics-port) stamps periodic
    # heartbeats with ``rss_bytes`` (host VmRSS) and ``memory_stats``
    # (first local device's memory_stats(): HBM in-use / peak) so memory
    # high-water is observable DURING the run, not only at run_start.
    "heartbeat": (
        ("phase", "elapsed_s"),
        ("k", "rss_bytes", "memory_stats", "sampler"),
    ),
    # One per nonzero health word observed (health.py): ``flags`` is the
    # packed bitmask, ``flag_names`` its decoded lanes, ``counters`` the
    # per-lane counts, ``where`` the observation point (em / score /
    # fused_sweep).
    "health": (
        ("flags", "flag_names"),
        ("k", "counters", "where"),
    ),
    # One per recovery action: an escalation-ladder attempt after a fatal
    # health word (action = regularize / centered / highest), the fused
    # sweep's host_fallback, or a reseed_empty pass. ``outcome`` is
    # recovered / fatal / retry / rerun.
    "recovery": (
        ("k", "attempt", "action", "outcome"),
        ("flags", "flag_names"),
    ),
    # One per retried (or abandoned: gave_up=true) checkpoint write
    # (utils/checkpoint.py bounded backoff).
    "io_retry": (
        ("op", "attempt", "error"),
        ("step", "delay_s", "gave_up"),
    ),
    # Preemption lifecycle (supervisor.py; docs/ROBUSTNESS.md "Run
    # lifecycle"): the cooperative stop flag was first observed at a poll
    # point. ``reason`` is sigterm / sigint / deadline / peer_lost /
    # preempt_injected; ``where`` the poll site (sweep / em /
    # stream_block / fused_emit / serve).
    "preempt": (
        ("reason",),
        ("where", "k", "em_iter", "peer"),
    ),
    # The stop's endgame, just before the process exits 75:
    # ``checkpointed`` says whether the emergency intra-K sub-step (or,
    # between Ks, the previous full step) is durable for --resume auto.
    "shutdown": (
        ("reason", "checkpointed"),
        ("step", "k", "em_iter"),
    ),
    # The liveness watchdog flagged a dead/wedged peer rank: its
    # heartbeat on the shared checkpoint filesystem aged past the
    # timeout. Followed by a peer_lost-reason preempt/shutdown pair.
    "peer_lost": (
        ("rank", "timeout_s"),
        ("age_s",),
    ),
    # Elastic recovery (stream rev v2.0; parallel/elastic.py,
    # docs/DISTRIBUTED.md "Elastic recovery"): the survivors sealed a
    # shrunken membership generation after a peer loss -- ``survivors``
    # are the surviving ORIGINAL rank ids, ``world_size`` the new world.
    # Emitted once per shrink by every surviving rank.
    "elastic_shrink": (
        ("generation", "survivors", "world_size"),
        ("lost_ranks", "attempt", "min_hosts"),
    ),
    # The shrunken world resumed the sweep from the newest checkpoint
    # (rev v2.0): pairs with the preceding elastic_shrink; ``attempt``
    # counts recovery rounds within one run.
    "elastic_resume": (
        ("generation", "attempt"),
        ("step", "k", "world_size"),
    ),
    # One per n_init > 1 fit (stream rev v1.4): which restart won and
    # every init's best criterion score (NaN/Inf scores are null).
    # ``mode`` is batched / sequential; ``batch_size`` the restart batch
    # the winner ran in (1 = the sequential driver); ``dropped`` lists
    # init indices removed by the drop-one-keep-survivors fault path
    # (models/restarts.py).
    "restart_select": (
        ("winner", "scores", "criterion"),
        ("mode", "batch_size", "dropped"),
    ),
    # Serving loop (stream rev v1.6; serving/server.py, docs/SERVING.md):
    # one per answered request. ``n`` is the request's row count,
    # ``latency_ms`` arrival-to-reply; failed requests carry ok=false +
    # ``error``.
    "serve_request": (
        ("model", "op", "n", "latency_ms"),
        # ``trace_id`` (rev v2.1): present under ``--metrics-port``; the
        # same id is echoed in the client's response for joining.
        ("version", "ok", "error", "trace_id"),
    ),
    # One per coalesced micro-batch dispatch: how many concurrent
    # requests' rows rode one padded executor call, the pow2-bucketed
    # row count actually dispatched, and whether the dispatch had to
    # AOT-compile (``compiled`` > 0 = a cold bucket; a warmed server
    # emits zeros -- the zero-recompile proof is observable per batch).
    # ``stacked`` (optional, rev v1.8) marks a cross-model stacked
    # dispatch: how many DIFFERENT models' groups rode one executable
    # call (serving/server.py --stack-models; bit-identical to
    # per-model dispatch).
    "serve_batch": (
        ("model", "requests", "rows", "padded_rows", "wall_ms"),
        ("version", "compiled", "stacked"),
    ),
    # One per adaptive micro-batching window adaptation (stream rev
    # v2.8; serving/server.py --tick-min-ms/--tick-max-ms,
    # docs/SERVING.md "Adaptive micro-batching"): the controller moved
    # the gather window or flipped auto-stacking. ``window_ms`` is the
    # NEW window; ``reason`` is ``backlog`` (queue still deep after a
    # gather -> snap to the floor), ``idle`` (a near-empty window ->
    # widen toward the ceiling), or ``auto_stack_on``/``auto_stack_off``
    # (the stackable-window streak crossed the hysteresis thresholds).
    # Present only when the adaptive bounds are set, so fixed --tick-ms
    # streams stay byte-identical.
    "serve_window": (
        ("window_ms", "reason"),
        ("prev_window_ms", "queue_rows", "arrival_per_s", "requests",
         "stacked_auto", "streak"),
    ),
    # One per shed request (stream rev v1.7; serving resilience,
    # docs/ROBUSTNESS.md "Serving"): admission control rejected the
    # request before it entered the batching queue. ``reason`` is
    # ``overloaded`` (bounded queue full) or ``shutting_down`` (arrival
    # after the drain began).
    "serve_shed": (
        ("reason",),
        ("model", "rows", "queued_rows", "max_queue_rows"),
    ),
    # One per deadline-expired request (rev v1.7): its budget
    # (``deadline_ms``, per-request or --default-deadline-ms) ran out
    # while queued, so it was rejected BEFORE dispatch -- the executor
    # never ran for it. ``waited_ms`` is how long it actually sat.
    "serve_deadline": (
        ("deadline_ms", "waited_ms"),
        ("model", "op", "n"),
    ),
    # One per hot-reloaded default route (rev v1.7): the registry grew a
    # new version and the server atomically swapped the version=None
    # route from ``from_version`` to ``to_version`` between ticks
    # (in-flight ticks finished on the old version; pinned-version
    # routes are untouched).
    "serve_reload": (
        ("model", "from_version", "to_version"),
        ("fingerprint",),
    ),
    # One per circuit-breaker state transition (rev v1.7;
    # serving/breaker.py): ``state`` is open / half_open / closed;
    # ``reason`` what tripped it (non_finite / registry / executor);
    # ``backoff_s`` the open window on an open transition; ``trips``
    # the route's consecutive-open count.
    "circuit": (
        ("model", "state"),
        ("version", "failures", "trips", "reason", "backoff_s"),
    ),
    # One per serve session, at shutdown (run_summary's serving
    # sibling): volume, QPS, latency percentiles, aggregated executor
    # cache counters, and the metrics-registry snapshot. Rev v1.7 adds
    # the resilience counters: ``shed``, ``deadline_expired``,
    # ``reloads``, and the ``breaker`` {trips, closes, open_routes}
    # rollup.
    # ``profile`` (optional, rev v2.2): the CompileWatch rollup for the
    # serve session -- same shape as run_summary.profile.
    "serve_summary": (
        ("requests", "batches", "rows", "wall_s", "qps", "latency_ms",
         "metrics"),
        # ``drift`` (optional, rev v2.4): the drift-plane rollup --
        # {windows, alarms, last {model-> last stats}}; present only
        # when --drift-interval-s was set, so drift-off streams stay
        # byte-identical.
        # ``http`` (optional, rev v2.7): the HTTP front-end rollup --
        # {requests, errors_4xx, errors_5xx, shed_connections, retries,
        # retries_exhausted, worker_crashes, worker_respawns,
        # worker_quarantines, workers}; present only under ``--http``,
        # so HTTP-off streams stay byte-identical. ``gmm diff`` folds it
        # into the ``http.errors_5xx`` / ``http.worker_crashes`` /
        # ``http.retries_exhausted`` default gates.
        # ``window`` (optional, rev v2.8): the adaptive micro-batching
        # rollup -- {adaptations, window_ms, min_ms, max_ms,
        # auto_stack}; present only under --tick-min-ms/--tick-max-ms.
        # ``stacked_fallthrough`` (optional, rev v2.8): rows-groups that
        # arrived in a stacked window but failed ``stackable_rows`` and
        # dispatched solo -- reconciles serve_batch counts against
        # ``stacked_batches``.
        ("models", "executor", "errors", "shed", "deadline_expired",
         "reloads", "breaker", "stacked_batches", "stacked_fallthrough",
         "profile", "drift", "http", "window"),
    ),
    # One per answered HTTP request (stream rev v2.7; serving/http.py,
    # docs/SERVING.md "HTTP front end"): ``status`` is the HTTP status
    # actually sent, ``latency_ms`` receive-to-reply on the handler
    # thread. ``worker`` (pool mode) names the worker slot that scored
    # it; ``retried`` marks answers that needed the sibling retry after
    # a worker crash. ``trace_id`` is the echoed X-GMM-Trace-Id, joining
    # the HTTP edge to the server-side serve_request/span records.
    "http_request": (
        ("method", "path", "status", "latency_ms"),
        ("model", "op", "n", "error", "worker", "retried", "trace_id"),
    ),
    # One per worker-pool process start (rev v2.7; serving/pool.py):
    # first launch and every respawn. ``respawn`` marks restarts after a
    # crash; ``attempt`` the consecutive-crash count driving the
    # jittered doubling ``backoff_s``.
    "worker_spawn": (
        ("worker", "pid"),
        ("socket", "attempt", "backoff_s", "respawn"),
    ),
    # One per worker-pool process exit (rev v2.7): ``crash`` is true for
    # unexpected deaths (anything outside a requested drain), and
    # ``quarantined`` marks the crash that tripped the crash-loop
    # quarantine (reason file written; the slot stops respawning while
    # siblings keep serving).
    "worker_exit": (
        ("worker", "exitcode"),
        ("pid", "reason", "crash", "quarantined"),
    ),
    # One per elapsed drift window per served (model, version) route
    # (stream rev v2.4; serving/server.py --drift-interval-s,
    # docs/OBSERVABILITY.md "Drift detection"): the window's request-
    # score sketch and assignment occupancy compared against the
    # model's TRAINING envelope (registry envelope.json). ``psi`` /
    # ``ks`` are over the shared score-bucket ladder, ``occupancy_l1``
    # over normalized per-cluster assignment mass, ``window_rows`` the
    # rows observed in the window. ``score_sketch`` / ``occupancy``
    # (optional) carry the window's raw mergeable summary so ``gmm
    # drift`` can re-aggregate a recorded stream offline at any window
    # granularity. ``alarm`` marks windows whose PSI crossed
    # --drift-psi-threshold (the paired drift_alarm record follows).
    "drift": (
        ("model", "psi", "ks", "occupancy_l1", "window_rows"),
        ("version", "alarm", "threshold", "score_sketch", "occupancy",
         "mean_score", "train_rows"),
    ),
    # The drift alarm (rev v2.4): PSI crossed the configured threshold
    # for a route's window. Rides the health-event conventions (named
    # flags, counted in the metrics registry, rendered as instants by
    # ``gmm timeline``) but is OBSERVATIONAL ONLY -- it is not a
    # health.py fault lane and never trips the serving circuit breaker.
    "drift_alarm": (
        ("model", "psi", "threshold"),
        ("version", "ks", "occupancy_l1", "window_rows", "flag_names"),
    ),
    # Lifecycle transition (stream rev v2.6; lifecycle/controller.py,
    # docs/ROBUSTNESS.md "Model lifecycle"): one per state-machine edge
    # of the closed serve->drift->retrain->promote loop. ``phase`` is
    # retrain / canary / promote / watch / rollback / quarantine /
    # cooldown; ``outcome`` the edge taken (e.g. retrain: published /
    # retry / exhausted; canary: pass / rejected; promote: promoted /
    # torn; watch: passed / violated). Gate values ride the record:
    # ``psi`` / ``ks`` over the shared score-bucket ladder,
    # ``mean_incumbent`` / ``mean_candidate`` / ``regression`` /
    # ``tolerance`` for the health_regression_scale x epsilon score
    # gate, ``shadow_rows``/``shadow_ticks`` for the duplicate-dispatch
    # window. ``candidate_version`` names the canary under evaluation;
    # ``from_version``/``to_version`` the route flip on promote and
    # rollback; ``reason`` what tripped a rollback / quarantine
    # (breaker_trip / drift_alarm / score_regression / canary gates /
    # retrain_exhausted). Counted in the metrics registry
    # (``lifecycle_<phase>s``) and folded by ``gmm diff`` into the
    # ``lifecycle.rollbacks`` / ``lifecycle.quarantines`` default gates.
    "lifecycle": (
        ("model", "phase"),
        ("outcome", "version", "candidate_version", "from_version",
         "to_version", "attempt", "reason", "psi", "ks",
         "mean_incumbent", "mean_candidate", "regression", "tolerance",
         "shadow_rows", "shadow_ticks", "alarms", "cooldown_s",
         "retry_in_s", "flag_names"),
    ),
    # Registry walk-back (rev v2.6; serving/registry.py ``load``): the
    # newest version of ``model`` was unreadable and resolution fell
    # back to an earlier one. Previously a warning only -- but a silent
    # walk-back is exactly what a botched promotion looks like, so it
    # is now a counted event (``gmm_registry_torn_total``) rendered by
    # ``gmm report`` and ``gmm timeline``. Observational: the fallback
    # still happens, serving is not interrupted.
    "registry_torn": (
        ("model", "version"),
        ("error",),
    ),
    # Autotune decision (stream rev v2.5; tuning/, docs/PERF.md
    # "Autotuning"): one per knob the profile-guided resolver touched.
    # ``chosen`` is the value the run actually used, ``source`` the
    # fallback-ladder rung that supplied it ('db' = recorded profile,
    # 'probe' = measured this run by the microprobe, 'static' = cost
    # model), ``candidates`` a {candidate: wall_per_iter_s|null} map of
    # what was considered, ``predicted_s`` the chosen candidate's
    # recorded/modelled wall per EM iteration (the ``gmm diff``
    # ``tune.regressions`` gate compares the run's measured wall/iter
    # against it), ``key`` the tuning-DB shape key that resolved,
    # ``surface`` = fit|fleet|serve, ``default`` the pre-resolution
    # value. Only ``autotune != 'off'`` runs emit these -- the default
    # stream stays byte-identical.
    "tune": (
        ("knob", "chosen", "source"),
        ("candidates", "predicted_s", "key", "surface", "default",
         "distance"),
    ),
    # Fleet fits (stream rev v1.8; tenancy/, docs/TENANCY.md): one per
    # `fit_fleet` invocation -- the fleet's identity card: tenant count,
    # packed-group count, and the dispatch mode ('scan' = bit-exact
    # lane mapping, 'vmap' = batched-matmul throughput).
    # ``group_shapes`` lists each group's {tenants, n_bucket, k_bucket}.
    "fleet_start": (
        ("tenants", "groups", "mode"),
        ("platform", "num_dimensions", "dtype", "covariance_type",
         "criterion", "chunk_size", "group_shapes"),
    ),
    # One per tenant as its group completes (rev v1.8): the tenant's
    # solo-fit summary scalars, or ``dropped: true`` + ``error`` when
    # the drop-one containment removed it from its group.
    "tenant_done": (
        ("tenant", "dropped"),
        ("k", "score", "loglik", "iters", "group", "num_events",
         "criterion", "error"),
    ),
    # One per fleet fit, at the end (rev v1.8): totals + the metrics-
    # registry snapshot (run_summary's fleet sibling).
    "fleet_summary": (
        ("tenants", "dropped", "groups", "wall_s"),
        ("mode", "metrics"),
    ),
    # One per XLA compilation observed while a CompileWatch is active
    # (stream rev v2.2; telemetry/profiling.py). ``source`` is ``aot``
    # -- an instrumented executable-cache build (models/gmm.py EM
    # executables, serving/executor.py AOT scoring programs): the
    # lower+compile timed at the call site and enriched with
    # ``compiled.cost_analysis()`` (``flops``, ``bytes_accessed``) and
    # ``memory_analysis()`` (argument/output/temp/generated-code bytes)
    # where the backend provides them -- or ``xla``: a bare
    # jax.monitoring backend-compile observation OUTSIDE any
    # instrumented site, i.e. a (re)compile the caches did not expect.
    # ``site`` names the emitting cache (em / em_batched / em_fleet /
    # serve / serve_stacked), ``phase`` the active span/phase tag,
    # ``key`` the cache's own key string.
    "compile": (
        ("source", "seconds"),
        ("site", "phase", "key", "flops", "bytes_accessed",
         "argument_bytes", "output_bytes", "temp_bytes",
         "generated_code_bytes"),
    ),
    # Trace span (rev v2.1; telemetry/spans.py): one per completed phase
    # of a traced fit or serve request -- name, this span's id, its
    # parent's id (absent on the root), and the measured duration.
    # ``trace_id`` usually arrives via the recorder context (one trace
    # per fit) but serve spans carry it per-record (one trace per
    # request). ``t0_mono_s`` is the span's START on the process
    # monotonic clock (the envelope's ``mono_s`` is the emission time =
    # span END), so a reader can order siblings and compute self-time.
    # ``thread`` (rev v2.3) is the emitting OS thread id: serve routes
    # span concurrent threads, and ``gmm timeline`` keys its per-rank
    # sub-tracks on it so overlapping spans from different threads
    # never collide on one rendered lane.
    "span": (
        ("name", "span_id", "duration_s"),
        ("parent_id", "trace_id", "t0_mono_s", "k", "status", "thread"),
    ),
    # One per fit: final scores, the 7-category phase profile, the
    # compile-vs-execute split, and the metrics-registry snapshot.
    # ``buckets`` (optional; host-driven sweeps) describes cluster-width
    # bucketing: {mode, em_widths, em_compiles, rebuckets} -- em_compiles
    # counts the DISTINCT padded widths EM compiled for.
    # ``health`` (optional): the numerical-containment summary --
    # {flags, flag_names, fatal, counters, recoveries, io_retries};
    # all-zero flags on a clean run (docs/ROBUSTNESS.md).
    # ``em_backend`` (optional, rev v1.5) mirrors run_start's.
    # ``elastic`` (optional, rev v2.0): present only when the run
    # survived at least one elastic shrink -- {generation, world_size,
    # shrinks, resumes}.
    # ``profile`` (optional, rev v2.2): the CompileWatch rollup --
    # {compiles, compile_seconds, xla_compiles, xla_compile_seconds,
    # sites, by_phase, cost {flops, bytes_accessed}, memory
    # {argument/output/temp/generated_code bytes}, watermarks,
    # hbm_peak_bytes}; present only when profiling was active
    # (telemetry/profiling.py), so pre-v2.2 readers and byte-identity
    # fixtures are untouched.
    # ``envelope`` (optional, rev v2.4): the training drift envelope --
    # the fit data's per-event score sketch + responsibility occupancy
    # (telemetry/sketch.py make_envelope), the reference distribution
    # serve-time drift is measured against; absent when envelope
    # computation is disabled (config.envelope=False) or the data
    # source was lazy/pipelined.
    "run_summary": (
        ("ideal_k", "score", "criterion", "final_loglik", "total_iters",
         "wall_s", "phase_profile", "compile", "metrics"),
        ("per_process", "memory_stats", "buckets", "health", "em_backend",
         "elastic", "profile", "envelope"),
    ),
}


def validate_record(rec: Any) -> List[str]:
    """Schema errors for one decoded record ([] = valid).

    Checks the common envelope (version, event type) and the per-event
    required fields; unknown extra fields are legal by design.
    """
    errors: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    for f in COMMON_FIELDS:
        if f not in rec:
            errors.append(f"missing common field {f!r}")
    if rec.get("schema") not in (None, SCHEMA_VERSION):
        errors.append(
            f"schema version {rec.get('schema')!r} != {SCHEMA_VERSION}")
    for pair_field in ("clock", "clock0"):
        pair = rec.get(pair_field)
        if pair is None:
            continue
        if not isinstance(pair, dict):
            errors.append(f"{pair_field} is {type(pair).__name__}, "
                          f"not an object")
            continue
        for f in CLOCK_FIELDS:
            if not isinstance(pair.get(f), (int, float)) \
                    or isinstance(pair.get(f), bool):
                errors.append(
                    f"{pair_field}.{f} must be a number, "
                    f"got {pair.get(f)!r}")
    event = rec.get("event")
    spec = EVENT_FIELDS.get(event) if isinstance(event, str) else None
    if spec is None:
        errors.append(f"unknown event type {event!r}")
        return errors
    required, _ = spec
    for f in required:
        if f not in rec:
            errors.append(f"{event}: missing required field {f!r}")
    return errors


def validate_stream(records: Iterable[Any]) -> List[str]:
    """Flattened schema errors over a decoded stream, prefixed by index."""
    errors = []
    for i, rec in enumerate(records):
        errors.extend(f"record {i}: {e}" for e in validate_record(rec))
    return errors
