"""Offline rendering of a telemetry stream: the ``gmm report`` backend.

Turns a ``--metrics-file`` JSONL stream back into the reference's
human-readable surfaces -- the 7-category phase-profile table
(``gaussian.cu:967``'s layout, shared with ``PhaseTimer.report`` so the
live ``--profile`` print and the offline report are byte-compatible), the
per-K selection sweep summary, and the per-iteration loglik trajectory --
from the stream alone: no pickle, no state files, no devices.

``gmm report --follow`` (alias ``gmm top``; rev v2.1) is the live
counterpart: an incremental tailer over the same stream -- a single
JSONL file, or a directory of per-rank ``*.jsonl`` streams -- that
re-renders a one-screen view as records arrive. It leans on the
recorder's line-buffered flush-per-record sink: a reader only ever sees
whole lines, so the tailer never has to re-parse a torn record. Where
``mono_s`` (rev v2.1 envelope) is present, rates and ages are computed
from monotonic deltas rather than wall-clock ``ts``.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional

from .schema import validate_stream


def render_phase_table(seconds: Dict[str, float],
                       counts: Optional[Dict[str, int]] = None) -> str:
    """Total + per-call average per category (gaussian.cu:967's layout).

    The single formatter behind both the live ``PhaseTimer.report`` and
    the offline ``gmm report`` phase table.
    """
    counts = counts or {}
    lines = ["Phase profile (seconds total / calls / avg):"]
    for name, total in seconds.items():
        n = max(counts.get(name, 0), 1)
        lines.append(f"  {name:<10s}\t{total:9.4f}\t{counts.get(name, 0):6d}"
                     f"\t{total / n:9.6f}")
    return "\n".join(lines)


def _fmt_run_start(rec: dict) -> str:
    bits = [f"run {rec.get('run_id', '?')}",
            f"platform={rec.get('platform', '?')}",
            f"N={rec.get('num_events', '?')}",
            f"D={rec.get('num_dimensions', '?')}",
            f"start_k={rec.get('start_k', '?')}"]
    if rec.get("target_k"):
        bits.append(f"target_k={rec['target_k']}")
    if rec.get("path"):
        bits.append(f"path={rec['path']}")
    if rec.get("em_backend"):
        # rev v1.5: which E-step backend actually ran; a fallback away
        # from a requested kernel carries its reason.
        b = f"backend={rec['em_backend']}"
        if rec.get("em_backend") == "jnp" and rec.get("em_backend_reason"):
            b += f" ({rec['em_backend_reason']})"
        bits.append(b)
    if rec.get("mesh"):
        bits.append(f"mesh={rec['mesh']}")
    if rec.get("process_count", 1) and rec.get("process_count", 1) > 1:
        bits.append(f"processes={rec['process_count']}")
    return "  ".join(str(b) for b in bits)


def _count_spans(node: dict) -> int:
    """Descendant count of one span-tree node (elision bookkeeping)."""
    return sum(1 + _count_spans(c) for c in node["children"])


def _render_span_profile(span_recs: List[dict], top_n: int = 10) -> List[str]:
    """"Span profile" section (rev v2.2): the top-N slowest spans by
    SELF time (total minus direct children), aggregated by span name --
    where the wall actually went, not just where the tree is deepest."""
    from .spans import build_span_tree

    agg: Dict[str, List[float]] = {}  # name -> [self_s, total_s, count]
    stack = list(build_span_tree(span_recs))
    while stack:
        node = stack.pop()
        s = node["span"]
        total = float(s.get("duration_s", 0) or 0)
        child_s = sum(float(c["span"].get("duration_s", 0) or 0)
                      for c in node["children"])
        slot = agg.setdefault(str(s.get("name", "?")), [0.0, 0.0, 0])
        slot[0] += max(total - child_s, 0.0)
        slot[1] += total
        slot[2] += 1
        stack.extend(node["children"])
    if not agg:
        return []
    rows = sorted(agg.items(), key=lambda kv: kv[1][0], reverse=True)
    out = [f"Span profile (top {min(top_n, len(rows))} by self time):",
           f"  {'span':<18s} {'self_s':>9s} {'total_s':>9s} {'count':>6s}"]
    for name, (self_s, total_s, count) in rows[:top_n]:
        out.append(f"  {name:<18s} {self_s:>9.3f} {total_s:>9.3f} "
                   f"{count:>6d}")
    if len(rows) > top_n:
        out.append(f"  ... {len(rows) - top_n} more span name(s)")
    out.append("")
    return out


def render_report(records: List[dict], max_trajectory_rows: int = 400) -> str:
    """The full ``gmm report`` text for one decoded stream."""
    out: List[str] = []
    starts = [r for r in records if r.get("event") == "run_start"]
    iters = [r for r in records if r.get("event") == "em_iter"]
    dones = [r for r in records if r.get("event") == "em_done"]
    merges = [r for r in records if r.get("event") == "merge"]
    chunks = [r for r in records if r.get("event") == "chunk_flush"]
    summaries = [r for r in records if r.get("event") == "run_summary"]

    serve_reqs = [r for r in records if r.get("event") == "serve_request"]
    serve_batches = [r for r in records
                     if r.get("event") == "serve_batch"]
    serve_summaries = [r for r in records
                       if r.get("event") == "serve_summary"]
    serve_sheds = [r for r in records if r.get("event") == "serve_shed"]
    serve_deadlines = [r for r in records
                       if r.get("event") == "serve_deadline"]
    serve_reloads = [r for r in records
                     if r.get("event") == "serve_reload"]
    serve_windows = [r for r in records
                     if r.get("event") == "serve_window"]
    circuits = [r for r in records if r.get("event") == "circuit"]
    http_reqs = [r for r in records if r.get("event") == "http_request"]
    worker_spawns = [r for r in records
                     if r.get("event") == "worker_spawn"]
    worker_exits = [r for r in records if r.get("event") == "worker_exit"]
    drift_windows = [r for r in records if r.get("event") == "drift"]
    drift_alarms = [r for r in records
                    if r.get("event") == "drift_alarm"]
    lifecycles = [r for r in records if r.get("event") == "lifecycle"]
    registry_torns = [r for r in records
                      if r.get("event") == "registry_torn"]

    fleet_starts = [r for r in records if r.get("event") == "fleet_start"]
    tenant_dones = [r for r in records if r.get("event") == "tenant_done"]
    fleet_summaries = [r for r in records
                       if r.get("event") == "fleet_summary"]

    rebuckets = [r for r in records if r.get("event") == "rebucket"]
    heartbeats = [r for r in records if r.get("event") == "heartbeat"]
    span_recs = [r for r in records if r.get("event") == "span"]
    compile_recs = [r for r in records if r.get("event") == "compile"]
    tune_recs = [r for r in records if r.get("event") == "tune"]

    selects = [r for r in records if r.get("event") == "restart_select"]
    healths = [r for r in records if r.get("event") == "health"]
    recoveries = [r for r in records if r.get("event") == "recovery"]
    io_retries = [r for r in records if r.get("event") == "io_retry"]
    preempts = [r for r in records if r.get("event") == "preempt"]
    shutdowns = [r for r in records if r.get("event") == "shutdown"]
    peer_losts = [r for r in records if r.get("event") == "peer_lost"]
    shrinks = [r for r in records if r.get("event") == "elastic_shrink"]
    resumes = [r for r in records if r.get("event") == "elastic_resume"]

    for s in starts:
        out.append(_fmt_run_start(s))
    if starts:
        out.append("")

    if tune_recs:
        # Autotune decisions (rev v2.5): what the profile-guided
        # resolver picked, from which fallback-ladder rung, against
        # which recorded/modelled wall.
        out.append(f"Autotune ({len(tune_recs)} decision(s)):")
        for r in tune_recs:
            line = (f"  {r.get('knob')}: {r.get('chosen')} "
                    f"[{r.get('source')}]")
            if r.get("default") not in (None, r.get("chosen")):
                line += f" (default {r.get('default')})"
            pred = r.get("predicted_s")
            if isinstance(pred, (int, float)):
                line += f", predicted {float(pred):.4f}s/iter"
            if r.get("surface") not in (None, "fit"):
                line += f" ({r.get('surface')})"
            out.append(line)
        out.append("")

    if dones:
        out.append("Model-order sweep (em_done):")
        out.append(f"  {'K':>5s}  {'loglik':>15s}  {'score':>15s}"
                   f"  {'iters':>6s}  {'seconds':>9s}")
        for r in dones:
            out.append(f"  {r['k']:>5d}  {r['loglik']:>15.6e}"
                       f"  {r['score']:>15.6e}  {r['iters']:>6d}"
                       f"  {r['seconds']:>9.3f}")
        if merges:
            out.append(f"  ({len(merges)} closest-pair merges)")
        if rebuckets:
            widths = ", ".join(
                f"{r.get('from_width')}->{r.get('to_width')}"
                for r in rebuckets[:8])
            if len(rebuckets) > 8:
                widths += ", ..."
            out.append(f"  ({len(rebuckets)} bucket recompactions: "
                       f"{widths})")
        out.append("")
    elif rebuckets:
        out.append(f"{len(rebuckets)} bucket recompactions "
                   "(rebucket; sweep_k_buckets)")
        out.append("")

    if iters:
        out.append("Loglik trajectory (em_iter):")
        out.append(f"  {'K':>5s} {'iter':>5s}  {'loglik':>15s}"
                   f"  {'delta':>12s}  {'wall_s':>9s}")
        shown = iters[:max_trajectory_rows]
        for r in shown:
            delta = r.get("delta")
            dstr = f"{delta:>12.4e}" if delta is not None else f"{'-':>12s}"
            out.append(f"  {r['k']:>5d} {r['iter']:>5d}"
                       f"  {r['loglik']:>15.6e}  {dstr}"
                       f"  {r['wall_s']:>9.4f}")
        if len(iters) > len(shown):
            out.append(f"  ... {len(iters) - len(shown)} more rows elided")
        out.append("")

    ingest_starts = [r for r in records if r.get("event") == "ingest_start"]
    ingest_summaries = [r for r in records
                        if r.get("event") == "ingest_summary"]
    if chunks or ingest_starts or ingest_summaries:
        if chunks:
            total_bytes = sum(int(r.get("bytes", 0)) for r in chunks)
            line = (f"Streaming: {len(chunks)} block flushes, "
                    f"{total_bytes / 1e6:.1f} MB host->device")
            waits = [float(r["prefetch_wait_s"]) for r in chunks
                     if r.get("prefetch_wait_s") is not None]
            computes = [float(r["compute_s"]) for r in chunks
                        if r.get("compute_s") is not None]
            if waits or computes:
                # rev v1.9 split: total host wall blocked on ingestion vs.
                # in the statistics dispatch, across all blocks.
                line += (f"; prefetch wait {sum(waits):.3f}s / "
                         f"compute {sum(computes):.3f}s")
            out.append(line)
        for r in ingest_starts:
            out.append(
                f"  ingest: {r.get('source', '?')} rows "
                f"[{r.get('row_start', '?')}, {r.get('row_stop', '?')}) "
                f"in {r.get('blocks', '?')} blocks, "
                f"queue depth {r.get('queue_depth', '?')}"
                + (f", mode={r['mode']}" if r.get("mode") else ""))
        for r in ingest_summaries:
            out.append(
                f"  ingest summary: {r.get('blocks_read', 0)} blocks "
                f"served, peak {r.get('peak_resident_blocks', 0)} resident "
                f"(queue depth {r.get('queue_depth', '?')}), "
                f"{float(r.get('bytes', 0)) / 1e6:.1f} MB read, "
                f"prefetch wait {float(r.get('prefetch_wait_s', 0)):.3f}s")
        out.append("")

    if (serve_reqs or serve_batches or serve_summaries or serve_sheds
            or serve_deadlines or serve_reloads or serve_windows
            or circuits or drift_windows or http_reqs or worker_spawns
            or worker_exits):
        out.append("Serving (rev v1.6; docs/SERVING.md):")
        if serve_reqs:
            by_model: Dict[str, List[dict]] = {}
            for r in serve_reqs:
                by_model.setdefault(str(r.get("model")), []).append(r)
            for model, rs in sorted(by_model.items()):
                ok = sum(1 for r in rs if r.get("ok"))
                rows = sum(int(r.get("n", 0)) for r in rs)
                lat = sorted(float(r.get("latency_ms", 0.0)) for r in rs)
                p50 = lat[len(lat) // 2] if lat else 0.0
                out.append(
                    f"  {model:<20s} {len(rs):6d} requests "
                    f"({len(rs) - ok} failed)  {rows:8d} rows  "
                    f"p50 {p50:.3f} ms")
        if serve_batches:
            reqs = sum(int(r.get("requests", 0)) for r in serve_batches)
            rows = sum(int(r.get("rows", 0)) for r in serve_batches)
            padded = sum(int(r.get("padded_rows", 0))
                         for r in serve_batches)
            compiled = sum(int(r.get("compiled", 0))
                           for r in serve_batches)
            out.append(
                f"  {len(serve_batches)} micro-batches: "
                f"{reqs / max(len(serve_batches), 1):.2f} requests/batch, "
                f"{rows} rows ({padded} dispatched after bucketing), "
                f"{compiled} AOT compiles")
        # Resilience (rev v1.7; docs/ROBUSTNESS.md "Serving").
        if serve_sheds:
            by_reason: Dict[str, int] = {}
            for r in serve_sheds:
                by_reason[str(r.get("reason"))] = \
                    by_reason.get(str(r.get("reason")), 0) + 1
            out.append("  shed: " + ", ".join(
                f"{n} {reason}" for reason, n in sorted(by_reason.items())))
        if serve_deadlines:
            waits = [float(r.get("waited_ms", 0.0))
                     for r in serve_deadlines]
            out.append(
                f"  {len(serve_deadlines)} requests expired past their "
                f"deadline (max waited {max(waits):.1f} ms)")
        for r in serve_reloads:
            out.append(
                f"  hot-reload {r.get('model')}: "
                f"v{r.get('from_version')} -> v{r.get('to_version')}")
        if serve_windows:
            # Adaptive micro-batching (rev v2.8): adaptation mix plus
            # where the gather window ended up.
            by_reason: Dict[str, int] = {}
            for r in serve_windows:
                reason = str(r.get("reason"))
                by_reason[reason] = by_reason.get(reason, 0) + 1
            last = serve_windows[-1]
            out.append(
                f"  adaptive window: {len(serve_windows)} adaptation(s) ("
                + ", ".join(f"{n} {reason}"
                            for reason, n in sorted(by_reason.items()))
                + f"), now {float(last.get('window_ms', 0)):.3f} ms")
        for r in circuits:
            ver = (f"@{r['version']}" if r.get("version") is not None
                   else "")
            tail = ""
            if r.get("state") == "open":
                tail = (f" (failures={r.get('failures')}, "
                        f"reason={r.get('reason')}, "
                        f"backoff {r.get('backoff_s')}s)")
            out.append(f"  circuit {r.get('model')}{ver}: "
                       f"{r.get('state')}{tail}")
        # Network front end (rev v2.7; docs/SERVING.md "HTTP front end").
        if http_reqs:
            by_status: Dict[str, int] = {}
            for r in http_reqs:
                key = f"{int(r.get('status', 0)) // 100}xx"
                by_status[key] = by_status.get(key, 0) + 1
            lat = sorted(float(r.get("latency_ms", 0.0))
                         for r in http_reqs)
            retried = sum(1 for r in http_reqs if r.get("retried"))
            line = (f"  http: {len(http_reqs)} requests ("
                    + ", ".join(f"{n} {k}"
                                for k, n in sorted(by_status.items()))
                    + f"), p50 {lat[len(lat) // 2]:.3f} ms")
            if retried:
                line += f", {retried} answered via sibling retry"
            out.append(line)
        if worker_spawns or worker_exits:
            crashes = [r for r in worker_exits if r.get("crash")]
            quarantined = [r for r in worker_exits
                           if r.get("quarantined")]
            respawns = sum(1 for r in worker_spawns if r.get("respawn"))
            line = (f"  workers: {len(worker_spawns)} spawn(s) "
                    f"({respawns} respawns), {len(crashes)} crash(es)")
            if quarantined:
                line += f", {len(quarantined)} quarantined"
            out.append(line)
            for r in crashes:
                out.append(
                    f"    worker {r.get('worker')} pid {r.get('pid')} "
                    f"exited {r.get('exitcode')}"
                    + (" -> QUARANTINED" if r.get("quarantined")
                       else ""))
        if drift_windows:
            # Drift plane (rev v2.4): latest window per (model, version);
            # alarm count from the dedicated drift_alarm records so a
            # superseded window's alarm still shows.
            latest_w: Dict[str, dict] = {}
            for r in drift_windows:
                ver = r.get("version")
                key = (f"{r.get('model')}@{ver}" if ver is not None
                       else str(r.get("model")))
                latest_w[key] = r
            for key, r in sorted(latest_w.items()):
                flag = " ALARM" if r.get("alarm") else ""
                out.append(
                    f"  drift {key}: psi {float(r.get('psi', 0)):.4f} "
                    f"ks {float(r.get('ks', 0)):.4f} "
                    f"occ_l1 {float(r.get('occupancy_l1', 0)):.4f} "
                    f"over {int(r.get('window_rows', 0))} rows "
                    f"({len(drift_windows)} window(s)){flag}")
            if drift_alarms:
                out.append(
                    f"  {len(drift_alarms)} drift alarm(s) "
                    f"(psi threshold "
                    f"{drift_alarms[-1].get('threshold')})")
        for s in serve_summaries:
            lat = s.get("latency_ms") or {}
            out.append(
                f"  summary: {s.get('requests')} requests in "
                f"{s.get('wall_s', 0):.2f}s = {s.get('qps')} QPS; "
                f"latency p50 {lat.get('p50')} ms, p99 {lat.get('p99')} "
                f"ms, max {lat.get('max')} ms")
            ex = s.get("executor") or {}
            if ex:
                out.append(
                    f"  executor: {ex.get('live_executables', 0)} live "
                    f"executables, {ex.get('compiles', 0)} compiles, "
                    f"{ex.get('hits', 0)} hits / "
                    f"{ex.get('misses', 0)} misses, "
                    f"{ex.get('evictions', 0)} evictions, "
                    f"{ex.get('pinned_states', 0)} pinned state(s), "
                    f"{ex.get('host_stagings', 0)} host staging(s)")
            win = s.get("window") or {}
            if win:
                out.append(
                    f"  window: {win.get('adaptations', 0)} "
                    f"adaptation(s), {win.get('window_ms', 0)} ms in "
                    f"[{win.get('min_ms', 0)}, {win.get('max_ms', 0)}]"
                    + (", auto-stack on" if win.get("auto_stack")
                       else ""))
            br = s.get("breaker") or {}
            if any(s.get(k) for k in ("shed", "deadline_expired",
                                      "reloads")) or any(br.values()):
                out.append(
                    f"  resilience: {s.get('shed', 0)} shed, "
                    f"{s.get('deadline_expired', 0)} past deadline, "
                    f"{br.get('trips', 0)} breaker trips "
                    f"({br.get('fastfails', 0)} fast-fails, "
                    f"{br.get('open_routes', 0)} open), "
                    f"{s.get('reloads', 0)} hot-reloads")
            http = s.get("http") or {}
            if http:
                out.append(
                    f"  http: {http.get('requests', 0)} requests "
                    f"({http.get('errors_4xx', 0)} 4xx, "
                    f"{http.get('errors_5xx', 0)} 5xx, "
                    f"{http.get('shed_connections', 0)} shed); "
                    f"workers {http.get('workers', 0)}: "
                    f"{http.get('worker_crashes', 0)} crash(es), "
                    f"{http.get('worker_respawns', 0)} respawn(s), "
                    f"{http.get('worker_quarantines', 0)} quarantined; "
                    f"{http.get('retries', 0)} sibling retries "
                    f"({http.get('retries_exhausted', 0)} exhausted)")
        out.append("")

    if lifecycles or registry_torns:
        out.append("Lifecycle (rev v2.6; docs/ROBUSTNESS.md "
                   "\"Model lifecycle\"):")
        for r in lifecycles:
            phase = str(r.get("phase"))
            model = str(r.get("model"))
            outc = r.get("outcome")
            bits = [f"  {phase} {model}"]
            if outc:
                bits.append(f"{outc}")
            if phase == "retrain" and r.get("candidate_version") is not None:
                bits.append(f"candidate v{r['candidate_version']}")
            if phase == "canary" and r.get("psi") is not None:
                bits.append(
                    f"psi {float(r['psi']):.4f} "
                    f"ks {float(r.get('ks', 0)):.4f} "
                    f"regression {float(r.get('regression', 0)):.4f} "
                    f"(tol {float(r.get('tolerance', 0)):.4f})")
            if phase in ("promote", "rollback") \
                    and r.get("to_version") is not None:
                bits.append(f"v{r.get('from_version')} -> "
                            f"v{r.get('to_version')}")
            if r.get("reason"):
                bits.append(f"reason={r['reason']}")
            if r.get("attempt") is not None:
                bits.append(f"attempt {r['attempt']}")
            out.append(": ".join([bits[0], " ".join(bits[1:])])
                       if len(bits) > 1 else bits[0])
        for r in registry_torns:
            out.append(
                f"  registry torn: {r.get('model')} v{r.get('version')} "
                f"unreadable, walked back ({r.get('error')})")
        out.append("")

    if fleet_starts or tenant_dones or fleet_summaries:
        out.append("Fleet (rev v1.8; docs/TENANCY.md):")
        for r in fleet_starts:
            out.append(
                f"  {r.get('tenants')} tenants in {r.get('groups')} "
                f"packed group(s), mode={r.get('mode')} "
                f"D={r.get('num_dimensions', '?')} "
                f"{r.get('covariance_type', '')}")
        for r in tenant_dones:
            if r.get("dropped"):
                out.append(f"  {str(r.get('tenant')):<20s} DROPPED "
                           f"({r.get('error', '?')})")
            else:
                score = r.get("score")
                sval = (f"{score:.6e}" if isinstance(score, (int, float))
                        else "-")
                out.append(
                    f"  {str(r.get('tenant')):<20s} K={r.get('k'):>3} "
                    f"{r.get('criterion', 'score')}={sval}  "
                    f"{r.get('iters', 0):>5} EM iters")
        for r in fleet_summaries:
            out.append(
                f"  summary: {r.get('tenants')} tenants "
                f"({r.get('dropped')} dropped) in {r.get('groups')} "
                f"group(s), {r.get('wall_s', 0):.2f}s")
        out.append("")

    for r in selects:
        scores = r.get("scores") or []
        out.append(f"Restart selection ({r.get('mode', '?')}, "
                   f"batch_size={r.get('batch_size', '?')}): "
                   f"winner init {r.get('winner')} of {len(scores)}")
        for i, s in enumerate(scores):
            marks = []
            if i == r.get("winner"):
                marks.append("winner")
            if i in (r.get("dropped") or []):
                marks.append("DROPPED")
            tail = f"  ({', '.join(marks)})" if marks else ""
            sval = f"{s:.6e}" if isinstance(s, (int, float)) else "-"
            out.append(f"  init {i:>3d}  "
                       f"{r.get('criterion', 'score')}={sval}{tail}")
    if selects:
        out.append("")

    if healths or recoveries or io_retries:
        out.append("Health / recovery (docs/ROBUSTNESS.md):")
        for r in healths:
            k = r.get("k")
            names = ",".join(r.get("flag_names") or []) or "?"
            where = r.get("where", "em")
            out.append(f"  health   K={k if k is not None else '-':>4} "
                       f"[{where}] flags=0x{int(r.get('flags', 0)):x} "
                       f"({names})")
        for r in recoveries:
            out.append(f"  recovery K={r.get('k', '-'):>4} "
                       f"attempt={r.get('attempt')} "
                       f"action={r.get('action')} -> {r.get('outcome')}")
        for r in io_retries:
            tail = " GAVE UP" if r.get("gave_up") else ""
            out.append(f"  io_retry {r.get('op')} "
                       f"step={r.get('step', '-')} "
                       f"attempt={r.get('attempt')}: "
                       f"{r.get('error')}{tail}")
        out.append("")

    if preempts or shutdowns or peer_losts or shrinks or resumes:
        out.append("Run lifecycle (preemption; docs/ROBUSTNESS.md):")
        for r in peer_losts:
            out.append(f"  peer_lost rank={r.get('rank')} heartbeat "
                       f"stale {r.get('age_s', '?')}s > timeout "
                       f"{r.get('timeout_s', '?')}s")
        for r in shrinks:
            survivors = r.get("survivors") or []
            lost = ",".join(str(x) for x in (r.get("lost_ranks") or []))
            out.append(f"  elastic_shrink gen={r.get('generation')} -> "
                       f"{r.get('world_size')} host(s) {survivors}"
                       + (f" (lost rank {lost})" if lost else "")
                       + (f" attempt={r['attempt']}"
                          if r.get("attempt") is not None else ""))
        for r in resumes:
            pos = ""
            if r.get("step") is not None:
                pos = f" from step {r['step']}"
                if r.get("k") is not None:
                    pos += f" (K={r['k']})"
            out.append(f"  elastic_resume gen={r.get('generation')} "
                       f"continued the sweep{pos}")
        for r in preempts:
            pos = ""
            if r.get("k") is not None:
                pos = f" at K={r['k']}"
                if r.get("em_iter") is not None:
                    pos += f" iter={r['em_iter']}"
            out.append(f"  preempt  reason={r.get('reason')} "
                       f"[{r.get('where', '?')}]{pos}")
        for r in shutdowns:
            if r.get("checkpointed"):
                pos = ""
                if r.get("step") is not None:
                    pos = f" (step {r['step']}"
                    pos += (f" iter {r['em_iter']})"
                            if r.get("em_iter") is not None else ")")
                ck = "checkpoint durable" + pos
            else:
                ck = "NO checkpoint (not resumable)"
            out.append(f"  shutdown reason={r.get('reason')} -> exit 75, "
                       f"{ck}")
        out.append("")

    if heartbeats:
        last = heartbeats[-1]
        out.append(
            f"Liveness: {len(heartbeats)} heartbeat(s), last "
            f"phase={last.get('phase', '?')} at "
            f"elapsed={float(last.get('elapsed_s', 0)):.0f}s")
        samples = [r for r in heartbeats if r.get("sampler")]
        rss = [int(r["rss_bytes"]) for r in samples
               if r.get("rss_bytes") is not None]
        if rss:
            line = (f"  resources ({len(samples)} samples): host RSS "
                    f"peak {max(rss) / 1e6:.1f} MB")
            hbm = [int((r.get("memory_stats") or {}).get(
                       "peak_bytes_in_use",
                       (r.get("memory_stats") or {}).get(
                           "bytes_in_use", 0)))
                   for r in samples if r.get("memory_stats")]
            if any(hbm):
                line += f", device peak {max(hbm) / 1e6:.1f} MB"
            out.append(line)
        out.append("")

    if span_recs:
        from .spans import build_span_tree

        traces = {str(r.get("trace_id")) for r in span_recs}
        out.append(f"Trace spans (rev v2.1): {len(span_recs)} span(s) "
                   f"in {len(traces)} trace(s)")
        max_span_rows = 120
        shown = 0
        elided = 0
        # Depth-first with an explicit stack; children are pre-sorted by
        # start time in build_span_tree.
        stack = [(root, 0) for root in reversed(build_span_tree(span_recs))]
        while stack:
            node, depth = stack.pop()
            s = node["span"]
            if shown >= max_span_rows:
                elided += 1 + _count_spans(node)
                continue
            shown += 1
            label = str(s.get("name", "?"))
            for key in ("k", "group", "model", "step"):
                if s.get(key) is not None:
                    label += f" {key}={s[key]}"
            status = ("" if s.get("status", "ok") == "ok"
                      else f"  [{s.get('status')}]")
            out.append(f"  {'  ' * depth}{label:<{max(30 - 2 * depth, 8)}s}"
                       f" {float(s.get('duration_s', 0)):>9.3f}s{status}")
            for child in reversed(node["children"]):
                stack.append((child, depth + 1))
        if elided:
            out.append(f"  ... {elided} more span(s) elided")
        out.append("")

    if span_recs:
        out.extend(_render_span_profile(span_recs))

    if compile_recs:
        # rev v2.2 (telemetry/profiling.py): per-compile observations --
        # instrumented cache builds ("aot", with cost/memory analyses)
        # vs. bare XLA backend compiles outside any site ("xla").
        aot = [r for r in compile_recs if r.get("source") == "aot"]
        xla = [r for r in compile_recs if r.get("source") != "aot"]
        out.append(
            f"Compile activity (rev v2.2): {len(aot)} instrumented "
            f"cache build(s) ({sum(float(r.get('seconds', 0)) for r in aot):.3f}s), "
            f"{len(xla)} other XLA compile(s) "
            f"({sum(float(r.get('seconds', 0)) for r in xla):.3f}s)")
        by_site: Dict[str, List[dict]] = {}
        for r in aot:
            by_site.setdefault(str(r.get("site", "?")), []).append(r)
        for site, rs in sorted(by_site.items()):
            line = (f"  {site}: {len(rs)} compile(s), "
                    f"{sum(float(r.get('seconds', 0)) for r in rs):.3f}s")
            flops = [float(r["flops"]) for r in rs
                     if r.get("flops") is not None]
            ba = [float(r["bytes_accessed"]) for r in rs
                  if r.get("bytes_accessed") is not None]
            if flops:
                line += f"; max {max(flops):.3g} flops"
            if ba:
                line += f" / {max(ba) / 1e6:.1f} MB accessed"
            temp = [int(r["temp_bytes"]) for r in rs
                    if r.get("temp_bytes") is not None]
            if temp:
                line += f"; temp {max(temp) / 1e6:.1f} MB"
            out.append(line)
        out.append("")

    for s in summaries:
        prof = s.get("phase_profile") or {}
        if prof.get("seconds"):
            out.append(render_phase_table(prof["seconds"],
                                          prof.get("counts")))
        comp = s.get("compile") or {}
        watch_prof = s.get("profile") or {}
        if comp or watch_prof:
            first = comp.get("first_call_s")
            warm = comp.get("warm_call_s")
            # rev v2.2: prefer MEASURED compile seconds (CompileWatch)
            # over the first-minus-warm heuristic; pre-v2.2 streams
            # carry only est_compile_s and keep rendering through it.
            measured = watch_prof.get("compile_seconds")
            est = comp.get("est_compile_s")
            out.append(
                "Compile/execute split: first call "
                + (f"{first:.3f}s" if first is not None else "-")
                + ", warm call "
                + (f"{warm:.3f}s" if warm is not None else "-")
                + ", compile "
                + (f"{measured:.3f}s (measured)" if measured is not None
                   else (f"{est:.3f}s (est.)" if est is not None else "-")))
        if watch_prof:
            line = (f"Profile (rev v2.2): {watch_prof.get('compiles', 0)} "
                    f"site compile(s), "
                    f"{watch_prof.get('xla_compiles', 0)} XLA compile(s) "
                    f"({float(watch_prof.get('xla_compile_seconds', 0)):.3f}s"
                    " total)")
            cost = watch_prof.get("cost") or {}
            if cost.get("flops") is not None:
                line += (f"; peak program {float(cost['flops']):.3g} flops"
                         f" / {float(cost.get('bytes_accessed', 0)) / 1e6:.1f}"
                         " MB accessed")
            if watch_prof.get("hbm_peak_bytes"):
                line += (f"; HBM peak "
                         f"{int(watch_prof['hbm_peak_bytes']) / 1e6:.1f} MB")
            out.append(line)
        hs = s.get("health")
        if hs is not None:
            if hs.get("flags"):
                out.append(
                    "Health: flags=0x%x (%s)%s  recoveries=%d io_retries=%d"
                    % (int(hs["flags"]),
                       ",".join(hs.get("flag_names") or []),
                       " FATAL" if hs.get("fatal") else "",
                       int(hs.get("recoveries", 0)),
                       int(hs.get("io_retries", 0))))
            else:
                out.append("Health: clean (all flags zero)")
        el = s.get("elastic")
        if el:
            out.append(
                f"Elastic: generation {el.get('generation')} "
                f"({el.get('world_size')} host(s) at finish, "
                f"{el.get('shrinks', 0)} shrink(s), "
                f"{el.get('resumes', 0)} resume(s))")
        backend = (f"  [backend={s['em_backend']}]"
                   if s.get("em_backend") else "")
        out.append(
            f"Best model: K={s.get('ideal_k')} "
            f"{s.get('criterion', 'score')}={s.get('score'):.6e} "
            f"loglik={s.get('final_loglik'):.6e} "
            f"({s.get('total_iters')} EM iterations, "
            f"{s.get('wall_s'):.2f}s){backend}")
        metrics = s.get("metrics") or {}
        counters = metrics.get("counters")
        if counters:
            out.append("Counters: " + "  ".join(
                f"{k}={v:g}" for k, v in sorted(counters.items())))
        out.append("")

    if not out:
        return "(no telemetry records)"
    return "\n".join(out).rstrip() + "\n"


# -- gmm report --follow / gmm top (rev v2.1) ---------------------------

# Records that end a stream: once one arrives, the tailer renders a last
# screen and exits instead of polling a finished run forever.
_TERMINAL_EVENTS = frozenset(
    ("run_summary", "serve_summary", "fleet_summary", "shutdown"))


def _discover_streams(path: str) -> List[str]:
    """The stream files behind one ``gmm top`` target: the file itself,
    or every ``*.jsonl`` in a directory of per-rank streams."""
    if os.path.isdir(path):
        return sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".jsonl"))
    return [path]


class StreamTailer:
    """Incremental reader of one JSONL stream file.

    Keeps a byte offset; each :meth:`poll` returns the records completed
    since the last one. Only whole lines are consumed -- a torn final
    line (caught mid-write) stays unread until its newline lands, which
    the recorder's flush-per-record sink guarantees eventually happens.
    A file that SHRANK (a new run truncating the same path) restarts the
    offset from zero.
    """

    def __init__(self, path: str):
        self.path = path
        self._offset = 0

    def poll(self) -> List[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []  # not created yet (or vanished): keep waiting
        if size < self._offset:
            self._offset = 0
        if size == self._offset:
            return []
        try:
            with open(self.path, "rb") as fh:
                fh.seek(self._offset)
                chunk = fh.read()
        except OSError:
            # The path races with the run: it can vanish between getsize
            # and open, or turn out to be a directory (a `gmm top` target
            # that did not exist at startup and was later created as a
            # per-rank stream dir -- follow_stream's per-poll rescan then
            # tails the member files; this placeholder just stays quiet).
            return []
        nl = chunk.rfind(b"\n")
        if nl < 0:
            return []
        consumed = chunk[:nl + 1]
        self._offset += len(consumed)
        records: List[dict] = []
        for raw in consumed.splitlines():
            raw = raw.strip()
            if not raw:
                continue
            try:
                records.append(json.loads(raw.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                continue  # live view: skip a bad line, don't die
        return records


def _iter_rate(iters: List[dict], window: int = 50) -> Optional[float]:
    """EM iterations/s over the trailing window -- from ``mono_s``
    deltas when every record carries one (rev v2.1), immune to
    wall-clock slew; ``ts`` fallback for older streams."""
    if len(iters) < 2:
        return None
    tail = iters[-window:]
    key = "mono_s" if all("mono_s" in r for r in tail) else "ts"
    dt = float(tail[-1][key]) - float(tail[0][key])
    if dt <= 0:
        return None
    return (len(tail) - 1) / dt


def render_follow(records: List[dict]) -> str:
    """The ``gmm top`` screen: a one-screen live view of the stream."""
    if not records:
        return "(gmm top: waiting for telemetry records...)\n"
    by: Dict[str, List[dict]] = {}
    for r in records:
        by.setdefault(str(r.get("event")), []).append(r)
    out: List[str] = []

    starts = by.get("run_start", [])
    fleet_starts = by.get("fleet_start", [])
    head = ["gmm top"]
    if starts:
        s = starts[-1]
        head.append(f"run {s.get('run_id', '?')}")
        head.append(f"platform={s.get('platform', '?')}")
        head.append(f"N={s.get('num_events', '?')} "
                    f"D={s.get('num_dimensions', '?')}")
        if s.get("path"):
            head.append(f"path={s['path']}")
    elif fleet_starts:
        s = fleet_starts[-1]
        head.append(f"fleet run {s.get('run_id', '?')}")
        head.append(f"platform={s.get('platform', '?')}")
    elif by.get("serve_request") or by.get("serve_batch"):
        head.append(f"serve run {records[-1].get('run_id', '?')}")
    out.append("  ".join(head))
    out.append("")

    iters = by.get("em_iter", [])
    dones = by.get("em_done", [])
    if iters:
        cur = iters[-1]
        rate = _iter_rate(iters)
        line = (f"EM: K={cur.get('k')} iter={cur.get('iter')} "
                f"loglik={float(cur.get('loglik', 0)):.6e}")
        if cur.get("delta") is not None:
            line += f" delta={float(cur['delta']):.3e}"
        if rate is not None:
            line += f"  ({rate:.1f} iters/s)"
        out.append(line)
    if dones:
        import math

        best = min(
            (r for r in dones
             if isinstance(r.get("score"), (int, float))
             and not math.isnan(float(r["score"]))),
            key=lambda r: float(r["score"]), default=None)
        line = f"Sweep: {len(dones)} model order(s) done"
        if best is not None:
            line += (f"; best K={best.get('k')} "
                     f"score={float(best['score']):.6e}")
        out.append(line)

    tenant_dones = by.get("tenant_done", [])
    if fleet_starts or tenant_dones:
        total = (fleet_starts[-1].get("tenants", "?")
                 if fleet_starts else "?")
        dropped = sum(1 for r in tenant_dones if r.get("dropped"))
        out.append(f"Fleet: {len(tenant_dones)}/{total} tenant(s) done"
                   + (f" ({dropped} dropped)" if dropped else ""))

    serve_reqs = by.get("serve_request", [])
    if serve_reqs:
        failed = sum(1 for r in serve_reqs if not r.get("ok"))
        rows = sum(int(r.get("n", 0)) for r in serve_reqs)
        lat = sorted(float(r.get("latency_ms", 0.0))
                     for r in serve_reqs[-200:])
        p50 = lat[len(lat) // 2] if lat else 0.0
        line = (f"Serve: {len(serve_reqs)} requests ({failed} failed), "
                f"{rows} rows, p50 {p50:.2f} ms")
        extras = []
        for kind, tag in (("serve_shed", "shed"),
                          ("serve_deadline", "deadline"),
                          ("serve_reload", "reload")):
            n = len(by.get(kind, []))
            if n:
                extras.append(f"{n} {tag}")
        opens = sum(1 for r in by.get("circuit", [])
                    if r.get("state") == "open")
        if opens:
            extras.append(f"{opens} breaker trip(s)")
        windows = by.get("serve_window", [])
        if windows:
            extras.append(
                f"{len(windows)} window adaptation(s) -> "
                f"{float(windows[-1].get('window_ms', 0)):.2f} ms")
        if extras:
            line += "  [" + ", ".join(extras) + "]"
        out.append(line)

    http_reqs = by.get("http_request", [])
    if http_reqs:
        # HTTP front-end rollup (rev v2.7): status classes + tail p50.
        err5 = sum(1 for r in http_reqs
                   if int(r.get("status", 0)) >= 500)
        retried = sum(1 for r in http_reqs if r.get("retried"))
        lat = sorted(float(r.get("latency_ms", 0.0))
                     for r in http_reqs[-200:])
        p50 = lat[len(lat) // 2] if lat else 0.0
        line = (f"http: {len(http_reqs)} requests ({err5} 5xx), "
                f"p50 {p50:.2f} ms")
        if retried:
            line += f"  [{retried} sibling retr{'y' if retried == 1 else 'ies'}]"
        out.append(line)
    worker_exits = by.get("worker_exit", [])
    worker_spawns = by.get("worker_spawn", [])
    if worker_spawns or worker_exits:
        crashes = sum(1 for r in worker_exits if r.get("crash"))
        quarantined = sum(1 for r in worker_exits
                          if r.get("quarantined"))
        line = (f"workers: {len(worker_spawns)} spawn(s), "
                f"{crashes} crash(es)")
        if quarantined:
            line += f"  [{quarantined} QUARANTINED]"
        out.append(line)

    drifts = by.get("drift", [])
    if drifts:
        # Drift rollup (rev v2.4): latest window per model; alarms from
        # the dedicated drift_alarm records so a scrolled-off window
        # still counts.
        latest: Dict[str, dict] = {}
        for r in drifts:
            latest[str(r.get("model"))] = r
        worst = max(latest.values(),
                    key=lambda r: float(r.get("psi", 0.0)))
        alarms = len(by.get("drift_alarm", []))
        line = (f"drift: {len(drifts)} window(s), "
                f"worst psi {float(worst.get('psi', 0.0)):.4f} "
                f"ks {float(worst.get('ks', 0.0)):.4f} "
                f"({worst.get('model')})")
        if alarms:
            line += f"  [{alarms} ALARM(s)]"
        out.append(line)

    lifecycles = by.get("lifecycle", [])
    if lifecycles:
        # Lifecycle rollup (rev v2.6): phase counts + the newest edge.
        phases: Dict[str, int] = {}
        for r in lifecycles:
            phases[str(r.get("phase"))] = \
                phases.get(str(r.get("phase")), 0) + 1
        last = lifecycles[-1]
        line = "lifecycle: " + ", ".join(
            f"{n} {phase}" for phase, n in sorted(phases.items()))
        line += (f"  [last: {last.get('phase')} {last.get('model')}"
                 + (f" {last.get('outcome')}" if last.get("outcome")
                    else "") + "]")
        out.append(line)
    torns = by.get("registry_torn", [])
    if torns:
        out.append(f"registry: {len(torns)} torn version walk-back(s)")

    healths = by.get("health", [])
    recoveries = by.get("recovery", [])
    if healths or recoveries:
        out.append(f"Health: {len(healths)} nonzero flag word(s), "
                   f"{len(recoveries)} recovery action(s)")
    shrinks = by.get("elastic_shrink", [])
    if shrinks:
        last = shrinks[-1]
        out.append(f"Elastic: generation {last.get('generation')} "
                   f"({last.get('world_size')} host(s))")

    samples = [r for r in by.get("heartbeat", []) if r.get("sampler")]
    if samples:
        last = samples[-1]
        line = "Resources:"
        if last.get("rss_bytes") is not None:
            line += f" host RSS {int(last['rss_bytes']) / 1e6:.1f} MB"
        mem = last.get("memory_stats") or {}
        if mem.get("bytes_in_use") is not None:
            line += f", device {int(mem['bytes_in_use']) / 1e6:.1f} MB"
            if mem.get("peak_bytes_in_use") is not None:
                line += (" (peak "
                         f"{int(mem['peak_bytes_in_use']) / 1e6:.1f} MB)")
        out.append(line)

    spans = by.get("span", [])
    if spans:
        last = spans[-1]
        out.append(f"Spans: {len(spans)} closed, last "
                   f"{last.get('name', '?')} "
                   f"({float(last.get('duration_s', 0)):.3f}s)")

    last = records[-1]
    tail = f"last event: {last.get('event')}"
    if last.get("ts") is not None:
        age = max(0.0, time.time() - float(last["ts"]))
        tail += f" ({age:.1f}s ago)"
    if any(k in _TERMINAL_EVENTS for k in by):
        # Anywhere, not just last: with the live plane on, the closing
        # fit/fleet span records land AFTER run_summary (they close when
        # the plane's ExitStack unwinds around the emitting code).
        tail += "  -- stream ended"
    out.append("")
    out.append(tail)
    return "\n".join(out) + "\n"


def follow_stream(path: str, interval_s: float = 1.0,
                  max_renders: Optional[int] = None, out=None) -> int:
    """The ``--follow`` loop: poll, merge, re-render until the stream
    ends (a terminal record) or ``max_renders`` screens were drawn."""
    out = out if out is not None else sys.stdout
    clear = bool(getattr(out, "isatty", lambda: False)())
    tailers: Dict[str, StreamTailer] = {}
    records: List[dict] = []
    renders = 0
    ended = False

    def _poll_all() -> List[dict]:
        # Re-discover EVERY poll, not just at startup: rank files that
        # join late (elastic regrowth, slow NFS create, a serve stream
        # landing beside a fit stream) get a tailer mid-follow and their
        # records appear on the next screen.
        for stream_path in _discover_streams(path):
            if stream_path not in tailers:
                tailers[stream_path] = StreamTailer(stream_path)
        new: List[dict] = []
        for t in tailers.values():
            new.extend(t.poll())
        return new

    def _render() -> None:
        nonlocal renders
        if clear:
            out.write("\x1b[2J\x1b[H")  # clear + home, like top(1)
        elif renders:
            out.write("\n--- refresh ---\n")
        out.write(render_follow(records))
        out.flush()
        renders += 1

    while True:
        new = _poll_all()
        if new or renders == 0:
            records.extend(new)
            _render()
        ended = ended or any(
            r.get("event") in _TERMINAL_EVENTS for r in new)
        if ended:
            # The run is over, but teardown records can TRAIL the
            # terminal one (with the live plane on, the closing
            # fit/fleet spans emit after run_summary, when the plane's
            # ExitStack unwinds). One short drain catches them, then a
            # final screen.
            time.sleep(min(interval_s, 0.2))
            tail_records = _poll_all()
            if tail_records:
                records.extend(tail_records)
                _render()
            return 0
        if max_renders is not None and renders >= max_renders:
            return 0
        time.sleep(interval_s)


def report_main(argv=None) -> int:
    """``gmm report <metrics.jsonl>``: render a stream on stdout."""
    import argparse

    from .recorder import read_stream

    p = argparse.ArgumentParser(
        prog="gmm report",
        description="Render a --metrics-file JSONL telemetry stream: phase "
        "profile, loglik trajectory, and model-order sweep summary. "
        "--follow (alias: `gmm top`) tails a LIVE stream -- a file or a "
        "directory of per-rank *.jsonl streams -- re-rendering a "
        "one-screen view as records arrive.")
    p.add_argument("metrics_file", help="JSONL stream from --metrics-file "
                   "(with --follow: a file or a stream directory)")
    p.add_argument("--validate", action="store_true",
                   help="exit nonzero if any record fails schema validation")
    p.add_argument("--json", action="store_true",
                   help="machine-readable rollup on stdout (the same "
                   "flat-metric shape `gmm diff` compares) instead of "
                   "the rendered report")
    p.add_argument("--follow", "-f", action="store_true",
                   help="live view: poll the stream and re-render one "
                   "screen as it grows; exits when the run's terminal "
                   "record (run_summary / serve_summary / fleet_summary "
                   "/ shutdown) arrives")
    p.add_argument("--interval", type=float, default=1.0, metavar="S",
                   help="--follow poll cadence in seconds (default 1)")
    p.add_argument("--max-renders", type=int, default=None, metavar="N",
                   help="--follow: stop after N screens (automation and "
                   "tests; default: until the stream ends)")
    args = p.parse_args(argv)
    if args.follow:
        return follow_stream(args.metrics_file,
                             interval_s=args.interval,
                             max_renders=args.max_renders)
    try:
        records = read_stream(args.metrics_file)
    except OSError as e:
        print(f"Cannot read {args.metrics_file!r}: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    if not records:
        print(f"{args.metrics_file}: empty stream", file=sys.stderr)
        return 1
    errors = validate_stream(records)
    for e in errors:
        print(f"schema: {e}", file=sys.stderr)
    if args.json:
        from .diff import summarize_run

        print(json.dumps(summarize_run(records), sort_keys=True))
    else:
        print(render_report(records), end="")
    return 1 if (errors and args.validate) else 0
