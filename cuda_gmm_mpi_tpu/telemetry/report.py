"""Offline rendering of a telemetry stream: the ``gmm report`` backend.

Turns a ``--metrics-file`` JSONL stream back into the reference's
human-readable surfaces -- the 7-category phase-profile table
(``gaussian.cu:967``'s layout, shared with ``PhaseTimer.report`` so the
live ``--profile`` print and the offline report are byte-compatible), the
per-K selection sweep summary, and the per-iteration loglik trajectory --
from the stream alone: no pickle, no state files, no devices.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional

from .schema import validate_stream


def render_phase_table(seconds: Dict[str, float],
                       counts: Optional[Dict[str, int]] = None) -> str:
    """Total + per-call average per category (gaussian.cu:967's layout).

    The single formatter behind both the live ``PhaseTimer.report`` and
    the offline ``gmm report`` phase table.
    """
    counts = counts or {}
    lines = ["Phase profile (seconds total / calls / avg):"]
    for name, total in seconds.items():
        n = max(counts.get(name, 0), 1)
        lines.append(f"  {name:<10s}\t{total:9.4f}\t{counts.get(name, 0):6d}"
                     f"\t{total / n:9.6f}")
    return "\n".join(lines)


def _fmt_run_start(rec: dict) -> str:
    bits = [f"run {rec.get('run_id', '?')}",
            f"platform={rec.get('platform', '?')}",
            f"N={rec.get('num_events', '?')}",
            f"D={rec.get('num_dimensions', '?')}",
            f"start_k={rec.get('start_k', '?')}"]
    if rec.get("target_k"):
        bits.append(f"target_k={rec['target_k']}")
    if rec.get("path"):
        bits.append(f"path={rec['path']}")
    if rec.get("em_backend"):
        # rev v1.5: which E-step backend actually ran; a fallback away
        # from a requested kernel carries its reason.
        b = f"backend={rec['em_backend']}"
        if rec.get("em_backend") == "jnp" and rec.get("em_backend_reason"):
            b += f" ({rec['em_backend_reason']})"
        bits.append(b)
    if rec.get("mesh"):
        bits.append(f"mesh={rec['mesh']}")
    if rec.get("process_count", 1) and rec.get("process_count", 1) > 1:
        bits.append(f"processes={rec['process_count']}")
    return "  ".join(str(b) for b in bits)


def render_report(records: List[dict], max_trajectory_rows: int = 400) -> str:
    """The full ``gmm report`` text for one decoded stream."""
    out: List[str] = []
    starts = [r for r in records if r.get("event") == "run_start"]
    iters = [r for r in records if r.get("event") == "em_iter"]
    dones = [r for r in records if r.get("event") == "em_done"]
    merges = [r for r in records if r.get("event") == "merge"]
    chunks = [r for r in records if r.get("event") == "chunk_flush"]
    summaries = [r for r in records if r.get("event") == "run_summary"]

    serve_reqs = [r for r in records if r.get("event") == "serve_request"]
    serve_batches = [r for r in records
                     if r.get("event") == "serve_batch"]
    serve_summaries = [r for r in records
                       if r.get("event") == "serve_summary"]
    serve_sheds = [r for r in records if r.get("event") == "serve_shed"]
    serve_deadlines = [r for r in records
                       if r.get("event") == "serve_deadline"]
    serve_reloads = [r for r in records
                     if r.get("event") == "serve_reload"]
    circuits = [r for r in records if r.get("event") == "circuit"]

    fleet_starts = [r for r in records if r.get("event") == "fleet_start"]
    tenant_dones = [r for r in records if r.get("event") == "tenant_done"]
    fleet_summaries = [r for r in records
                       if r.get("event") == "fleet_summary"]

    selects = [r for r in records if r.get("event") == "restart_select"]
    healths = [r for r in records if r.get("event") == "health"]
    recoveries = [r for r in records if r.get("event") == "recovery"]
    io_retries = [r for r in records if r.get("event") == "io_retry"]
    preempts = [r for r in records if r.get("event") == "preempt"]
    shutdowns = [r for r in records if r.get("event") == "shutdown"]
    peer_losts = [r for r in records if r.get("event") == "peer_lost"]
    shrinks = [r for r in records if r.get("event") == "elastic_shrink"]
    resumes = [r for r in records if r.get("event") == "elastic_resume"]

    for s in starts:
        out.append(_fmt_run_start(s))
    if starts:
        out.append("")

    if dones:
        out.append("Model-order sweep (em_done):")
        out.append(f"  {'K':>5s}  {'loglik':>15s}  {'score':>15s}"
                   f"  {'iters':>6s}  {'seconds':>9s}")
        for r in dones:
            out.append(f"  {r['k']:>5d}  {r['loglik']:>15.6e}"
                       f"  {r['score']:>15.6e}  {r['iters']:>6d}"
                       f"  {r['seconds']:>9.3f}")
        if merges:
            out.append(f"  ({len(merges)} closest-pair merges)")
        out.append("")

    if iters:
        out.append("Loglik trajectory (em_iter):")
        out.append(f"  {'K':>5s} {'iter':>5s}  {'loglik':>15s}"
                   f"  {'delta':>12s}  {'wall_s':>9s}")
        shown = iters[:max_trajectory_rows]
        for r in shown:
            delta = r.get("delta")
            dstr = f"{delta:>12.4e}" if delta is not None else f"{'-':>12s}"
            out.append(f"  {r['k']:>5d} {r['iter']:>5d}"
                       f"  {r['loglik']:>15.6e}  {dstr}"
                       f"  {r['wall_s']:>9.4f}")
        if len(iters) > len(shown):
            out.append(f"  ... {len(iters) - len(shown)} more rows elided")
        out.append("")

    ingest_starts = [r for r in records if r.get("event") == "ingest_start"]
    ingest_summaries = [r for r in records
                        if r.get("event") == "ingest_summary"]
    if chunks or ingest_starts or ingest_summaries:
        if chunks:
            total_bytes = sum(int(r.get("bytes", 0)) for r in chunks)
            line = (f"Streaming: {len(chunks)} block flushes, "
                    f"{total_bytes / 1e6:.1f} MB host->device")
            waits = [float(r["prefetch_wait_s"]) for r in chunks
                     if r.get("prefetch_wait_s") is not None]
            computes = [float(r["compute_s"]) for r in chunks
                        if r.get("compute_s") is not None]
            if waits or computes:
                # rev v1.9 split: total host wall blocked on ingestion vs.
                # in the statistics dispatch, across all blocks.
                line += (f"; prefetch wait {sum(waits):.3f}s / "
                         f"compute {sum(computes):.3f}s")
            out.append(line)
        for r in ingest_starts:
            out.append(
                f"  ingest: {r.get('source', '?')} rows "
                f"[{r.get('row_start', '?')}, {r.get('row_stop', '?')}) "
                f"in {r.get('blocks', '?')} blocks, "
                f"queue depth {r.get('queue_depth', '?')}"
                + (f", mode={r['mode']}" if r.get("mode") else ""))
        for r in ingest_summaries:
            out.append(
                f"  ingest summary: {r.get('blocks_read', 0)} blocks "
                f"served, peak {r.get('peak_resident_blocks', 0)} resident "
                f"(queue depth {r.get('queue_depth', '?')}), "
                f"{float(r.get('bytes', 0)) / 1e6:.1f} MB read, "
                f"prefetch wait {float(r.get('prefetch_wait_s', 0)):.3f}s")
        out.append("")

    if (serve_reqs or serve_batches or serve_summaries or serve_sheds
            or serve_deadlines or serve_reloads or circuits):
        out.append("Serving (rev v1.6; docs/SERVING.md):")
        if serve_reqs:
            by_model: Dict[str, List[dict]] = {}
            for r in serve_reqs:
                by_model.setdefault(str(r.get("model")), []).append(r)
            for model, rs in sorted(by_model.items()):
                ok = sum(1 for r in rs if r.get("ok"))
                rows = sum(int(r.get("n", 0)) for r in rs)
                lat = sorted(float(r.get("latency_ms", 0.0)) for r in rs)
                p50 = lat[len(lat) // 2] if lat else 0.0
                out.append(
                    f"  {model:<20s} {len(rs):6d} requests "
                    f"({len(rs) - ok} failed)  {rows:8d} rows  "
                    f"p50 {p50:.3f} ms")
        if serve_batches:
            reqs = sum(int(r.get("requests", 0)) for r in serve_batches)
            rows = sum(int(r.get("rows", 0)) for r in serve_batches)
            padded = sum(int(r.get("padded_rows", 0))
                         for r in serve_batches)
            compiled = sum(int(r.get("compiled", 0))
                           for r in serve_batches)
            out.append(
                f"  {len(serve_batches)} micro-batches: "
                f"{reqs / max(len(serve_batches), 1):.2f} requests/batch, "
                f"{rows} rows ({padded} dispatched after bucketing), "
                f"{compiled} AOT compiles")
        # Resilience (rev v1.7; docs/ROBUSTNESS.md "Serving").
        if serve_sheds:
            by_reason: Dict[str, int] = {}
            for r in serve_sheds:
                by_reason[str(r.get("reason"))] = \
                    by_reason.get(str(r.get("reason")), 0) + 1
            out.append("  shed: " + ", ".join(
                f"{n} {reason}" for reason, n in sorted(by_reason.items())))
        if serve_deadlines:
            waits = [float(r.get("waited_ms", 0.0))
                     for r in serve_deadlines]
            out.append(
                f"  {len(serve_deadlines)} requests expired past their "
                f"deadline (max waited {max(waits):.1f} ms)")
        for r in serve_reloads:
            out.append(
                f"  hot-reload {r.get('model')}: "
                f"v{r.get('from_version')} -> v{r.get('to_version')}")
        for r in circuits:
            ver = (f"@{r['version']}" if r.get("version") is not None
                   else "")
            tail = ""
            if r.get("state") == "open":
                tail = (f" (failures={r.get('failures')}, "
                        f"reason={r.get('reason')}, "
                        f"backoff {r.get('backoff_s')}s)")
            out.append(f"  circuit {r.get('model')}{ver}: "
                       f"{r.get('state')}{tail}")
        for s in serve_summaries:
            lat = s.get("latency_ms") or {}
            out.append(
                f"  summary: {s.get('requests')} requests in "
                f"{s.get('wall_s', 0):.2f}s = {s.get('qps')} QPS; "
                f"latency p50 {lat.get('p50')} ms, p99 {lat.get('p99')} "
                f"ms, max {lat.get('max')} ms")
            ex = s.get("executor") or {}
            if ex:
                out.append(
                    f"  executor: {ex.get('live_executables', 0)} live "
                    f"executables, {ex.get('compiles', 0)} compiles, "
                    f"{ex.get('hits', 0)} hits / "
                    f"{ex.get('misses', 0)} misses, "
                    f"{ex.get('evictions', 0)} evictions")
            br = s.get("breaker") or {}
            if any(s.get(k) for k in ("shed", "deadline_expired",
                                      "reloads")) or any(br.values()):
                out.append(
                    f"  resilience: {s.get('shed', 0)} shed, "
                    f"{s.get('deadline_expired', 0)} past deadline, "
                    f"{br.get('trips', 0)} breaker trips "
                    f"({br.get('fastfails', 0)} fast-fails, "
                    f"{br.get('open_routes', 0)} open), "
                    f"{s.get('reloads', 0)} hot-reloads")
        out.append("")

    if fleet_starts or tenant_dones or fleet_summaries:
        out.append("Fleet (rev v1.8; docs/TENANCY.md):")
        for r in fleet_starts:
            out.append(
                f"  {r.get('tenants')} tenants in {r.get('groups')} "
                f"packed group(s), mode={r.get('mode')} "
                f"D={r.get('num_dimensions', '?')} "
                f"{r.get('covariance_type', '')}")
        for r in tenant_dones:
            if r.get("dropped"):
                out.append(f"  {str(r.get('tenant')):<20s} DROPPED "
                           f"({r.get('error', '?')})")
            else:
                score = r.get("score")
                sval = (f"{score:.6e}" if isinstance(score, (int, float))
                        else "-")
                out.append(
                    f"  {str(r.get('tenant')):<20s} K={r.get('k'):>3} "
                    f"{r.get('criterion', 'score')}={sval}  "
                    f"{r.get('iters', 0):>5} EM iters")
        for r in fleet_summaries:
            out.append(
                f"  summary: {r.get('tenants')} tenants "
                f"({r.get('dropped')} dropped) in {r.get('groups')} "
                f"group(s), {r.get('wall_s', 0):.2f}s")
        out.append("")

    for r in selects:
        scores = r.get("scores") or []
        out.append(f"Restart selection ({r.get('mode', '?')}, "
                   f"batch_size={r.get('batch_size', '?')}): "
                   f"winner init {r.get('winner')} of {len(scores)}")
        for i, s in enumerate(scores):
            marks = []
            if i == r.get("winner"):
                marks.append("winner")
            if i in (r.get("dropped") or []):
                marks.append("DROPPED")
            tail = f"  ({', '.join(marks)})" if marks else ""
            sval = f"{s:.6e}" if isinstance(s, (int, float)) else "-"
            out.append(f"  init {i:>3d}  "
                       f"{r.get('criterion', 'score')}={sval}{tail}")
    if selects:
        out.append("")

    if healths or recoveries or io_retries:
        out.append("Health / recovery (docs/ROBUSTNESS.md):")
        for r in healths:
            k = r.get("k")
            names = ",".join(r.get("flag_names") or []) or "?"
            where = r.get("where", "em")
            out.append(f"  health   K={k if k is not None else '-':>4} "
                       f"[{where}] flags=0x{int(r.get('flags', 0)):x} "
                       f"({names})")
        for r in recoveries:
            out.append(f"  recovery K={r.get('k', '-'):>4} "
                       f"attempt={r.get('attempt')} "
                       f"action={r.get('action')} -> {r.get('outcome')}")
        for r in io_retries:
            tail = " GAVE UP" if r.get("gave_up") else ""
            out.append(f"  io_retry {r.get('op')} "
                       f"step={r.get('step', '-')} "
                       f"attempt={r.get('attempt')}: "
                       f"{r.get('error')}{tail}")
        out.append("")

    if preempts or shutdowns or peer_losts or shrinks or resumes:
        out.append("Run lifecycle (preemption; docs/ROBUSTNESS.md):")
        for r in peer_losts:
            out.append(f"  peer_lost rank={r.get('rank')} heartbeat "
                       f"stale {r.get('age_s', '?')}s > timeout "
                       f"{r.get('timeout_s', '?')}s")
        for r in shrinks:
            survivors = r.get("survivors") or []
            lost = ",".join(str(x) for x in (r.get("lost_ranks") or []))
            out.append(f"  elastic_shrink gen={r.get('generation')} -> "
                       f"{r.get('world_size')} host(s) {survivors}"
                       + (f" (lost rank {lost})" if lost else "")
                       + (f" attempt={r['attempt']}"
                          if r.get("attempt") is not None else ""))
        for r in resumes:
            pos = ""
            if r.get("step") is not None:
                pos = f" from step {r['step']}"
                if r.get("k") is not None:
                    pos += f" (K={r['k']})"
            out.append(f"  elastic_resume gen={r.get('generation')} "
                       f"continued the sweep{pos}")
        for r in preempts:
            pos = ""
            if r.get("k") is not None:
                pos = f" at K={r['k']}"
                if r.get("em_iter") is not None:
                    pos += f" iter={r['em_iter']}"
            out.append(f"  preempt  reason={r.get('reason')} "
                       f"[{r.get('where', '?')}]{pos}")
        for r in shutdowns:
            if r.get("checkpointed"):
                pos = ""
                if r.get("step") is not None:
                    pos = f" (step {r['step']}"
                    pos += (f" iter {r['em_iter']})"
                            if r.get("em_iter") is not None else ")")
                ck = "checkpoint durable" + pos
            else:
                ck = "NO checkpoint (not resumable)"
            out.append(f"  shutdown reason={r.get('reason')} -> exit 75, "
                       f"{ck}")
        out.append("")

    for s in summaries:
        prof = s.get("phase_profile") or {}
        if prof.get("seconds"):
            out.append(render_phase_table(prof["seconds"],
                                          prof.get("counts")))
        comp = s.get("compile") or {}
        if comp:
            first = comp.get("first_call_s")
            warm = comp.get("warm_call_s")
            est = comp.get("est_compile_s")
            out.append(
                "Compile/execute split: first call "
                + (f"{first:.3f}s" if first is not None else "-")
                + ", warm call "
                + (f"{warm:.3f}s" if warm is not None else "-")
                + ", est. compile "
                + (f"{est:.3f}s" if est is not None else "-"))
        hs = s.get("health")
        if hs is not None:
            if hs.get("flags"):
                out.append(
                    "Health: flags=0x%x (%s)%s  recoveries=%d io_retries=%d"
                    % (int(hs["flags"]),
                       ",".join(hs.get("flag_names") or []),
                       " FATAL" if hs.get("fatal") else "",
                       int(hs.get("recoveries", 0)),
                       int(hs.get("io_retries", 0))))
            else:
                out.append("Health: clean (all flags zero)")
        el = s.get("elastic")
        if el:
            out.append(
                f"Elastic: generation {el.get('generation')} "
                f"({el.get('world_size')} host(s) at finish, "
                f"{el.get('shrinks', 0)} shrink(s), "
                f"{el.get('resumes', 0)} resume(s))")
        backend = (f"  [backend={s['em_backend']}]"
                   if s.get("em_backend") else "")
        out.append(
            f"Best model: K={s.get('ideal_k')} "
            f"{s.get('criterion', 'score')}={s.get('score'):.6e} "
            f"loglik={s.get('final_loglik'):.6e} "
            f"({s.get('total_iters')} EM iterations, "
            f"{s.get('wall_s'):.2f}s){backend}")
        metrics = s.get("metrics") or {}
        counters = metrics.get("counters")
        if counters:
            out.append("Counters: " + "  ".join(
                f"{k}={v:g}" for k, v in sorted(counters.items())))
        out.append("")

    if not out:
        return "(no telemetry records)"
    return "\n".join(out).rstrip() + "\n"


def report_main(argv=None) -> int:
    """``gmm report <metrics.jsonl>``: render a stream on stdout."""
    import argparse

    from .recorder import read_stream

    p = argparse.ArgumentParser(
        prog="gmm report",
        description="Render a --metrics-file JSONL telemetry stream: phase "
        "profile, loglik trajectory, and model-order sweep summary.")
    p.add_argument("metrics_file", help="JSONL stream from --metrics-file")
    p.add_argument("--validate", action="store_true",
                   help="exit nonzero if any record fails schema validation")
    args = p.parse_args(argv)
    try:
        records = read_stream(args.metrics_file)
    except OSError as e:
        print(f"Cannot read {args.metrics_file!r}: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    if not records:
        print(f"{args.metrics_file}: empty stream", file=sys.stderr)
        return 1
    errors = validate_stream(records)
    for e in errors:
        print(f"schema: {e}", file=sys.stderr)
    print(render_report(records), end="")
    return 1 if (errors and args.validate) else 0
