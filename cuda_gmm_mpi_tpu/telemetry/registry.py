"""Run-scoped metrics registry: counters, gauges, histograms, series.

The numeric complement of the event stream (recorder.py): events answer
"what happened when", the registry answers "how much in total". One
instance lives on each :class:`RunRecorder`; its ``snapshot()`` is folded
into the ``run_summary`` record. Thread-safe -- fused-sweep emissions and
streaming flushes arrive from io_callback / transfer threads.

Instrument kinds:
  counter    monotonically accumulating totals (em_iters, h2d_bytes, ...)
  gauge      last-written value (active_k, first EM call seconds, ...)
  histogram  count/sum/min/max aggregate of observed values (phase spans)
  series     bounded append-only trajectory (active-K across the sweep)
"""

from __future__ import annotations

import threading
from typing import Dict, List

_SERIES_CAP = 4096  # bound memory for arbitrarily long sweeps


class MetricsRegistry:
    """Counters/gauges/histograms/series keyed by flat string names."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Dict[str, float]] = {}
        self._series: Dict[str, List[float]] = {}

    def count(self, name: str, value: float = 1) -> None:
        """Add ``value`` to the counter ``name`` (created at 0)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def gauge(self, name: str, value: float) -> None:
        """Set gauge ``name`` to ``value`` (last write wins)."""
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """Fold ``value`` into histogram ``name`` (count/sum/min/max)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                self._hists[name] = {"count": 1, "sum": value,
                                     "min": value, "max": value}
            else:
                h["count"] += 1
                h["sum"] += value
                h["min"] = min(h["min"], value)
                h["max"] = max(h["max"], value)

    def series(self, name: str, value: float) -> None:
        """Append ``value`` to the bounded trajectory ``name``."""
        with self._lock:
            s = self._series.setdefault(name, [])
            if len(s) < _SERIES_CAP:
                s.append(value)

    def snapshot(self) -> dict:
        """JSON-ready copy of every instrument (empty kinds omitted)."""
        with self._lock:
            out = {}
            if self._counters:
                out["counters"] = dict(self._counters)
            if self._gauges:
                out["gauges"] = dict(self._gauges)
            if self._hists:
                out["histograms"] = {k: dict(v)
                                     for k, v in self._hists.items()}
            if self._series:
                out["series"] = {k: list(v)
                                 for k, v in self._series.items()}
            return out
