"""Run-scoped metrics registry: counters, gauges, histograms, series.

The numeric complement of the event stream (recorder.py): events answer
"what happened when", the registry answers "how much in total". One
instance lives on each :class:`RunRecorder`; its ``snapshot()`` is folded
into the ``run_summary`` record. Thread-safe -- fused-sweep emissions and
streaming flushes arrive from io_callback / transfer threads.

Instrument kinds:
  counter    monotonically accumulating totals (em_iters, h2d_bytes, ...)
  gauge      last-written value (active_k, first EM call seconds, ...)
  histogram  count/sum/min/max aggregate of observed values (phase spans)
  series     bounded append-only trajectory (active-K across the sweep)
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List

_SERIES_CAP = 4096  # bound memory for arbitrarily long sweeps

# Fixed histogram bucket bounds (rev v2.2): one log ladder shared by
# every histogram, wide enough to cover sub-millisecond phase spans and
# multi-second serve latencies in ms alike. The exporter renders these
# as cumulative OpenMetrics ``_bucket{le=...}`` lines so p50/p99 are
# scrapeable; the rollup snapshot() keeps its count/sum/min/max shape
# (run_summary.metrics stays byte-stable).
BUCKET_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                 500.0, 1000.0, 2500.0, 5000.0, 10000.0)


class MetricsRegistry:
    """Counters/gauges/histograms/series keyed by flat string names."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Dict[str, float]] = {}
        # name -> per-bucket (non-cumulative) counts, one slot per
        # BUCKET_BOUNDS entry plus the +Inf overflow slot.
        self._buckets: Dict[str, List[int]] = {}
        self._series: Dict[str, List[float]] = {}

    def count(self, name: str, value: float = 1) -> None:
        """Add ``value`` to the counter ``name`` (created at 0)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def gauge(self, name: str, value: float) -> None:
        """Set gauge ``name`` to ``value`` (last write wins)."""
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """Fold ``value`` into histogram ``name`` (count/sum/min/max,
        plus the fixed BUCKET_BOUNDS bucket counts)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                self._hists[name] = {"count": 1, "sum": value,
                                     "min": value, "max": value}
            else:
                h["count"] += 1
                h["sum"] += value
                h["min"] = min(h["min"], value)
                h["max"] = max(h["max"], value)
            buckets = self._buckets.get(name)
            if buckets is None:
                buckets = self._buckets[name] = \
                    [0] * (len(BUCKET_BOUNDS) + 1)
            buckets[bisect.bisect_left(BUCKET_BOUNDS, value)] += 1

    def series(self, name: str, value: float) -> None:
        """Append ``value`` to the bounded trajectory ``name``."""
        with self._lock:
            s = self._series.setdefault(name, [])
            if len(s) < _SERIES_CAP:
                s.append(value)

    def _snapshot_locked(self) -> dict:
        out = {}
        if self._counters:
            out["counters"] = dict(self._counters)
        if self._gauges:
            out["gauges"] = dict(self._gauges)
        if self._hists:
            out["histograms"] = {k: dict(v)
                                 for k, v in self._hists.items()}
        if self._series:
            out["series"] = {k: list(v)
                             for k, v in self._series.items()}
        return out

    def snapshot(self) -> dict:
        """JSON-ready copy of every instrument (empty kinds omitted)."""
        with self._lock:
            return self._snapshot_locked()

    def snapshot_buckets(self) -> Dict[str, List[int]]:
        """Per-histogram fixed-bucket counts (non-cumulative; one slot
        per BUCKET_BOUNDS bound plus the trailing +Inf slot). Kept out
        of :meth:`snapshot` so the run_summary.metrics payload -- and
        every fixture asserting its exact shape -- stays byte-stable;
        the OpenMetrics exporter is the consumer."""
        with self._lock:
            return {k: list(v) for k, v in self._buckets.items()}

    def snapshot_with_buckets(self) -> tuple:
        """``(snapshot(), snapshot_buckets())`` under ONE lock hold.

        The scrape path needs the pair to agree: taken separately, an
        ``observe()`` landing between the two calls yields a histogram
        whose ``_count`` disagrees with its cumulative ``+Inf`` bucket
        on the same exposition."""
        with self._lock:
            return (self._snapshot_locked(),
                    {k: list(v) for k, v in self._buckets.items()})
