"""``gmm drift``: offline drift analytics against a training envelope.

Stream rev v2.4. The serve-time drift plane (serving/server.py) emits
windowed ``drift`` events while traffic flows; this module is the
offline half of the loop (docs/OBSERVABILITY.md "Drift detection"):
compare a recorded serve stream OR a raw dataset file against the
training envelope a registry version carries, and gate the result for
CI with ``gmm diff``-style ``--fail-on`` specs.

Target grammar (mirrors ``gmm diff``/``gmm timeline``):

* a ``*.jsonl`` file or a directory of per-rank streams is a recorded
  serve stream -- its ``drift`` events' serialized sketches are merged
  (sketch merge is exact, so N windows re-aggregate into one) and the
  merged window is re-scored against the envelope;
* anything else is a raw dataset file (the fit CLI's input formats):
  rows are scored under the registry model through the same
  :class:`~..serving.executor.ScoringExecutor` family the server uses,
  then sketched on the envelope's ladder.

``--rebuild-envelope`` flips the dataset mode from *judging* to
*publishing*: the computed envelope atomically replaces
``envelope.json`` for the (model, version) -- ``model.npz`` and
``manifest.json`` stay bit-identical -- which is how pre-v2.4 registry
versions are backfilled.

Exit-code contract (docs/API.md):

* 0 = clean (no gate tripped; report-only when no ``--fail-on`` given),
* 1 = at least one named gate tripped,
* 2 = usage error / unreadable target / version without an envelope.

Gates are ABSOLUTE (``psi>0.2`` trips when the observed PSI exceeds
0.2); relative ``%`` specs need a baseline run and belong to ``gmm
diff``, so they are rejected here.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List, Optional, Tuple

from . import sketch as tl_sketch
from .diff import FailSpec, stream_files
from .recorder import read_stream

# The metric namespace --fail-on specs may gate on: the keys of
# compare_to_envelope()'s verdict. A typo'd gate that could never trip
# is a silent hole in CI, so unknown metrics are a usage error (unlike
# gmm diff, whose metric space is open-ended).
GATE_METRICS = ("psi", "ks", "occupancy_l1", "window_rows")


def _check_gate(spec: FailSpec, value: Optional[float]) -> Optional[str]:
    """Absolute-threshold gate: a trip message, or None."""
    if value is None:
        return None
    tripped = (value > spec.threshold if spec.op == ">"
               else value < spec.threshold)
    if not tripped:
        return None
    return (f"{spec.metric}: {value:g} (limit "
            f"{spec.op}{spec.threshold:g})")


def _is_stream_target(path: str) -> bool:
    return os.path.isdir(path) or path.endswith(".jsonl")


def _merge_stream(path: str, model: Optional[str],
                  version: Optional[int]
                  ) -> Tuple[str, Optional[int],
                             tl_sketch.StreamSketch, List[int]]:
    """Merge a recorded stream's ``drift`` events into one window.

    Returns (model, version, merged sketch, summed occupancy); raises
    ValueError when the stream carries no usable drift events or spans
    several models and ``--model`` did not disambiguate.
    """
    files = stream_files(path)
    if not files:
        raise ValueError(f"{path}: no *.jsonl streams in directory")
    events: List[dict] = []
    for f in files:
        for r in read_stream(f):
            if not isinstance(r, dict) or r.get("event") != "drift":
                continue
            if model is not None and r.get("model") != model:
                continue
            if version is not None and r.get("version") != version:
                continue
            if r.get("score_sketch"):
                events.append(r)
    if not events:
        raise ValueError(
            f"{path}: no drift events"
            + (f" for model {model!r}" if model else "")
            + " (serve with --drift-interval-s to record them)")
    names = sorted({str(r.get("model")) for r in events})
    if len(names) > 1:
        raise ValueError(
            f"{path}: drift events for several models "
            f"({', '.join(names)}); pick one with --model")
    versions = sorted({r.get("version") for r in events
                       if r.get("version") is not None})
    sk = tl_sketch.StreamSketch.from_dict(events[0]["score_sketch"])
    occ_width = max((len(r.get("occupancy") or []) for r in events),
                    default=0)
    import numpy as np
    occ = np.zeros(max(occ_width, 1), dtype=np.int64)
    for i, r in enumerate(events):
        if i:
            sk.merge(tl_sketch.StreamSketch.from_dict(r["score_sketch"]))
        row = np.asarray(r.get("occupancy") or [], dtype=np.int64)
        occ[:len(row)] += row
    return (names[0], (versions[-1] if len(versions) == 1 else version),
            sk, [int(c) for c in occ])


def _sketch_dataset(path: str, served, bounds
                    ) -> Tuple[tl_sketch.StreamSketch, List[int]]:
    """Score a raw dataset under a registry model (the server's own
    executor family -- same shift, same numeric path) and sketch it on
    ``bounds``."""
    import numpy as np

    from ..io.readers import read_data
    from ..serving.executor import ScoringExecutor

    data = read_data(path)
    if data.ndim != 2 or data.shape[1] != served.d:
        raise ValueError(
            f"{path}: {data.shape} does not match model "
            f"{served.name}@{served.version} (d={served.d})")
    rows = data.astype(np.dtype(served.dtype), copy=False)
    rows = rows - served.data_shift[None, :].astype(rows.dtype)
    ex = ScoringExecutor(dtype=served.dtype, diag_only=served.diag_only)
    sk = tl_sketch.StreamSketch(bounds)
    occ = np.zeros(served.k, dtype=np.int64)
    block = 65536
    for lo in range(0, rows.shape[0], block):
        w, logz = ex.infer(served.state, rows[lo:lo + block],
                           want="proba")
        sk.update(logz)
        occ += np.bincount(np.argmax(w[:, :served.k], axis=1),
                           minlength=served.k)
    return sk, [int(c) for c in occ]


def drift_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="gmm drift",
        description="Compare a recorded serve stream (*.jsonl / stream "
                    "directory) or a raw dataset file against a "
                    "registry version's training envelope; gate on "
                    "PSI/KS/occupancy shift for CI.")
    parser.add_argument("target",
                        help="serve stream (*.jsonl file or per-rank "
                        "stream directory) or raw dataset file")
    parser.add_argument("--registry", required=True, metavar="DIR",
                        help="model registry root (gmm export)")
    parser.add_argument("--model", default=None,
                        help="model name (required for dataset targets; "
                        "inferred from a single-model stream)")
    parser.add_argument("--version", type=int, default=None,
                        help="registry version (default: stream's "
                        "version, else newest)")
    parser.add_argument("--fail-on", action="append", default=[],
                        metavar="SPEC",
                        help="absolute gate over "
                        + "/".join(GATE_METRICS)
                        + ", e.g. 'psi>0.2' or 'window_rows<100'. "
                        "Repeatable; no specs = report-only (exit 0).")
    parser.add_argument("--rebuild-envelope", action="store_true",
                        help="dataset targets only: recompute the "
                        "training envelope from TARGET and atomically "
                        "publish envelope.json for (model, version); "
                        "model.npz and manifest stay bit-identical")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable verdict on stdout")
    parser.add_argument("--device", default=None,
                        help="JAX platform for dataset scoring: tpu | "
                        "cpu | gpu (default: auto; stream targets "
                        "never touch a device)")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    if args.device:
        os.environ["JAX_PLATFORMS"] = args.device
        import jax

        jax.config.update("jax_platforms", args.device)

    specs: List[FailSpec] = []
    try:
        for raw in args.fail_on:
            spec = FailSpec(raw)
            if spec.relative:
                raise ValueError(
                    f"relative spec {raw!r}: gmm drift gates are "
                    f"absolute (use gmm diff for run-vs-run deltas)")
            if spec.metric not in GATE_METRICS:
                raise ValueError(
                    f"unknown drift metric {spec.metric!r} in {raw!r} "
                    f"(choose from {', '.join(GATE_METRICS)})")
            specs.append(spec)
    except ValueError as e:
        print(f"gmm drift: {e}")
        return 2

    from ..serving.registry import ModelRegistry, RegistryError

    stream_mode = _is_stream_target(args.target)
    if not stream_mode and not args.model:
        print("gmm drift: dataset targets need --model")
        return 2
    if args.rebuild_envelope and stream_mode:
        print("gmm drift: --rebuild-envelope needs a raw dataset "
              "target (a serve stream only holds windowed sketches)")
        return 2

    registry = ModelRegistry(args.registry)
    try:
        model_name = args.model
        version = args.version
        if stream_mode:
            model_name, version, sk, occ = _merge_stream(
                args.target, args.model, args.version)
            served = registry.load(model_name, version)
        else:
            served = registry.load(model_name, version)
            if args.rebuild_envelope:
                bounds = tl_sketch.SCORE_BOUNDS
            elif served.envelope and served.envelope.get("score"):
                bounds = served.envelope["score"]["bounds"]
            else:
                bounds = tl_sketch.SCORE_BOUNDS
            sk, occ = _sketch_dataset(args.target, served, bounds)
        version = int(served.version)
        model_name = served.name
    except (OSError, ValueError, RegistryError) as e:
        print(f"gmm drift: {e}")
        return 2

    if args.rebuild_envelope:
        envelope = tl_sketch.make_envelope(
            sk, occ, k=served.k, num_events=sk.count)
        try:
            registry.publish_envelope(model_name, version, envelope)
        except (OSError, RegistryError) as e:
            print(f"gmm drift: {e}")
            return 2
        if args.json:
            print(json.dumps({
                "model": model_name, "version": version,
                "rebuilt": True,
                "envelope": tl_sketch.envelope_stanza(envelope),
            }, sort_keys=True))
        else:
            print(f"gmm drift: rebuilt envelope for "
                  f"{model_name}@{version} from {sk.count} rows "
                  f"(k={served.k}); model.npz/manifest untouched")
        return 0

    envelope = served.envelope
    if not envelope or not envelope.get("score"):
        print(f"gmm drift: {model_name}@{version} has no training "
              f"envelope (refit with envelope=True or backfill via "
              f"gmm drift --rebuild-envelope DATA)")
        return 2

    try:
        stats: Dict[str, float] = tl_sketch.compare_to_envelope(
            envelope, sk, occ)
    except ValueError as e:
        print(f"gmm drift: {e}")
        return 2

    failures = [msg for msg in (_check_gate(s, stats.get(s.metric))
                                for s in specs) if msg is not None]
    verdict = {
        "model": model_name,
        "version": version,
        "source": "stream" if stream_mode else "dataset",
        "target": args.target,
        "train_rows": int(envelope["score"].get("count", 0)),
        "fail_on": [s.raw for s in specs],
        "failures": failures,
        "clean": not failures,
        **stats,
    }
    if args.json:
        print(json.dumps(verdict, sort_keys=True))
        return 1 if failures else 0
    print(f"gmm drift: {model_name}@{version} vs "
          f"{'stream' if stream_mode else 'dataset'} {args.target}")
    print(f"  window_rows  {stats['window_rows']:>10}   "
          f"(envelope: {verdict['train_rows']} rows)")
    for name in ("psi", "ks", "occupancy_l1"):
        print(f"  {name:<12} {stats[name]:>10g}")
    if failures:
        for msg in failures:
            print(f"DRIFT {msg}")
        print(f"{len(failures)} gate(s) tripped")
        return 1
    print(f"clean: no gates tripped ({len(specs)} gates)")
    return 0
