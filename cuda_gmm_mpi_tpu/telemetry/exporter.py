"""OpenMetrics exporter + resource sampler: the pull half of the live plane.

Stream rev v2.1 (docs/OBSERVABILITY.md "Live metrics endpoint"). The JSONL
stream is post-hoc by construction; this module makes the SAME counters
observable while the run is still going, with stdlib only:

* :class:`MetricsExporter` -- a daemon :class:`~http.server.ThreadingHTTPServer`
  serving ``GET /metrics`` in the Prometheus/OpenMetrics text exposition
  format, rendered on demand from a live :class:`~.registry.MetricsRegistry`
  snapshot (counters / gauges / histogram rollups) plus whatever run gauges
  the owning loop provides via a callable (current K, serve queue depth,
  breaker states, elastic generation, ...). An ``em_iters``-rate gauge
  (``gmm_em_iters_per_s``) is derived between scrapes. Enabled via
  ``GMMConfig.metrics_port`` / ``--metrics-port``; port 0 binds an
  OS-assigned ephemeral port (tests; the bound port is on ``.port``).

* :class:`ResourceSampler` -- a daemon thread that periodically stamps
  device ``memory_stats()`` (HBM in-use / peak) and host RSS onto
  ``heartbeat`` records, so memory high-water lands on the stream during
  the run instead of exactly once at ``run_start``.

Both are strictly additive: nothing here starts unless ``metrics_port``
is set, keeping disabled-plane runs byte-identical to pre-v2.1.
"""

from __future__ import annotations

import contextlib
import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from . import recorder as _recorder

CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")

# The most recently started exporter (None when stopped). Lets a caller
# that enabled the plane deep inside a fit (--metrics-port=0 binds an
# OS-assigned port) discover the bound port: tests and bench scrape
# ``current_exporter().port`` instead of plumbing the exporter out
# through every fit signature.
_current: Optional["MetricsExporter"] = None


def current_exporter() -> Optional["MetricsExporter"]:
    """The live exporter, if one is running in this process."""
    return _current

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def metric_name(key: str, prefix: str = "gmm_") -> str:
    """Registry key -> exposition metric name (``serve.latency_ms`` ->
    ``gmm_serve_latency_ms``)."""
    name = _NAME_RE.sub("_", key)
    if not name or not (name[0].isalpha() or name[0] in "_:"):
        name = "_" + name
    return prefix + name


def host_rss_bytes() -> Optional[int]:
    """This process's resident set size, psutil-free.

    ``/proc/self/status`` VmRSS where available (Linux); falls back to
    ``getrusage`` ru_maxrss (a HIGH-WATER mark, not instantaneous -- still
    the right bound for a memory gauge); None where neither works.
    """
    try:
        with open("/proc/self/status", "r", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024
    except Exception:
        return None


def _fmt(value: Any) -> str:
    try:
        f = float(value)
    except (TypeError, ValueError):
        return "0"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_openmetrics(snapshot: Dict[str, Dict[str, Any]],
                       extra_gauges: Optional[Dict[str, Any]] = None,
                       buckets: Optional[Dict[str, Any]] = None) -> str:
    """Registry snapshot (+ run gauges) -> OpenMetrics text exposition.

    Counters become ``gmm_<name>_total``; gauges stay gauges. A
    histogram with fixed-bucket counts available (rev v2.2;
    ``buckets[key]`` = per-bucket counts over
    ``registry.BUCKET_BOUNDS`` + the +Inf slot) renders as a real
    OpenMetrics histogram -- cumulative ``_bucket{le=...}`` lines, so
    serve latency p50/p99 are scrapeable -- with the extremes as
    separate ``_minimum`` / ``_maximum`` gauge families (``_min`` /
    ``_max`` are not valid histogram sample suffixes, and a strict
    parser may reject the whole scrape over them); one without bucket
    counts keeps the old summary rendering, ``_min`` / ``_max`` gauges
    included, byte-identical to pre-v2.2. ``extra_gauges`` keys are
    already full metric names (the owning loop namespaces them). Ends
    with the mandatory ``# EOF``.
    """
    from .registry import BUCKET_BOUNDS

    lines = []
    for key, value in sorted((snapshot.get("counters") or {}).items()):
        name = metric_name(key)
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name}_total {_fmt(value)}")
    for key, value in sorted((snapshot.get("gauges") or {}).items()):
        name = metric_name(key)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt(value)}")
    for key, h in sorted((snapshot.get("histograms") or {}).items()):
        name = metric_name(key)
        counts = (buckets or {}).get(key)
        if counts:
            lines.append(f"# TYPE {name} histogram")
            cum = 0
            for le, n in zip(BUCKET_BOUNDS, counts):
                cum += int(n)
                lines.append(
                    f'{name}_bucket{{le="{_fmt(le)}"}} {cum}')
            cum += int(counts[len(BUCKET_BOUNDS)]) \
                if len(counts) > len(BUCKET_BOUNDS) else 0
            lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
        else:
            lines.append(f"# TYPE {name} summary")
        lines.append(f"{name}_count {_fmt(h.get('count', 0))}")
        lines.append(f"{name}_sum {_fmt(h.get('sum', 0.0))}")
        for agg in ("min", "max"):
            if agg in h:
                # Histogram form: the extremes get family names a strict
                # parser cannot read as suffixed samples of ``name``.
                suffix = agg if not counts else agg + "imum"
                lines.append(f"# TYPE {name}_{suffix} gauge")
                lines.append(f"{name}_{suffix} {_fmt(h[agg])}")
    for key, value in sorted((extra_gauges or {}).items()):
        name = _NAME_RE.sub("_", str(key))
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """``GET /metrics`` endpoint over a live registry.

    ``registry_provider`` returns the CURRENT registry (a callable, not a
    snapshot -- elastic retries swap recorders); ``gauges_provider`` (may
    be None) returns ``{full_metric_name: value}`` run gauges evaluated
    per scrape. Binds localhost by default: an observability endpoint is
    not a public service.
    """

    def __init__(self, registry_provider: Callable[[], Any],
                 gauges_provider: Optional[Callable[[], Dict[str, Any]]] = None,
                 port: int = 0, host: str = "127.0.0.1"):
        self._registry_provider = registry_provider
        self._gauges_provider = gauges_provider
        self._requested = (host, int(port))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._last_scrape: Optional[tuple] = None  # (mono_s, em_iters)
        self.scrapes = 0

    @property
    def port(self) -> Optional[int]:
        """The BOUND port (resolves port 0 after start())."""
        return self._httpd.server_address[1] if self._httpd else None

    def render(self) -> str:
        buckets: Dict[str, Any] = {}
        try:
            registry = self._registry_provider()
            if registry is None:
                snapshot = {}
            else:
                # Fixed-bucket counts (rev v2.2): kept out of snapshot()
                # so run_summary.metrics stays byte-stable; the scrape
                # endpoint is where the buckets surface. One atomic
                # locked read -- a histogram's _count and its cumulative
                # +Inf bucket must agree on the same exposition.
                pair_fn = getattr(registry, "snapshot_with_buckets", None)
                if callable(pair_fn):
                    snapshot, buckets = pair_fn()
                else:
                    snapshot = registry.snapshot()
        except Exception:
            snapshot = {}
        gauges: Dict[str, Any] = {}
        if self._gauges_provider is not None:
            try:
                gauges.update(self._gauges_provider() or {})
            except Exception:
                pass
        # Derived rate: em_iters/s between scrapes (0 until the second
        # scrape -- a rate needs two samples).
        now = time.perf_counter()
        iters = (snapshot.get("counters") or {}).get("em_iters")
        with self._lock:
            self.scrapes += 1
            if iters is not None:
                rate = 0.0
                if self._last_scrape is not None:
                    dt = now - self._last_scrape[0]
                    if dt > 0:
                        rate = max(0.0, (iters - self._last_scrape[1]) / dt)
                self._last_scrape = (now, iters)
                gauges.setdefault("gmm_em_iters_per_s", round(rate, 3))
        return render_openmetrics(snapshot, gauges, buckets)

    def start(self) -> "MetricsExporter":
        if self._httpd is not None:
            return self
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = exporter.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = ThreadingHTTPServer(self._requested, _Handler)
        self._httpd.daemon_threads = True
        httpd = self._httpd
        # Tight poll: serve_forever's default 0.5s poll makes stop()
        # (which joins the shutdown) add up to half a second to every
        # fit's teardown -- visible noise in the --obs overhead A/B.
        self._thread = threading.Thread(
            target=lambda: httpd.serve_forever(poll_interval=0.02),
            name="gmm-metrics-exporter", daemon=True)
        self._thread.start()
        global _current
        _current = self
        return self

    def stop(self) -> None:
        global _current
        if _current is self:
            _current = None
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class ResourceSampler:
    """Periodic memory stamps on the heartbeat lane.

    Emits a ``heartbeat`` record (``sampler: true``) every ``interval_s``
    with host RSS and device ``memory_stats()``, via the recorder's
    thread-safe ``emit`` -- bypassing the liveness heartbeat's rate
    limiter, which exists to keep PASSIVE phases quiet, not to throttle
    an explicitly requested sampler.
    """

    def __init__(self, recorder: Optional[Any] = None,
                 interval_s: float = 10.0, phase: str = "sampler"):
        self._recorder = recorder
        self._interval_s = max(0.05, float(interval_s))
        self._phase = phase
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0

    def _rec(self):
        return (self._recorder if self._recorder is not None
                else _recorder.current())

    def sample_once(self) -> Optional[dict]:
        rec = self._rec()
        if not rec.active:
            return None
        fields: Dict[str, Any] = {"sampler": True}
        rss = host_rss_bytes()
        if rss is not None:
            fields["rss_bytes"] = rss
        stats = _recorder.memory_stats()
        if stats is not None:
            # memory_stats() values are ints already; keep the dict JSON
            # round-trippable even if a plugin hands back numpy scalars.
            fields["memory_stats"] = json.loads(
                json.dumps(stats, default=_recorder._json_default))
        self.samples += 1
        return rec.emit(
            "heartbeat", phase=self._phase,
            elapsed_s=round(time.perf_counter() - rec._t0, 3), **fields)

    def _loop(self):
        # Sample-then-wait: the first stamp lands immediately, so even a
        # run shorter than one interval gets its resource mark.
        while True:
            try:
                self.sample_once()
            except Exception:
                # The sampler must never take the run down: a flaky
                # device-stats plugin degrades to missing samples.
                pass
            if self._stop.wait(self._interval_s):
                return

    def start(self) -> "ResourceSampler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="gmm-resource-sampler", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


@contextlib.contextmanager
def live_plane(port: Optional[int],
               registry_provider: Callable[[], Any],
               gauges_provider: Optional[Callable[[], Dict[str, Any]]] = None,
               recorder: Optional[Any] = None,
               sampler_interval_s: float = 10.0):
    """The one-call composition every long-running path uses: exporter +
    resource sampler, both on iff ``port`` is not None (the
    ``--metrics-port`` gate). Yields the exporter (None when disabled)."""
    if port is None:
        yield None
        return
    import os

    # Tests and the --obs benchmark shrink the cadence without plumbing
    # an interval through every fit signature.
    sampler_interval_s = float(
        os.environ.get("GMM_SAMPLER_INTERVAL_S") or sampler_interval_s)
    with MetricsExporter(registry_provider, gauges_provider,
                         port=port) as exporter:
        with ResourceSampler(recorder, interval_s=sampler_interval_s):
            yield exporter
