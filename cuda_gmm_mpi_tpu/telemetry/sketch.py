"""Mergeable streaming summaries + drift statistics (stream rev v2.4).

The drift-observability substrate (docs/OBSERVABILITY.md "Drift
detection"): a :class:`StreamSketch` is a small, serializable summary of
a value stream -- a fixed-log-bucket histogram (the same bisect-ladder
scheme as ``registry.BUCKET_BOUNDS``, extended symmetrically so signed
per-event log-likelihoods land in resolved buckets), exact count /
min / max, and Welford mean/M2 moments -- built so that sketches MERGE:
``merge(a, b)`` over any split of a stream reproduces the one-shot
sketch (bucket counts, count, min, max exactly; mean/M2 via Chan's
parallel formulas, associative up to float rounding). Per-rank,
per-window, and per-tenant sketches therefore compose into one, which
is what lets a training envelope be assembled across hosts and a serve
stream be re-aggregated offline by ``gmm drift``.

Everything here is numpy + stdlib on purpose: sketches are built on the
serve hot path and parsed by offline CLI tools, neither of which should
pull in jax.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from .registry import BUCKET_BOUNDS

# Symmetric ladder over the BUCKET_BOUNDS decades: per-event
# log-likelihood scores are signed (densities above/below 1), so the
# positive latency ladder alone would dump every negative score into one
# underflow slot. 45 finite bounds + the trailing +Inf slot.
SCORE_BOUNDS: tuple = (tuple(-b for b in reversed(BUCKET_BOUNDS))
                       + (0.0,) + tuple(BUCKET_BOUNDS))

ENVELOPE_VERSION = 1

# Proportion floor for PSI: empty buckets would make ln(q/p) blow up, so
# both distributions are clamped elementwise to this before the sum --
# the standard PSI stabilizer, and part of the pinned-fixture contract.
PSI_EPS = 1e-6


class StreamSketch:
    """Mergeable streaming summary: log-bucket histogram + moments.

    Buckets follow ``MetricsRegistry.observe``'s ladder semantics:
    bucket ``i`` counts values ``<= bounds[i]`` (``searchsorted`` left),
    with one trailing overflow slot. Non-finite inputs are dropped (they
    are accounted separately by the health machinery, not the sketch).
    """

    __slots__ = ("bounds", "count", "mean", "m2", "vmin", "vmax",
                 "buckets")

    def __init__(self, bounds: Sequence[float] = SCORE_BOUNDS):
        self.bounds = tuple(float(b) for b in bounds)
        self.count = 0
        self.mean = 0.0
        self.m2 = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.buckets: List[int] = [0] * (len(self.bounds) + 1)

    def update(self, values) -> "StreamSketch":
        """Fold a batch of values in (vectorized; returns self)."""
        x = np.asarray(values, dtype=np.float64).reshape(-1)
        x = x[np.isfinite(x)]
        n = int(x.size)
        if n == 0:
            return self
        idx = np.searchsorted(self.bounds, x, side="left")
        for i, c in zip(*np.unique(idx, return_counts=True)):
            self.buckets[int(i)] += int(c)
        # Chan's parallel-update formulas with the batch as one summary:
        # exactly the pairwise merge below, so update-then-merge and
        # merge-then-update agree.
        b_mean = float(x.mean())
        b_m2 = float(np.sum((x - b_mean) ** 2))
        total = self.count + n
        delta = b_mean - self.mean
        self.m2 += b_m2 + delta * delta * self.count * n / total
        self.mean += delta * n / total
        self.count = total
        self.vmin = min(self.vmin, float(x.min()))
        self.vmax = max(self.vmax, float(x.max()))
        return self

    def merge(self, other: "StreamSketch") -> "StreamSketch":
        """Fold another sketch in (same bounds required; returns self)."""
        if tuple(other.bounds) != self.bounds:
            raise ValueError(
                f"cannot merge sketches with different bucket ladders "
                f"({len(other.bounds)} vs {len(self.bounds)} bounds)")
        if other.count == 0:
            return self
        total = self.count + other.count
        delta = other.mean - self.mean
        self.m2 += other.m2 + delta * delta * self.count * other.count / total
        self.mean += delta * other.count / total
        self.count = total
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        for i, c in enumerate(other.buckets):
            self.buckets[i] += c
        return self

    @property
    def variance(self) -> float:
        return self.m2 / self.count if self.count > 1 else 0.0

    def proportions(self) -> np.ndarray:
        """Normalized bucket mass [len(bounds)+1] (zeros when empty)."""
        counts = np.asarray(self.buckets, dtype=np.float64)
        total = counts.sum()
        return counts / total if total > 0 else counts

    def to_dict(self) -> dict:
        """JSON-ready form; carries its own ladder so a reader aligns
        observed sketches to an envelope's buckets without guessing."""
        return {
            "bounds": list(self.bounds),
            "count": int(self.count),
            "mean": float(self.mean),
            "m2": float(self.m2),
            "min": (float(self.vmin) if self.count else None),
            "max": (float(self.vmax) if self.count else None),
            "buckets": list(self.buckets),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "StreamSketch":
        sk = cls(bounds=d["bounds"])
        sk.count = int(d["count"])
        sk.mean = float(d["mean"])
        sk.m2 = float(d["m2"])
        sk.vmin = float(d["min"]) if d.get("min") is not None else math.inf
        sk.vmax = float(d["max"]) if d.get("max") is not None else -math.inf
        buckets = [int(c) for c in d["buckets"]]
        if len(buckets) != len(sk.buckets):
            raise ValueError(
                f"sketch has {len(buckets)} buckets for "
                f"{len(sk.bounds)} bounds")
        sk.buckets = buckets
        return sk


def _clamped_props(counts) -> np.ndarray:
    counts = np.asarray(counts, dtype=np.float64)
    total = counts.sum()
    p = counts / total if total > 0 else counts
    return np.maximum(p, PSI_EPS)


def psi(expected_buckets, observed_buckets) -> float:
    """Population stability index between two bucket-count vectors.

    ``sum((q - p) * ln(q / p))`` over proportions clamped to
    ``PSI_EPS``; >= 0, with 0 iff the clamped distributions agree.
    Conventional reading: < 0.1 stable, 0.1-0.25 moderate shift,
    > 0.25 major shift.
    """
    p = _clamped_props(expected_buckets)
    q = _clamped_props(observed_buckets)
    if len(p) != len(q):
        raise ValueError(f"bucket count mismatch: {len(p)} vs {len(q)}")
    return float(np.sum((q - p) * np.log(q / p)))


def ks(expected_buckets, observed_buckets) -> float:
    """Kolmogorov-Smirnov statistic over the shared bucket ladder:
    max |CDF_p - CDF_q| of the normalized bucket masses, in [0, 1]."""
    p = np.asarray(expected_buckets, dtype=np.float64)
    q = np.asarray(observed_buckets, dtype=np.float64)
    if len(p) != len(q):
        raise ValueError(f"bucket count mismatch: {len(p)} vs {len(q)}")
    p = p / p.sum() if p.sum() > 0 else p
    q = q / q.sum() if q.sum() > 0 else q
    return float(np.max(np.abs(np.cumsum(p) - np.cumsum(q))))


def occupancy_l1(expected_counts, observed_counts) -> float:
    """L1 distance between normalized per-cluster occupancy vectors,
    in [0, 2]. A K mismatch zero-pads the shorter side (a served
    model's K never changes within a version, but offline comparisons
    may cross rebuilt envelopes)."""
    p = np.asarray(expected_counts, dtype=np.float64).reshape(-1)
    q = np.asarray(observed_counts, dtype=np.float64).reshape(-1)
    width = max(len(p), len(q), 1)
    p = np.pad(p, (0, width - len(p)))
    q = np.pad(q, (0, width - len(q)))
    p = p / p.sum() if p.sum() > 0 else p
    q = q / q.sum() if q.sum() > 0 else q
    return float(np.sum(np.abs(p - q)))


def make_envelope(score_sketch: StreamSketch, occupancy,
                  *, k: int, num_events: int) -> dict:
    """The training envelope: the fit-time score sketch + per-cluster
    responsibility occupancy counts, as persisted in ``envelope.json``
    and ``run_summary.envelope``."""
    return {
        "version": ENVELOPE_VERSION,
        "score": score_sketch.to_dict(),
        "occupancy": [int(c) for c in np.asarray(occupancy).reshape(-1)],
        "k": int(k),
        "num_events": int(num_events),
    }


def merge_envelopes(envelopes: Sequence[dict]) -> Optional[dict]:
    """Fold per-rank/per-shard envelopes into one (None if none valid).
    Occupancy vectors must agree on K (same compacted model)."""
    parts = [e for e in envelopes if e and e.get("score")]
    if not parts:
        return None
    sk = StreamSketch.from_dict(parts[0]["score"])
    occ = np.asarray(parts[0]["occupancy"], dtype=np.int64)
    for e in parts[1:]:
        sk.merge(StreamSketch.from_dict(e["score"]))
        occ = occ + np.asarray(e["occupancy"], dtype=np.int64)
    return make_envelope(
        sk, occ, k=int(parts[0]["k"]),
        num_events=sum(int(e["num_events"]) for e in parts))


def envelope_stanza(envelope: dict) -> dict:
    """The small manifest ``envelope`` stanza (registry manifest.json):
    enough to see an envelope exists and its shape without reading
    ``envelope.json``."""
    score = envelope.get("score", {}) or {}
    return {
        "version": int(envelope.get("version", ENVELOPE_VERSION)),
        "rows": int(score.get("count", 0)),
        "k": int(envelope.get("k", 0)),
        "buckets": len(score.get("buckets", [])),
        "mean_score": score.get("mean"),
    }


def compare_to_envelope(envelope: dict, score_sketch: StreamSketch,
                        occupancy) -> Dict[str, float]:
    """The drift statistics of one observed window vs a training
    envelope -- the payload of a ``drift`` event and of the ``gmm
    drift`` verdict. The observed sketch is aligned to the envelope's
    ladder by construction (serve builds windows from the envelope's
    bounds); a ladder mismatch raises."""
    ref = StreamSketch.from_dict(envelope["score"])
    if tuple(score_sketch.bounds) != tuple(ref.bounds):
        raise ValueError("observed sketch ladder != envelope ladder")
    return {
        "psi": round(psi(ref.buckets, score_sketch.buckets), 6),
        "ks": round(ks(ref.buckets, score_sketch.buckets), 6),
        "occupancy_l1": round(occupancy_l1(
            envelope.get("occupancy", []), occupancy), 6),
        "window_rows": int(score_sketch.count),
    }
