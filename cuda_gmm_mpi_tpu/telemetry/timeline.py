"""Unified timeline: Perfetto/Chrome trace export with clock alignment.

Stream rev v2.3 (docs/OBSERVABILITY.md "Timeline export"). The recorded
streams are rich but flat: spans, per-iteration EM records, chunk
flushes, serve batches, compile events, resource heartbeats -- each
stamped with ``mono_s``, a clock comparable only *within* one process.
This module is the glue that turns one or more streams (a single file, a
per-rank directory, or a fit stream and a serve stream together) into
ONE Chrome trace-event JSON document that Perfetto / ``chrome://tracing``
loads directly -- the standard operator answer to "where did the time go
across ranks".

Event mapping (the full table lives in docs/OBSERVABILITY.md):

* ``span`` records -> nested ``X`` (complete) duration events, one
  Perfetto track per (stream = pid, emitting thread = tid);
* ``em_iter`` / ``chunk_flush`` / ``serve_batch`` / ``serve_request`` /
  ``http_request`` / ``compile`` -> ``X`` slices with args (loglik,
  prefetch wait, batch rows, HTTP status, flops), each ending at its
  record's emission time;
* sampler ``heartbeat`` resource stamps and stream-derived rates ->
  ``C`` counter tracks (host RSS, device bytes, EM iters/s, queued
  rows), and rev v2.4 ``drift`` windows -> per-model PSI/KS counter
  tracks;
* ``health`` / ``preempt`` / ``elastic_shrink`` / ``circuit`` /
  ``drift_alarm`` / ... -> instant events;
* serve ``trace_id`` s -> flow arrows (``s``/``f``) joining a client's
  request slice to the server-side ``serve_route`` span that answered
  it.

Cross-stream alignment: each stream's records are placed on one shared
wall-clock timebase by estimating the stream's mono->wall mapping
``wall ~= a * mono_s + b`` from its v2.3 ``clock``/``clock0`` anchor
pairs (atomically-sampled wall+mono, emitted at the stream head and on
every heartbeat -- telemetry/recorder.py). With two or more anchors
spread over enough run time the slope ``a`` absorbs clock drift (skew
correction); with one anchor the offset ``b`` alone aligns the stream.
Pre-v2.3 streams fall back to per-record ``(ts, mono_s)`` pairs -- the
same arithmetic but anchored on non-atomic samples -- and the export is
loudly marked ``alignment: estimated`` (metadata + stderr banner).
Records with no ``mono_s`` at all use raw ``ts``.

``gmm timeline`` is the CLI (cli.py); exit codes 0 = exported (and, with
``--validate``, structurally clean), 1 = the emitted document failed its
own ``--validate`` oracle (an exporter bug, not a user error), 2 = usage
error / unreadable stream. ``validate_trace`` is the structural oracle
the tests and ``bench.py --timeline`` reuse.
"""

from __future__ import annotations

import json
import math
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from .recorder import read_stream

# Mono-anchor spread below which slope fitting is numerically
# meaningless: with anchors closer than this, drift cannot be told from
# sampling noise, so alignment falls back to a pure offset (a = 1).
MIN_SKEW_SPAN_S = 0.5

# Sanity clamp on the fitted mono->wall slope. Real oscillator drift is
# parts-per-million; anything outside this band means corrupted anchors
# (or a fixture deliberately abusing them), and a wild slope would smear
# every event, so the fit degrades to offset-only instead.
MAX_SKEW = 0.05

# Fixed per-pid tid layout for non-span tracks (span tracks take
# 1..99, one per emitting OS thread, in first-seen order).
_TID_EM = 100        # em_iter / chunk_flush slices
_TID_SERVE = 110     # serve_request / serve_batch slices
_TID_COMPILE = 120   # compile slices
_TID_EVENTS = 130    # instant events

# Record kinds rendered as instant events on the "events" track. The
# remaining kinds (run_start, summaries, em_done, ...) are process-scope
# instants: one-per-run marks rather than moments inside a phase.
_THREAD_INSTANTS = frozenset((
    "health", "recovery", "io_retry", "preempt", "shutdown", "peer_lost",
    "elastic_shrink", "elastic_resume", "circuit", "serve_shed",
    "serve_deadline", "serve_reload", "merge", "rebucket",
    "drift_alarm", "lifecycle", "registry_torn",
    "worker_spawn", "worker_exit",
))
_PROCESS_INSTANTS = frozenset((
    "run_start", "run_summary", "serve_summary", "fleet_start",
    "fleet_summary", "em_done", "tenant_done", "ingest_start",
    "ingest_summary", "restart_select",
))

# Slice args copied verbatim (when present) from the source record.
_SLICE_ARGS = {
    "span": ("k", "status", "trace_id", "span_id", "parent_id"),
    "em_iter": ("k", "iter", "loglik", "delta", "epsilon", "timing"),
    "chunk_flush": ("k", "iter", "block", "chunks", "bytes",
                    "prefetch_wait_s", "compute_s"),
    "serve_batch": ("model", "requests", "rows", "padded_rows",
                    "compiled", "stacked", "version"),
    "serve_request": ("model", "op", "n", "ok", "error", "trace_id",
                      "version"),
    "http_request": ("method", "path", "status", "model", "op", "n",
                     "error", "worker", "retried", "trace_id"),
    "compile": ("source", "site", "phase", "key", "flops",
                "bytes_accessed", "argument_bytes", "output_bytes"),
}


def _num(value) -> Optional[float]:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    f = float(value)
    return f if math.isfinite(f) else None


def _median(values: List[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    return vs[n // 2] if n % 2 else (vs[n // 2 - 1] + vs[n // 2]) / 2.0


# ---------------------------------------------------------------- alignment


def _anchor_pairs(records: List[dict]) -> List[Tuple[float, float]]:
    """The stream's (mono, wall) alignment anchors from v2.3
    ``clock``/``clock0`` envelope pairs, deduped and mono-sorted."""
    pairs = set()
    for r in records:
        for field in ("clock0", "clock"):
            c = r.get(field)
            if not isinstance(c, dict):
                continue
            mono, wall = _num(c.get("mono")), _num(c.get("wall"))
            if mono is not None and wall is not None:
                pairs.add((mono, wall))
    return sorted(pairs)


def fit_alignment(records: List[dict]) -> dict:
    """Estimate one stream's mono->wall mapping ``wall ~= a*mono + b``.

    Returns ``{"a", "b", "mode", "anchors", "residual_s"}`` where mode is
    ``clock`` (v2.3 atomic anchors), ``estimated`` (pre-v2.3 fallback on
    per-record ``(ts, mono_s)`` pairs), or ``wall`` (no ``mono_s`` at
    all: records map through raw ``ts``, a/b unused). ``residual_s`` is
    the worst anchor's distance from the fit -- the alignment tolerance a
    reader can hold the merge to (heartbeat-pair tolerance).
    """
    pairs = _anchor_pairs(records)
    mode = "clock"
    if not pairs:
        mode = "estimated"
        seen = set()
        for r in records:
            mono, wall = _num(r.get("mono_s")), _num(r.get("ts"))
            if mono is not None and wall is not None:
                seen.add((mono, wall))
        pairs = sorted(seen)
    if not pairs:
        return {"a": 1.0, "b": 0.0, "mode": "wall", "anchors": 0,
                "residual_s": 0.0}
    a = 1.0
    span = pairs[-1][0] - pairs[0][0]
    if len(pairs) >= 2 and span >= MIN_SKEW_SPAN_S:
        # Least-squares slope over the anchors: absorbs mono-vs-wall
        # drift (skew) across a long run. Clamped -- a slope far from 1
        # means garbage anchors, where offset-only alignment is the
        # honest answer.
        mono_mean = sum(m for m, _ in pairs) / len(pairs)
        wall_mean = sum(w for _, w in pairs) / len(pairs)
        var = sum((m - mono_mean) ** 2 for m, _ in pairs)
        if var > 0.0:
            slope = sum((m - mono_mean) * (w - wall_mean)
                        for m, w in pairs) / var
            if abs(slope - 1.0) <= MAX_SKEW:
                a = slope
    b = _median([w - a * m for m, w in pairs])
    residual = max(abs(a * m + b - w) for m, w in pairs)
    return {"a": a, "b": b, "mode": mode, "anchors": len(pairs),
            "residual_s": round(residual, 6)}


def _wall_of(rec: dict, align: dict) -> Optional[float]:
    """One record's emission time on the shared wall timebase."""
    mono = _num(rec.get("mono_s"))
    if mono is not None and align["mode"] != "wall":
        return align["a"] * mono + align["b"]
    return _num(rec.get("ts"))


# ------------------------------------------------------------- trace build


class _Stream:
    """One loaded stream file: its records, alignment, and pid."""

    __slots__ = ("label", "path", "records", "align", "pid", "rank",
                 "tag")

    def __init__(self, label: str, path: str, records: List[dict]):
        self.label = label
        self.path = path
        self.records = records
        self.align = fit_alignment(records)
        self.pid = 0  # assigned by build_timeline
        rank = None
        tag = None
        for r in records:
            if rank is None:
                rank = r.get("rank", r.get("process"))
            if tag is None and isinstance(r.get("path"), str):
                tag = r["path"]
            if rank is not None and tag is not None:
                break
        self.rank = rank if isinstance(rank, int) else 0
        self.tag = tag or "run"


def load_streams(targets: List[str]) -> List[_Stream]:
    """Load every stream behind the targets (files and/or per-rank
    directories). Raises OSError/ValueError on unreadable or empty
    input -- the CLI's exit-2 class."""
    from .diff import stream_files

    streams: List[_Stream] = []
    for target in targets:
        files = stream_files(target)
        if not files:
            raise ValueError(f"{target}: no *.jsonl streams in directory")
        for f in files:
            records = [r for r in read_stream(f) if isinstance(r, dict)]
            if not records:
                raise ValueError(f"{f}: empty stream")
            if not any("event" in r for r in records):
                raise ValueError(f"{f}: not a telemetry stream "
                                 f"(no 'event' records)")
            label = os.path.basename(f)
            if label.endswith(".jsonl"):
                label = label[:-len(".jsonl")]
            if os.path.isdir(target):
                label = f"{os.path.basename(os.path.normpath(target))}/" \
                        f"{label}"
            streams.append(_Stream(label, f, records))
    if not streams:
        raise ValueError("no input streams")
    return streams


def _us(wall: float, t0: float) -> float:
    """Wall seconds -> trace microseconds relative to the export origin."""
    return round((wall - t0) * 1e6, 3)


def _args_for(rec: dict, kind: str) -> Dict[str, Any]:
    out = {}
    for field in _SLICE_ARGS.get(kind, ()):
        if rec.get(field) is not None:
            out[field] = rec[field]
    return out


def _slice_of(rec: dict, align: dict) -> Optional[Tuple[float, float]]:
    """(start_wall, duration_s) of one sliceable record, or None.

    Every slice-shaped record is emitted at its END, carrying its own
    measured duration -- except spans, whose ``t0_mono_s`` start is
    exact on the stream's mono clock.
    """
    kind = rec.get("event")
    if kind == "span":
        dur = _num(rec.get("duration_s")) or 0.0
        t0_mono = _num(rec.get("t0_mono_s"))
        if t0_mono is not None and align["mode"] != "wall":
            return align["a"] * t0_mono + align["b"], dur
        end = _wall_of(rec, align)
        return (end - dur, dur) if end is not None else None
    if kind == "em_iter":
        dur = _num(rec.get("wall_s")) or 0.0
    elif kind == "chunk_flush":
        dur = ((_num(rec.get("prefetch_wait_s")) or 0.0)
               + (_num(rec.get("compute_s")) or 0.0))
    elif kind == "serve_batch":
        dur = (_num(rec.get("wall_ms")) or 0.0) / 1e3
    elif kind in ("serve_request", "http_request"):
        dur = (_num(rec.get("latency_ms")) or 0.0) / 1e3
    elif kind == "compile":
        dur = _num(rec.get("seconds")) or 0.0
    else:
        return None
    end = _wall_of(rec, align)
    return (end - dur, dur) if end is not None else None


def build_timeline(targets: List[str]) -> dict:
    """Merge the targets' streams into one Chrome trace-event document.

    Returns ``{"traceEvents": [...], "displayTimeUnit": "ms",
    "metadata": {...}}``. ``metadata.alignment`` is ``clock`` only when
    EVERY stream carried v2.3 anchors; any fallback stream demotes the
    whole export to ``estimated`` (the banner the CLI prints).
    """
    streams = load_streams(targets)

    # pids: stable rank-major order; collisions (a fit stream and a
    # serve stream both rank 0) get distinct pids by stream order.
    streams.sort(key=lambda s: (s.rank, s.label))
    for i, s in enumerate(streams):
        s.pid = i + 1

    # The export origin: the earliest aligned moment across all streams.
    # Slice STARTS can precede every emission time (the root fit span
    # opens before run_start is written), so the scan covers both.
    t0 = None
    for s in streams:
        for r in s.records:
            w = _wall_of(r, s.align)
            sliced = _slice_of(r, s.align)
            if sliced is not None:
                w = sliced[0] if w is None else min(w, sliced[0])
            if w is not None and (t0 is None or w < t0):
                t0 = w
    if t0 is None:
        raise ValueError("no timestamped records in any stream")

    events: List[dict] = []
    flows_s: List[dict] = []   # serve_request flow starts by trace_id
    span_index: Dict[str, List[dict]] = {}  # trace_id -> span events

    for s in streams:
        a = s.align
        rank_name = f"rank {s.rank}" if s.tag != "serve" else "serve"
        events.append({"ph": "M", "name": "process_name", "pid": s.pid,
                       "args": {"name": f"{rank_name} · {s.label} "
                                        f"[{s.tag}]"}})
        span_tids: Dict[Any, int] = {}
        used_tracks = set()
        prev_em: Optional[Tuple[float, float]] = None  # (wall, iter rate)

        def track(tid: int, name: str) -> int:
            if tid not in used_tracks:
                used_tracks.add(tid)
                events.append({"ph": "M", "name": "thread_name",
                               "pid": s.pid, "tid": tid,
                               "args": {"name": name}})
            return tid

        for rec in s.records:
            kind = rec.get("event")
            if not isinstance(kind, str):
                continue
            wall = _wall_of(rec, a)
            if wall is None:
                continue

            if kind == "span":
                thread = rec.get("thread", 0)
                if thread not in span_tids:
                    span_tids[thread] = 1 + len(span_tids)
                tid = track(span_tids[thread],
                            "spans" if len(span_tids) == 1 and thread == 0
                            else f"spans (thread {thread})")
                start, dur = _slice_of(rec, a)
                ev = {"ph": "X", "name": str(rec.get("name", "span")),
                      "cat": "span", "pid": s.pid, "tid": tid,
                      "ts": _us(start, t0), "dur": round(dur * 1e6, 3),
                      "args": _args_for(rec, kind)}
                events.append(ev)
                tid_key = rec.get("trace_id")
                if isinstance(tid_key, str):
                    span_index.setdefault(tid_key, []).append(ev)
                continue

            sliced = _slice_of(rec, a)
            if sliced is not None:
                start, dur = sliced
                if kind in ("em_iter", "chunk_flush"):
                    tid = track(_TID_EM, "em")
                elif kind in ("serve_request", "serve_batch",
                              "http_request"):
                    tid = track(_TID_SERVE, "serve")
                else:
                    tid = track(_TID_COMPILE, "compile")
                name = kind
                if kind == "em_iter":
                    name = f"em_iter k={rec.get('k')}"
                elif kind == "compile":
                    name = f"compile:{rec.get('site') or rec.get('source')}"
                elif kind == "serve_request":
                    name = f"serve:{rec.get('op', 'request')}"
                elif kind == "http_request":
                    name = (f"http:{rec.get('op')}" if rec.get("op")
                            else f"http:{rec.get('path', 'request')}")
                ev = {"ph": "X", "name": name, "cat": kind, "pid": s.pid,
                      "tid": tid, "ts": _us(start, t0),
                      "dur": round(dur * 1e6, 3),
                      "args": _args_for(rec, kind)}
                events.append(ev)
                if kind in ("serve_request", "http_request") \
                        and isinstance(rec.get("trace_id"), str):
                    flows_s.append({"ph": "s", "cat": "serve",
                                    "name": "request",
                                    "id": rec["trace_id"], "pid": s.pid,
                                    "tid": tid, "ts": ev["ts"]})
                if kind == "em_iter":
                    # Stream-derived rate counter: iters/s from
                    # consecutive emission deltas (the registry's
                    # em_iters counter, differentiated).
                    if prev_em is not None and wall > prev_em[0]:
                        events.append({
                            "ph": "C", "name": "em iters/s",
                            "pid": s.pid, "ts": _us(wall, t0),
                            "args": {"iters_per_s": round(
                                1.0 / (wall - prev_em[0]), 3)}})
                    prev_em = (wall, dur)
                continue

            ts = _us(wall, t0)
            if kind == "heartbeat":
                rss = _num(rec.get("rss_bytes"))
                if rss is not None:
                    events.append({"ph": "C", "name": "host RSS bytes",
                                   "pid": s.pid, "ts": ts,
                                   "args": {"rss_bytes": rss}})
                mem = rec.get("memory_stats") or {}
                dev = _num(mem.get("bytes_in_use")) \
                    if isinstance(mem, dict) else None
                if dev is not None:
                    events.append({"ph": "C", "name": "device bytes",
                                   "pid": s.pid, "ts": ts,
                                   "args": {"bytes_in_use": dev}})
                continue
            if kind == "drift":
                # Drift windows (rev v2.4) -> per-model PSI/KS counter
                # tracks: distribution shift against time, next to the
                # serve slices that produced it.
                model = rec.get("model", "?")
                for field in ("psi", "ks"):
                    v = _num(rec.get(field))
                    if v is not None:
                        events.append({
                            "ph": "C", "name": f"drift {field} ({model})",
                            "pid": s.pid, "ts": ts, "args": {field: v}})
                continue
            if kind == "serve_shed":
                queued = _num(rec.get("queued_rows"))
                if queued is not None:
                    events.append({"ph": "C", "name": "queued rows",
                                   "pid": s.pid, "ts": ts,
                                   "args": {"queued_rows": queued}})
            if kind in _THREAD_INSTANTS or kind in _PROCESS_INSTANTS:
                scope = "p" if kind in _PROCESS_INSTANTS else "t"
                args = {k: v for k, v in rec.items()
                        if k not in ("event", "schema", "ts", "mono_s",
                                     "run_id", "process", "clock",
                                     "clock0")
                        and isinstance(v, (str, int, float, bool))}
                events.append({"ph": "i", "name": kind, "cat": kind,
                               "pid": s.pid,
                               "tid": track(_TID_EVENTS, "events"),
                               "ts": ts, "s": scope, "args": args})

    # Flow arrows: a client's serve_request slice -> the server-side
    # serve_route span tree that answered it (same trace_id, possibly a
    # different stream). Only emitted as a PAIR -- an unpaired flow
    # start is a validation error by design.
    n_flows = 0
    for flow in flows_s:
        spans = span_index.get(flow["id"])
        if not spans:
            continue
        root = min(spans, key=lambda e: e["ts"])
        events.append(flow)
        events.append({"ph": "f", "bp": "e", "cat": "serve",
                       "name": "request", "id": flow["id"],
                       "pid": root["pid"], "tid": root["tid"],
                       "ts": max(root["ts"], flow["ts"])})
        n_flows += 1

    # Per-track monotone order: metadata first, then time order with
    # enclosing slices before their children (longer dur wins ties).
    events.sort(key=lambda e: (0 if e["ph"] == "M" else 1,
                               e.get("ts", 0.0), -e.get("dur", 0.0)))

    modes = {s.align["mode"] for s in streams}
    alignment = "clock" if modes == {"clock"} else "estimated"
    meta = {
        "alignment": alignment,
        "origin_wall_s": round(t0, 6),
        "streams": [{
            "label": s.label, "pid": s.pid, "rank": s.rank,
            "path": s.tag, "records": len(s.records),
            "alignment": s.align["mode"],
            "anchors": s.align["anchors"],
            "skew": round(s.align["a"] - 1.0, 9),
            "residual_s": s.align["residual_s"],
        } for s in streams],
        "flow_count": n_flows,
    }
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": meta}


def summarize_trace(doc: dict) -> dict:
    """Event/track/flow counts of one trace document (bench + CLI)."""
    evs = doc.get("traceEvents") or []
    tracks = {(e.get("pid"), e.get("tid")) for e in evs
              if e.get("ph") in ("X", "B", "E", "i")}
    return {
        "events": sum(1 for e in evs if e.get("ph") != "M"),
        "slices": sum(1 for e in evs if e.get("ph") == "X"),
        "instants": sum(1 for e in evs if e.get("ph") == "i"),
        "counters": sum(1 for e in evs if e.get("ph") == "C"),
        "flows": sum(1 for e in evs if e.get("ph") == "s"),
        "tracks": len(tracks),
        "pids": len({e.get("pid") for e in evs}),
        "alignment": (doc.get("metadata") or {}).get("alignment"),
    }


# -------------------------------------------------------------- validation


_KNOWN_PH = frozenset("MXBEiICsft")


def validate_trace(doc: Any) -> List[str]:
    """Structural errors of one trace-event document ([] = clean).

    The oracle ``--validate`` and the tests hold every export to:
    nonzero event count; known phase letters; ``X`` slices with
    nonnegative durations; matched ``B``/``E`` per track (this exporter
    is X-only, but hand-edited traces stay checkable); per-track
    non-decreasing timestamps in file order (Perfetto tolerates disorder,
    but an out-of-order export means broken alignment arithmetic); and
    every flow id carrying both its start and its finish, in order.
    """
    errors: List[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, not an object"]
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents missing or not a list"]
    real = [e for e in evs if isinstance(e, dict) and e.get("ph") != "M"]
    if not real:
        errors.append("no events (only metadata or empty)")
    last_ts: Dict[Tuple[Any, Any], float] = {}
    be_stack: Dict[Tuple[Any, Any], int] = {}
    flow_s: Dict[Any, float] = {}
    flow_f: Dict[Any, float] = {}
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            errors.append(f"event {i}: not an object")
            continue
        ph = e.get("ph")
        if ph not in _KNOWN_PH:
            errors.append(f"event {i}: unknown ph {ph!r}")
            continue
        if ph == "M":
            continue
        if "pid" not in e:
            errors.append(f"event {i}: missing pid")
        ts = _num(e.get("ts"))
        if ts is None or ts < 0:
            errors.append(f"event {i} ({ph}): bad ts {e.get('ts')!r}")
            continue
        key = (e.get("pid"), e.get("tid"))
        if ph in ("X", "B", "E", "i", "C"):
            if ts < last_ts.get(key, float("-inf")):
                errors.append(
                    f"event {i} ({ph} {e.get('name')!r}): ts {ts} goes "
                    f"backwards on track pid={key[0]} tid={key[1]}")
            last_ts[key] = ts
        if ph == "X":
            dur = _num(e.get("dur"))
            if dur is None or dur < 0:
                errors.append(f"event {i} (X {e.get('name')!r}): bad "
                              f"dur {e.get('dur')!r}")
        elif ph == "B":
            be_stack[key] = be_stack.get(key, 0) + 1
        elif ph == "E":
            depth = be_stack.get(key, 0)
            if depth <= 0:
                errors.append(f"event {i}: E without open B on track "
                              f"pid={key[0]} tid={key[1]}")
            else:
                be_stack[key] = depth - 1
        elif ph == "C":
            args = e.get("args")
            if not isinstance(args, dict) or not args or any(
                    _num(v) is None for v in args.values()):
                errors.append(f"event {i} (C {e.get('name')!r}): counter "
                              f"args must be numeric")
        elif ph == "s":
            fid = e.get("id")
            flow_s[fid] = min(ts, flow_s.get(fid, ts))
        elif ph in ("f", "t"):
            flow_f[e.get("id")] = ts
    for key, depth in be_stack.items():
        if depth:
            errors.append(f"{depth} unmatched B event(s) on track "
                          f"pid={key[0]} tid={key[1]}")
    for fid, ts in flow_s.items():
        if fid not in flow_f:
            errors.append(f"flow {fid!r}: start without finish")
        elif flow_f[fid] < ts:
            errors.append(f"flow {fid!r}: finish at {flow_f[fid]} "
                          f"precedes start at {ts}")
    for fid in flow_f:
        if fid not in flow_s:
            errors.append(f"flow {fid!r}: finish without start")
    return errors


# --------------------------------------------------------------------- CLI


def _default_out(target: str) -> str:
    base = os.path.normpath(target)
    if base.endswith(".jsonl"):
        base = base[:-len(".jsonl")]
    return base + ".trace.json"


def timeline_main(argv=None) -> int:
    """``gmm timeline RUN [RUN ...]``: export a Chrome/Perfetto trace.

    Exit 0 = exported (and validate-clean when ``--validate``),
    1 = ``--validate`` found structural errors in the emitted document,
    2 = usage error / unreadable or empty stream.
    """
    import argparse

    parser = argparse.ArgumentParser(
        prog="gmm timeline",
        description="Convert recorded telemetry streams (a JSONL file, a "
        "per-rank stream directory, or several targets together -- e.g. "
        "a fit stream plus a serve stream) into ONE Chrome trace-event "
        "JSON file for Perfetto / chrome://tracing: nested span slices "
        "per rank, EM/serve/compile slices with args, resource counter "
        "tracks, instant events, and flow arrows joining serve requests "
        "to their server-side spans. Streams are merged onto one wall "
        "timebase via the v2.3 clock anchors (run head + heartbeats); "
        "pre-v2.3 streams align via a ts-based estimate and the export "
        "is marked 'alignment: estimated'.")
    parser.add_argument("targets", nargs="+", metavar="RUN",
                        help="stream file or per-rank stream directory "
                        "(repeat to merge runs, e.g. fit + serve)")
    parser.add_argument("-o", "--out", default=None, metavar="FILE",
                        help="output trace path (default: first target "
                        "with .trace.json suffix)")
    parser.add_argument("--validate", action="store_true",
                        help="re-load the emitted JSON and check the "
                        "trace-event structure (phase letters, X "
                        "durations, per-track timestamp order, flow "
                        "pairing, nonzero event count)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable summary on stdout "
                        "instead of the human one")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    try:
        doc = build_timeline(args.targets)
    except (OSError, ValueError) as e:
        print(f"gmm timeline: {e}", file=sys.stderr)
        return 2

    out_path = args.out or _default_out(args.targets[0])
    try:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
            fh.write("\n")
    except OSError as e:
        print(f"gmm timeline: cannot write {out_path!r}: {e}",
              file=sys.stderr)
        return 2

    meta = doc["metadata"]
    summary = summarize_trace(doc)
    validate_ok = None
    if args.validate:
        with open(out_path, "r", encoding="utf-8") as fh:
            reloaded = json.load(fh)
        verrors = validate_trace(reloaded)
        validate_ok = not verrors
        for err in verrors:
            print(f"gmm timeline: validate: {err}", file=sys.stderr)

    if meta["alignment"] == "estimated":
        print("gmm timeline: alignment: estimated -- at least one "
              "stream predates the v2.3 clock anchors; cross-stream "
              "offsets are inferred from per-record (ts, mono_s) pairs "
              "and may be off by wall-clock slew", file=sys.stderr)

    if args.json:
        record = dict(summary)
        record.update({"out": out_path,
                       "streams": len(meta["streams"])})
        if validate_ok is not None:
            record["validate_ok"] = validate_ok
        print(json.dumps(record, sort_keys=True))
    else:
        print(f"{out_path}: {summary['events']} events "
              f"({summary['slices']} slices, {summary['counters']} "
              f"counter samples, {summary['flows']} flow(s)) across "
              f"{summary['tracks']} track(s), {len(meta['streams'])} "
              f"stream(s); alignment: {meta['alignment']}"
              + ("" if validate_ok is None else
                 f"; validate: {'clean' if validate_ok else 'FAILED'}"))
        print(f"open in https://ui.perfetto.dev or chrome://tracing")
    return 0 if validate_ok in (None, True) else 1
