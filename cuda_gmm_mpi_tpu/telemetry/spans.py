"""Trace spans: fit/request-scoped timing trees on the telemetry stream.

Stream rev v2.1 (docs/OBSERVABILITY.md "Trace spans"). A *trace* is one
logical unit of work -- a whole fit, or one serve route dispatch -- named
by a ``trace_id``; a *span* is one timed phase inside it (sweep, per-K
EM, checkpoint save, recovery, the serve prepare/dispatch/answer hops),
emitted as a ``span``-typed record when the phase completes: name, this
span's id, its parent span's id, start (``t0_mono_s``, process-monotonic)
and measured ``duration_s``. Parentage nests lexically via a thread-local
span stack, so the records of one trace reconstruct into a single-rooted
tree (:func:`build_span_tree`) with zero coordination at emit time.

Spans are part of the live observability plane and are OFF by default:
:func:`span` is a no-op unless a :func:`trace` is active on the calling
thread (fits activate one only when ``GMMConfig.metrics_port`` is set;
``gmm serve`` per route batch under ``--metrics-port``), so with the
plane disabled the stream stays byte-identical to pre-v2.1 runs.

Emission rides the ambient :class:`~.recorder.RunRecorder` -- the JSONL
stream stays the single source of truth; the exporter and ``gmm report``
both read spans from it rather than from a side channel.
"""

from __future__ import annotations

import contextlib
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from . import recorder as _recorder


def mint_trace_id() -> str:
    """A fresh trace identity (16 hex chars; uuid4-derived)."""
    return uuid.uuid4().hex[:16]


def _mint_span_id() -> str:
    return uuid.uuid4().hex[:16]


class _TraceState(threading.local):
    """Per-thread active trace: id + open-span stack (parentage)."""

    def __init__(self):
        self.trace_id: Optional[str] = None
        self.stack: List[str] = []
        # Parallel to ``stack``: the open spans' NAMES, so observers
        # (telemetry/profiling.py tags compile events with the active
        # phase) can ask "where are we?" without a span-id lookup.
        self.names: List[str] = []


_tls = _TraceState()


def active() -> bool:
    """True when a trace is active on this thread (spans will emit)."""
    return _tls.trace_id is not None


def current_trace_id() -> Optional[str]:
    return _tls.trace_id


def current_span_name() -> Optional[str]:
    """The innermost open span's name on this thread (None outside any
    span -- including always when no trace is active)."""
    return _tls.names[-1] if _tls.names else None


@contextlib.contextmanager
def trace(trace_id: Optional[str] = None):
    """Activate a trace on this thread for the enclosed block.

    Nested activation reuses the outer trace (one tree per unit of work,
    however deep the call stack); pass an explicit ``trace_id`` to join
    records to an identity minted elsewhere (serve requests).
    """
    if _tls.trace_id is not None:
        yield _tls.trace_id
        return
    tid = trace_id or mint_trace_id()
    _tls.trace_id = tid
    try:
        yield tid
    finally:
        _tls.trace_id = None
        _tls.stack = []
        _tls.names = []


class _OpenSpan:
    """A begun-but-unfinished span (the non-lexical API's handle)."""

    __slots__ = ("name", "span_id", "parent_id", "trace_id", "t0",
                 "fields", "recorder")

    def __init__(self, name, span_id, parent_id, trace_id, t0, fields,
                 recorder):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.trace_id = trace_id
        self.t0 = t0
        self.fields = fields
        self.recorder = recorder


def begin(name: str, recorder: Optional[Any] = None,
          **fields) -> Optional[_OpenSpan]:
    """Non-lexical span start, for phases a ``with`` block cannot wrap
    (a sweep loop with mid-loop raises). Returns None -- and :func:`end`
    accepts None -- when no trace is active, so call sites need no gate.
    A begun span that never reaches :func:`end` (exception path) simply
    never emits; its completed children are orphan-promoted by
    :func:`build_span_tree`."""
    rec = recorder if recorder is not None else _recorder.current()
    tid = _tls.trace_id
    if tid is None or not rec.active:
        return None
    handle = _OpenSpan(name, _mint_span_id(),
                       _tls.stack[-1] if _tls.stack else None,
                       tid, time.perf_counter(), dict(fields), rec)
    _tls.stack.append(handle.span_id)
    _tls.names.append(name)
    return handle


def end(handle: Optional[_OpenSpan], status: str = "ok",
        **fields) -> Optional[dict]:
    """Finish a :func:`begin` span: emit its record and pop the stack
    (including any abandoned descendants a raise left behind)."""
    if handle is None:
        return None
    if handle.span_id in _tls.stack:
        i = _tls.stack.index(handle.span_id)
        del _tls.stack[i:]
        del _tls.names[i:]
    extra: Dict[str, Any] = dict(handle.fields)
    extra.update(fields)
    if handle.parent_id is not None:
        extra["parent_id"] = handle.parent_id
    return handle.recorder.emit(
        "span", name=handle.name, span_id=handle.span_id,
        trace_id=handle.trace_id, t0_mono_s=round(handle.t0, 6),
        duration_s=round(time.perf_counter() - handle.t0, 6),
        # rev v2.3: the emitting OS thread, so timeline readers can lane
        # concurrent serve routes separately (spans nest per thread by
        # construction, but only per thread).
        thread=threading.get_native_id(),
        status=status, **extra)


@contextlib.contextmanager
def span(name: str, recorder: Optional[Any] = None, **fields):
    """Emit a ``span`` record around the enclosed block.

    No-op (yields None) unless a trace is active on this thread AND the
    recorder has a sink -- both gates keep the disabled-plane stream
    byte-identical. A raising block still closes its span, with
    ``status="error"`` so a truncated tree is distinguishable from a
    crash mid-phase.
    """
    handle = begin(name, recorder=recorder, **fields)
    if handle is None:
        yield None
        return
    status = "ok"
    try:
        yield handle.span_id
    except BaseException:
        status = "error"
        raise
    finally:
        end(handle, status=status)


def build_span_tree(records) -> List[dict]:
    """Reconstruct span trees from decoded stream records.

    Returns the list of root nodes (one per trace in a healthy stream),
    each ``{"span": <record>, "children": [...]}`` with children ordered
    by start time. Orphans (a parent id that never completed -- crash
    mid-phase) are promoted to roots rather than dropped.
    """
    spans = [r for r in records if r.get("event") == "span"]
    by_id = {s["span_id"]: {"span": s, "children": []} for s in spans}
    roots = []
    for s in spans:
        node = by_id[s["span_id"]]
        parent = by_id.get(s.get("parent_id"))
        if parent is not None:
            parent["children"].append(node)
        else:
            roots.append(node)

    def _t0(node):
        return node["span"].get("t0_mono_s", 0.0)

    for node in by_id.values():
        node["children"].sort(key=_t0)
    roots.sort(key=_t0)
    return roots
