"""RunRecorder: the run-scoped event bus behind every execution path.

The structured replacement for the reference's printf telemetry
(``gaussian.cu`` status prints + the ``profile_t`` report at :967): one
recorder spans one fit, stamps every record with the schema version, a run
id, and this process's rank, and appends JSON lines to the configured sink
(``GMMConfig.metrics_file`` / ``--metrics-file``; default off).

Multi-controller semantics ("host-0 aggregation"): every rank runs the
instrumentation -- its registry accumulates, and collective summary
gathers execute everywhere -- but only process 0 writes the file, so a
multi-host run yields ONE coherent stream whose records carry the rank
tags of the data they aggregate (``run_summary.per_process``).

Activation is run-scoped, not global: ``with use(recorder):`` makes it the
ambient recorder that instrumented layers find via ``current()`` (models
never thread a recorder argument through their signatures). The default
ambient recorder is inert, so uninstrumented library use costs one
attribute check per touchpoint.

``write_line`` is the shared one-JSON-object-per-line formatter; the legacy
``utils.logging_.metrics_line`` is a thin adapter over it (same stderr
bytes as before this subsystem existed).
"""

from __future__ import annotations

import contextlib
import json
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from .registry import MetricsRegistry
from .schema import SCHEMA_VERSION


def _json_default(o):
    """Coerce numpy scalars/arrays (the usual payload types) to JSON."""
    item = getattr(o, "item", None)
    if callable(item):
        try:
            return o.item()
        except (TypeError, ValueError):
            pass
    tolist = getattr(o, "tolist", None)
    if callable(tolist):
        return o.tolist()
    return str(o)


def write_line(record: Dict[str, Any], stream=None) -> str:
    """Write one record as a compact JSON line; returns the line."""
    line = json.dumps(record, default=_json_default)
    print(line, file=stream or sys.stderr)
    return line


def _clock_pair() -> Dict[str, float]:
    """An atomically-sampled (wall, mono) clock pair (stream rev v2.3).

    ``wall`` is CLOCK_REALTIME (``time.time()``), ``mono`` the process
    monotonic clock (``time.perf_counter()``) -- sampled back-to-back,
    with the wall read bracketed by two mono reads so the pair's skew is
    bounded by half the bracket width. One pair per stream head plus one
    per heartbeat lets ``gmm timeline`` estimate every stream's
    mono->wall offset (and its drift) and merge multi-rank / fit+serve
    streams onto one timebase (docs/OBSERVABILITY.md "Timeline export").
    """
    m0 = time.perf_counter()
    wall = time.time()
    m1 = time.perf_counter()
    return {"wall": round(wall, 6), "mono": round((m0 + m1) / 2.0, 6)}


class RunRecorder:
    """Schema-versioned JSONL event bus for one run.

    ``path``: JSONL sink file (truncated at first emit -- one run, one
    stream; rank 0 only). ``stream``: an open text stream sink instead
    (tests). ``stderr_passthrough``: additionally mirror every record to
    stderr in the legacy ``metrics_line`` format. With neither path nor
    stream the recorder is inert (``active`` False) and every method is a
    cheap no-op.
    """

    def __init__(self, path: Optional[str] = None, stream=None,
                 stderr_passthrough: bool = False,
                 heartbeat_interval_s: float = 30.0,
                 run_id: Optional[str] = None):
        self._path = path
        self._stream = stream
        self._stderr = stderr_passthrough
        self._fh = None
        self._lock = threading.Lock()
        self._context: Dict[str, Any] = {}
        self._process: Optional[int] = None
        self._writer: Optional[bool] = None
        self._heartbeat_interval_s = heartbeat_interval_s
        # 0.0 (not t0): the first heartbeat() call emits immediately --
        # one early liveness mark per run -- then rate-limiting kicks in.
        self._last_heartbeat = 0.0
        self._t0 = time.perf_counter()
        self._emitted = False
        # v2.3: the recorder-start clock pair (CLOCK_REALTIME wall +
        # perf_counter mono, sampled back-to-back). The stream head
        # carries it alongside a fresh emit-time pair so readers get two
        # alignment anchors even before the first heartbeat.
        self._clock0 = _clock_pair()
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self.metrics = MetricsRegistry()

    @property
    def active(self) -> bool:
        return self._path is not None or self._stream is not None

    @property
    def emitted(self) -> bool:
        """Whether any record has been emitted -- i.e. the stream is open.

        The owning loop's first record (``run_start`` / the first serve
        event) defines the stream head; background observers
        (telemetry.profiling's CompileWatch) consult this to buffer
        their records until the head is written, preserving the
        stream-ordering contract (docs/OBSERVABILITY.md).
        """
        return self._emitted

    def set_context(self, **fields) -> None:
        """Merge static fields into every subsequent record (None drops)."""
        with self._lock:
            for k, v in fields.items():
                if v is None:
                    self._context.pop(k, None)
                else:
                    self._context[k] = v

    def _resolve_process(self) -> None:
        # Deferred: constructing a recorder must not initialize a JAX
        # backend (fit_gmm builds it BEFORE pinning the platform). First
        # emit happens after device setup, where process_index is safe.
        if self._process is not None:
            return
        try:
            import jax

            self._process = int(jax.process_index())
        except Exception:
            self._process = 0
        self._writer = self._process == 0

    def _sink(self):
        if self._stream is not None:
            return self._stream
        if self._fh is None and self._path is not None:
            # Truncate: one run, one stream. Rank 0 only (host-0
            # aggregation); other ranks keep accumulating metrics.
            # Line-buffered: paired with the per-record flush in emit()
            # this is the durability guarantee --follow tailers and
            # post-crash forensics rely on (a killed process never
            # leaves a completed record stuck in a userspace buffer,
            # and a reader only ever sees whole lines).
            self._fh = open(self._path, "w", buffering=1, encoding="utf-8")
        return self._fh

    def emit(self, event: str, **fields) -> Optional[dict]:
        """Append one stamped record to the sink; returns the record."""
        if not self.active:
            return None
        self._resolve_process()
        rec: Dict[str, Any] = {
            "event": event,
            "schema": SCHEMA_VERSION,
            "ts": round(time.time(), 6),
            # Process-monotonic sibling of ts (rev v2.1): report/--follow
            # compute durations from mono_s deltas, immune to wall-clock
            # slew. Comparable only within one process's records.
            "mono_s": round(time.perf_counter(), 6),
            "run_id": self.run_id,
            "process": self._process,
        }
        rec.update(self._context)
        rec.update(fields)
        # v2.3 alignment anchors: the stream head (run_start / a serve
        # stream's first record) and every heartbeat carry an
        # atomically-sampled wall/mono clock pair; the head additionally
        # carries the recorder-construction pair (clock0) so even a
        # heartbeat-free stream holds two anchors for drift estimation.
        # Explicit-kwarg clock (tests, replayers) wins.
        if "clock" not in fields:
            if not self._emitted:
                rec["clock"] = _clock_pair()
                rec["clock0"] = dict(self._clock0)
            elif event == "heartbeat":
                rec["clock"] = _clock_pair()
        self._emitted = True
        with self._lock:
            if self._writer:
                sink = self._sink()
                if sink is not None:
                    sink.write(json.dumps(rec, default=_json_default) + "\n")
                    sink.flush()  # crash-robust: every record is durable
            if self._stderr:
                write_line(rec)
        return rec

    def heartbeat(self, phase: str, **fields) -> None:
        """Rate-limited liveness record (at most one per interval)."""
        if not self.active:
            return
        now = time.perf_counter()
        if now - self._last_heartbeat < self._heartbeat_interval_s:
            return
        self._last_heartbeat = now
        self.emit("heartbeat", phase=phase,
                  elapsed_s=round(now - self._t0, 3), **fields)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


_NULL = RunRecorder()  # inert ambient default
_stack: List[RunRecorder] = []


def current() -> RunRecorder:
    """The ambient recorder (inert unless a run activated one)."""
    return _stack[-1] if _stack else _NULL


@contextlib.contextmanager
def use(recorder: RunRecorder):
    """Make ``recorder`` the ambient recorder for the enclosed run."""
    _stack.append(recorder)
    try:
        yield recorder
    finally:
        _stack.pop()


def read_stream(path: str) -> List[dict]:
    """Decode a JSONL metrics file; raises OSError/ValueError on bad input."""
    records = []
    with open(path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError as e:
                raise ValueError(f"{path}:{i + 1}: not JSON: {e}") from None
    return records


def memory_stats() -> Optional[dict]:
    """First local device's memory_stats(), or None where unsupported
    (CPU backends and some plugins return None or raise)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        return dict(stats) if stats else None
    except Exception:
        return None
