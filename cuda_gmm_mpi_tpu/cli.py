"""Command-line driver: ``gmm num_clusters infile outfile [target_num_clusters]``.

L6 of the layer map -- same positional CLI as the reference
(``gaussian.cu:1111-1178``, ``README.txt:66-70``) with every compile-time knob
from ``gaussian.h`` promoted to a runtime flag (SURVEY.md SS5.6), including the
north-star ``--device=tpu`` selector (BASELINE.json).

Argument validation mirrors validateArguments (gaussian.cu:1111-1166):
num_clusters in [1, max_clusters]; infile must be openable; absent
target_num_clusters means "search down to 1, keep best Rissanen"
(stop_number logic, gaussian.cu:177-181).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gmm",
        description="TPU-native GMM-EM clustering with Rissanen model-order "
        "search (capabilities of CUDA-GMM-MPI's gaussianMPI).",
        epilog="Subcommands: `gmm report FILE.jsonl` renders a "
        "--metrics-file telemetry stream (phase profile, loglik "
        "trajectory, sweep summary) offline; `gmm export` persists a "
        "fitted model (sweep checkpoint or .summary) into a serving "
        "registry; `gmm serve` runs the micro-batched scoring loop over "
        "a registry (JSONL protocol, or `--http PORT [--workers N]` for "
        "the supervised HTTP tier; docs/SERVING.md); `gmm fleet` fits "
        "a manifest of per-tenant datasets as packed multi-tenant "
        "dispatches (docs/TENANCY.md); `gmm diff A B` compares two runs "
        "with --fail-on regression gates (exit 0 clean / 1 regressed); "
        "`gmm drift TARGET` compares a serve stream or dataset against "
        "a registry version's training envelope (PSI/KS drift gates); "
        "`gmm runs DIR` indexes historical run streams.",
    )
    from ._version import __version__

    p.add_argument("--version", action="version",
                   version=f"%(prog)s {__version__}")
    p.add_argument("num_clusters", type=int,
                   help="number of starting clusters")
    p.add_argument("infile", help="input data: CSV (first line = header) or "
                   "*.bin (int32 N, int32 D, float32 data)")
    p.add_argument("outfile", help="output basename; writes "
                   "<outfile>.summary and <outfile>.results")
    p.add_argument("target_num_clusters", type=int, nargs="?", default=0,
                   help="desired number of clusters (<= num_clusters); "
                   "omit to search for the best Rissanen score")

    g = p.add_argument_group("runtime config (reference gaussian.h defines)")
    g.add_argument("--device", default=None,
                   help="JAX platform: tpu | cpu | gpu (default: auto)")
    g.add_argument("--cpu-devices", type=int, default=None,
                   help="virtual CPU device count (validate sharded runs "
                   "without a cluster, SURVEY.md SS4; use with --device=cpu)")
    g.add_argument("--diag-only", action="store_true",
                   help="diagonal covariance (DIAG_ONLY, gaussian.h:23); "
                   "shorthand for --covariance-type=diag")
    g.add_argument("--covariance-type", default="full",
                   choices=["full", "diag", "spherical", "tied"],
                   help="covariance family: the reference's full/diag plus "
                   "spherical (sigma^2 I per cluster) and tied (one shared "
                   "covariance) as capability upgrades")
    g.add_argument("--criterion", default="rissanen",
                   choices=["rissanen", "bic", "aic", "aicc"],
                   help="model-order selection score: the reference's "
                   "Rissanen/MDL (gaussian.cu:826), or BIC/AIC/AICc with "
                   "family-correct parameter counts")
    g.add_argument("--min-iters", type=int, default=100,
                   help="MIN_ITERS (gaussian.h:27)")
    g.add_argument("--max-iters", type=int, default=100,
                   help="MAX_ITERS (gaussian.h:26)")
    g.add_argument("--max-clusters", type=int, default=512,
                   help="MAX_CLUSTERS bound for num_clusters (gaussian.h:10)")
    g.add_argument("--dynamic-range", type=float, default=1e3,
                   help="COVARIANCE_DYNAMIC_RANGE regularizer (gaussian.h:12)")
    g.add_argument("--epsilon-scale", type=float, default=0.01,
                   help="convergence epsilon scale (gaussian.cu:458)")
    g.add_argument("--no-output", action="store_true",
                   help="skip .summary/.results content (ENABLE_OUTPUT=0)")
    g.add_argument("--verbose", "-v", action="store_true",
                   help="status prints (ENABLE_PRINT, gaussian.h:35)")
    g.add_argument("--debug", action="store_true",
                   help="debug prints (ENABLE_DEBUG, gaussian.h:31)")

    d = p.add_argument_group(
        "distributed (multi-controller; the reference's mpirun equivalent, "
        "gaussian.cu:128-207 -- run the SAME command on every host)")
    d.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="coordination-service address (rank 0's); enables "
                   "jax.distributed. On TPU pods omit all three flags and "
                   "initialize from the environment with --num-processes=0")
    d.add_argument("--num-processes", type=int, default=None,
                   help="total process count (MPI world size)")
    d.add_argument("--process-id", type=int, default=None,
                   help="this process's rank (0-based)")
    d.add_argument("--part-dir", default=None,
                   help="rank-local scratch dir for .results parts (pods "
                   "whose output dir is not writable everywhere); assembly "
                   "byte-gathers to rank 0 over the runtime when parts are "
                   "not on a shared filesystem")

    t = p.add_argument_group("TPU-native tuning")
    t.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"],
                   help="compute dtype (float64 needs no TPU and is exact "
                   "for oracle comparisons)")
    t.add_argument("--chunk-size", type=int, default=65536,
                   help="events per fused E+M pass")
    t.add_argument("--precision", default="highest",
                   choices=["highest", "high", "default"],
                   help="matmul precision on MXU")
    t.add_argument("--quad-mode", default="expanded",
                   choices=["expanded", "packed", "centered"],
                   help="quadratic-form evaluation strategy; 'packed' halves "
                   "the dominant MACs but measures SLOWER on XLA/TPU "
                   "(layout-bound, see docs/PERF.md) -- kept for study")
    t.add_argument("--no-center", action="store_true",
                   help="disable global data centering")
    t.add_argument("--seed-method", default="even",
                   choices=["even", "kmeans++"],
                   help="initial means: reference evenly-spaced rows, or "
                   "k-means++ D^2-weighted sampling (--seed sets its RNG)")
    t.add_argument("--seed", type=int, default=0,
                   help="RNG seed for randomized paths (kmeans++ seeding)")
    t.add_argument("--n-init", type=int, default=1,
                   help="independent restarts with varied kmeans++ seeds; "
                   "best Rissanen kept (1 = reference single-init)")
    t.add_argument("--restart-batch-size", type=int, default=None,
                   metavar="R",
                   help="restarts per batched-EM dispatch: the n_init "
                   "restarts vmap over a leading restart axis and run as "
                   "one compiled program per batch (R x arithmetic "
                   "intensity, zero extra uploads). Default: auto-sized "
                   "from a host-memory heuristic (GMM_RESTART_MEM_BYTES / "
                   "GMM_RESTART_BATCH_SIZE override); 1 = sequential "
                   "restarts (identical winner, just slower)")
    t.add_argument("--pallas", default="auto", choices=["auto", "always", "never"],
                   help="legacy spelling of --estep-backend ('always' == "
                        "pallas, 'never' == jnp; see docs/PERF.md)")
    t.add_argument("--estep-backend", default="auto",
                   choices=["auto", "pallas", "jnp"],
                   help="E-step/statistics backend: 'pallas' runs the fused "
                   "E+M kernel (batched + unbatched, M-step epilogue "
                   "fused; interpret mode off-TPU), 'jnp' pins the XLA "
                   "path, 'auto' routes per docs/PERF.md. The backend "
                   "that actually ran lands on the telemetry stream as "
                   "em_backend")
    t.add_argument("--autotune", default="off",
                   choices=["off", "db", "probe"],
                   help="profile-guided knob resolution (docs/PERF.md "
                   "'Autotuning'): 'db' resolves unset tunable knobs "
                   "(chunk size, E-step backend, sweep bucketing, "
                   "restart batch) from the nearest recorded profile in "
                   "the tuning database, 'probe' measures missing rows "
                   "first (2-3 real EM iterations per candidate). "
                   "Explicitly-passed knobs are never touched; results "
                   "stay in the documented parity class. Default off "
                   "(byte-identical streams)")
    t.add_argument("--tuning-db", default=None, metavar="PATH",
                   help="tuning database path (default GMM_TUNING_DB or "
                   "~/.cache/gmm/tuning.json); `gmm tune` writes it")
    t.add_argument("--precompute-features", action="store_true",
                   help="hoist the [N, F] outer-product features out of the "
                   "EM loop (built once, held in HBM: N*F*4 bytes); "
                   "full-covariance in-memory runs only")
    t.add_argument("--fused-sweep", action="store_true",
                   help="run the whole model-order sweep as one device "
                   "program (fastest; composes with --checkpoint-dir and "
                   "--profile via per-K emission -- profile attribution is "
                   "coarse: whole-K spans land in e_step)")
    t.add_argument("--sweep-k-buckets", default="pow2",
                   choices=["pow2", "off"],
                   help="cluster-width bucketing for the host-driven sweep: "
                   "'pow2' (default) recompacts the state to power-of-two "
                   "padded widths as K drops (~2x sweep-level FLOPs for "
                   "<= ceil(log2 K0)+1 compiled EM widths); 'off' keeps one "
                   "fixed width. The fused sweep is fixed-width by design")
    t.add_argument("--mesh", default=None,
                   help="device mesh 'DATA[,CLUSTER]', e.g. --mesh=4 or "
                   "--mesh=4,2; default: all devices on the event axis")
    t.add_argument("--profile", action="store_true",
                   help="per-phase timing report (reference profile_t taxonomy)")
    t.add_argument("--trace-dir", default=None,
                   help="capture a jax.profiler trace of the fit "
                   "(TensorBoard-viewable) into this directory")
    t.add_argument("--debug-nans", action="store_true",
                   help="trap NaN/Inf at the producing op (sanitizer mode)")
    t.add_argument("--no-validate-input", action="store_true",
                   help="skip the NaN/Inf input-row check at load")
    t.add_argument("--stream-events", action="store_true",
                   help="out-of-core mode: event chunks stay in host RAM "
                   "and stream through the device per E+M pass (N bounded "
                   "by host memory, not HBM; slower -- use only when the "
                   "data exceeds device memory). Composes with --mesh=S to "
                   "stream blocks sharded over S local devices")
    t.add_argument("--ingest", default="resident",
                   choices=["resident", "pipelined"],
                   help="how --stream-events chunks reach the host: "
                   "'resident' loads the whole slice up front; 'pipelined' "
                   "prefetches per-block byte ranges from the input file on "
                   "a background thread while the device computes, so peak "
                   "host memory is O(queue depth x block), never O(N) -- "
                   "results bit-identical (docs/PERF.md)")
    t.add_argument("--ingest-queue-depth", type=int, default=4,
                   help="prefetched blocks held in host RAM by "
                   "--ingest=pipelined (the memory/overlap trade)")
    t.add_argument("--em-mode", default="full", choices=["full", "minibatch"],
                   help="'full' runs exact batch EM; 'minibatch' runs "
                   "stepwise EM (Cappe-Moulines decayed sufficient "
                   "statistics) over --minibatch-size event slices -- "
                   "approximate, but each step touches only a fraction of "
                   "the data (pairs with --ingest=pipelined for fits that "
                   "never hold the dataset in host memory)")
    t.add_argument("--minibatch-size", type=int, default=0,
                   help="events per stepwise-EM minibatch (rounded up to "
                   "whole stream blocks); 0 = one block per step")
    t.add_argument("--minibatch-t0", type=float, default=2.0,
                   help="stepwise-EM decay offset t0 in the step size "
                   "(t + t0)^-alpha (larger = more damping early)")
    t.add_argument("--minibatch-alpha", type=float, default=0.7,
                   help="stepwise-EM decay exponent alpha in (0.5, 1]: "
                   "smaller forgets faster, 1.0 averages all history")
    t.add_argument("--checkpoint-dir", default=None,
                   help="orbax checkpoint directory for the K-sweep (resume "
                   "with the same path)")
    t.add_argument("--checkpoint-keep", type=int, default=2,
                   help="retained checkpoint steps (newest + fallbacks); "
                   "older steps are pruned after each durable save")
    t.add_argument("--checkpoint-retries", type=int, default=3,
                   help="bounded retries (jittered backoff) for checkpoint "
                   "writes -- a transient EIO no longer kills the run "
                   "(telemetry records io_retry events)")
    t.add_argument("--max-runtime", type=float, default=None,
                   metavar="SECONDS",
                   help="wall-clock budget: reaching it acts like SIGTERM "
                   "-- cooperative stop, emergency intra-K checkpoint "
                   "(with --checkpoint-dir), exit 75 (EX_TEMPFAIL). "
                   "Front-runs a batch scheduler's hard kill")
    t.add_argument("--resume", default="auto", choices=["auto", "never"],
                   help="checkpoint resume policy: 'auto' (default) "
                   "resumes from the newest step INCLUDING a preempted "
                   "run's mid-EM sub-step; 'never' starts fresh (new "
                   "checkpoints are still written)")
    t.add_argument("--preempt-poll-iters", type=int, default=25,
                   help="EM iterations per supervised segment (stop-flag "
                   "poll cadence mid-K; ~1/N E-step overhead, results "
                   "bit-identical). Active with --checkpoint-dir")
    t.add_argument("--peer-timeout", type=float, default=60.0,
                   metavar="SECONDS",
                   help="multi-host liveness watchdog: a peer rank whose "
                   "heartbeat (on the checkpoint filesystem) is stale "
                   "beyond this fails loudly with PeerLostError + an "
                   "emergency checkpoint instead of hanging in the next "
                   "collective; 0 disables")
    t.add_argument("--elastic", action="store_true",
                   help="elastic multi-host recovery: on peer loss the "
                   "surviving hosts rendezvous on the checkpoint "
                   "filesystem, seal a shrunken generation-stamped "
                   "membership, re-shard, restore the newest checkpoint, "
                   "and continue -- instead of exiting 75 and waiting for "
                   "a full-world restart. Requires --checkpoint-dir "
                   "(docs/DISTRIBUTED.md 'Elastic recovery')")
    t.add_argument("--min-hosts", type=int, default=1, metavar="N",
                   help="smallest world --elastic may shrink to; a loss "
                   "that would go below this exits 75 as without "
                   "--elastic")
    t.add_argument("--allow-nonfinite", action="store_true",
                   help="count-and-quarantine NaN/Inf input rows at load "
                   "(they are DROPPED with a warning) instead of "
                   "rejecting the file; single-process runs only")
    t.add_argument("--recovery", default="retry", choices=["retry", "off"],
                   help="what a FATAL health flag (non-finite loglik/"
                   "params) does: 'retry' rolls back and climbs the "
                   "escalation ladder (regularize -> centered -> highest "
                   "precision); 'off' raises immediately with a "
                   "diagnostic bundle. Detection is always on "
                   "(docs/ROBUSTNESS.md)")
    t.add_argument("--max-recovery-attempts", type=int, default=3,
                   help="escalation rungs attempted per fault before "
                   "failing loudly")
    t.add_argument("--recovery-reseed-empty", action="store_true",
                   help="at a target-K fit, reseed empty clusters from "
                   "worst-fit events instead of eliminating them "
                   "(reference-style elimination is the default)")
    t.add_argument("--sweep-log", default=None, metavar="FILE.jsonl",
                   help="write the per-K sweep trajectory (num_clusters, "
                   "loglik, score, criterion, em_iters, seconds) as JSON "
                   "lines (rank 0; machine-readable sibling of the -v "
                   "per-K prints)")
    t.add_argument("--metrics-file", default=None, metavar="FILE.jsonl",
                   help="run-scoped telemetry stream: schema-versioned "
                   "JSONL records (run_start, per-iteration em_iter, per-K "
                   "em_done, merge, chunk_flush, heartbeat, run_summary "
                   "with the 7-category phase profile and metrics "
                   "registry) for every execution path; render it with "
                   "`gmm report FILE.jsonl` (docs/OBSERVABILITY.md)")
    t.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="live observability plane (rev v2.1): serve "
                   "Prometheus/OpenMetrics text on "
                   "127.0.0.1:PORT/metrics (0 = OS-assigned ephemeral "
                   "port), sample host RSS + device memory onto "
                   "heartbeat records, and emit trace spans around the "
                   "sweep / per-K EM / checkpoint phases (default: off; "
                   "streams stay byte-identical)")
    t.add_argument("--init-from", default=None, metavar="MODEL.summary",
                   help="warm-start: initial means from a saved .summary "
                   "model (its K must equal num_clusters); covariances/"
                   "weights restart from the reference seed recipe")
    t.add_argument("--predict-from", default=None, metavar="MODEL.summary",
                   help="skip fitting: load a saved .summary model (this "
                   "framework's or the reference's own output) and write "
                   "<outfile>.results memberships for infile under it; the "
                   "num_clusters positional is ignored")
    return p


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "report":
        # `gmm report <metrics.jsonl>`: offline rendering of a
        # --metrics-file telemetry stream (phase profile, loglik
        # trajectory, sweep summary) -- no devices, no state files.
        from .telemetry import report_main

        return report_main(argv[1:])
    if argv and argv[0] == "top":
        # `gmm top <metrics.jsonl|stream-dir>`: alias for
        # `gmm report --follow` -- a live one-screen view of a running
        # fit or server, re-rendered as the stream grows.
        from .telemetry import report_main

        return report_main(["--follow"] + argv[1:])
    if argv and argv[0] == "export":
        # `gmm export`: persist a model (sweep checkpoint / .summary)
        # into a serving registry (docs/SERVING.md).
        from .serving.registry import export_main

        return export_main(argv[1:])
    if argv and argv[0] == "serve":
        # `gmm serve`: the micro-batched scoring loop over a registry
        # (JSONL protocol on stdin/socket, or --http [--workers N] for
        # the supervised HTTP front end; docs/SERVING.md).
        from .serving.server import serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "fleet":
        # `gmm fleet`: fit a manifest of per-tenant input files as
        # packed multi-tenant dispatches (docs/TENANCY.md).
        from .tenancy.cli import fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "diff":
        # `gmm diff A B`: cross-run regression analytics over two
        # telemetry streams / bench records, with --fail-on gates and
        # a CI exit-code contract (0 clean / 1 regressions / 2 usage).
        from .telemetry.diff import diff_main

        return diff_main(argv[1:])
    if argv and argv[0] == "drift":
        # `gmm drift TARGET`: compare a recorded serve stream or a raw
        # dataset file against a registry version's training envelope
        # (PSI/KS/occupancy shift) with --fail-on gates and the same
        # 0/1/2 exit contract as `gmm diff`; --rebuild-envelope
        # backfills envelope.json for existing versions.
        from .telemetry.drift import drift_main

        return drift_main(argv[1:])
    if argv and argv[0] == "lifecycle":
        # `gmm lifecycle STREAM`: drive the drift->retrain->canary->
        # promote loop offline from a recorded serve stream against a
        # registry (docs/ROBUSTNESS.md "Model lifecycle"); the live
        # in-serve form is `gmm serve --lifecycle policy.json`.
        from .lifecycle.cli import lifecycle_main

        return lifecycle_main(argv[1:])
    if argv and argv[0] == "timeline":
        # `gmm timeline RUN [RUN ...]`: export recorded streams (file,
        # per-rank directory, fit + serve together) as ONE Chrome
        # trace-event JSON for Perfetto / chrome://tracing, with
        # cross-stream clock alignment (docs/OBSERVABILITY.md).
        from .telemetry.timeline import timeline_main

        return timeline_main(argv[1:])
    if argv and argv[0] == "tune":
        # `gmm tune`: offline autotuner sweep -- probe candidate knob
        # settings at a shape, write the tuning DB, print the decision
        # table a later --autotune=db run resolves from (docs/PERF.md
        # "Autotuning").
        from .tuning.cli import tune_main

        return tune_main(argv[1:])
    if argv and argv[0] == "runs":
        # `gmm runs DIR`: index historical run streams (run id, config
        # fingerprint, backend, wall, iters/s, health).
        from .telemetry.diff import runs_main

        return runs_main(argv[1:])
    args = build_parser().parse_args(argv)

    # Platform must be pinned before JAX initializes its backends. Set the env
    # for child processes AND update the config directly: environments that
    # preload jax at interpreter start (sitecustomize hooks) have already read
    # JAX_PLATFORMS, so only the config.update reliably takes effect here.
    if args.device:
        os.environ["JAX_PLATFORMS"] = args.device
        import jax

        jax.config.update("jax_platforms", args.device)
    if args.cpu_devices:
        # Older JAX has no jax_num_cpu_devices config; fall back to the
        # XLA_FLAGS device-count forcing (effective when jax has not been
        # preloaded yet) rather than crashing the CLI.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{args.cpu_devices}").strip()
        import jax

        try:
            jax.config.update("jax_num_cpu_devices", args.cpu_devices)
        except AttributeError:
            pass
    if args.dtype == "float64":
        import jax

        jax.config.update("jax_enable_x64", True)

    # Heavy imports deferred until after platform selection.
    import jax

    from .config import GMMConfig
    from .io import FileSource, read_data, write_summary
    from .io.writers import stream_results
    from .models import fit_gmm, iter_memberships
    from .validation import InvalidInputError

    # Argument validation BEFORE any backend/runtime initialization
    # (validateArguments runs before MPI work in the reference too,
    # gaussian.cu:169): a wedged or absent accelerator must not turn an
    # arg error's exit code into a backend crash.
    if not os.path.isfile(args.infile):
        print("Invalid infile.\n", file=sys.stderr)  # gaussian.cu:1130
        return 2
    try:
        config = GMMConfig(
            dtype=args.dtype,
            max_clusters=args.max_clusters,
            covariance_dynamic_range=args.dynamic_range,
            diag_only=args.diag_only,
            covariance_type=args.covariance_type,
            min_iters=args.min_iters,
            max_iters=args.max_iters,
            criterion=args.criterion,
            epsilon_scale=args.epsilon_scale,
            matmul_precision=args.precision,
            chunk_size=args.chunk_size,
            quad_mode=args.quad_mode,
            center_data=not args.no_center,
            seed_method=args.seed_method,
            seed=args.seed,
            n_init=args.n_init,
            restart_batch_size=args.restart_batch_size,
            use_pallas=args.pallas,
            estep_backend=args.estep_backend,
            autotune=args.autotune,
            tuning_db=args.tuning_db,
            fused_sweep=args.fused_sweep,
            sweep_k_buckets=args.sweep_k_buckets,
            device=args.device,
            mesh_shape=_parse_mesh(args.mesh),
            enable_debug=args.debug,
            enable_print=args.verbose or args.debug,
            enable_output=not args.no_output,
            profile=args.profile,
            metrics_file=args.metrics_file,
            metrics_port=args.metrics_port,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_keep=args.checkpoint_keep,
            checkpoint_retries=args.checkpoint_retries,
            recovery=args.recovery,
            max_recovery_attempts=args.max_recovery_attempts,
            recovery_reseed_empty=args.recovery_reseed_empty,
            debug_nans=args.debug_nans,
            validate_input=not args.no_validate_input,
            stream_events=args.stream_events,
            ingest=args.ingest,
            ingest_queue_depth=args.ingest_queue_depth,
            em_mode=args.em_mode,
            minibatch_size=args.minibatch_size,
            minibatch_t0=args.minibatch_t0,
            minibatch_alpha=args.minibatch_alpha,
            precompute_features=args.precompute_features,
            max_runtime_s=args.max_runtime,
            resume=args.resume,
            preempt_poll_iters=args.preempt_poll_iters,
            peer_timeout_s=args.peer_timeout,
            elastic=args.elastic,
            min_hosts=args.min_hosts,
        )
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    distributed_flags = (args.coordinator is not None
                         or args.num_processes is not None
                         or args.process_id is not None)
    if args.predict_from is not None:
        # Inference-only mode: K comes from the model file, so the fit-mode
        # cluster-count validations don't apply (the positional is ignored,
        # as --help documents).
        if distributed_flags:
            print("--predict-from is a single-process mode", file=sys.stderr)
            return 1
        # No sweep and no fitting happen in this mode; rejecting beats
        # silently ignoring flags the user believes took effect.
        fit_only = [
            ("--sweep-log", args.sweep_log),
            ("--metrics-file", args.metrics_file),
            ("--metrics-port", args.metrics_port is not None),
            ("--init-from", args.init_from),
            ("--checkpoint-dir", args.checkpoint_dir),
            ("--fused-sweep", args.fused_sweep),
            ("--sweep-k-buckets", args.sweep_k_buckets != "pow2"),
            ("--n-init", args.n_init != 1),
            ("--restart-batch-size", args.restart_batch_size is not None),
            ("--mesh", args.mesh),
            ("--seed-method", args.seed_method != "even"),
            ("--stream-events", args.stream_events),
            ("--ingest", args.ingest != "resident"),
            ("--em-mode", args.em_mode != "full"),
            ("--autotune", args.autotune != "off"),
        ]
        for flag, present in fit_only:
            if present:
                print(f"{flag} has no effect with --predict-from",
                      file=sys.stderr)
                return 1
        return _predict_main(args, config)
    if not (1 <= args.num_clusters <= config.max_clusters):
        print("Invalid number of starting clusters\n", file=sys.stderr)  # :1122
        return 1
    if args.target_num_clusters > args.num_clusters:
        print("target_num_clusters must be less than equal to num_clusters\n",
              file=sys.stderr)  # :1150
        return 4

    # MPI_Init equivalent (gaussian.cu:130-140): any distributed flag brings
    # up the multi-controller runtime; --num-processes=0 initializes from the
    # environment (TPU pod launchers).
    if distributed_flags:
        from .parallel import distributed

        try:
            distributed.initialize(
                coordinator_address=args.coordinator,
                num_processes=args.num_processes,
                process_id=args.process_id,
                auto=(args.num_processes == 0),
            )
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 1
    pid, nproc = jax.process_index(), jax.process_count()

    for flag, target in (("--sweep-log", args.sweep_log),
                         ("--metrics-file", args.metrics_file)):
        # Fail-fast (an unwritable log path must not surface as a crash
        # AFTER an hours-long fit), but only once the runtime is up: only
        # rank 0 writes these files, and in multi-host runs every rank must
        # reach the same proceed/abort decision or the others hang in the
        # first collective.
        if not target:
            continue
        ok = True
        if pid == 0:
            try:
                _probe_writable(target)
            except OSError as e:
                print(f"Cannot write {flag}={target!r}: {e}",
                      file=sys.stderr)
                ok = False
        if not _all_ranks_ok(ok, nproc):
            return 1

    if args.allow_nonfinite and nproc > 1:
        # Quarantine drops rows, which would shift every host's slice
        # bounds; multi-host runs must reject instead (validate_input).
        print("--allow-nonfinite is a single-process mode", file=sys.stderr)
        return 1
    if args.allow_nonfinite and args.ingest == "pipelined":
        # Quarantine materializes the data to drop rows -- the opposite of
        # out-of-core ingestion, and dropped rows would shift every block's
        # byte range. The streaming validator rejects bad rows instead.
        print("--allow-nonfinite requires --ingest=resident (quarantine "
              "rewrites the event array; pipelined ingestion reads fixed "
              "byte ranges)", file=sys.stderr)
        return 1

    t_io0 = time.perf_counter()
    if nproc > 1 or args.ingest == "pipelined":
        # Range-reader loading: fit_gmm pulls data through the file source
        # instead of a materialized array -- each host only its slice
        # (the anti-MPI_Bcast; the reference broadcast the ENTIRE dataset,
        # gaussian.cu:191-201), and --ingest=pipelined only the blocks in
        # flight.
        def _open_source(path):
            src = FileSource(path)
            src.shape  # force the header/shape parse inside the error guard
            return src
        fit_input, rc = _read_events_or_none(_open_source, args.infile)
        if fit_input is None:
            return rc
        n_events, n_dims = fit_input.shape
    else:
        def _read(path):
            import numpy as np

            from .io.readers import read_data as rd

            # Ingest-time integrity screen (io/readers.py): with
            # --allow-nonfinite bad rows are counted and dropped here;
            # otherwise the fit-time validator rejects them (same
            # collective-safe path multi-host uses).
            return rd(path,
                      screen=("quarantine" if args.allow_nonfinite
                              else "off"),
                      screen_dtype=np.dtype(config.dtype))
        fit_input = data = None
        data, rc = _read_events_or_none(_read, args.infile)
        if data is None:
            return rc
        fit_input = data
        n_events, n_dims = data.shape
    t_io = time.perf_counter() - t_io0
    if config.enable_print and pid == 0:
        print(f"Number of events: {n_events}")
        print(f"Number of dimensions: {n_dims}\n")  # gaussian.cu:223-224
        stop = args.target_num_clusters or 1
        print(f"Starting with {args.num_clusters} cluster(s), will stop at "
              f"{stop} cluster(s).")  # :226

    from .utils.profiling import trace

    init_means = None
    if args.init_from:
        # Multi-host safe like the --sweep-log probe: every rank loads and
        # validates, then all ranks agree on one proceed/abort decision (a
        # lone rank bailing here would strand the others in fit_gmm's first
        # collective).
        from .io.readers import read_summary

        ok = True
        try:
            init_means = read_summary(args.init_from)["means"]
        except (OSError, ValueError) as e:
            print(f"Cannot load --init-from={args.init_from!r}: {e}",
                  file=sys.stderr)
            ok = False
        if ok and init_means.shape != (args.num_clusters, n_dims):
            print(f"--init-from model is {init_means.shape[0]} clusters x "
                  f"{init_means.shape[1]} dims but this fit needs "
                  f"({args.num_clusters}, {n_dims}).", file=sys.stderr)
            ok = False
        if not _all_ranks_ok(ok, nproc):
            return 1

    from . import supervisor as supervisor_mod
    from .health import NumericalFaultError
    from .supervisor import PeerLostError, PreemptedError
    from .utils.checkpoint import CheckpointRestoreError

    # The run supervisor turns SIGTERM/SIGINT and the --max-runtime
    # deadline into a cooperative stop with an emergency intra-K
    # checkpoint and exit 75 (EX_TEMPFAIL) -- the preemption-safe
    # execution contract (docs/ROBUSTNESS.md "Run lifecycle"). It stays
    # active through output writing so the multi-host assembly barriers
    # are timeout-bounded while the liveness watchdog runs.
    sup = supervisor_mod.RunSupervisor(max_runtime_s=config.max_runtime_s)
    try:
        with supervisor_mod.use(sup):
            return _fit_and_write(args, config, fit_input, pid, nproc,
                                  init_means, t_io)
    except PreemptedError as e:
        print(f"Preempted -- {e}", file=sys.stderr)
        return supervisor_mod.EX_TEMPFAIL
    except PeerLostError as e:
        print(f"Peer lost -- {e}", file=sys.stderr)
        return supervisor_mod.EX_TEMPFAIL
    except CheckpointRestoreError as e:
        print(f"Checkpoint unreadable -- {e}", file=sys.stderr)
        return supervisor_mod.EX_IOERR


def _fit_and_write(args, config, fit_input, pid, nproc, init_means,
                   t_io) -> int:
    """The supervised span of ``main``: fit, then write outputs."""
    # Single-process: the in-memory array itself, or (--ingest=pipelined)
    # the FileSource -- iter_memberships slices both, so the memberships
    # pass stays out-of-core when the fit was.
    data = fit_input
    from . import supervisor as supervisor_mod
    from .health import NumericalFaultError
    from .io import write_summary
    from .io.writers import stream_results
    from .models import fit_gmm, iter_memberships
    from .utils.profiling import trace
    from .validation import InvalidInputError

    with trace(args.trace_dir):
        try:
            result = fit_gmm(
                fit_input, args.num_clusters, args.target_num_clusters,
                config=config, init_means=init_means,
            )
        except InvalidInputError as e:
            # Data-content errors (non-finite rows from the input validator)
            # get the reference's abort style; genuine internal ValueErrors
            # still crash loudly with their tracebacks.
            print(str(e), file=sys.stderr)
            return 1
        except NumericalFaultError as e:
            # An unrecovered (or recovery-disabled) numerical fault: the
            # loud-failure contract -- print the diagnostic bundle, exit
            # EX_SOFTWARE, never write a poisoned model
            # (docs/ROBUSTNESS.md; docs/API.md exit-code table).
            print(f"Numerical fault -- no model written.\n{e}",
                  file=sys.stderr)
            return supervisor_mod.EX_SOFTWARE

    t_out0 = time.perf_counter()
    if pid == 0:
        summary_path = args.outfile + ".summary"
        write_summary(summary_path, result, enable_output=config.enable_output)
        if config.enable_print:
            _print_clusters(result)  # ENABLE_PRINT dump, gaussian.cu:1032-1039
        if args.sweep_log:
            import json

            with open(args.sweep_log, "w") as f:
                for k, ll, riss, iters, secs in result.sweep_log:
                    f.write(json.dumps({
                        "num_clusters": int(k), "loglik": float(ll),
                        "score": float(riss),
                        "criterion": config.criterion,
                        "em_iters": int(iters),
                        "seconds": float(secs),
                    }) + "\n")
    if config.enable_output:
        # Streamed: posteriors recomputed + written chunk-by-chunk, so the
        # N x K membership matrix never exists in host RAM. Multi-host: each
        # host writes its own slice's part, rank 0 assembles in order (the
        # reference gathered all memberships over MPI_Send/Recv to rank 0,
        # gaussian.cu:783-823; here only formatted bytes cross the local FS).
        if nproc > 1:
            from .parallel.distributed import (
                assemble_results_multihost, results_part_path,
            )

            start, stop_row = result.host_range
            local = fit_input.read_range(start, stop_row)
            out_path = args.outfile + ".results"
            part_path = results_part_path(out_path, part_dir=args.part_dir)
            stream_results(part_path, iter_memberships(result, local, config))
            # Assembles on rank 0 via the shared-FS fast path when the parts
            # are visible there, else a chunked byte-gather over the runtime
            # (the MPI_Send/Recv membership gather, gaussian.cu:798-817 --
            # no shared filesystem assumed).
            assemble_results_multihost(out_path, part_path)
        else:
            stream_results(args.outfile + ".results",
                           iter_memberships(result, data, config))
    t_out = time.perf_counter() - t_out0

    if config.profile:
        em_s = sum(rec[4] for rec in result.sweep_log)
        if result.profile_report:
            print(result.profile_report)  # 7-category table (gaussian.cu:967)
        print(f"I/O time: {(t_io + t_out) * 1e3:.3f} (ms)")  # :1093
        print(f"EM time: {em_s * 1e3:.3f} (ms) over "
              f"{sum(r[3] for r in result.sweep_log)} iterations")
    return 0


def _predict_main(args, config) -> int:
    """Inference-only mode: memberships for infile under a saved model.

    The reference has no analog (its .summary is write-only; re-scoring data
    meant a full re-fit) -- this closes the loop on the model file as an
    interchange format. Output is the standard ``<outfile>.results`` plus a
    ``.summary`` echo of the model used.
    """
    from .estimator import GaussianMixture
    from .io import read_data, write_summary
    from .io.writers import stream_results
    from .models import iter_memberships
    from .utils.profiling import trace

    t0 = time.perf_counter()
    # Model first: a bad model path must fail in milliseconds, not after
    # parsing a multi-GB infile.
    try:
        gm = GaussianMixture.from_summary(args.predict_from, config=config)
    except (OSError, ValueError) as e:
        print(f"Cannot load model {args.predict_from!r}: {e}",
              file=sys.stderr)
        return 1
    def _read(path):
        import numpy as np

        return read_data(path,
                         screen=("quarantine" if args.allow_nonfinite
                                 else "off"),
                         screen_dtype=np.dtype(config.dtype))

    data, rc = _read_events_or_none(_read, args.infile)
    if data is None:
        return rc
    if config.validate_input:
        import numpy as np

        from .validation import InvalidInputError, validate_finite

        try:
            validate_finite(data, dtype=np.dtype(config.dtype))
        except InvalidInputError as e:
            print(str(e), file=sys.stderr)
            return 1
    d_model = gm.result_.num_dimensions
    if data.shape[1] != d_model:
        print(f"Model has {d_model} dimensions but {args.infile!r} has "
              f"{data.shape[1]}.", file=sys.stderr)
        return 1
    if config.enable_print:
        print(f"Number of events: {data.shape[0]}")
        print(f"Scoring under {gm.n_components_}-cluster model "
              f"{args.predict_from!r}.")
        _print_clusters(gm.result_)
    echo_path = args.outfile + ".summary"
    if (os.path.exists(echo_path)
            and os.path.samefile(echo_path, args.predict_from)):
        # The echo is a re-derived (pi-from-N, non-PD-reset) copy, not a
        # byte copy -- never let it clobber the model it was loaded from.
        print(f"outfile would overwrite the loaded model {echo_path!r}; "
              "skipping the .summary echo", file=sys.stderr)
    else:
        write_summary(echo_path, gm.result_,
                      enable_output=config.enable_output)
    if config.enable_output:
        with trace(args.trace_dir):
            stream_results(args.outfile + ".results",
                           iter_memberships(gm.result_, data, config))
    if config.profile:
        print(f"Inference time: {(time.perf_counter() - t0) * 1e3:.3f} (ms)")
    return 0


def _probe_writable(path: str) -> None:
    """Raise OSError unless ``path`` will accept a write (without ever
    creating-then-removing the target itself -- that could race a
    concurrent process's freshly written file)."""
    if os.path.exists(path):
        # Existing target: append is non-destructive, so probe it directly
        # (also rejects directories / read-only files), and never remove it.
        with open(path, "a"):
            pass
        return
    import tempfile

    if os.path.lexists(path):
        # Dangling symlink: the eventual write follows the link, so probe
        # the RESOLVED parent directory (a sibling probe next to the
        # symlink would test the wrong filesystem).
        target = os.path.realpath(path)
    else:
        # Absent target: probe with a unique sibling temp file.
        target = path
    fd, probe = tempfile.mkstemp(
        dir=os.path.dirname(target) or ".",
        prefix=os.path.basename(target) + ".probe.")
    os.close(fd)
    os.remove(probe)


def _all_ranks_ok(ok: bool, nproc: int) -> bool:
    """Collectively agree a proceed/abort decision (see allgather_host)."""
    if nproc <= 1:
        return ok
    import numpy as np

    from .parallel.distributed import allgather_host

    return bool(allgather_host(np.asarray([ok])).all())


def _read_events_or_none(reader, path):
    """Shared input-parse guard (gaussian.cu:204-205 message): returns
    ``(value, 0)``, or ``(None, exit_code)`` after printing the
    reference's abort message. Unreadable or torn input (OSError, a
    truncated BIN payload) maps to 74 (EX_IOERR); malformed CONTENT
    (ragged rows, empty file) keeps the reference's exit 1."""
    from .io.readers import TruncatedInputError

    try:
        return reader(path), 0
    except Exception as e:
        print("Error parsing input file. This could be due to an empty file "
              f"or an inconsistent number of dimensions. Aborting. ({e})",
              file=sys.stderr)
        from . import supervisor as supervisor_mod

        if isinstance(e, (OSError, TruncatedInputError)):
            return None, supervisor_mod.EX_IOERR
        return None, 1


def _print_clusters(result) -> None:
    """Final-model stdout dump (the reference's ENABLE_PRINT path prints
    every saved cluster via printCluster, gaussian.cu:1032-1039, 1199-1201)."""
    import numpy as np

    from .io.writers import write_cluster

    state = result.state
    means = result.means
    for c in range(result.ideal_num_clusters):
        print(f"Cluster #{c}")
        write_cluster(
            sys.stdout,
            float(np.asarray(state.pi)[c]), float(np.asarray(state.N)[c]),
            means[c], np.asarray(state.R)[c],
        )
        print()


def _parse_mesh(spec):
    if not spec:
        return None
    parts = [int(x) for x in spec.split(",")]
    if len(parts) == 1:
        return (parts[0], 1)
    if len(parts) == 2:
        return tuple(parts)
    raise SystemExit("--mesh must be DATA or DATA,CLUSTER")


if __name__ == "__main__":
    sys.exit(main())
