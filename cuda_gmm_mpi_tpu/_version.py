"""Single in-package version source, dependency-free.

Kept apart from __init__ so tooling that wants the version without the
package's eager jax-importing surface can read this module (or the file)
directly. Bump together with pyproject.toml.
"""

__version__ = "0.5.0"
