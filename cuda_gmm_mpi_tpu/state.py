"""GMM model state as a JAX pytree.

TPU-native re-design of the reference's ``clusters_t`` struct-of-arrays
(``gaussian.h:62-76``): the same fields (N, pi, constant, avgvar, means, R, Rinv)
plus an ``active`` mask that replaces the reference's realloc-and-shift cluster
compaction (``gaussian.cu:866-874, 902-907``) with fixed shapes, so EM never
recompiles per K. The model-order sweep bucket-compacts between Ks
(``compact_to`` + ``bucket_width``): the padded width shrinks to the active
count's power-of-two bucket, bounding compiles at ceil(log2 K0) + 1 widths
while cutting the masked-slot waste that a single fixed width pays at small K
(docs/PERF.md "Bucketed cluster-width compaction").

The big ``memberships`` array (N x M posteriors, ``gaussian.h:75``) is deliberately
NOT part of the state: the fused E+M pass never materializes it (SURVEY.md SS7
"hard parts"); posteriors are recomputed on demand for output only.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GMMState:
    """Parameters of a K-component Gaussian mixture, padded to a fixed K.

    Shapes (K = padded cluster count, D = dimensions):
      N        [K]     soft event counts      (clusters_t.N)
      pi       [K]     mixture weights        (clusters_t.pi)
      constant [K]     log normalizing const  (clusters_t.constant)
                       = -D/2*ln(2*pi) - 1/2*ln|R|   (gaussian_kernel.cu:241)
      avgvar   [K]     diagonal regularizer   (clusters_t.avgvar)
      means    [K, D]                         (clusters_t.means)
      R        [K, D, D] covariance           (clusters_t.R)
      Rinv     [K, D, D] inverse covariance   (clusters_t.Rinv)
      active   [K]     bool mask; True = cluster participates. Replaces the
                       reference's in-place compaction; inactive clusters are
                       algebraically inert (log-density forced to -inf).
    """

    N: jax.Array
    pi: jax.Array
    constant: jax.Array
    avgvar: jax.Array
    means: jax.Array
    R: jax.Array
    Rinv: jax.Array
    active: jax.Array

    @property
    def num_clusters_padded(self) -> int:
        return self.N.shape[0]

    @property
    def num_dimensions(self) -> int:
        return self.means.shape[-1]

    def num_active(self) -> jax.Array:
        """Number of active clusters (traced value under jit)."""
        return jnp.sum(self.active.astype(jnp.int32))

    def replace(self, **kwargs) -> "GMMState":
        return dataclasses.replace(self, **kwargs)


def zeros_state(num_clusters: int, num_dimensions: int, dtype=jnp.float32) -> GMMState:
    """An all-inactive state of the given padded size."""
    K, D = num_clusters, num_dimensions
    eye = jnp.broadcast_to(jnp.eye(D, dtype=dtype), (K, D, D))
    return GMMState(
        N=jnp.zeros((K,), dtype),
        pi=jnp.zeros((K,), dtype),
        constant=jnp.zeros((K,), dtype),
        avgvar=jnp.zeros((K,), dtype),
        means=jnp.zeros((K, D), dtype),
        R=eye,
        Rinv=eye,
        active=jnp.zeros((K,), bool),
    )


def bucket_width(k_active: int, padded: int, multiple: int = 1,
                 mode: str = "pow2") -> int:
    """Padded width the sweep should run ``k_active`` clusters at.

    ``pow2``: the smallest power of two >= k_active, rounded up to a
    multiple of ``multiple`` (the cluster-mesh axis extent, so sharded
    states stay evenly partitionable) and clamped to the current
    ``padded`` width (buckets only ever shrink). ``off``: the current
    width, i.e. no rebucketing. Bounds the distinct EM widths of a
    K0 -> 1 sweep to ceil(log2 K0) + 1.
    """
    if mode == "off":
        return padded
    if mode != "pow2":
        raise ValueError(f"unknown bucket mode {mode!r}")
    w = 1 << max(0, k_active - 1).bit_length()  # smallest pow2 >= k_active
    if multiple > 1:
        w = ((w + multiple - 1) // multiple) * multiple
    return min(w, padded)


@functools.partial(jax.jit, static_argnames=("num_clusters",))
def compact_to(state: GMMState, num_clusters: int) -> GMMState:
    """Jittable shape-SHRINKING compaction: gather active rows to the front.

    The device-side sibling of :func:`compact` with a STATIC output width,
    so the model-order sweep can rebuild a narrower state when the active
    count crosses a bucket boundary (order_search's ``sweep_k_buckets``)
    without a host round trip. Active clusters keep their relative order
    (the reference's left-shift compaction order, gaussian.cu:869-871);
    trailing rows beyond the active count are filled with inactive slots
    (in original order), which stay algebraically inert through the
    ``active`` mask. ``num_clusters`` must be >= the active count --
    callers derive it from the host-known k (``bucket_width``).
    """
    K = state.num_clusters_padded
    if num_clusters > K:
        raise ValueError(
            f"compact_to grows the state ({K} -> {num_clusters}); use "
            "parallel.sharded_em.pad_state_clusters to widen")
    pos = jnp.arange(K, dtype=jnp.int32)
    # Unique integer keys (active slots first, original order preserved on
    # both sides) make the argsort deterministic without relying on a
    # stable-sort guarantee.
    idx = jnp.argsort(jnp.where(state.active, pos, pos + K))[:num_clusters]
    take = lambda a: jnp.take(a, idx, axis=0)
    return GMMState(
        N=take(state.N), pi=take(state.pi), constant=take(state.constant),
        avgvar=take(state.avgvar), means=take(state.means), R=take(state.R),
        Rinv=take(state.Rinv), active=take(state.active),
    )


def clone_state(state: GMMState) -> GMMState:
    """Fresh-buffer copy of a state (async device copy; no host sync).

    The recovery rollback point: the sweep donates each K's input state
    into the EM call (``run_em(donate=True)`` reuses its buffers in
    place), so rolling back after a detected numerical fault needs a
    clone taken BEFORE the donation. A state is K x D x D-small -- the
    clone costs ~one parameter-set of HBM, nothing against the event
    data, and dispatches asynchronously.
    """
    return jax.tree_util.tree_map(jnp.copy, state)


def compact(state: GMMState) -> Tuple[GMMState, int]:
    """Host-side compaction: drop inactive clusters, preserving relative order.

    Equivalent to the reference's left-shift compaction (gaussian.cu:869-871,
    903-907) applied at output time. Not jittable (shape depends on the mask).
    """
    mask = jax.device_get(state.active)
    idx = jnp.asarray([i for i, a in enumerate(mask) if a], dtype=jnp.int32)
    n_active = int(idx.shape[0])
    take = lambda a: jnp.take(jnp.asarray(jax.device_get(a)), idx, axis=0)
    return (
        GMMState(
            N=take(state.N), pi=take(state.pi), constant=take(state.constant),
            avgvar=take(state.avgvar), means=take(state.means), R=take(state.R),
            Rinv=take(state.Rinv), active=jnp.ones((n_active,), bool),
        ),
        n_active,
    )
