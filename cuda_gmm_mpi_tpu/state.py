"""GMM model state as a JAX pytree.

TPU-native re-design of the reference's ``clusters_t`` struct-of-arrays
(``gaussian.h:62-76``): the same fields (N, pi, constant, avgvar, means, R, Rinv)
plus an ``active`` mask that replaces the reference's realloc-and-shift cluster
compaction (``gaussian.cu:866-874, 902-907``) with fixed shapes, so the whole
model-order sweep runs under a single jit compilation instead of recompiling per K.

The big ``memberships`` array (N x M posteriors, ``gaussian.h:75``) is deliberately
NOT part of the state: the fused E+M pass never materializes it (SURVEY.md SS7
"hard parts"); posteriors are recomputed on demand for output only.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GMMState:
    """Parameters of a K-component Gaussian mixture, padded to a fixed K.

    Shapes (K = padded cluster count, D = dimensions):
      N        [K]     soft event counts      (clusters_t.N)
      pi       [K]     mixture weights        (clusters_t.pi)
      constant [K]     log normalizing const  (clusters_t.constant)
                       = -D/2*ln(2*pi) - 1/2*ln|R|   (gaussian_kernel.cu:241)
      avgvar   [K]     diagonal regularizer   (clusters_t.avgvar)
      means    [K, D]                         (clusters_t.means)
      R        [K, D, D] covariance           (clusters_t.R)
      Rinv     [K, D, D] inverse covariance   (clusters_t.Rinv)
      active   [K]     bool mask; True = cluster participates. Replaces the
                       reference's in-place compaction; inactive clusters are
                       algebraically inert (log-density forced to -inf).
    """

    N: jax.Array
    pi: jax.Array
    constant: jax.Array
    avgvar: jax.Array
    means: jax.Array
    R: jax.Array
    Rinv: jax.Array
    active: jax.Array

    @property
    def num_clusters_padded(self) -> int:
        return self.N.shape[0]

    @property
    def num_dimensions(self) -> int:
        return self.means.shape[-1]

    def num_active(self) -> jax.Array:
        """Number of active clusters (traced value under jit)."""
        return jnp.sum(self.active.astype(jnp.int32))

    def replace(self, **kwargs) -> "GMMState":
        return dataclasses.replace(self, **kwargs)


def zeros_state(num_clusters: int, num_dimensions: int, dtype=jnp.float32) -> GMMState:
    """An all-inactive state of the given padded size."""
    K, D = num_clusters, num_dimensions
    eye = jnp.broadcast_to(jnp.eye(D, dtype=dtype), (K, D, D))
    return GMMState(
        N=jnp.zeros((K,), dtype),
        pi=jnp.zeros((K,), dtype),
        constant=jnp.zeros((K,), dtype),
        avgvar=jnp.zeros((K,), dtype),
        means=jnp.zeros((K, D), dtype),
        R=eye,
        Rinv=eye,
        active=jnp.zeros((K,), bool),
    )


def compact(state: GMMState) -> Tuple[GMMState, int]:
    """Host-side compaction: drop inactive clusters, preserving relative order.

    Equivalent to the reference's left-shift compaction (gaussian.cu:869-871,
    903-907) applied at output time. Not jittable (shape depends on the mask).
    """
    mask = jax.device_get(state.active)
    idx = jnp.asarray([i for i, a in enumerate(mask) if a], dtype=jnp.int32)
    n_active = int(idx.shape[0])
    take = lambda a: jnp.take(jnp.asarray(jax.device_get(a)), idx, axis=0)
    return (
        GMMState(
            N=take(state.N), pi=take(state.pi), constant=take(state.constant),
            avgvar=take(state.avgvar), means=take(state.means), R=take(state.R),
            Rinv=take(state.Rinv), active=jnp.ones((n_active,), bool),
        ),
        n_active,
    )
