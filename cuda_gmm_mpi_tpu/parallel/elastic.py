"""Elastic multi-host membership: shrink the world and continue.

The reference is fail-stop: one dead MPI rank kills (or wedges) the whole
``gaussianMPI`` job, because every collective assumes the fixed
MPI_COMM_WORLD built at startup (PAPER.md SS0 -- model state replicated,
data broadcast to all nodes). PR 4's liveness watchdog upgraded that hang
to a loud exit 75; this module upgrades exit 75 to *continuing*: when a
peer is declared lost, the surviving hosts rendezvous ON THE CHECKPOINT
FILESYSTEM (the only channel that does not need the dead peer), agree on a
shrunken world via a generation-stamped membership file, and the drivers
refit over the survivors -- bounds recomputed by ``host_chunk_bounds``,
shards re-read through the pipelined source, state restored from the
newest checkpoint (replicated, so any world size can restore it).

Protocol (docs/DISTRIBUTED.md "Elastic recovery"):

1. Generation ``g`` is the current membership: ``membership/gen<g>.json``
   holding the surviving ORIGINAL rank ids (sorted) and the original world
   size. Generation 0 is implicit (all ranks of the launch world) unless a
   seed file exists.
2. On ``PeerLostError`` each survivor *announces* itself for generation
   ``g+1`` (``gen<g+1>.rank<r>.alive`` marker, atomic tmp+rename).
3. The COORDINATOR -- the lowest announced surviving rank -- collects
   announcements for a bounded window, then atomically publishes
   ``gen<g+1>.json`` with the announced set. Ties are impossible (ranks
   are unique); determinism for a given survivor set follows from the
   sorted rank list and the single writer.
4. Non-coordinators poll for the published file (bounded); a rank that
   finds itself EXCLUDED (it announced too late) exits 75 exactly as a
   non-elastic peer loss would -- the survivors' membership is already
   sealed and must not be perturbed.

The *world overlay* is the process-local consequence of a new membership:
``world()`` reports (my contiguous rank, world size) over the survivors
instead of the launch-time ``jax.process_index()/process_count()``, and
``host_chunk_bounds`` consumers (order_search._prepare_fit) re-shard with
it. NOTE the JAX multi-controller runtime itself cannot shrink in
process: a real multi-host shrink needs the launcher to restart the
runtime at the new world size (docs/DISTRIBUTED.md); in-process elastic
recovery is exact for single-controller runs (including the simulated
multi-rank chaos harness) and :func:`assert_world_coherent` fails loudly
-- instead of hanging in the first collective -- when an overlay diverges
from a live multi-controller runtime.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, List, Optional, Tuple

from ..testing import faults

MEMBERSHIP_DIRNAME = "membership"

_GEN_FMT = "gen{g}.json"


@dataclasses.dataclass(frozen=True)
class Membership:
    """One sealed generation of the elastic world.

    ``ranks`` are ORIGINAL (launch-world) rank ids, sorted; a rank's
    position in the tuple is its new contiguous rank, so shard bounds and
    coordinator election are deterministic for a given survivor set.
    """

    generation: int
    ranks: Tuple[int, ...]
    world_size0: int  # the launch world's size (generation 0)

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    def index_of(self, orig_rank: int) -> Optional[int]:
        """The survivor's new contiguous rank, or None if excluded."""
        try:
            return self.ranks.index(int(orig_rank))
        except ValueError:
            return None


def membership_dir(checkpoint_dir: str) -> str:
    return os.path.join(os.path.abspath(checkpoint_dir),
                        MEMBERSHIP_DIRNAME)


def _fsync_dir(directory: str) -> None:
    """POSIX-gated directory fsync: durably persist a just-renamed entry.

    Windows cannot ``os.open`` a directory (and rename durability is the
    filesystem's problem there); skip instead of crashing.
    """
    if os.name != "posix":
        return
    try:
        dir_fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def write_membership(directory: str, m: Membership) -> str:
    """Atomically publish one generation file (tmp + replace + dir fsync).

    The single-writer publish of the rendezvous protocol: a reader either
    sees the complete file or no file, never a torn one.
    """
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, _GEN_FMT.format(g=int(m.generation)))
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"generation": int(m.generation),
                   "ranks": [int(r) for r in m.ranks],
                   "world_size0": int(m.world_size0),
                   "sealed_at": time.time()}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(directory)
    return path


def read_membership(directory: str,
                    generation: Optional[int] = None) -> Optional[Membership]:
    """The requested (default: newest) sealed generation, or None."""
    if not os.path.isdir(directory):
        return None
    if generation is None:
        gens = []
        for f in os.listdir(directory):
            if f.startswith("gen") and f.endswith(".json"):
                body = f[3:-5]
                if body.isdigit():
                    gens.append(int(body))
        if not gens:
            return None
        generation = max(gens)
    path = os.path.join(directory, _GEN_FMT.format(g=int(generation)))
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return Membership(generation=int(doc["generation"]),
                      ranks=tuple(sorted(int(r) for r in doc["ranks"])),
                      world_size0=int(doc.get("world_size0",
                                              len(doc["ranks"]))))


def announce_alive(directory: str, generation: int, rank: int) -> str:
    """This rank's survivor announcement for ``generation`` (atomic)."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory,
                        f"gen{int(generation)}.rank{int(rank):05d}.alive")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"{os.getpid()} {time.time():.3f}\n")
    os.replace(tmp, path)
    return path


def announced_ranks(directory: str, generation: int) -> List[int]:
    """Sorted original ranks that have announced for ``generation``."""
    if not os.path.isdir(directory):
        return []
    prefix = f"gen{int(generation)}.rank"
    out = []
    for f in os.listdir(directory):
        if f.startswith(prefix) and f.endswith(".alive"):
            body = f[len(prefix):-6]
            if body.isdigit():
                out.append(int(body))
    return sorted(out)


def rendezvous(directory: str, *, my_rank: int, prev: Membership,
               lost: Tuple[int, ...], window_s: float = 5.0,
               poll_s: float = 0.05) -> Membership:
    """Seal generation ``prev.generation + 1`` over the survivors.

    Deterministic for a given survivor set: every survivor announces, the
    lowest announced rank publishes the sorted announced set once its
    expected peers are in (or the window closes), everyone else polls for
    the published file. Raises the caller's give-up path
    (:class:`~cuda_gmm_mpi_tpu.supervisor.PeerLostError`) when the file
    never appears -- the coordinator died too; exit 75 as today.
    """
    from .. import supervisor

    gen = int(prev.generation) + 1
    expected = tuple(r for r in prev.ranks
                     if r not in set(int(x) for x in lost))
    if int(my_rank) not in expected:
        raise supervisor.PeerLostError(
            f"rank {my_rank} was declared lost by the generation-{gen} "
            "membership; not rejoining a sealed world", rank=int(my_rank))
    announce_alive(directory, gen, my_rank)

    sealed = read_membership(directory, gen)
    if sealed is not None:
        return _check_included(sealed, my_rank)

    deadline = time.monotonic() + max(float(window_s), 0.0)
    # Coordinator = the lowest rank the PREVIOUS membership expects to
    # survive. If it is actually dead too, its absence surfaces as a
    # publish timeout below and the caller's bounded retry re-runs the
    # whole declare-lost -> rendezvous cycle against the newer loss.
    coordinator = min(expected)
    if int(my_rank) == coordinator:
        while time.monotonic() < deadline:
            have = announced_ranks(directory, gen)
            if set(expected).issubset(have):
                break
            time.sleep(poll_s)
        survivors = tuple(r for r in announced_ranks(directory, gen)
                          if r in expected)
        sealed = Membership(generation=gen, ranks=survivors,
                            world_size0=prev.world_size0)
        write_membership(directory, sealed)
        return _check_included(sealed, my_rank)
    while time.monotonic() < deadline:
        sealed = read_membership(directory, gen)
        if sealed is not None:
            return _check_included(sealed, my_rank)
        time.sleep(poll_s)
    raise supervisor.PeerLostError(
        f"elastic rendezvous for generation {gen} timed out after "
        f"{window_s:.1f}s (coordinator rank {coordinator} did not publish "
        "a membership); giving up", rank=coordinator,
        timeout_s=float(window_s))


def _check_included(sealed: Membership, my_rank: int) -> Membership:
    from .. import supervisor

    if sealed.index_of(my_rank) is None:
        raise supervisor.PeerLostError(
            f"rank {my_rank} is excluded from the sealed generation-"
            f"{sealed.generation} membership {sealed.ranks}; exiting as a "
            "lost peer", rank=int(my_rank))
    return sealed


# -- the process-local world overlay ----------------------------------------

_overlay: Optional[Membership] = None
_overlay_rank: int = 0  # my ORIGINAL rank within the overlay membership
_counters: Dict[str, int] = {"shrinks": 0, "resumes": 0}


def set_world_overlay(m: Membership, my_orig_rank: int) -> None:
    """Adopt a sealed membership as this process's effective world."""
    global _overlay, _overlay_rank
    idx = m.index_of(my_orig_rank)
    if idx is None:
        raise ValueError(
            f"rank {my_orig_rank} is not in membership {m.ranks}")
    _overlay = m
    _overlay_rank = int(my_orig_rank)


def clear_world_overlay() -> None:
    global _overlay
    _overlay = None


def current_membership() -> Optional[Membership]:
    return _overlay


def generation() -> int:
    """The effective membership generation (0 = the launch world)."""
    return 0 if _overlay is None else int(_overlay.generation)


def world() -> Tuple[int, int]:
    """(rank, world_size) of the EFFECTIVE world: the elastic overlay when
    one is adopted, the launch runtime otherwise. Shard-bounds consumers
    (``host_chunk_bounds`` callers) use this instead of raw
    ``jax.process_index()/process_count()`` so a refit after a shrink
    recomputes every survivor's slice over the new world."""
    if _overlay is not None:
        return int(_overlay.index_of(_overlay_rank)), _overlay.world_size
    import jax

    return int(jax.process_index()), int(jax.process_count())


def original_rank() -> int:
    """This process's LAUNCH-world rank (heartbeat files, membership
    announcements, and coordinator election all speak original ranks)."""
    if _overlay is not None:
        return _overlay_rank
    import jax

    return int(jax.process_index())


def peer_ranks() -> Optional[List[int]]:
    """Original rank ids of my current peers (heartbeat files to watch),
    or None when no overlay is adopted (watch the whole launch world)."""
    if _overlay is None:
        return None
    return [int(r) for r in _overlay.ranks if int(r) != _overlay_rank]


def assert_world_coherent() -> None:
    """Fail loudly -- instead of hanging in the first collective -- when
    an elastic overlay shrank the world but the live multi-controller
    runtime still spans the launch world. The runtime cannot drop ranks
    in process; a real multi-host shrink restarts it at the new size
    (docs/DISTRIBUTED.md "Elastic recovery")."""
    if _overlay is None:
        return
    import jax

    if int(jax.process_count()) > 1 \
            and int(jax.process_count()) != _overlay.world_size:
        raise RuntimeError(
            f"elastic membership generation {_overlay.generation} has "
            f"{_overlay.world_size} host(s) but the live multi-controller "
            f"runtime spans {jax.process_count()}: collectives would hang "
            "on the dead ranks. Restart the surviving hosts' runtime at "
            "the new world size (docs/DISTRIBUTED.md 'Elastic recovery').")


def note_shrink() -> None:
    _counters["shrinks"] += 1


def note_resume() -> None:
    _counters["resumes"] += 1


def run_summary_section() -> Optional[dict]:
    """The ``run_summary.elastic`` block (None when nothing elastic
    happened -- clean runs carry no elastic section)."""
    if _overlay is None and not _counters["shrinks"]:
        return None
    return {
        "generation": generation(),
        "world_size": world()[1],
        "shrinks": int(_counters["shrinks"]),
        "resumes": int(_counters["resumes"]),
    }


def live_gauges() -> dict:
    """Elastic run gauges for the OpenMetrics exporter (rev v2.1;
    telemetry/exporter.py): keys are final metric names. Cheap enough
    to evaluate per scrape; generation 0 / launch world on clean runs,
    so the gauges exist (and are alertable) before anything shrinks."""
    return {
        "gmm_elastic_generation": generation(),
        "gmm_elastic_shrinks": int(_counters["shrinks"]),
        "gmm_elastic_resumes": int(_counters["resumes"]),
    }


def reset() -> None:
    """Test hook: drop the overlay and counters (module state is
    process-wide)."""
    global _overlay
    _overlay = None
    _counters["shrinks"] = 0
    _counters["resumes"] = 0


def take_collective_timeout(name: str, timeout_s) -> None:
    """Deterministic ``collective_timeout`` chaos hook for barriers: when
    armed (and the optional ``name`` matches), raise the exact
    PeerLostError a timed-out collective would."""
    cfg = faults.take("collective_timeout", name=name)
    if cfg is None:
        return
    from .. import supervisor

    raise supervisor.PeerLostError(
        f"barrier {name!r} timed out (injected collective_timeout): a "
        "peer rank is dead or wedged",
        rank=(int(cfg["rank"]) if "rank" in cfg else None),
        timeout_s=float(cfg.get("timeout_s", timeout_s or 0.0)))
