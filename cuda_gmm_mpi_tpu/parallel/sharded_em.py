"""Sharded EM: the whole per-K EM loop as one SPMD program over the mesh.

This is the collapse of the reference's entire L3 layer (SURVEY.md SS2.8/3.2):
where the reference stages every M-step substep device->host->OpenMP
reduction->MPI_Allreduce->host->device (~10 boundary crossings and 4 network
collectives per EM iteration, ``gaussian.cu:541-741``), here the full
``while`` loop runs inside ONE ``shard_map``-wrapped jit:

  - events sharded over the ``data`` mesh axis; each device scans its local
    chunks with the fused E+M pass,
  - sufficient statistics psum'd over ``data`` (the MPI_Allreduce of
    N / means-sums / R-sums / loglik, gaussian.cu:516,566,605,658 -- one fused
    collective of the whole stats pytree instead of four staged ones),
  - optionally clusters sharded over the ``cluster`` axis: the E-step
    normalization becomes a two-stage collective log-sum-exp (pmax + psum)
    and each shard updates only its own clusters' parameters,
  - parameter update replicated (data axis) / local (cluster axis); no
    parameter broadcast ever happens because SPMD program order replaces the
    reference's MPI_Bcast-after-merge (gaussian.cu:918-924).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import GMMConfig
from ..models.gmm import GMMModel, em_while_loop, resolve_iters
from ..ops.mstep import SuffStats
from ..ops.estep import posteriors
from ..telemetry import profiling as tl_profiling
from .mesh import (
    CLUSTER_AXIS, DATA_AXIS, make_mesh, pad_clusters, shard_chunks,
    state_pspecs,
)

try:  # newer jax exposes shard_map at top level; fall back to experimental
    from jax import shard_map as _shard_map_impl  # type: ignore
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def _shard_map_params() -> frozenset:
    import inspect

    return frozenset(inspect.signature(_shard_map_impl).parameters)


_SHARD_MAP_PARAMS = _shard_map_params()

# check_rep-era (older) jax has a second relevant limitation: an ordered
# io_callback inside a shard_map'd while_loop trips an XLA
# sharding-propagation CHECK abort (process-killing, not catchable), so
# per-K fused-sweep emission must be declared unsupported there -- fused
# runs that want emission (checkpoint/profile/telemetry) then fall back to
# the host-driven sweep with a warning instead of crashing.
SHARD_MAP_FUSED_EMIT_OK = "check_vma" in _SHARD_MAP_PARAMS


def shard_map(f, *, check_vma=None, **kwargs):
    """Version-bridging shard_map: newer jax spells the replication-check
    flag ``check_vma``, older releases ``check_rep`` (same semantics, and
    this codebase always disables it -- the EM state specs are replicated
    by construction). Translate to whatever the installed jax accepts so
    every mesh path works across the supported version range."""
    if check_vma is not None:
        if "check_vma" in _SHARD_MAP_PARAMS:
            kwargs["check_vma"] = check_vma
        elif "check_rep" in _SHARD_MAP_PARAMS:
            kwargs["check_rep"] = check_vma
    return _shard_map_impl(f, **kwargs)


def pad_state_clusters(state, cluster_size: int):
    """Pad the state's K axis to a multiple of the cluster-axis size with
    inert (inactive, identity-R) slots. No-op when already aligned."""
    Kp = pad_clusters(state.num_clusters_padded, cluster_size)
    if Kp == state.num_clusters_padded:
        return state
    pad = Kp - state.num_clusters_padded
    D = state.num_dimensions
    eye = jnp.broadcast_to(jnp.eye(D, dtype=state.R.dtype), (pad, D, D))
    zk = jnp.zeros((pad,), state.N.dtype)
    return state.replace(
        N=jnp.concatenate([state.N, zk]),
        pi=jnp.concatenate([state.pi, zk]),
        constant=jnp.concatenate([state.constant, zk]),
        avgvar=jnp.concatenate([state.avgvar, zk]),
        means=jnp.concatenate(
            [state.means, jnp.zeros((pad, D), state.means.dtype)]
        ),
        R=jnp.concatenate([state.R, eye]),
        Rinv=jnp.concatenate([state.Rinv, eye]),
        active=jnp.concatenate([state.active, jnp.zeros((pad,), bool)]),
    )


def prepare_inference_state(model, state):
    """(placed_state, K_columns): the shared all-local-devices inference
    preparation for ShardedGMMModel and the mesh StreamingGMMModel.

    Localizes a multi-controller global state (non-fully-addressable
    leaves) to host numpy first, pads K to the cluster axis when the model
    shards clusters, and places the result on ``model._inference_mesh`` in
    ONE host->device transfer. One-slot cache keyed on the state's
    identity so a streamed output pass prepares once; the strong reference
    (not ``id()``) pins the state so a recycled address can never serve a
    stale prepared state.
    """
    cached = model._inference_cache
    if cached is not None and cached[0] is state:
        return cached[1], cached[2]
    local = state
    if any(isinstance(a, jax.Array) and not a.is_fully_addressable
           for a in jax.tree_util.tree_leaves(state)):
        local = jax.tree_util.tree_map(
            lambda a: np.asarray(jax.device_get(a)), state)
    k_cols = int(np.asarray(jax.device_get(local.N)).shape[0])
    if model.cluster_size > 1:
        local = pad_state_clusters(
            jax.tree_util.tree_map(jnp.asarray, local), model.cluster_size)
    prepared = jax.device_put(
        local,
        jax.tree_util.tree_map(
            lambda s: NamedSharding(model._inference_mesh, s),
            state_pspecs()),
    )
    model._inference_cache = (state, prepared, k_cols)
    return prepared, k_cols


def infer_posteriors_sharded(model, state, xb):
    """(w [B, K], logZ [B]) for one [inference_block, D] event block,
    computed on all of the model's local devices in parallel."""
    prepared, k_cols = prepare_inference_state(model, state)
    # device_put straight from the host buffer: one per-shard placement,
    # no intermediate default-device commit.
    xb = jax.device_put(xb, model._x_sharding)
    w, logz = model._post_sharded(prepared, xb)
    return w[:, :k_cols], logz


def memberships_sharded(model, state, data_chunks,
                        return_logz: bool = False):
    """Materialized posteriors [N_padded, K] -- output path only.

    Same contract as GMMModel.memberships, but each block of
    ``_inference_data_size`` chunks is evaluated in ONE sharded dispatch
    across the host's local devices (the within-host half of the
    reference's all-GPU membership recompute, gaussian.cu:768-823).
    """
    chunks = np.asarray(data_chunks)
    C, B, D = chunks.shape
    S = model._inference_data_size
    w_out, z_out = [], []
    for i in range(0, C, S):
        blk = chunks[i:i + S]
        nvalid = blk.shape[0]
        if nvalid < S:  # pad the tail to a whole sharded block
            blk = np.concatenate(
                [blk, np.zeros((S - nvalid, B, D), blk.dtype)])
        w, logz = model.infer_posteriors(state, blk.reshape(S * B, D))
        w_out.append(np.asarray(jax.device_get(w))[:nvalid * B])
        if return_logz:
            z_out.append(np.asarray(jax.device_get(logz))[:nvalid * B])
    w = np.concatenate(w_out, axis=0)
    if return_logz:
        return w, np.concatenate(z_out, axis=0)
    return w


def batched_state_pspecs():
    """PartitionSpecs for a restart-batched GMMState: the leading restart
    axis is replicated (every shard holds all R lanes of its cluster
    slice); the K axis keeps its cluster sharding."""
    return jax.tree_util.tree_map(
        lambda s: P(*((None,) + tuple(s))), state_pspecs()
    )


def make_psum_reduce(data_axis: str = DATA_AXIS):
    """Stats reduction hook: one psum of the whole SuffStats pytree.

    The TPU-native MPI_Allreduce (SURVEY.md SS2.8 table): loglik, Nk, M1, M2
    reduced in a single fused collective over the event-sharding axis.
    """

    def reduce(stats: SuffStats) -> SuffStats:
        return jax.tree_util.tree_map(
            lambda a: lax.psum(a, data_axis), stats
        )

    return reduce


class ShardedGMMModel:
    """Drop-in GMMModel with the EM loop running under shard_map on a mesh.

    Interface-compatible with GMMModel.run_em/memberships so fit_gmm and the
    order search are oblivious to the parallelism (the reference needed
    bespoke MPI/OpenMP plumbing through every step of main()).
    """

    # Per-K fused-sweep emission: the io_callback fires once per local
    # device shard (cluster shards all-gathered to full state first); the
    # host sink dedupes by step. See make_fused_sweep. Version-gated:
    # check_rep-era jax CHECK-aborts on io_callback under shard_map
    # (SHARD_MAP_FUSED_EMIT_OK above), where emission-wanting runs fall
    # back to the host-driven sweep.
    supports_fused_emit = SHARD_MAP_FUSED_EMIT_OK

    def __init__(self, config: GMMConfig = GMMConfig(), mesh=None,
                 stats_fn=None):
        self.config = config
        self._emit_target = None  # host sink for fused-sweep per-K emission
        self.last_health = None  # health counters of the latest run_em
        self.mesh = mesh if mesh is not None else make_mesh(config.mesh_shape)
        self.data_size = self.mesh.shape[DATA_AXIS]
        self.cluster_size = self.mesh.shape[CLUSTER_AXIS]
        cluster_axis = CLUSTER_AXIS if self.cluster_size > 1 else None

        kw = dict(
            diag_only=config.diag_only,
            quad_mode=config.quad_mode,
            matmul_precision=config.matmul_precision,
        )
        self._kw = kw

        from ..ops.pallas import (
            make_batched_stats_fn, make_mstep_fn, make_stats_fn,
            resolve_estep_backend,
        )

        if stats_fn is None:
            self.estep_backend, self.estep_backend_reason = \
                resolve_estep_backend(
                    config, cluster_sharded=cluster_axis is not None)
            stats_fn = make_stats_fn(
                config, cluster_sharded=cluster_axis is not None,
                cluster_axis=cluster_axis,
            )
            # Batched kernel + fused M-step epilogue: data-axis-sharded
            # meshes only (the hooks are None on cluster-sharded meshes,
            # whose pi/tied psums live in the jnp update).
            self._batched_stats_fn = make_batched_stats_fn(
                config, cluster_sharded=cluster_axis is not None)
            self._mstep_fn = make_mstep_fn(
                config, cluster_sharded=cluster_axis is not None)
            self._mstep_fn_batched = make_mstep_fn(
                config, cluster_sharded=cluster_axis is not None,
                batched=True)
        else:
            self.estep_backend = "custom"
            self.estep_backend_reason = "caller-supplied stats_fn"
            self._batched_stats_fn = None
            self._mstep_fn = self._mstep_fn_batched = None
        self._stats_fn = stats_fn
        self._cluster_axis = cluster_axis
        # Buckets must stay evenly partitionable over the cluster axis
        # (order_search rounds widths up to this before rebucketing).
        self.bucket_multiple = self.cluster_size
        # EM executables per (trajectory_len, donate) variant; jax.jit's
        # shape-keyed cache handles the per-bucket-width memoization within
        # each variant (same contract as GMMModel._em_executable).
        self._em_exec_cache: dict = {}
        self._em_run = self._em_executable(0, False)

        # Posterior pass for output/inference: ALL local devices in parallel
        # (the reference computes final memberships on every GPU and gathers,
        # gaussian.cu:768-823; round-1/2 funneled this through one device).
        # Multi-host runs use the host-local submesh so each host's output
        # pass is collective-free across hosts.
        self._inference_mesh = (
            self.mesh if jax.process_count() == 1 else self.mesh.local_mesh
        )
        self._inference_data_size = self._inference_mesh.shape[DATA_AXIS]
        post_fn = functools.partial(posteriors, cluster_axis=cluster_axis,
                                    **kw)
        sspec = state_pspecs()
        self._post_sharded = jax.jit(
            shard_map(
                lambda s, x: post_fn(s, x),
                mesh=self._inference_mesh,
                in_specs=(sspec, P(DATA_AXIS, None)),
                out_specs=(P(DATA_AXIS, CLUSTER_AXIS), P(DATA_AXIS)),
                check_vma=False,
            )
        )
        self._x_sharding = NamedSharding(self._inference_mesh,
                                         P(DATA_AXIS, None))
        self._inference_cache = None  # one-slot (id(state) -> prepared)

        # Rank-tag the ambient telemetry stream (rev v2.3): per-rank
        # records carry the pre-shrink rank and world size, so `gmm
        # timeline` can lay multi-host stream directories out as one
        # Perfetto track per rank. Context-only -- inactive recorders
        # (the default) emit nothing, keeping no-recorder runs
        # byte-identical.
        from ..telemetry import recorder as _tl_recorder
        from . import elastic as _elastic
        rec = _tl_recorder.current()
        if rec.active:
            rec.set_context(rank=int(_elastic.original_rank()),
                            world_size=int(jax.process_count()))

    def prepare(self, state, data_chunks, wts_chunks, host_local: bool = False):
        """Pad K to the cluster-axis size and place data sharded on the mesh.

        ``host_local=True`` (required under ``jax.process_count() > 1``)
        declares that ``data_chunks``/``wts_chunks`` are THIS host's slice of
        the global chunk grid (equal-shaped across hosts, from
        ``distributed.host_chunk_bounds``); the global sharded arrays are then
        assembled with zero cross-host traffic.
        """
        from . import elastic

        # Elastic worlds: a sealed shrink that diverged from the live
        # multi-controller runtime must fail loudly here, not hang in the
        # first psum on the dead ranks (docs/DISTRIBUTED.md).
        elastic.assert_world_coherent()
        if jax.process_count() > 1:
            from .distributed import (
                require_host_local_chunks, sharded_chunks_from_host_data,
            )

            # Shared multi-controller contract (clear error instead of a
            # shape-mismatch deadlock); then assemble the global sharded
            # arrays from the equal-shaped host-local slices with zero
            # cross-host traffic.
            require_host_local_chunks(
                host_local, np.asarray(data_chunks).shape,
                "silently duplicate every event process_count times")
            chunks, wts = sharded_chunks_from_host_data(
                self.mesh, np.asarray(data_chunks), np.asarray(wts_chunks)
            )
        else:
            chunks, wts = shard_chunks(self.mesh, data_chunks, wts_chunks)
        return self.prepare_state(state), chunks, wts

    def prepare_state(self, state):
        """Pad the state's K axis to the cluster mesh axis and place it on
        the mesh -- WITHOUT touching any data chunks (the checkpoint-restore
        path uses this so resuming never re-uploads the dataset). The state
        is replicated on every host; converting it requires that no cluster
        shard spans hosts."""
        state = pad_state_clusters(state, self.cluster_size)
        sspec = state_pspecs()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            local_cluster = self.mesh.local_mesh.shape[CLUSTER_AXIS]
            if local_cluster != self.cluster_size:
                raise NotImplementedError(
                    "multi-host runs require the cluster mesh axis to fit "
                    f"within one host (cluster axis {self.cluster_size}, "
                    f"host-local extent {local_cluster}); put hosts on the "
                    "data axis"
                )
            return multihost_utils.host_local_array_to_global_array(
                state, self.mesh, sspec
            )
        return jax.device_put(
            state,
            jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s), sspec
            ),
        )

    def _em_executable(self, trajectory_len: int, donate: bool):
        """Memoized SPMD EM loop per (trajectory, donation) variant.

        After the psum the loglik (and the trajectory log) is replicated on
        every shard, so those out-specs are fully replicated like the
        scalars. ``donate`` forwards the state's buffers for in-place reuse
        (same contract as GMMModel.run_em's ``donate``).
        """
        key = (trajectory_len, donate)
        fn = self._em_exec_cache.get(key)
        if fn is None:
            em_fn = functools.partial(
                em_while_loop,
                reduce_stats=make_psum_reduce(DATA_AXIS),
                cluster_axis=self._cluster_axis,
                stats_fn=self._stats_fn,
                mstep_fn=self._mstep_fn,
                covariance_type=self.config.covariance_type,
                precompute_features=self.config.precompute_features,
                trajectory_len=trajectory_len,
                dynamic_range=self.config.covariance_dynamic_range,
                regression_scale=self.config.health_regression_scale,
                **self._kw,
            )
            sspec = state_pspecs()
            scalar = P()
            out_specs = (sspec, scalar, scalar)
            if trajectory_len:
                out_specs = out_specs + (scalar,)
            # Trailing health counters: replicated by construction (the
            # loglik lanes ride the data psum, the per-cluster-shard state
            # lanes psum over the cluster axis inside health.state_counts).
            out_specs = out_specs + (scalar,)
            fn = self._em_exec_cache[key] = tl_profiling.ProfiledExecutable(
                jax.jit(
                    shard_map(
                        em_fn,
                        mesh=self.mesh,
                        in_specs=(sspec, P(DATA_AXIS, None, None),
                                  P(DATA_AXIS, None), scalar, scalar,
                                  scalar),
                        out_specs=out_specs,
                        check_vma=False,
                    ),
                    donate_argnums=(0,) if donate else (),
                ),
                site="em_sharded")
        return fn

    def run_em(self, state, data_chunks, wts_chunks, epsilon: float,
               min_iters: Optional[int] = None, max_iters: Optional[int] = None,
               *, trajectory: bool = False, donate: bool = False):
        lo, hi = resolve_iters(self.config, min_iters, max_iters)
        run = self._em_executable(
            int(self.config.max_iters) if trajectory else 0, donate)
        out = run(
            state, data_chunks, wts_chunks,
            jnp.asarray(epsilon, data_chunks.dtype), lo, hi,
        )
        self.last_health = out[-1]
        return out[:-1]

    # Supervised segmented EM (preemption-safe execution, supervisor.py):
    # the driver consumes only run_em/last_health/config -- all provided
    # here with GMMModel's exact semantics -- so the sharded model borrows
    # the implementation verbatim. Mid-K stops and intra-K emergency
    # checkpoints therefore work on a mesh too; health counters stay
    # psum-exact per segment.
    run_em_resumable = GMMModel.run_em_resumable

    # Batched n_init restarts on the mesh: the restart axis is replicated,
    # the data axis stays sharded -- the vmap rides INSIDE the shard_map,
    # so the per-restart psums batch into one fused collective per stats
    # reduction, and every device runs all R lanes over its event shard.
    supports_batched_restarts = True
    run_em_batched = GMMModel.run_em_batched
    run_em_batched_resumable = GMMModel.run_em_batched_resumable

    def _em_batched_executable(self, trajectory_len: int, donate: bool):
        """shard_map(vmap(em_while_loop)) per (trajectory, donate) variant
        (the mesh sibling of GMMModel._em_batched_executable; see the
        class-level batched-restart comment for the axis layout)."""
        key = ("batched", trajectory_len, donate)
        fn = self._em_exec_cache.get(key)
        if fn is None:
            if self._batched_stats_fn is not None:
                # Data-axis-sharded + Pallas backend: the explicit batched
                # loop rides the leading-R kernel inside the shard_map --
                # each device runs ONE batched kernel launch per iteration
                # over its event shard, and the per-lane stats psum over
                # 'data' as one fused collective ([R, ...] leaves).
                from ..models.gmm import em_while_loop_batched

                batched = functools.partial(
                    em_while_loop_batched,
                    batched_stats_fn=self._batched_stats_fn,
                    mstep_fn=self._mstep_fn_batched,
                    reduce_stats=make_psum_reduce(DATA_AXIS),
                    cluster_axis=self._cluster_axis,
                    covariance_type=self.config.covariance_type,
                    trajectory_len=trajectory_len,
                    dynamic_range=self.config.covariance_dynamic_range,
                    regression_scale=self.config.health_regression_scale,
                    **self._kw,
                )
            else:
                em_fn = functools.partial(
                    em_while_loop,
                    reduce_stats=make_psum_reduce(DATA_AXIS),
                    cluster_axis=self._cluster_axis,
                    stats_fn=self._stats_fn,
                    covariance_type=self.config.covariance_type,
                    precompute_features=self.config.precompute_features,
                    trajectory_len=trajectory_len,
                    dynamic_range=self.config.covariance_dynamic_range,
                    regression_scale=self.config.health_regression_scale,
                    **self._kw,
                )

                def batched(states, rids, data_chunks, wts_chunks, epsilon,
                            lo_r, hi_r):
                    run_one = lambda s, rid, lo, hi: em_fn(
                        s, data_chunks, wts_chunks, epsilon, lo, hi,
                        restart_id=rid)
                    return jax.vmap(run_one, in_axes=(0, 0, 0, 0))(
                        states, rids, lo_r, hi_r)

            bspec = batched_state_pspecs()
            scalar = P()
            out_specs = (bspec, scalar, scalar)
            if trajectory_len:
                out_specs = out_specs + (scalar,)
            out_specs = out_specs + (scalar,)  # [R, NUM_FLAGS] health
            fn = self._em_exec_cache[key] = jax.jit(
                shard_map(
                    batched,
                    mesh=self.mesh,
                    in_specs=(bspec, scalar, P(DATA_AXIS, None, None),
                              P(DATA_AXIS, None), scalar, scalar, scalar),
                    out_specs=out_specs,
                    check_vma=False,
                ),
                donate_argnums=(0,) if donate else (),
            )
        return fn

    # Multi-tenant fleet fits on the mesh (tenancy/; docs/TENANCY.md):
    # the tenant axis is replicated, each tenant's OWN chunk grid shards
    # over the data axis, and the lanes map inside the shard_map -- scan
    # mode keeps every lane's per-shard arithmetic (and psum order) the
    # exact HLO of a solo sharded fit, so sharded fleet results stay
    # bit-identical to sharded solo fits.
    supports_fleet = True
    run_em_fleet = GMMModel.run_em_fleet

    def _em_fleet_executable(self, trajectory_len: int, donate: bool,
                             mode: str):
        """shard_map(lax.map|vmap(em_while_loop)) over per-tenant data
        (the mesh sibling of GMMModel._em_fleet_executable; see the
        class-level fleet comment for the axis layout)."""
        key = ("fleet", mode, trajectory_len, donate)
        fn = self._em_exec_cache.get(key)
        if fn is None:
            em_fn = functools.partial(
                em_while_loop,
                reduce_stats=make_psum_reduce(DATA_AXIS),
                cluster_axis=self._cluster_axis,
                stats_fn=None,
                covariance_type=self.config.covariance_type,
                precompute_features=False,
                trajectory_len=trajectory_len,
                dynamic_range=self.config.covariance_dynamic_range,
                regression_scale=self.config.health_regression_scale,
                **self._kw,
            )

            def fleet(states, tids, data_chunks, wts_chunks, eps_t,
                      lo_t, hi_t):
                if mode == "vmap":
                    return jax.vmap(
                        lambda s, tid, c, w, e, lo, hi: em_fn(
                            s, c, w, e, lo, hi, restart_id=tid))(
                        states, tids, data_chunks, wts_chunks, eps_t,
                        lo_t, hi_t)
                return lax.map(
                    lambda args: em_fn(args[0], args[2], args[3], args[4],
                                       args[5], args[6],
                                       restart_id=args[1]),
                    (states, tids, data_chunks, wts_chunks, eps_t,
                     lo_t, hi_t))

            bspec = batched_state_pspecs()
            scalar = P()
            out_specs = (bspec, scalar, scalar)
            if trajectory_len:
                out_specs = out_specs + (scalar,)
            out_specs = out_specs + (scalar,)  # [T, NUM_FLAGS] health
            fn = self._em_exec_cache[key] = jax.jit(
                shard_map(
                    fleet,
                    mesh=self.mesh,
                    in_specs=(bspec, scalar,
                              P(None, DATA_AXIS, None, None),
                              P(None, DATA_AXIS, None), scalar, scalar,
                              scalar),
                    out_specs=out_specs,
                    check_vma=False,
                ),
                donate_argnums=(0,) if donate else (),
            )
        return fn

    def prepare_fleet(self, data_chunks, wts_chunks):
        """Place one group's packed [T, C, B, D] chunk grid on the mesh:
        tenant axis replicated, each lane's chunk axis sharded over
        ``data`` (the fleet sibling of :meth:`prepare`'s data placement).
        Single-controller only -- a multi-controller fleet would need
        per-host tenant slicing the way host_chunk_bounds slices events."""
        if jax.process_count() > 1:
            raise NotImplementedError(
                "fleet fits are single-controller; multi-controller runs "
                "fit one tenant at a time (tenancy/fleet.py)")
        chunks = jax.device_put(
            np.asarray(data_chunks),
            NamedSharding(self.mesh, P(None, DATA_AXIS, None, None)))
        wts = jax.device_put(
            np.asarray(wts_chunks),
            NamedSharding(self.mesh, P(None, DATA_AXIS, None)))
        return chunks, wts

    def prepare_states_batched(self, host_states):
        """Stack R host seed states into one restart-batched state and
        place it on the mesh (restart axis replicated, K axis
        cluster-sharded). Each lane is padded to the cluster-axis extent
        first, exactly like :meth:`prepare_state` does for one state."""
        padded = [
            pad_state_clusters(
                jax.tree_util.tree_map(jnp.asarray, s), self.cluster_size)
            for s in host_states
        ]
        batched = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *padded)
        bspec = batched_state_pspecs()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            local_cluster = self.mesh.local_mesh.shape[CLUSTER_AXIS]
            if local_cluster != self.cluster_size:
                raise NotImplementedError(
                    "multi-host runs require the cluster mesh axis to fit "
                    "within one host; put hosts on the data axis")
            return multihost_utils.host_local_array_to_global_array(
                batched, self.mesh, bspec
            )
        return jax.device_put(
            batched,
            jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s), bspec
            ),
        )

    def host_batched_state(self, states):
        """Host-local numpy copy of a restart-batched (possibly global
        multi-host) state -- the batched sibling of
        order_search._host_state, used by checkpoints and the batched
        recovery ladder."""
        leaves = jax.tree_util.tree_leaves(states)
        if jax.process_count() > 1 and any(
                isinstance(l, jax.Array) and not l.is_fully_addressable
                for l in leaves):
            from jax.experimental import multihost_utils

            states = multihost_utils.global_array_to_host_local_array(
                states, self.mesh, batched_state_pspecs()
            )
        return jax.device_get(states)

    def rebucket_state(self, state, num_clusters: int):
        """Bucket recompaction on the mesh: compact the (tiny) K-state to
        the new width and re-place it with the cluster-axis sharding.

        ``num_clusters`` is rounded up to the cluster-axis extent so every
        shard keeps an equal slice (the caller already rounds via
        ``bucket_multiple``; this re-rounds defensively). Single-controller
        only -- order_search keeps multi-controller sweeps fixed-width (a
        per-rebucket cross-host reshard of a KxDxD state is not worth the
        collective).
        """
        num_clusters = pad_clusters(num_clusters, self.cluster_size)
        if num_clusters >= state.num_clusters_padded:
            return state
        from ..state import compact_to

        narrow = compact_to(
            jax.tree_util.tree_map(
                lambda a: jnp.asarray(np.asarray(jax.device_get(a))), state),
            num_clusters)
        return self.prepare_state(narrow)

    def make_fused_sweep(self, with_emit: bool = False,
                         emit_light: bool = False, **static):
        """Whole-sweep-on-device under shard_map, any mesh layout.

        On cluster-sharded meshes the order-reduction step all-gathers the
        K-state along the cluster axis (tiny: K x D x D), runs the
        elimination + pair scan + merge replicated, and re-slices each
        shard's rows -- the pair scan needs the full K-state, which each
        device otherwise only holds 1/cluster_size of.

        ``with_emit=True`` compiles in the per-K ordered ``io_callback``
        (checkpoint/profile hook, same contract as the plain model's): the
        callback fires once per LOCAL device shard with the FULL state
        (cluster shards all-gathered first), so every process -- including
        each rank of a multi-controller run -- observes a complete
        checkpoint payload per K and the host sink dedupes arrivals by
        step (order_search._run_fused_sweep).
        """
        from ..models.fused_sweep import fused_sweep
        from ..models.gmm import cached_fused_sweep
        from ..ops.merge import eliminate_and_reduce

        cluster_axis = CLUSTER_AXIS if self.cluster_size > 1 else None
        diag_only = self._kw["diag_only"]

        emit_cb = emit_gather_fn = None
        if with_emit:
            def emit_cb(payload):
                target = self._emit_target
                if target is not None:
                    target(payload)
                # Completion token (see fused_sweep): the device waits for
                # the emission, bounding crash loss to one step.
                return np.int32(0)

            if cluster_axis is not None and not emit_light:
                def emit_gather_fn(state):
                    return jax.tree_util.tree_map(
                        lambda a: lax.all_gather(a, cluster_axis, axis=0,
                                                 tiled=True),
                        state,
                    )

        reduce_order_fn = None
        if cluster_axis is not None:
            def reduce_order_fn(state):
                full = jax.tree_util.tree_map(
                    lambda a: lax.all_gather(a, cluster_axis, axis=0,
                                            tiled=True),
                    state,
                )
                new_full, k_active, min_d, pair = eliminate_and_reduce(
                    full, diag_only=diag_only
                )
                idx = lax.axis_index(cluster_axis)
                k_local = state.N.shape[0]
                new_local = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_slice_in_dim(
                        a, idx * k_local, k_local, 0
                    ),
                    new_full,
                )
                return new_local, k_active, min_d, pair

        def build():
            sweep_fn = functools.partial(
                fused_sweep, stats_fn=self._stats_fn,
                reduce_stats=make_psum_reduce(DATA_AXIS),
                cluster_axis=cluster_axis,
                covariance_type=self.config.covariance_type,
                criterion=self.config.criterion,
                reduce_order_fn=reduce_order_fn, emit_cb=emit_cb,
                emit_light=emit_light, emit_gather_fn=emit_gather_fn,
                precompute_features=self.config.precompute_features,
                dynamic_range=self.config.covariance_dynamic_range,
                regression_scale=self.config.health_regression_scale,
                **self._kw, **static,
            )
            sspec = state_pspecs()
            scalar = P()
            base_in = (sspec, P(DATA_AXIS, None, None),
                       P(DATA_AXIS, None), scalar, scalar, scalar)
            # Final scalar: the sweep's cumulative health counters.
            out_specs = (sspec, scalar, scalar, scalar, scalar, scalar)
            # Resume changes the arg pytree (an extra sweep-position dict),
            # so the two variants are separate shard_maps; both live behind
            # one cached callable with the plain model's calling convention
            # (positional optional resume).
            resume_spec = dict(
                best_state=sspec, best_ll=scalar, best_riss=scalar,
                k=scalar, log=scalar, step=scalar,
            )
            variants = {}

            def get(with_resume: bool):
                fn = variants.get(with_resume)
                if fn is None:
                    if with_resume:
                        body = lambda s, c, w, e, lo, hi, r: sweep_fn(
                            s, c, w, e, lo, hi, r)
                        in_specs = base_in + (resume_spec,)
                    else:
                        body = lambda s, c, w, e, lo, hi: sweep_fn(
                            s, c, w, e, lo, hi)
                        in_specs = base_in
                    fn = variants[with_resume] = jax.jit(
                        shard_map(body, mesh=self.mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_vma=False)
                    )
                return fn

            def run(state, chunks, wts, eps, lo, hi, resume=None):
                if resume is None:
                    return get(False)(state, chunks, wts, eps, lo, hi)
                resume = {k: jax.tree_util.tree_map(jnp.asarray, v)
                          for k, v in resume.items()}
                return get(True)(state, chunks, wts, eps, lo, hi, resume)

            return run

        return cached_fused_sweep(
            self, dict(static, with_emit=with_emit, emit_light=emit_light),
            build)

    @property
    def inference_block(self) -> int:
        """Events per output-path block: one chunk per local data shard."""
        return self.config.chunk_size * self._inference_data_size

    def infer_posteriors(self, state, xb):
        """(w [B, K], logZ [B]) for one [inference_block, D] event block,
        computed on all local devices in parallel. ``state`` is the plain
        (compacted, unpadded) fit result state."""
        return infer_posteriors_sharded(self, state, xb)

    def memberships(self, state, data_chunks, return_logz: bool = False):
        """All-local-devices output pass (memberships_sharded)."""
        return memberships_sharded(self, state, data_chunks, return_logz)
