"""Device mesh construction and data placement.

The TPU-native replacement for the reference's process/thread/device topology
(MPI ranks x OpenMP threads x GPUs, ``gaussian.cu:133-139, 289-301``): a 2-D
``jax.sharding.Mesh`` with axes

  ``data``    -- events sharded along it (the reference's only strategy:
                 contiguous event shards per GPU, gaussian.cu:347-377)
  ``cluster`` -- clusters sharded along it (cross-device generalization of the
                 reference's per-cluster grid dimension, e.g. estep1's
                 blockIdx.y, gaussian_kernel.cu:396)

On real hardware the data axis should map to ICI-adjacent devices so the
sufficient-statistics psum rides ICI, with DCN only across slices (the
reference's intra-node OpenMP vs inter-node MPI split, collapsed into XLA
collective lowering).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
CLUSTER_AXIS = "cluster"


def make_mesh(
    shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the (data, cluster) mesh. ``shape=None`` puts every device on the
    data axis (pure event-parallel, the reference's layout)."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices), 1)
    n = shape[0] * shape[1]
    if n > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[:n]).reshape(shape)
    return Mesh(arr, (DATA_AXIS, CLUSTER_AXIS))


def shard_chunks(mesh: Mesh, data_chunks, wts_chunks):
    """Place [num_chunks, B, D] event chunks sharded along the data axis.

    The per-host loading analog of the reference's per-GPU event-slice upload
    (gaussian.cu:347-377) -- but as one sharded global array, never replicated.
    """
    cspec = NamedSharding(mesh, P(DATA_AXIS, None, None))
    wspec = NamedSharding(mesh, P(DATA_AXIS, None))
    return (
        jax.device_put(data_chunks, cspec),
        jax.device_put(wts_chunks, wspec),
    )


def state_pspecs(diag_only: bool = False):
    """PartitionSpecs for a GMMState pytree: K axis sharded over 'cluster'."""
    from ..state import GMMState

    return GMMState(
        N=P(CLUSTER_AXIS), pi=P(CLUSTER_AXIS), constant=P(CLUSTER_AXIS),
        avgvar=P(CLUSTER_AXIS), means=P(CLUSTER_AXIS, None),
        R=P(CLUSTER_AXIS, None, None), Rinv=P(CLUSTER_AXIS, None, None),
        active=P(CLUSTER_AXIS),
    )


def stats_pspecs(diag_only: bool = False):
    """PartitionSpecs for SuffStats: per-cluster stats sharded over 'cluster'."""
    from ..ops.mstep import SuffStats

    m2 = P(CLUSTER_AXIS, None) if diag_only else P(CLUSTER_AXIS, None, None)
    return SuffStats(loglik=P(), Nk=P(CLUSTER_AXIS), M1=P(CLUSTER_AXIS, None),
                     M2=m2, sanitized=P())


def pad_clusters(num_clusters: int, cluster_size: int) -> int:
    """Padded K: a multiple of the cluster-axis size (inactive tail slots)."""
    return int(math.ceil(num_clusters / cluster_size) * cluster_size)
