"""Distributed layer (SURVEY L3): mesh, sharded EM step, multihost init.

The TPU-native replacement for the reference's MPI+OpenMP+memcpy reduction
stack (SURVEY.md SS2.8): ``jax.lax.psum`` of the sufficient-statistics pytree
over an ICI/DCN device mesh inside ``shard_map``.
"""

from .distributed import host_slice, initialize, sharded_chunks_from_host_data
from .mesh import make_mesh, pad_clusters, shard_chunks, state_pspecs
from .sharded_em import ShardedGMMModel, make_psum_reduce

__all__ = [
    "host_slice", "initialize", "sharded_chunks_from_host_data",
    "make_mesh", "pad_clusters", "shard_chunks", "state_pspecs",
    "ShardedGMMModel", "make_psum_reduce",
]
