"""Distributed layer (SURVEY L3): mesh, sharded EM step, multihost init.

The TPU-native replacement for the reference's MPI+OpenMP+memcpy reduction
stack (SURVEY.md SS2.8): ``jax.lax.psum`` of the sufficient-statistics pytree
over an ICI/DCN device mesh inside ``shard_map``.
"""

from .mesh import make_mesh, shard_chunks
from .sharded_em import ShardedGMMModel

__all__ = ["make_mesh", "shard_chunks", "ShardedGMMModel"]
