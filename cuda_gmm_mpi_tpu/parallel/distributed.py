"""Multi-host bootstrap and per-host sharded data loading.

TPU-native replacement for the reference's MPI bootstrap + dataset broadcast
(``gaussian.cu:130-207``): instead of rank 0 reading the file and
``MPI_Bcast``-ing the ENTIRE dataset to every node (full replication,
gaussian.cu:191-201), each host loads only its contiguous slice of the events
and assembles a single globally-sharded array -- the data is never replicated
anywhere. The multi-controller runtime (``jax.distributed.initialize``) is the
analog of ``MPI_Init_thread`` (gaussian.cu:133); world size/rank come from the
same coordinator concept as MPI_COMM_WORLD.

Single-host callers can use everything here unchanged (process_count==1).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
) -> Tuple[int, int]:
    """Initialize the multi-controller runtime; returns (process_id, count).

    No-op with no arguments (the reference likewise runs under plain
    ``./gaussianMPI`` without mpirun). ``auto=True`` initializes from the
    environment (TPU pod launchers). Explicit bring-up requires ALL of
    coordinator_address/num_processes/process_id -- a partial set raises
    instead of silently running single-process with wrong results. This is
    the MPI_Init/rank/size equivalent (gaussian.cu:133-139).
    """
    if auto:
        jax.distributed.initialize()
    elif (coordinator_address is not None or num_processes is not None
          or process_id is not None):
        if (coordinator_address is None or num_processes is None
                or process_id is None):
            raise ValueError(
                "distributed bring-up needs ALL of coordinator_address, "
                "num_processes, and process_id (or auto=True for "
                "environment-driven initialization)"
            )
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return jax.process_index(), jax.process_count()


def global_moments(local_data: np.ndarray, chunk_size: int, num_chunks: int):
    """Global per-dimension (mean, E[x^2]-E[x]^2) from per-host slices,
    bit-identical for every process count.

    Each host computes per-chunk (count, sum, sum-of-squares) float64
    partials for its ``num_chunks`` chunk slots (``host_chunk_bounds``
    guarantees chunk-aligned, equal-count slices; missing tail chunks
    contribute zeros). The [nproc * num_chunks, 1+2D] partial matrix --
    whose rows are in GLOBAL chunk order by construction -- is then reduced
    the same way on every host, so a 1-process and an N-process run of the
    same problem produce the exact same bits. This is the distributed
    version of the seeding moments (averageVariance,
    gaussian_kernel.cu:71-102, computed there from one GPU's shard; here
    from ALL data). Returns (mean[D], var[D]) as float64.
    """
    d = local_data.shape[1]
    parts = np.zeros((num_chunks, 1 + 2 * d), np.float64)
    for j in range(num_chunks):
        block = local_data[j * chunk_size:(j + 1) * chunk_size]
        if block.shape[0] == 0:
            continue
        parts[j, 0] = block.shape[0]
        parts[j, 1:1 + d] = block.sum(axis=0, dtype=np.float64)
        parts[j, 1 + d:] = (block.astype(np.float64) ** 2).sum(axis=0)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(parts))
        parts = gathered.reshape(-1, 1 + 2 * d)
    total = parts.sum(axis=0)
    n = total[0]
    if n <= 0:
        raise ValueError("no events across all hosts")
    mean = total[1:1 + d] / n
    var = total[1 + d:] / n - mean * mean
    return mean, var


def barrier(name: str = "gmm_barrier") -> None:
    """Cross-host sync point (the MPI_Barrier analog -- needed only at host
    filesystem rendezvous like output assembly, never inside compute)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def host_slice(num_events: int, process_id: int, process_count: int):
    """This host's contiguous event range [start, stop).

    Mirrors the reference's contiguous per-GPU sharding arithmetic
    (events_per_gpu * gpu_num, gaussian.cu:347-368) at host granularity, but
    distributes the remainder across the first hosts instead of dumping it on
    one rank (the reference's remainder quirk, gaussian.cu:350-352).
    """
    base, rem = divmod(num_events, process_count)
    start = process_id * base + min(process_id, rem)
    stop = start + base + (1 if process_id < rem else 0)
    return start, stop


def host_chunk_bounds(
    num_events: int,
    chunk_size: int,
    data_axis_size: int,
    process_id: int,
    process_count: int,
):
    """(start, stop, num_chunks) for this host's slice, with EQUAL chunk
    counts on every host.

    ``host_slice`` alone lets the event remainder produce different per-host
    padded chunk counts (host A 3 chunks, host B 2), which the global-array
    assembly cannot reconcile. Here the GLOBAL event count is padded up to a
    whole number of ``chunk_size`` x ``data_axis_size`` blocks first, the
    chunk grid is split evenly across hosts, and each host pads its own tail
    locally -- every host returns the same-shaped array by construction.
    Requires ``process_count`` to divide ``data_axis_size`` (hosts each own
    an equal share of the data axis).
    """
    if data_axis_size % process_count:
        raise ValueError(
            f"data axis size {data_axis_size} not divisible by "
            f"{process_count} processes"
        )
    step = chunk_size * data_axis_size
    total = num_events + ((-num_events) % step)
    chunks_total = total // chunk_size
    per_host = chunks_total // process_count
    start = min(process_id * per_host * chunk_size, num_events)
    stop = min((process_id + 1) * per_host * chunk_size, num_events)
    return start, stop, per_host


def sharded_chunks_from_host_data(
    mesh: Mesh,
    local_chunks: np.ndarray,
    local_wts: np.ndarray,
):
    """Assemble per-host chunk arrays into one globally data-sharded array.

    Each host passes the chunks for ITS slice of the events (shape
    [local_num_chunks, B, D]); the result is a global [total_chunks, B, D]
    array sharded over the mesh's data axis with no cross-host transfer --
    the anti-MPI_Bcast (SURVEY.md SS2.8 "Bcast of the dataset -> per-host
    sharded loading").
    """
    from jax.experimental import multihost_utils

    cspec = NamedSharding(mesh, P(DATA_AXIS, None, None))
    wspec = NamedSharding(mesh, P(DATA_AXIS, None))
    if jax.process_count() == 1:
        return (
            jax.device_put(local_chunks, cspec),
            jax.device_put(local_wts, wspec),
        )
    chunks = multihost_utils.host_local_array_to_global_array(
        local_chunks, mesh, P(DATA_AXIS, None, None)
    )
    wts = multihost_utils.host_local_array_to_global_array(
        local_wts, mesh, P(DATA_AXIS, None)
    )
    return chunks, wts
