"""Multi-host bootstrap and per-host sharded data loading.

TPU-native replacement for the reference's MPI bootstrap + dataset broadcast
(``gaussian.cu:130-207``): instead of rank 0 reading the file and
``MPI_Bcast``-ing the ENTIRE dataset to every node (full replication,
gaussian.cu:191-201), each host loads only its contiguous slice of the events
and assembles a single globally-sharded array -- the data is never replicated
anywhere. The multi-controller runtime (``jax.distributed.initialize``) is the
analog of ``MPI_Init_thread`` (gaussian.cu:133); world size/rank come from the
same coordinator concept as MPI_COMM_WORLD.

Single-host callers can use everything here unchanged (process_count==1).
"""

from __future__ import annotations

import os
import time
import zlib
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
) -> Tuple[int, int]:
    """Initialize the multi-controller runtime; returns (process_id, count).

    No-op with no arguments (the reference likewise runs under plain
    ``./gaussianMPI`` without mpirun). ``auto=True`` initializes from the
    environment (TPU pod launchers). Explicit bring-up requires ALL of
    coordinator_address/num_processes/process_id -- a partial set raises
    instead of silently running single-process with wrong results. This is
    the MPI_Init/rank/size equivalent (gaussian.cu:133-139).
    """
    if auto:
        jax.distributed.initialize()
    elif (coordinator_address is not None or num_processes is not None
          or process_id is not None):
        if (coordinator_address is None or num_processes is None
                or process_id is None):
            raise ValueError(
                "distributed bring-up needs ALL of coordinator_address, "
                "num_processes, and process_id (or auto=True for "
                "environment-driven initialization)"
            )
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return jax.process_index(), jax.process_count()


def global_moments(local_data: np.ndarray, chunk_size: int, num_chunks: int):
    """Global per-dimension (mean, E[x^2]-E[x]^2) from per-host slices,
    bit-identical for every process count.

    Each host computes per-chunk (count, sum, sum-of-squares) float64
    partials for its ``num_chunks`` chunk slots (``host_chunk_bounds``
    guarantees chunk-aligned, equal-count slices; missing tail chunks
    contribute zeros). The [nproc * num_chunks, 1+2D] partial matrix --
    whose rows are in GLOBAL chunk order by construction -- is then reduced
    the same way on every host, so a 1-process and an N-process run of the
    same problem produce the exact same bits. This is the distributed
    version of the seeding moments (averageVariance,
    gaussian_kernel.cu:71-102, computed there from one GPU's shard; here
    from ALL data). Returns (mean[D], var[D]) as float64.
    """
    d = local_data.shape[1]
    parts = np.zeros((num_chunks, 1 + 2 * d), np.float64)
    for j in range(num_chunks):
        block = local_data[j * chunk_size:(j + 1) * chunk_size]
        if block.shape[0] == 0:
            continue
        parts[j] = moment_part(block)
    return reduce_moment_parts(parts)


def moment_part(block: np.ndarray) -> np.ndarray:
    """One chunk's [1+2D] float64 (count, sum, sum-of-squares) partial --
    the per-chunk half of :func:`global_moments`, shared with the pipelined
    ingestion pass (io/pipeline.py) so a per-block-read moments pass builds
    the EXACT same partials matrix a resident slice would."""
    d = block.shape[1]
    part = np.empty((1 + 2 * d,), np.float64)
    part[0] = block.shape[0]
    part[1:1 + d] = block.sum(axis=0, dtype=np.float64)
    part[1 + d:] = (block.astype(np.float64) ** 2).sum(axis=0)
    return part


def reduce_moment_parts(parts: np.ndarray):
    """(mean[D], var[D]) float64 from a [num_chunks, 1+2D] partials matrix;
    the reduction half of :func:`global_moments` (same allgather, same
    summation order, so every builder of the same partials matrix gets the
    same bits)."""
    d = (parts.shape[1] - 1) // 2
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(parts))
        parts = gathered.reshape(-1, 1 + 2 * d)
    total = parts.sum(axis=0)
    n = total[0]
    if n <= 0:
        raise ValueError("no events across all hosts")
    mean = total[1:1 + d] / n
    var = total[1 + d:] / n - mean * mean
    return mean, var


def allgather_host(values: np.ndarray) -> np.ndarray:
    """Gather a small host array from every process: [nproc, *values.shape].

    Single-process: returns ``values[None]`` without touching the runtime.
    The shared primitive behind every collectively-agreed abort (input
    validation, writability prechecks): all ranks exchange their local
    verdicts and reach the SAME proceed/raise decision, so one bad rank can
    never strand the others in a later collective.
    """
    values = np.asarray(values)
    if jax.process_count() == 1:
        return values[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(values))


def allgather_json(obj) -> list:
    """Gather one small JSON-serializable object from every process:
    returns ``[rank0_obj, rank1_obj, ...]`` identically on all ranks.

    The telemetry layer's host-0 aggregation primitive: each rank's
    metrics-registry snapshot rides a padded uint8 buffer through
    ``process_allgather`` (two collectives: max-length, then payload), so
    the one stream process 0 writes can carry every rank's numbers
    (``run_summary.per_process``). Single-process: ``[obj]``, no runtime
    touched. Keep payloads small -- this is for summaries, not data.
    """
    import json

    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils

    payload = json.dumps(obj).encode("utf-8")
    sizes = np.asarray(multihost_utils.process_allgather(
        np.asarray([len(payload)], np.int64))).reshape(-1)
    cap = int(sizes.max())
    buf = np.zeros((max(cap, 1),), np.uint8)
    buf[:len(payload)] = np.frombuffer(payload, np.uint8)
    rows = np.asarray(multihost_utils.process_allgather(buf)).reshape(
        len(sizes), -1)
    return [
        json.loads(rows[i, :int(sizes[i])].tobytes().decode("utf-8"))
        for i in range(len(sizes))
    ]


def barrier(name: str = "gmm_barrier",
            timeout_s: Optional[float] = None) -> None:
    """Cross-host sync point (the MPI_Barrier analog -- needed only at host
    filesystem rendezvous like output assembly, never inside compute).

    With ``timeout_s`` -- passed explicitly, or implied by an active run
    supervisor whose liveness watchdog is running -- the collective is
    bounded: a dead or wedged peer raises
    :class:`~cuda_gmm_mpi_tpu.supervisor.PeerLostError` after the timeout
    instead of blocking this rank forever (the reference's failure mode:
    one dead MPI rank hangs every ``MPI_Allreduce`` survivor). The
    underlying collective cannot be cancelled; the raise abandons its
    daemon thread, which is fine because the caller's next act is an
    emergency checkpoint and a loud exit.
    """
    # Deterministic collective_timeout chaos hook (testing.faults): fires
    # BEFORE the single-process early return so the collective-loss leg of
    # elastic recovery is rehearsable without a real multi-host mesh.
    from . import elastic

    elastic.take_collective_timeout(name, timeout_s)
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    if timeout_s is None:
        from .. import supervisor

        timeout_s = supervisor.current().collective_timeout_s
    if not timeout_s:
        multihost_utils.sync_global_devices(name)
        return

    import threading

    done = threading.Event()
    err: list = []

    def _run():
        try:
            multihost_utils.sync_global_devices(name)
        except Exception as e:  # surfaced on the caller thread below
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_run, name=f"gmm-barrier-{name}",
                         daemon=True)
    t.start()
    if not done.wait(float(timeout_s)):
        from .. import supervisor

        raise supervisor.PeerLostError(
            f"barrier {name!r} timed out after {timeout_s:.1f}s: a peer "
            "rank is dead or wedged", timeout_s=float(timeout_s))
    if err:
        raise err[0]


# -- rank heartbeats (the liveness watchdog's exchange medium) --------------
#
# Deliberately filesystem-based, not a device collective: multi-host runs
# already require a checkpoint filesystem every rank can reach
# (docs/DISTRIBUTED.md), a background-thread collective would interleave
# with the main thread's compute collectives, and a hung peer is exactly
# the case where collectives stop returning. supervisor.LivenessWatchdog
# drives these.

def heartbeat_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"rank{int(rank):05d}.hb")


def write_rank_heartbeat(directory: str, rank: int) -> None:
    """Atomically touch this rank's heartbeat file (tmp + rename, so a
    reader never sees a partial write and mtime moves monotonically)."""
    os.makedirs(directory, exist_ok=True)
    path = heartbeat_path(directory, rank)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"{os.getpid()} {time.time():.3f}\n")
    os.replace(tmp, path)


def read_rank_heartbeat(directory: str, rank: int) -> Optional[float]:
    """The peer's last-heartbeat mtime (seconds since epoch, the shared
    filesystem's clock), or None if it never wrote one."""
    try:
        return os.stat(heartbeat_path(directory, rank)).st_mtime
    except OSError:
        return None


def host_slice(num_events: int, process_id: int, process_count: int):
    """This host's contiguous event range [start, stop).

    Mirrors the reference's contiguous per-GPU sharding arithmetic
    (events_per_gpu * gpu_num, gaussian.cu:347-368) at host granularity, but
    distributes the remainder across the first hosts instead of dumping it on
    one rank (the reference's remainder quirk, gaussian.cu:350-352).
    """
    base, rem = divmod(num_events, process_count)
    start = process_id * base + min(process_id, rem)
    stop = start + base + (1 if process_id < rem else 0)
    return start, stop


def require_host_local_chunks(host_local: bool, chunks_shape,
                              consequence: str) -> None:
    """The shared multi-controller ``prepare()`` contract (ShardedGMMModel
    and StreamingGMMModel): the caller must pass THIS host's chunk slice
    (``host_local=True``), and every host's chunk array must be identically
    shaped -- collectively verified so an inconsistent chunking fails with
    a clear error on every rank instead of a shape-mismatch deadlock in the
    first collective. ``consequence`` finishes the sentence "passing
    full-dataset chunks here would ..." for the model's failure mode."""
    if not host_local:
        raise ValueError(
            "multi-controller run: prepare() must receive this host's "
            "LOCAL chunk slice (derive it with "
            "parallel.distributed.host_chunk_bounds) and host_local=True. "
            f"Passing full-dataset chunks here would {consequence}. "
            "fit_gmm/GaussianMixture handle this automatically; only "
            "direct model drivers need host_chunk_bounds "
            "(docs/DISTRIBUTED.md).")
    from jax.experimental import multihost_utils

    multihost_utils.assert_equal(
        np.asarray(chunks_shape),
        "per-host chunk array shapes differ across hosts; derive slices "
        "with parallel.distributed.host_chunk_bounds")


def host_chunk_bounds(
    num_events: int,
    chunk_size: int,
    data_axis_size: int,
    process_id: int,
    process_count: int,
):
    """(start, stop, num_chunks) for this host's slice, with EQUAL chunk
    counts on every host.

    ``host_slice`` alone lets the event remainder produce different per-host
    padded chunk counts (host A 3 chunks, host B 2), which the global-array
    assembly cannot reconcile. Here the GLOBAL event count is padded up to a
    whole number of ``chunk_size`` x ``data_axis_size`` blocks first, the
    chunk grid is split evenly across hosts, and each host pads its own tail
    locally -- every host returns the same-shaped array by construction.
    Requires ``process_count`` to divide ``data_axis_size`` (hosts each own
    an equal share of the data axis).
    """
    if data_axis_size % process_count:
        raise ValueError(
            f"data axis size {data_axis_size} not divisible by "
            f"{process_count} processes"
        )
    step = chunk_size * data_axis_size
    total = num_events + ((-num_events) % step)
    chunks_total = total // chunk_size
    per_host = chunks_total // process_count
    start = min(process_id * per_host * chunk_size, num_events)
    stop = min((process_id + 1) * per_host * chunk_size, num_events)
    return start, stop, per_host


def results_part_path(out_path: str, part_dir: Optional[str] = None) -> str:
    """This rank's .results part file path. Default: beside ``out_path``
    (enables the shared-FS zero-copy assembly fast path); ``part_dir``
    relocates it (e.g. rank-local scratch on pods without a shared FS)."""
    d = part_dir or os.path.dirname(os.path.abspath(out_path))
    os.makedirs(d, exist_ok=True)  # scratch dirs need not pre-exist
    return os.path.join(
        d, os.path.basename(out_path) + f".part{jax.process_index():05d}"
    )


def _part_fingerprint(path: str, sample: int = 1 << 20) -> int:
    """crc32 of the part's first and last ``sample`` bytes (whole file when
    smaller). Cheap staleness guard for the shared-FS fast path: a leftover
    part from a crashed prior run only passes if its size AND boundary bytes
    match this run's -- and these runs are deterministic, so a file that
    matches both holds the same bytes. O(sample), not O(file)."""
    size = os.path.getsize(path)
    crc = 0
    with open(path, "rb") as f:
        crc = zlib.crc32(f.read(sample), crc)
        if size > sample:
            f.seek(max(size - sample, sample))
            crc = zlib.crc32(f.read(sample), crc)
    return crc


def assemble_results_multihost(
    out_path: str,
    part_path: str,
    chunk_bytes: int = 32 * 1024 * 1024,
) -> None:
    """Assemble every rank's part file into ``out_path`` on rank 0 -- with or
    WITHOUT a shared filesystem.

    The TPU-native replacement for the reference's hand-rolled MPI_Send/Recv
    membership gather (``gaussian.cu:798-817``), which shipped the raw
    posteriors over the network; here the FORMATTED bytes move instead (the
    events are range-sharded in rank order, so in-order concatenation
    reproduces the single-host file byte for byte):

    1. All ranks allgather their part's (size, crc32).
    2. Shared-FS fast path: if rank 0 can see every rank's part at the
       exact gathered size AND checksum, it concatenates locally -- zero
       bytes cross the network.
    3. Otherwise the parts are gathered to rank 0 through the runtime in
       fixed ``chunk_bytes`` rounds (one ``process_allgather`` of a
       [chunk_bytes] uint8 buffer per round, every rank participating),
       spooled per-rank on rank 0's local disk, and concatenated in rank
       order. Peak memory is O(nproc * chunk_bytes) regardless of N.

    Every rank must call this (it contains collectives). Each rank's part
    file is deleted after assembly.
    """
    from jax.experimental import multihost_utils

    pid, nproc = jax.process_index(), jax.process_count()
    barrier("results_parts")  # parts fully written everywhere

    size = os.path.getsize(part_path)
    meta = np.asarray([size, _part_fingerprint(part_path)], np.int64)
    metas = np.asarray(
        multihost_utils.process_allgather(meta)
    ).reshape(nproc, 2)
    sizes = metas[:, 0]

    # Rank 0 probes the shared-FS fast path: every part visible under ITS
    # derivation of the part naming, with matching size and checksum (a
    # stale file that matches both holds the identical bytes).
    part_dir_local = os.path.dirname(os.path.abspath(part_path))

    def path_of(i: int) -> str:  # rank 0 only
        return os.path.join(
            part_dir_local, os.path.basename(out_path) + f".part{i:05d}"
        )

    visible = 0
    if pid == 0:
        visible = int(all(
            os.path.isfile(path_of(i))
            and os.path.getsize(path_of(i)) == int(sizes[i])
            and _part_fingerprint(path_of(i)) == int(metas[i, 1])
            for i in range(nproc)
        ))
    flags = np.asarray(
        multihost_utils.process_allgather(
            np.asarray([visible], np.int64))
    ).reshape(-1)
    use_fs = bool(flags[0])  # rank 0's verdict, replicated everywhere

    if use_fs:
        if pid == 0:
            import shutil

            with open(out_path, "wb") as out:
                for i in range(nproc):
                    with open(path_of(i), "rb") as f:
                        shutil.copyfileobj(f, out, chunk_bytes)
            for i in range(nproc):
                os.remove(path_of(i))
        barrier("results_done")
        # Non-zero ranks' parts were rank 0's same files; nothing left here.
        if pid != 0 and os.path.isfile(part_path):
            os.remove(part_path)
        return

    # Byte-gather over the runtime (no shared FS).
    nrounds = int(max(
        (int(s) + chunk_bytes - 1) // chunk_bytes for s in sizes
    )) if int(sizes.max()) > 0 else 0
    spool_fhs = []
    spool_paths = []
    if pid == 0:
        import tempfile

        spool_dir = tempfile.mkdtemp(prefix="gmm_results_gather_")
        spool_paths = [os.path.join(spool_dir, f"rank{i}")
                       for i in range(nproc)]
        spool_fhs = [open(p, "wb") for p in spool_paths]
    try:
        with open(part_path, "rb") as f:
            for r in range(nrounds):
                buf = f.read(chunk_bytes)
                arr = np.zeros((chunk_bytes,), np.uint8)
                if buf:
                    arr[:len(buf)] = np.frombuffer(buf, np.uint8)
                gathered = np.asarray(
                    multihost_utils.process_allgather(arr)
                ).reshape(nproc, chunk_bytes)
                if pid == 0:
                    lo = r * chunk_bytes
                    for i in range(nproc):
                        ln = max(0, min(int(sizes[i]) - lo, chunk_bytes))
                        if ln:
                            spool_fhs[i].write(gathered[i, :ln].tobytes())
        if pid == 0:
            import shutil

            for fh in spool_fhs:
                fh.close()
            spool_fhs = []
            with open(out_path, "wb") as out:
                for p in spool_paths:
                    with open(p, "rb") as f:
                        shutil.copyfileobj(f, out, chunk_bytes)
    finally:
        for fh in spool_fhs:
            fh.close()
        if pid == 0 and spool_paths:
            import shutil

            shutil.rmtree(os.path.dirname(spool_paths[0]),
                          ignore_errors=True)
    barrier("results_done")
    os.remove(part_path)


def sharded_chunks_from_host_data(
    mesh: Mesh,
    local_chunks: np.ndarray,
    local_wts: np.ndarray,
):
    """Assemble per-host chunk arrays into one globally data-sharded array.

    Each host passes the chunks for ITS slice of the events (shape
    [local_num_chunks, B, D]); the result is a global [total_chunks, B, D]
    array sharded over the mesh's data axis with no cross-host transfer --
    the anti-MPI_Bcast (SURVEY.md SS2.8 "Bcast of the dataset -> per-host
    sharded loading").
    """
    from jax.experimental import multihost_utils

    cspec = NamedSharding(mesh, P(DATA_AXIS, None, None))
    wspec = NamedSharding(mesh, P(DATA_AXIS, None))
    if jax.process_count() == 1:
        return (
            jax.device_put(local_chunks, cspec),
            jax.device_put(local_wts, wspec),
        )
    chunks = multihost_utils.host_local_array_to_global_array(
        local_chunks, mesh, P(DATA_AXIS, None, None)
    )
    wts = multihost_utils.host_local_array_to_global_array(
        local_wts, mesh, P(DATA_AXIS, None)
    )
    return chunks, wts
