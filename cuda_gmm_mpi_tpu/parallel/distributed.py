"""Multi-host bootstrap and per-host sharded data loading.

TPU-native replacement for the reference's MPI bootstrap + dataset broadcast
(``gaussian.cu:130-207``): instead of rank 0 reading the file and
``MPI_Bcast``-ing the ENTIRE dataset to every node (full replication,
gaussian.cu:191-201), each host loads only its contiguous slice of the events
and assembles a single globally-sharded array -- the data is never replicated
anywhere. The multi-controller runtime (``jax.distributed.initialize``) is the
analog of ``MPI_Init_thread`` (gaussian.cu:133); world size/rank come from the
same coordinator concept as MPI_COMM_WORLD.

Single-host callers can use everything here unchanged (process_count==1).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Tuple[int, int]:
    """Initialize the multi-controller runtime; returns (process_id, count).

    No-op on single-process runs (the reference likewise runs under plain
    ``./gaussianMPI`` without mpirun). With arguments (or the standard cluster
    env vars), brings up jax.distributed -- the MPI_Init/rank/size equivalent
    (gaussian.cu:133-139).
    """
    if coordinator_address is not None or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return jax.process_index(), jax.process_count()


def host_slice(num_events: int, process_id: int, process_count: int):
    """This host's contiguous event range [start, stop).

    Mirrors the reference's contiguous per-GPU sharding arithmetic
    (events_per_gpu * gpu_num, gaussian.cu:347-368) at host granularity, but
    distributes the remainder across the first hosts instead of dumping it on
    one rank (the reference's remainder quirk, gaussian.cu:350-352).
    """
    base, rem = divmod(num_events, process_count)
    start = process_id * base + min(process_id, rem)
    stop = start + base + (1 if process_id < rem else 0)
    return start, stop


def host_chunk_bounds(
    num_events: int,
    chunk_size: int,
    data_axis_size: int,
    process_id: int,
    process_count: int,
):
    """(start, stop, num_chunks) for this host's slice, with EQUAL chunk
    counts on every host.

    ``host_slice`` alone lets the event remainder produce different per-host
    padded chunk counts (host A 3 chunks, host B 2), which the global-array
    assembly cannot reconcile. Here the GLOBAL event count is padded up to a
    whole number of ``chunk_size`` x ``data_axis_size`` blocks first, the
    chunk grid is split evenly across hosts, and each host pads its own tail
    locally -- every host returns the same-shaped array by construction.
    Requires ``process_count`` to divide ``data_axis_size`` (hosts each own
    an equal share of the data axis).
    """
    if data_axis_size % process_count:
        raise ValueError(
            f"data axis size {data_axis_size} not divisible by "
            f"{process_count} processes"
        )
    step = chunk_size * data_axis_size
    total = num_events + ((-num_events) % step)
    chunks_total = total // chunk_size
    per_host = chunks_total // process_count
    start = min(process_id * per_host * chunk_size, num_events)
    stop = min((process_id + 1) * per_host * chunk_size, num_events)
    return start, stop, per_host


def sharded_chunks_from_host_data(
    mesh: Mesh,
    local_chunks: np.ndarray,
    local_wts: np.ndarray,
):
    """Assemble per-host chunk arrays into one globally data-sharded array.

    Each host passes the chunks for ITS slice of the events (shape
    [local_num_chunks, B, D]); the result is a global [total_chunks, B, D]
    array sharded over the mesh's data axis with no cross-host transfer --
    the anti-MPI_Bcast (SURVEY.md SS2.8 "Bcast of the dataset -> per-host
    sharded loading").
    """
    from jax.experimental import multihost_utils

    cspec = NamedSharding(mesh, P(DATA_AXIS, None, None))
    wspec = NamedSharding(mesh, P(DATA_AXIS, None))
    if jax.process_count() == 1:
        return (
            jax.device_put(local_chunks, cspec),
            jax.device_put(local_wts, wspec),
        )
    chunks = multihost_utils.host_local_array_to_global_array(
        local_chunks, mesh, P(DATA_AXIS, None, None)
    )
    wts = multihost_utils.host_local_array_to_global_array(
        local_wts, mesh, P(DATA_AXIS, None)
    )
    return chunks, wts
