"""Input-data validation shared by the fit, inference, and CLI paths.

The reference's ``atof``-based reader (readData.cpp:49-129) admits NaN/Inf
values silently, and they poison every statistic downstream. This module is
the single home of the rejection logic so the promise of
``GMMConfig.validate_input`` holds on every path that consumes event data.
"""

from __future__ import annotations

import numpy as np


class InvalidInputError(ValueError):
    """The input data itself is unusable (e.g. non-finite event rows).

    A dedicated type so callers (the CLI) can give data-content problems the
    reference's one-line abort style while letting genuine internal
    ValueErrors crash loudly with their tracebacks."""


def finite_row_stats(local: np.ndarray, start: int = 0, dtype=None):
    """(n_bad, first_bad_global_row) for one slice -- no decision, no
    collective. The scan half of :func:`validate_finite`, split out so the
    pipelined ingestion path (io/pipeline.py) can accumulate it chunk by
    chunk and still make ONE collectively agreed raise/continue decision.
    """
    finite = np.isfinite(local)
    if dtype is not None and np.dtype(dtype).itemsize < local.dtype.itemsize:
        finite &= np.abs(local) <= np.finfo(dtype).max
    finite = finite.all(axis=1)
    bad = np.flatnonzero(~finite)
    n_bad = int(bad.size)
    first_bad = start + int(bad[0]) if n_bad else -1
    return n_bad, first_bad


def raise_if_nonfinite(n_bad: int, first_bad: int,
                       collective: bool = False) -> None:
    """The decision half of :func:`validate_finite`: one (optionally
    collective) raise/continue verdict from accumulated scan counts."""
    if collective:
        from .parallel.distributed import allgather_host

        counts = allgather_host(np.asarray([n_bad, first_bad], np.int64))
        n_bad = int(counts[:, 0].sum())
        firsts = counts[:, 1][counts[:, 1] >= 0]
        first_bad = int(firsts.min()) if firsts.size else -1
    if n_bad:
        raise InvalidInputError(
            f"input contains {n_bad} non-finite event row(s) "
            f"(first at global row {first_bad}); NaN/Inf events silently "
            "poison every statistic the reference computes -- clean the "
            "data or pass validate_input=False/--no-validate-input to "
            "proceed anyway"
        )


def validate_finite(local: np.ndarray, start: int = 0,
                    collective: bool = False, dtype=None) -> None:
    """Reject rows that are (or will become) non-finite; collective-safe.

    With ``collective``, every rank must reach the same raise/continue
    decision: a lone rank raising before a later collective would leave the
    clean ranks blocked in it forever (``parallel.distributed.allgather_host``
    is the shared primitive). ``dtype`` names the COMPUTE dtype: a value like
    1e39 is finite in the reader's float64 but overflows to Inf when cast to
    float32, which is exactly the poisoning this guards against -- checked
    by magnitude so the raw data needn't be cast first.
    """
    n_bad, first_bad = finite_row_stats(local, start, dtype=dtype)
    raise_if_nonfinite(n_bad, first_bad, collective=collective)
