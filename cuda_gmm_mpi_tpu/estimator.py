"""High-level estimator API: fit / predict / score / sample.

The reference is a single binary with one CLI (``gaussian.cu:1171-1178``); its
only "API" is the ``.summary``/``.results`` file pair. This module exposes the
same capability as a library estimator with the familiar scikit-learn surface,
so the framework is usable programmatically (the CLI in ``cli.py`` remains the
reference-compatible entry point).

All heavy paths reuse the jitted fused E+M machinery; nothing here adds new
numerics.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from .config import GMMConfig
from .models.gmm import GMMModel, chunk_events
from .models.order_search import GMMResult, fit_gmm


class GaussianMixture:
    """K-component Gaussian mixture fit by the TPU-native EM engine.

    Parameters mirror the reference CLI (``num_clusters`` /
    ``target_num_clusters``, gaussian.cu:1111-1178) plus the runtime config.
    With ``target_components=0`` (default) the Rissanen/MDL model-order search
    picks the best K in [1, n_components], exactly like running the reference
    without a target argument (stop_number logic, gaussian.cu:177-181); pass
    ``target_components=n_components`` to skip the search and fit a fixed K.

    Attributes after ``fit``:
      weights_      [K] mixture weights (pi)
      means_        [K, D] in original data coordinates
      covariances_  [K, D, D]
      n_components_ selected K (<= n_components when searching)
      rissanen_     best Rissanen/MDL score (gaussian.cu:826)
      loglik_       total log-likelihood of the best model
      result_       the full GMMResult (sweep log, profile, ...)
    """

    def __init__(
        self,
        n_components: int,
        target_components: int = 0,
        config: Optional[GMMConfig] = None,
        means_init: Optional[np.ndarray] = None,
        **config_overrides,
    ):
        if config is not None and config_overrides:
            raise ValueError("pass either config or field overrides, not both")
        self.n_components = n_components
        self.target_components = target_components
        self.config = config or GMMConfig(**config_overrides)
        # sklearn's means_init: [K, D] starting means in data coordinates,
        # overriding the seeding policy (covariances/weights still start
        # from the reference seed recipe).
        self.means_init = means_init
        self.result_: Optional[GMMResult] = None
        self._model: Optional[GMMModel] = None

    # -- fitting ----------------------------------------------------------

    def fit(self, X: np.ndarray, y=None, *,
            sample_weight: Optional[np.ndarray] = None) -> "GaussianMixture":
        """Fit; ``sample_weight`` ([N] nonnegative) weights every sufficient
        statistic per event (integer weights == replicated rows) -- an
        upgrade over sklearn's GaussianMixture, whose fit() takes none.

        ``y`` is ignored (sklearn estimator convention: pipelines call
        fit(X, y) positionally, so ``sample_weight`` is keyword-only to keep
        labels from ever landing in the weight slot)."""
        if y is not None:
            # Loud break for pre-y-parameter callers: fit(X, w) used to bind
            # w to sample_weight positionally (float OR integer multiplicity
            # weights); dropping it silently would change results without
            # any signal. Pipelines legitimately passing labels see the same
            # warning once -- this estimator is unsupervised, so any y is
            # ignored and saying so beats guessing dtypes.
            import warnings

            warnings.warn(
                "fit() ignores y (unsupervised estimator); if you meant "
                "per-event weights, pass fit(X, sample_weight=...)",
                UserWarning, stacklevel=2)
        X = np.asarray(X)
        if X.ndim != 2:
            raise ValueError(f"X must be [n_events, n_dims], got {X.shape}")
        self.result_ = fit_gmm(
            X, self.n_components, self.target_components, config=self.config,
            init_means=self.means_init, sample_weight=sample_weight,
        )
        # Inference reuses the FITTED model: a sharded fit keeps its sharded
        # posterior pass (all local devices in parallel) for
        # predict/predict_proba/score too, instead of funneling through one
        # device via a fresh plain model.
        self._model = self.result_.model or GMMModel(self.config)
        return self

    def fit_predict(self, X: np.ndarray, y=None, *,
                    sample_weight: np.ndarray | None = None) -> np.ndarray:
        """Fit and return the hard cluster assignment of X (sklearn surface).

        ``y`` is ignored; ``sample_weight`` is keyword-only (see fit())."""
        return self.fit(X, sample_weight=sample_weight).predict(X)

    # -- sklearn interop (clone(), pipelines, grid search) ---------------

    def get_params(self, deep: bool = True) -> dict:
        return {
            "n_components": self.n_components,
            "target_components": self.target_components,
            "config": self.config,
            "means_init": self.means_init,
        }

    def set_params(self, **params) -> "GaussianMixture":
        import dataclasses

        known = ("n_components", "target_components", "config", "means_init")
        config_updates = {}
        for k, v in params.items():
            if k in known:
                setattr(self, k, v)
            elif hasattr(self.config, k):
                config_updates[k] = v  # config fields addressable directly
            else:
                raise ValueError(f"unknown parameter {k!r}")
        if config_updates:
            # diag_only and covariance_type are one coupled setting
            # (GMMConfig.__post_init__): whichever one the user set
            # explicitly must win over the carried-over value of the other,
            # which would otherwise silently snap the update back.
            if ("covariance_type" in config_updates
                    and "diag_only" not in config_updates):
                config_updates["diag_only"] = False
            elif ("diag_only" in config_updates
                    and "covariance_type" not in config_updates):
                cur = self.config.covariance_type
                if config_updates["diag_only"] and cur in ("full", "tied"):
                    config_updates["covariance_type"] = "diag"
                elif not config_updates["diag_only"] and cur in (
                        "diag", "spherical"):
                    config_updates["covariance_type"] = "full"
            self.config = dataclasses.replace(self.config, **config_updates)
        return self

    @classmethod
    def from_summary(cls, path: str, config: Optional[GMMConfig] = None,
                     **config_overrides) -> "GaussianMixture":
        """Rebuild a fitted estimator from a ``.summary`` model file.

        Accepts this framework's output or the reference's own (same format,
        gaussian.cu:1180-1197; the reference never reads these back). Means
        and covariances carry the format's 3-decimal precision, so
        predictions are approximate relative to the in-process fitted model;
        pickle the estimator's ``result_`` for exact persistence.
        """
        from .io.readers import read_summary
        from .ops.constants import compute_constants
        from .state import GMMState

        import jax

        m = read_summary(path)
        k, d = m["means"].shape
        gm = cls(k, target_components=k, config=config, **config_overrides)
        if (gm.config.dtype == "float64"
                and not jax.config.jax_enable_x64):
            # Same guard as the fit path: refuse silent float32 truncation.
            raise ValueError(
                "dtype='float64' needs jax_enable_x64; set "
                "jax.config.update('jax_enable_x64', True) at startup")
        if gm.config.diag_only:
            offdiag = m["R"] - np.stack([np.diag(np.diag(r))
                                         for r in m["R"]])
            if np.abs(offdiag).max() > 0:
                # Silently dropping off-diagonal covariance terms would
                # compute every posterior under the wrong densities.
                raise ValueError(
                    f"{path!r} holds full covariances (nonzero "
                    "off-diagonals) but the config requests "
                    f"covariance_type={gm.config.covariance_type!r}; load "
                    "it without --diag-only/diag config")
        if gm.config.covariance_type == "spherical":
            diags = np.stack([np.diag(r) for r in m["R"]])
            if np.abs(diags - diags[:, :1]).max() > 0:
                # Same contract as the diag guard above: scoring a
                # non-spherical model under a spherical config would
                # silently use the wrong densities.
                raise ValueError(
                    f"{path!r} holds non-spherical covariances (unequal "
                    "variances within a cluster) but the config requests "
                    "covariance_type='spherical'")
        if gm.config.covariance_type == "tied" and k > 1:
            if np.abs(m["R"] - m["R"][:1]).max() > 0:
                raise ValueError(
                    f"{path!r} holds per-cluster covariances (clusters "
                    "differ) but the config requests "
                    "covariance_type='tied'")
        dtype = jnp.float64 if gm.config.dtype == "float64" else jnp.float32
        eye = jnp.broadcast_to(jnp.eye(d, dtype=dtype), (k, d, d))
        state = GMMState(
            N=jnp.asarray(m["N"], dtype),
            pi=jnp.asarray(m["pi"], dtype),
            constant=jnp.zeros((k,), dtype),
            avgvar=jnp.zeros((k,), dtype),
            means=jnp.asarray(m["means"], dtype),
            R=jnp.asarray(m["R"], dtype),
            Rinv=eye,  # placeholder; compute_constants derives it from R
            active=jnp.ones((k,), bool),
        )
        # Recompute Rinv/constant/pi coherently from R and N (the summary's
        # pi is printf-rounded; constants_kernel semantics, including the
        # identity reset of clusters whose 3-decimal R rounded non-PD).
        state = compute_constants(state, diag_only=gm.config.diag_only)
        gm.result_ = GMMResult(
            state=state,
            ideal_num_clusters=k,
            min_rissanen=float("nan"),
            final_loglik=float("nan"),
            epsilon=float("nan"),
            num_events=0,
            num_dimensions=d,
            data_shift=np.zeros((d,), np.float64),
        )
        gm._model = GMMModel(gm.config)
        return gm

    # -- serving registry round-trip (docs/SERVING.md) -------------------

    def to_registry(self, registry, name: str, *, version=None,
                    run_id=None) -> int:
        """Persist this fitted estimator into a serving model registry.

        ``registry`` is a :class:`~cuda_gmm_mpi_tpu.serving.ModelRegistry`
        or a root directory path. Unlike the 3-decimal ``.summary``
        format, the artifact stores the exact state leaves, so a model
        re-hydrated via :meth:`from_registry` (or served by ``gmm
        serve``) scores bit-identically to this in-memory estimator.
        Returns the assigned version.
        """
        from .serving.registry import ModelRegistry

        if not isinstance(registry, ModelRegistry):
            registry = ModelRegistry(registry)
        return registry.save(name, self._fitted, config=self.config,
                             run_id=run_id, version=version)

    @classmethod
    def from_registry(cls, registry, name: str, version=None,
                      ) -> "GaussianMixture":
        """Rebuild a fitted estimator from a serving-registry artifact
        (exact round-trip; the manifest supplies dtype and covariance
        family)."""
        from .serving.registry import ModelRegistry

        if not isinstance(registry, ModelRegistry):
            registry = ModelRegistry(registry)
        m = registry.load(name, version)
        gm = cls(m.k, target_components=m.k,
                 config=GMMConfig(dtype=m.dtype,
                                  covariance_type=m.covariance_type))
        import jax

        if m.dtype == "float64" and not jax.config.jax_enable_x64:
            raise ValueError(
                "dtype='float64' needs jax_enable_x64; set "
                "jax.config.update('jax_enable_x64', True) at startup")
        gm.result_ = GMMResult(
            state=m.state,
            ideal_num_clusters=m.k,
            min_rissanen=(float("nan") if m.manifest.get("score") is None
                          else float(m.manifest["score"])),
            final_loglik=(float("nan") if m.manifest.get("loglik") is None
                          else float(m.manifest["loglik"])),
            epsilon=float("nan"),
            num_events=int(m.manifest.get("num_events", 0)),
            num_dimensions=m.d,
            data_shift=np.asarray(m.data_shift, np.float64),
        )
        gm._model = GMMModel(gm.config)
        return gm

    @property
    def _fitted(self) -> GMMResult:
        if self.result_ is None:
            raise RuntimeError("estimator is not fitted; call fit(X) first")
        return self.result_

    @property
    def n_iter_(self) -> int:
        """EM iterations run at the selected K (from the sweep log).

        Note the reference's shipped semantics pin min_iters == max_iters ==
        100 (gaussian.h:26-27), which short-circuits the convergence test --
        under those defaults this is always max_iters.
        """
        res = self._fitted
        for row in res.sweep_log:
            if int(row[0]) == res.ideal_num_clusters:
                return int(row[3])
        return 0

    @property
    def weights_(self) -> np.ndarray:
        return self._fitted.weights

    @property
    def means_(self) -> np.ndarray:
        return self._fitted.means

    @property
    def covariances_(self) -> np.ndarray:
        return self._fitted.covariances

    @property
    def n_components_(self) -> int:
        return self._fitted.ideal_num_clusters

    @property
    def rissanen_(self) -> float:
        return self._fitted.min_rissanen

    @property
    def loglik_(self) -> float:
        return self._fitted.final_loglik

    # -- inference --------------------------------------------------------

    def _posteriors_and_evidence(self, X: np.ndarray):
        """(w [N, K], logZ [N]) for arbitrary data under the fitted model.

        Single-device fits route through the serving executor
        (serving/executor.py): AOT-compiled scoring programs cached per
        (N-bucket, K-bucket, D), so repeated calls with VARYING row
        counts reuse one compiled executable per pow2 bucket instead of
        retracing per distinct N (the pre-serving behavior -- jit keys
        on exact shapes, so every new N paid a full trace+compile).
        Sharded and streaming fits keep the model's own chunked
        ``memberships`` pass (the executor is a one-device program; a
        mesh fit's posterior pass spans all local devices).
        """
        from .validation import validate_finite

        res = self._fitted
        dtype = np.dtype(self.config.dtype)
        X = np.asarray(X, dtype)
        if self.config.validate_input:
            # Same promise on inference as on fit: NaN/Inf rows abort with
            # a clear message instead of silently emitting NaN posteriors.
            validate_finite(X)
        X = X - res.data_shift[None, :].astype(dtype)
        if (getattr(self._model, "mesh", None) is None
                and not self.config.stream_events):
            from .serving.executor import executor_for_config

            w, logz = executor_for_config(self.config).infer(
                res.state, X, want="proba")
            # The executor pads K to its pow2 bucket; inactive pad slots
            # carry exactly-zero responsibility -- slice them off.
            return w[:, :res.state.num_clusters_padded], logz
        chunks, _ = chunk_events(X, self.config.chunk_size)
        # Host chunks passed through: each model places its own blocks (the
        # sharded model puts them per-shard; an eager jnp.asarray here would
        # upload the whole dataset to one device first).
        w, logz = self._model.memberships(res.state, chunks, return_logz=True)
        n = X.shape[0]
        return w[:n], logz[:n]

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        """Posterior responsibilities [N, K] (the .results memberships,
        gaussian.cu:1042-1059)."""
        return self._posteriors_and_evidence(X)[0]

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Hard cluster assignment: argmax posterior per event."""
        return np.argmax(self.predict_proba(X), axis=1)

    def score_samples(self, X: np.ndarray) -> np.ndarray:
        """Per-event log evidence log p(x) (estep2's logZ,
        gaussian_kernel.cu:489-495)."""
        return self._posteriors_and_evidence(X)[1]

    def score(self, X: np.ndarray) -> float:
        """Mean per-event log-likelihood."""
        return float(np.mean(self.score_samples(X)))

    def _criterion_on(self, X: np.ndarray, criterion: str) -> float:
        from .ops.formulas import model_score

        n = np.asarray(X).shape[0]
        ll = float(np.sum(self.score_samples(X)))
        return float(model_score(
            ll, self.n_components_, n, self._fitted.num_dimensions,
            criterion=criterion,
            covariance_type=self.config.covariance_type,
        ))

    def bic(self, X: np.ndarray) -> float:
        """Bayesian information criterion on X (lower is better) -- the
        scikit-learn-familiar sibling of the Rissanen/MDL score the order
        search minimizes (they differ only in the reference's N*D vs N
        sample-count convention). Delegates to ops.formulas.model_score so
        the formula lives once."""
        return self._criterion_on(X, "bic")

    def aic(self, X: np.ndarray) -> float:
        """Akaike information criterion on X (lower is better)."""
        return self._criterion_on(X, "aic")

    def sample(self, n_samples: int, seed: Optional[int] = None
               ) -> tuple[np.ndarray, np.ndarray]:
        """Draw events from the fitted mixture (generation -- absent from the
        reference, natural for a library estimator).

        Returns ``(X, y)`` -- samples and their component labels -- shaped
        like sklearn's ``GaussianMixture.sample`` so code written against
        sklearn keeps working. Deliberate differences: a ``seed`` kwarg
        (sklearn reuses the estimator's ``random_state``), ``X`` cast to
        ``config.dtype`` (sklearn returns float64), and per-component
        counts drawn via ``rng.choice`` rather than one multinomial."""
        rng = np.random.default_rng(self.config.seed if seed is None else seed)
        pi = np.asarray(self.weights_, np.float64)
        pi = pi / pi.sum()
        comps = rng.choice(len(pi), size=n_samples, p=pi)
        mu = np.asarray(self.means_, np.float64)
        cov = np.asarray(self.covariances_, np.float64)
        out = np.empty((n_samples, mu.shape[1]), np.float64)
        for c in range(len(pi)):
            m = comps == c
            if m.any():
                out[m] = rng.multivariate_normal(mu[c], cov[c], size=int(m.sum()))
        return out.astype(np.dtype(self.config.dtype)), comps
