"""Ragged-tenant packing: T independent datasets -> pow2-bucketed groups.

The fleet workload (docs/TENANCY.md) is thousands of SMALL independent
mixtures -- the reference's own flow-cytometry domain fits one model per
patient sample (PAPER.md §0). Dispatching them one fit at a time pays a
full host round-trip and executable lookup per tenant; packing them into
shape-bucketed groups lets the fleet driver run each group as ONE
compiled EM dispatch (``GMMModel.run_em_fleet``).

The packing policy is the PR-2/PR-7 pow2 bucketing applied per tenant:

- the EVENT axis pads to the smallest power-of-two bucket >= N_t,
  expressed as a forced chunk count (``chunk_events(num_chunks=...)``)
  whose pad rows carry ZERO weight -- exactly the tail padding every solo
  fit already does, so the pad is algebraically inert (zero-weight rows
  contribute exact zeros to every sufficient statistic);
- the CLUSTER axis pads to the pow2 bucket >= K_t with inert inactive
  slots (``seed_state_from_parts``'s ``num_clusters_padded``; the
  ``pad_state_clusters`` shape), rounded up to the cluster-mesh axis on
  sharded models so lanes stay evenly partitionable.

Tenants sharing a (chunk-count, K-bucket) signature group together; one
group is one device program. Per-tenant seeding, centering shift, moment
computation, and convergence epsilon all reuse the solo fit's exact host
recipe (``order_search._seed_rows`` / ``distributed.global_moments`` with
the solo chunk count), which is what makes the fleet's per-tenant results
bit-identical to solo fits by construction rather than by parallel
maintenance.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import GMMConfig
from ..validation import validate_finite


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's fit request: its own events, K, target, and seed."""

    name: str
    data: np.ndarray              # [N_t, D] events (in-memory)
    num_clusters: int             # starting K_t
    target_num_clusters: int = 0  # 0 = search down to 1, keep best score
    seed: Optional[int] = None    # None -> config.seed

    def __post_init__(self):
        data = np.asarray(self.data)
        if data.ndim != 2 or data.shape[0] < 1:
            raise ValueError(
                f"tenant {self.name!r}: data must be a non-empty "
                f"[N, D] array, got shape {data.shape}")
        if self.num_clusters < 1:
            raise ValueError(
                f"tenant {self.name!r}: num_clusters must be >= 1")
        if self.target_num_clusters > self.num_clusters:
            raise ValueError(
                f"tenant {self.name!r}: target_num_clusters "
                f"({self.target_num_clusters}) must be <= num_clusters "
                f"({self.num_clusters})")


@dataclasses.dataclass
class FleetGroup:
    """One packed-shape bucket: the tenants one EM dispatch will serve."""

    indices: List[int]   # positions into the fleet's tenant list
    num_chunks: int      # forced chunk count (pow2 event bucket / chunk)
    k_bucket: int        # shared padded cluster width
    n_bucket: int        # pow2 event bucket (num_chunks * chunk_size)


@dataclasses.dataclass
class PackedGroup:
    """Host-side arrays of one group, ready for device placement."""

    group: FleetGroup
    chunks: np.ndarray        # [T, C, B, D] per-tenant packed chunk grids
    wts: np.ndarray           # [T, C, B] weight rows (0 beyond N_t)
    states: list              # per-lane host GMMState, padded to k_bucket
    epsilons: np.ndarray      # [T] per-tenant convergence epsilon
    shifts: np.ndarray        # [T, D] per-tenant centering shift
    n_events: np.ndarray      # [T] true event counts
    k0: np.ndarray            # [T] starting cluster counts
    targets: np.ndarray       # [T] target cluster counts (0 = search)
    names: List[str]
    solo_chunks: np.ndarray   # [T] each tenant's solo-fit chunk count
    data_axis: int            # data-mesh extent the layout was packed for


def pow2_bucket(n: int, lo: int = 1) -> int:
    """Smallest power of two >= ``n`` (>= ``lo``) -- the event-axis
    bucketing policy shared with the serving executor."""
    b = 1 << max(0, int(n) - 1).bit_length()
    return max(b, int(lo))


def plan_fleet(tenants: List[TenantSpec], config: GMMConfig,
               data_axis: int = 1, cluster_axis: int = 1,
               ) -> List[FleetGroup]:
    """Group tenants by packed shape: (forced chunk count, K bucket).

    ``data_axis``/``cluster_axis`` are the target model's mesh extents:
    the chunk count rounds up to a data-axis multiple (every shard gets an
    equal chunk slice) and the K bucket to a cluster-axis multiple (the
    ``pad_state_clusters`` contract). ``config.fleet_group_size`` splits
    oversized groups so one dispatch's [T, C, B, D] device residency
    stays bounded.
    """
    if not tenants:
        raise ValueError("fit_fleet needs at least one tenant")
    dims = {int(np.asarray(t.data).shape[1]) for t in tenants}
    if len(dims) > 1:
        raise ValueError(
            f"all tenants must share one dimensionality; got D in "
            f"{sorted(dims)} (run mixed-D fleets as separate calls)")
    names = [t.name for t in tenants]
    if len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise ValueError(f"duplicate tenant names: {dupes}")
    for t in tenants:
        if t.num_clusters > config.max_clusters:
            raise ValueError(
                f"tenant {t.name!r}: num_clusters ({t.num_clusters}) "
                f"exceeds max_clusters ({config.max_clusters})")

    chunk = int(config.chunk_size)
    by_shape: Dict[Tuple[int, int], List[int]] = {}
    meta: Dict[Tuple[int, int], int] = {}
    for i, t in enumerate(tenants):
        n = int(np.asarray(t.data).shape[0])
        n_bucket = pow2_bucket(n)
        num_chunks = -(-n_bucket // chunk)          # ceil
        num_chunks += (-num_chunks) % max(data_axis, 1)
        kb = pow2_bucket(t.num_clusters)
        if cluster_axis > 1:
            kb += (-kb) % cluster_axis
        key = (num_chunks, kb)
        by_shape.setdefault(key, []).append(i)
        meta[key] = num_chunks * chunk
    groups: List[FleetGroup] = []
    cap = config.fleet_group_size
    for (num_chunks, kb), idxs in sorted(by_shape.items()):
        step = len(idxs) if cap is None else max(1, int(cap))
        for lo in range(0, len(idxs), step):
            groups.append(FleetGroup(
                indices=idxs[lo:lo + step], num_chunks=num_chunks,
                k_bucket=kb, n_bucket=meta[(num_chunks, kb)]))
    return groups


def pack_group(group: FleetGroup, tenants: List[TenantSpec],
               config: GMMConfig, data_axis: int = 1) -> PackedGroup:
    """Pack one group's tenants into stacked [T, ...] host arrays.

    Per tenant, this is exactly the solo fit's ``_prepare_fit`` recipe --
    float64 chunk-ordered moments at the SOLO chunk count (so the
    centering shift and variance floor are bit-identical to the solo
    fit's), centering, seeding rows via ``order_search._seed_rows`` at
    the tenant's seed, and the convergence epsilon from the TRUE event
    count -- followed by the group's forced chunk count, whose extra
    all-zero chunks are algebraically inert.
    """
    from ..models.gmm import chunk_events
    from ..models.order_search import _seed_rows
    from ..ops.formulas import convergence_epsilon
    from ..ops.seeding import seed_state_from_parts
    from ..parallel.distributed import global_moments, host_chunk_bounds
    from ..testing import faults

    dtype = np.dtype(config.dtype)
    chunk = int(config.chunk_size)
    chunks_l, wts_l, states, eps_l, shifts = [], [], [], [], []
    n_l, k_l, tgt_l, names, solo_l = [], [], [], [], []
    for lane, i in enumerate(group.indices):
        t = tenants[i]
        data = np.ascontiguousarray(np.asarray(t.data))
        n, d = data.shape
        if config.validate_input:
            validate_finite(data, 0, collective=False, dtype=dtype)
        # Moments at the SOLO chunk count: global_moments' partial-matrix
        # reduction depends on the chunk-slot layout, and the solo fit's
        # shift must be reproduced bit-for-bit.
        _, _, solo_chunks = host_chunk_bounds(n, chunk, data_axis, 0, 1)
        mean64, var64 = global_moments(data, chunk, solo_chunks)
        if config.center_data:
            shift = mean64.astype(dtype)
        else:
            shift = np.zeros((d,), dtype)
        local = data.astype(dtype, copy=False)
        if config.center_data:
            local = local - shift[None, :]
        var_mean = float(var64.mean())
        # The tenant's SOLO chunk layout first, then its pad chunks
        # interleaved PER DATA SHARD: shard s of the group must hold
        # exactly the solo fit's shard-s chunk block (plus trailing
        # all-zero chunks, which a shard-local scan accumulates as
        # exact zeros) -- appending all pads at the end instead would
        # move real chunks ACROSS shards and regroup the stats psum,
        # which is a bit-level change (tests/test_tenancy.py sharded
        # parity).
        c_solo, w_solo = chunk_events(local, chunk,
                                      num_chunks=solo_chunks)
        B = c_solo.shape[1]
        c_np = np.zeros((group.num_chunks, B, d), dtype)
        w_np = np.zeros((group.num_chunks, B), dtype)
        per_solo = solo_chunks // max(data_axis, 1)
        per_g = group.num_chunks // max(data_axis, 1)
        for s in range(max(data_axis, 1)):
            c_np[s * per_g:s * per_g + per_solo] = \
                c_solo[s * per_solo:(s + 1) * per_solo]
            w_np[s * per_g:s * per_g + per_solo] = \
                w_solo[s * per_solo:(s + 1) * per_solo]
        rows = _seed_rows(data, None, t.num_clusters, d, n, dtype,
                          seed_method=config.seed_method,
                          seed=(config.seed if t.seed is None
                                else int(t.seed)))
        state = seed_state_from_parts(
            np.asarray(rows, dtype) - np.asarray(shift, dtype)[None, :],
            n, var_mean, t.num_clusters,
            num_clusters_padded=group.k_bucket,
            covariance_dynamic_range=config.covariance_dynamic_range,
            dtype=dtype)
        if lane == 0:
            # Deterministic seed poisoning targets lane 0 of the group
            # (the batched-restart convention, models/restarts.py).
            state = faults.maybe_poison_state(state)
        chunks_l.append(c_np)
        wts_l.append(w_np)
        states.append(state)
        eps_l.append(convergence_epsilon(n, d, config.epsilon_scale))
        shifts.append(np.asarray(shift, np.float64))
        n_l.append(n)
        k_l.append(t.num_clusters)
        tgt_l.append(t.target_num_clusters)
        names.append(t.name)
        solo_l.append(solo_chunks)
    return PackedGroup(
        group=group,
        chunks=np.stack(chunks_l),
        wts=np.stack(wts_l),
        states=states,
        epsilons=np.asarray(eps_l, np.float64),
        shifts=np.stack(shifts),
        n_events=np.asarray(n_l, np.int64),
        k0=np.asarray(k_l, np.int64),
        targets=np.asarray(tgt_l, np.int64),
        names=names,
        solo_chunks=np.asarray(solo_l, np.int64),
        data_axis=int(max(data_axis, 1)),
    )


def unpack_rows(packed: PackedGroup, lane: int) -> np.ndarray:
    """One tenant's rows back out of the packed grid (fit coordinates).

    The ragged round-trip contract (tests/test_tenancy.py): gathering
    the lane's per-shard solo chunk blocks (the pad chunks interleave
    per data shard -- see :func:`pack_group`) and dropping the pad rows
    returns exactly the centered rows that went in -- packing is pure
    layout, never arithmetic. Add ``packed.shifts[lane]`` back for
    original coordinates (a float round-trip, not a bit one: centering
    subtracts in the compute dtype).
    """
    n = int(packed.n_events[lane])
    d = packed.chunks.shape[-1]
    S = packed.data_axis
    per_solo = int(packed.solo_chunks[lane]) // S
    per_g = packed.chunks.shape[1] // S
    grid = np.asarray(packed.chunks[lane])
    blocks = [grid[s * per_g:s * per_g + per_solo] for s in range(S)]
    return np.concatenate(blocks, axis=0).reshape(-1, d)[:n]
