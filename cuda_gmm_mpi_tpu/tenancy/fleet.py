"""Fleet fits: thousands of independent GMMs as a handful of dispatches.

The multi-tenancy driver (docs/TENANCY.md): T independent datasets --
per-tenant N_t / K_t / seed, shared D and covariance family -- pack into
pow2 (event-bucket, cluster-bucket) groups (``tenancy/packing.py``) and
each group runs its whole model-order sweep through ONE fleet EM
executable per step (``GMMModel.run_em_fleet``: the PR-5/6 restart axis
generalized into a dataset axis -- per-tenant data, weights, epsilon, and
iteration bounds ride a leading tenant axis).

Contracts (tests/test_tenancy.py):

- **solo parity** -- every tenant's fitted model is BIT-IDENTICAL to a
  solo ``fit_gmm`` of that tenant at the same seed/config (plain and
  sharded meshes, full and diag covariance): the per-tenant host recipe
  (moments, shift, seeding, epsilon) is the solo code path itself, the
  packing pad is algebraically inert, and the default ``fleet_mode=
  'scan'`` maps lanes with ``lax.map``, so each lane's arithmetic is the
  exact HLO of its solo run. ``fleet_mode='vmap'`` trades bit-parity for
  [T, B, K] batched matmuls (reduction-order tolerance).
- **per-tenant freeze-out** -- a tenant that converges (or finishes its
  sweep) freezes (``max_iters=0`` lanes pass through bit-identically)
  while its groupmates keep iterating.
- **drop-one containment** -- per-tenant health ROWS ([T, NUM_FLAGS]):
  a tenant whose EM goes fatal is DROPPED from the group (``recovery``
  action ``drop_tenant``) and its survivors' results are untouched;
  ``recovery='off'`` raises instead (the PR-5 drop_restart shape).
- **preempt/resume** -- with a checkpoint dir, every completed sweep
  step is durable per group (``checkpoint_dir/group<i>/``); SIGTERM /
  deadline between steps exits 75 and ``--resume auto`` continues
  bit-identically.

Telemetry (stream rev v1.8, docs/OBSERVABILITY.md): ``fleet_start`` /
per-tenant ``tenant_done`` / closing ``fleet_summary``, rendered by
``gmm report`` ("Fleet" section). The per-init run_start/run_summary
contract stays the restart driver's; fleet streams are fleet-shaped.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import time
from typing import Dict, List, Optional

import jax
import numpy as np

from .. import health, supervisor, telemetry
from ..config import GMMConfig
from ..models.restarts import (
    _host_batched, _pad_sweep_logs, _place_batched, _place_batched_state,
    _where_lanes,
)
from ..ops.formulas import model_score
from ..state import clone_state, compact
from ..telemetry import RunRecorder
from ..telemetry import exporter as tl_exporter
from ..telemetry import spans as tl_spans
from ..utils.logging_ import get_logger
from .packing import TenantSpec, pack_group, plan_fleet


@dataclasses.dataclass
class TenantResult:
    """One tenant's outcome: a fitted model, or why it was dropped."""

    name: str
    index: int        # position in the fleet's tenant list
    group: int        # packed-group index
    result: Optional[object] = None   # GMMResult; None when dropped
    error: Optional[str] = None       # the drop diagnosis

    @property
    def dropped(self) -> bool:
        return self.result is None


@dataclasses.dataclass
class FleetResult:
    """All tenants' outcomes plus the fleet-level accounting."""

    tenants: List[TenantResult]
    groups: List[dict]    # per-group {tenants, n_bucket, k_bucket, ...}
    mode: str
    wall_s: float

    def __getitem__(self, name: str) -> TenantResult:
        for t in self.tenants:
            if t.name == name:
                return t
        raise KeyError(name)

    @property
    def dropped(self) -> List[TenantResult]:
        return [t for t in self.tenants if t.dropped]

    @property
    def fitted(self) -> List[TenantResult]:
        return [t for t in self.tenants if not t.dropped]


def _reject_unsupported(config: GMMConfig) -> None:
    """Loud rejection of config combinations the fleet driver cannot
    honor -- silently ignoring a requested mode would fit tenants under
    different semantics than the flag promised."""
    why = None
    if config.stream_events:
        why = "stream_events has no single EM program to map tenants over"
    elif config.fused_sweep:
        why = "fused_sweep runs one whole-sweep program per dataset"
    elif config.n_init > 1:
        why = "n_init restarts nest a second batch axis (fit tenants solo)"
    elif config.precompute_features:
        why = "precompute_features would hold [T, C, B, F] features"
    elif config.use_pallas == "always" or config.estep_backend == "pallas":
        why = ("the Pallas kernels batch the restart axis over SHARED "
               "event tiles; the fleet loop runs the jnp path")
    elif config.recovery_reseed_empty:
        why = "recovery_reseed_empty is a solo target-K refinement pass"
    if why is not None:
        raise ValueError(f"fit_fleet cannot honor this config: {why}")
    if jax.process_count() > 1:
        raise ValueError(
            "fleet fits are single-controller; multi-controller runs fit "
            "one tenant at a time")


def fit_fleet(tenants: List[TenantSpec], config: GMMConfig = GMMConfig(),
              model=None, verbose: Optional[bool] = None) -> FleetResult:
    """Fit every tenant's mixture -- the fleet library entry point.

    Mirrors ``fit_gmm``'s ambient-subsystem contract: ``metrics_file``
    activates a run-scoped telemetry recorder (already-active ambient
    recorders are reused) and ``max_runtime_s`` a signal-free deadline
    supervisor, and a preemption surfaces as
    :class:`~cuda_gmm_mpi_tpu.supervisor.PreemptedError` for the CLI's
    exit-75 contract.
    """
    _reject_unsupported(config)
    with contextlib.ExitStack() as stack:
        if config.metrics_file and not telemetry.current().active:
            rec = RunRecorder(config.metrics_file)
            stack.enter_context(telemetry.use(rec))
            stack.enter_context(rec)
        if config.max_runtime_s is not None \
                and not supervisor.current().active:
            stack.enter_context(supervisor.use(supervisor.RunSupervisor(
                max_runtime_s=config.max_runtime_s,
                install_signals=False)))
        if config.metrics_port is not None:
            # Live observability plane (rev v2.1): /metrics exporter +
            # resource sampler + a fleet-rooted span trace. Entirely
            # gated so metrics_port=None keeps streams byte-identical.
            from ..parallel import elastic

            stack.enter_context(tl_exporter.live_plane(
                config.metrics_port,
                registry_provider=lambda: telemetry.current().metrics,
                gauges_provider=elastic.live_gauges))
            rec = telemetry.current()
            tid = stack.enter_context(tl_spans.trace())
            if rec.active:
                rec.set_context(trace_id=tid)
                stack.callback(rec.set_context, trace_id=None)
            stack.enter_context(tl_spans.span("fleet"))
        if config.autotune != "off" and tenants:
            # Profile-guided knob resolution (tuning/): fleet_mode and
            # chunk_size from the nearest recorded profile at the
            # fleet's LARGEST packed shape (db/static only -- a fleet
            # fit never burns tenant wall probing). The resolved config
            # comes back autotune='off' so nothing downstream re-runs
            # this; `tune` events ride the ambient stream.
            from ..tuning import resolve_fleet_config_ex

            config, _ = resolve_fleet_config_ex(
                config,
                max(int(t.data.shape[0]) for t in tenants),
                int(tenants[0].data.shape[1]),
                max(int(t.num_clusters) for t in tenants))
        return _fit_fleet(tenants, config, model, verbose)


def _fit_fleet(tenants, config, model, verbose) -> FleetResult:
    log = get_logger(config)
    rec = telemetry.current()
    verbose = config.enable_print if verbose is None else verbose
    t_start = time.perf_counter()

    if config.device:
        jax.config.update("jax_platforms", config.device)
    if config.dtype == "float64" and not jax.config.jax_enable_x64:
        raise ValueError(
            "dtype='float64' needs jax_enable_x64; set "
            "jax.config.update('jax_enable_x64', True) at startup (the "
            "CLI does this for --dtype=float64)")

    if model is None:
        if config.mesh_shape is not None:
            from ..parallel import ShardedGMMModel

            model = ShardedGMMModel(config)
        else:
            from ..models.gmm import GMMModel

            model = GMMModel(config)
    if not getattr(model, "supports_fleet", False):
        raise ValueError(
            f"{type(model).__name__} has no fleet EM loop")

    groups = plan_fleet(
        tenants, config,
        data_axis=int(getattr(model, "data_size", 1)),
        cluster_axis=int(getattr(model, "cluster_size", 1)))
    mode = config.fleet_mode
    d = int(np.asarray(tenants[0].data).shape[1])
    log.info("fleet fit: %d tenants in %d packed group(s), mode=%s",
             len(tenants), len(groups), mode)
    if rec.active:
        rec.set_context(path="fleet")
        rec.emit(
            "fleet_start",
            tenants=len(tenants), groups=len(groups), mode=mode,
            platform=jax.devices()[0].platform,
            num_dimensions=d, dtype=config.dtype,
            covariance_type=config.covariance_type,
            criterion=config.criterion,
            chunk_size=int(config.chunk_size),
            group_shapes=[{"tenants": len(g.indices),
                           "n_bucket": int(g.n_bucket),
                           "k_bucket": int(g.k_bucket)}
                          for g in groups],
        )

    out: List[Optional[TenantResult]] = [None] * len(tenants)
    group_meta: List[dict] = []
    # One elastic-recovery budget spans the whole fleet fit: a peer loss
    # during any group shrinks the world once and every LATER group fits
    # over the survivors too (membership generations only move forward).
    recovery = None
    for gi, group in enumerate(groups):
        packed = pack_group(group, tenants, config,
                            data_axis=int(getattr(model, "data_size", 1)))
        ckpt = None
        if config.checkpoint_dir:
            import os

            from ..utils.checkpoint import SweepCheckpointer

            ckpt = SweepCheckpointer(
                os.path.join(config.checkpoint_dir, f"group{gi}"),
                keep=config.checkpoint_keep,
                retries=config.checkpoint_retries,
                allow_world_change=config.elastic)
        t0 = time.perf_counter()
        # Non-lexical span (a preempt raises through the retry loop; an
        # un-ended span simply never emits -- see telemetry/spans.py).
        g_span = tl_spans.begin("fleet_group", group=gi,
                                tenants=len(group.indices))
        while True:
            try:
                results = _run_group(model, config, packed, ckpt, rec, log,
                                     verbose, mode, gi)
                break
            except supervisor.PeerLostError as e:
                # Per-group elastic continue: shrink + resume THIS group
                # from its own checkpoint subdirectory; completed groups'
                # results are already in ``out`` and are not refitted.
                if recovery is None:
                    recovery = supervisor.ElasticRecovery.maybe(config)
                if recovery is None:
                    raise
                config = recovery.recover(e, config)
        tl_spans.end(g_span)
        group_meta.append({
            "tenants": len(group.indices),
            "n_bucket": int(group.n_bucket),
            "k_bucket": int(group.k_bucket),
            "num_chunks": int(group.num_chunks),
            "seconds": round(time.perf_counter() - t0, 6),
        })
        for lane, i in enumerate(group.indices):
            tr = results[lane]
            out[i] = tr
            if rec.active:
                fields: Dict[str, object] = dict(
                    tenant=tr.name, dropped=tr.dropped, group=gi,
                    num_events=int(packed.n_events[lane]))
                if tr.dropped:
                    fields["error"] = tr.error
                else:
                    r = tr.result
                    fields.update(
                        k=int(r.ideal_num_clusters),
                        score=_json_float(r.min_rissanen),
                        loglik=_json_float(r.final_loglik),
                        iters=int(sum(row[3] for row in r.sweep_log)),
                        criterion=config.criterion)
                rec.emit("tenant_done", **fields)
                rec.metrics.count("tenants_dropped" if tr.dropped
                                  else "tenants_fitted")
            if verbose:
                if tr.dropped:
                    print(f"tenant {tr.name}: DROPPED ({tr.error})")
                else:
                    print(f"tenant {tr.name}: "
                          f"{config.criterion}="
                          f"{tr.result.min_rissanen:.6e} "
                          f"K={tr.result.ideal_num_clusters}")

    wall = time.perf_counter() - t_start
    fleet = FleetResult(tenants=[t for t in out if t is not None],
                        groups=group_meta, mode=mode,
                        wall_s=round(wall, 6))
    if rec.active:
        rec.emit("fleet_summary",
                 tenants=len(fleet.tenants),
                 dropped=len(fleet.dropped),
                 groups=len(groups), mode=mode,
                 wall_s=round(wall, 6),
                 metrics=rec.metrics.snapshot())
        rec.set_context(path=None)
    return fleet


def _json_float(x) -> Optional[float]:
    x = float(x)
    return x if math.isfinite(x) else None


def _fleet_elim(model, config, mode: str):
    """Order-reduction for a tenant-batched state: scan mode lax.maps the
    per-lane ``eliminate_and_reduce`` (bit-identical to the solo
    dispatch); vmap mode reuses the restart driver's vmapped executable."""
    import functools

    from jax import lax

    from ..models.restarts import _elim_reduce_batched_jit
    from ..ops.merge import eliminate_and_reduce

    if mode == "vmap":
        return _elim_reduce_batched_jit(config.diag_only)
    fn = functools.partial(eliminate_and_reduce,
                           diag_only=config.diag_only)
    cache = model.__dict__.setdefault("_fleet_elim_cache", {})
    jitted = cache.get(config.diag_only)
    if jitted is None:
        jitted = cache[config.diag_only] = jax.jit(
            lambda s: lax.map(fn, s))
    return jitted


def _run_group(model, config, packed, ckpt, rec, log, verbose, mode,
               group_index) -> List[TenantResult]:
    """One packed group through the whole per-tenant model-order sweep.

    The fleet mirror of the batched-restart sweep (``restarts._run_batch``)
    with per-LANE datasets: every lane carries its own k trajectory,
    epsilon, event count, and stop target; one fleet EM dispatch + one
    mapped order-reduction dispatch per step serve every live lane.
    """
    from ..models.order_search import (
        _COV_CODE, _CRITERION_CODE, _resume_mismatch, _shutdown_and_raise,
        GMMResult, compute_envelope,
    )

    sup = supervisor.current()
    T = len(packed.names)
    d = packed.chunks.shape[-1]

    states = _place_batched(model, packed.states)
    chunks_d, wts_d = model.prepare_fleet(packed.chunks, packed.wts)
    if rec.active:
        rec.metrics.count("h2d_bytes", int(packed.chunks.nbytes)
                          + int(packed.wts.nbytes))

    K0 = packed.k0.copy()
    k_r = packed.k0.copy()
    stop_r = np.where(packed.targets > 0, packed.targets, 1)
    alive = np.ones((T,), bool)
    dropped = np.zeros((T,), bool)
    drop_error: List[Optional[str]] = [None] * T
    min_riss_r = np.full((T,), np.inf)
    ideal_k_r = k_r.copy()
    best_ll_r = np.full((T,), -np.inf)
    sweep_logs: List[list] = [[] for _ in range(T)]
    health_lane = np.zeros((T, health.NUM_FLAGS), np.int64)
    # The first EM call donates the seed buffers; best must not alias.
    best_states = clone_state(states)
    elim = _fleet_elim(model, config, mode)

    step = 0
    if ckpt is not None and config.resume != "never":
        restored = ckpt.restore()
        if restored is not None and (
                "fleet" not in restored
                or int(np.asarray(restored["state"].N).shape[0]) != T
                or not np.array_equal(np.asarray(restored["k0"],
                                                 np.int64), K0)
                or not np.array_equal(
                    np.asarray(restored["n_events"], np.int64),
                    packed.n_events)
                or _resume_mismatch(restored, config, log)):
            restored = None
        if restored is not None:
            states = _place_batched_state(model, restored["state"])
            best_states = _place_batched_state(model,
                                               restored["best_state"])
            k_r = np.asarray(restored["k"], np.int64).copy()
            alive = np.asarray(restored["alive"], bool).copy()
            dropped = np.asarray(restored["dropped"], bool).copy()
            min_riss_r = np.asarray(restored["min_rissanen"],
                                    np.float64).copy()
            ideal_k_r = np.asarray(restored["ideal_k"], np.int64).copy()
            best_ll_r = np.asarray(restored["best_ll"], np.float64).copy()
            lens = np.asarray(restored["sweep_len"], np.int64)
            rows_log = np.asarray(restored["sweep_log"], np.float64)
            sweep_logs = [
                [tuple(row) for row in rows_log[t][:int(lens[t])]]
                for t in range(T)
            ]
            health_lane = np.asarray(restored["health_lane"],
                                     np.int64).copy()
            step = int(np.asarray(restored["step"])) + 1
            log.info("resumed fleet group %d from checkpoint: step %d",
                     group_index, step)
            rec.metrics.count("resumes") if rec.active else None

    def host_payload():
        return {
            "state": _host_batched(model, states),
            "best_state": _host_batched(model, best_states),
            "min_rissanen": np.asarray(min_riss_r, np.float64),
            "ideal_k": np.asarray(ideal_k_r, np.int64),
            "best_ll": np.asarray(best_ll_r, np.float64),
            "k": np.asarray(k_r, np.int64),
            "alive": alive.astype(np.int64),
            "dropped": dropped.astype(np.int64),
            "k0": K0,
            "targets": packed.targets,
            "n_events": packed.n_events,
            "fleet": 1,
            "num_clusters": int(packed.group.k_bucket),
            "criterion_code": _CRITERION_CODE[config.criterion],
            "cov_code": _COV_CODE[config.covariance_type],
            "health_lane": health_lane,
            "sweep_log": _pad_sweep_logs(sweep_logs),
            "sweep_len": np.asarray([len(l) for l in sweep_logs],
                                    np.int64),
        }

    while alive.any():
        k_top = int(k_r[alive].max())
        if sup.active and sup.poll(where="fleet", k=k_top, em_iter=step):
            _shutdown_and_raise(sup, rec, log, ckpt,
                                step=step - 1 if step else None, k=k_top,
                                checkpointed=ckpt is not None and step > 0)
        t0 = time.perf_counter()
        live = alive.copy()
        lo_t = np.where(live, min(config.min_iters, config.max_iters),
                        0).astype(np.int32)
        hi_t = np.where(live, config.max_iters, 0).astype(np.int32)
        states, ll_d, iters_d = model.run_em_fleet(
            states, chunks_d, wts_d, packed.epsilons,
            min_iters=lo_t, max_iters=hi_t, donate=True, mode=mode)
        counts = np.asarray(jax.device_get(model.last_health), np.int64)
        counts = counts.reshape(T, health.NUM_FLAGS)
        next_states, k_active_d, min_d_d, pair_d = elim(states)
        ll_np, iters_np, k_active_np, min_d_np, pair_np = map(
            np.asarray,
            jax.device_get((ll_d, iters_d, k_active_d, min_d_d, pair_d)))
        dt = time.perf_counter() - t0

        # --- per-tenant fault containment (drop-one, PR-5 shape) -------
        fatal_t = np.asarray([
            health.word_is_fatal(health.pack_word(counts[t]))
            for t in range(T)
        ]) & live
        if fatal_t.any():
            if config.recovery == "off":
                bad = [packed.names[t] for t in np.flatnonzero(fatal_t)]
                total = counts[fatal_t].sum(axis=0)
                raise health.NumericalFaultError(
                    f"numerical fault in tenant(s) {', '.join(bad)} at "
                    f"K={k_top} and recovery is 'off'",
                    health.fault_bundle(total, k=k_top, where="fleet",
                                        config=config))
            for t in np.flatnonzero(fatal_t):
                health_lane[t] += counts[t]
                word = health.pack_word(counts[t])
                names = health.flag_names(word)
                drop_error[t] = (
                    f"fatal numerical fault at K={int(k_r[t])} "
                    f"(flags={names})")
                log.warning(
                    "tenant %s hit a fatal numerical fault at K=%d; "
                    "dropped from the fleet (survivors continue)",
                    packed.names[t], int(k_r[t]))
                if rec.active:
                    rec.set_context(tenant=packed.names[t])
                    rec.emit("health", k=int(k_r[t]), where="fleet",
                             flags=int(word), flag_names=names,
                             counters=health.counts_dict(counts[t]))
                    rec.emit("recovery", k=int(k_r[t]), attempt=1,
                             action="drop_tenant", outcome="dropped",
                             flags=int(word), flag_names=names)
                    rec.metrics.count("tenant_drops")
                    rec.set_context(tenant=None)
            alive &= ~fatal_t
            dropped |= fatal_t
            live &= ~fatal_t

        # --- scoring + best-model save per live lane --------------------
        improved = np.zeros((T,), bool)
        for t in np.flatnonzero(live):
            health_lane[t] += counts[t]
            word = health.pack_word(counts[t])
            ll_f = float(ll_np[t])
            riss = model_score(ll_f, int(k_r[t]),
                               int(packed.n_events[t]), d,
                               criterion=config.criterion,
                               covariance_type=config.covariance_type)
            score_ok = math.isfinite(riss)
            if not score_ok:
                health_lane[t, health.NONFINITE_SCORE] += 1
                log.warning("non-finite %s score at K=%d (tenant %s); "
                            "excluded from best-model selection",
                            config.criterion, int(k_r[t]),
                            packed.names[t])
            sweep_logs[t].append((int(k_r[t]), ll_f, riss,
                                  int(iters_np[t]), dt))
            if rec.active and word:
                rec.set_context(tenant=packed.names[t])
                rec.emit("health", k=int(k_r[t]), where="fleet",
                         flags=int(word),
                         flag_names=health.flag_names(word),
                         counters=health.counts_dict(counts[t]))
                rec.metrics.count("health_events")
                rec.set_context(tenant=None)
            if rec.active:
                rec.metrics.count("em_iters", int(iters_np[t]))
            if verbose:
                print(f"tenant {packed.names[t]} K={int(k_r[t])}: "
                      f"loglik={ll_f:.6e} {config.criterion}={riss:.6e} "
                      f"iters={int(iters_np[t])} ({dt:.2f}s)")
            if score_ok and (
                k_r[t] == K0[t]
                or (riss < min_riss_r[t] and packed.targets[t] == 0)
                or k_r[t] == packed.targets[t]
            ):  # gaussian.cu:839, per lane, NaN-score-guarded
                improved[t] = True
                min_riss_r[t] = riss
                ideal_k_r[t] = k_r[t]
                best_ll_r[t] = ll_f
        if improved.any():
            best_states = _where_lanes(improved, states, best_states)
        if rec.active:
            rec.heartbeat("fleet", k=k_top)

        # --- sweep advance per lane -------------------------------------
        finished = live & (k_r <= stop_r)
        alive &= ~finished
        live &= ~finished
        if not alive.any():
            break
        merge_mask = np.zeros((T,), bool)
        for t in np.flatnonzero(live):
            k_new = int(k_active_np[t])
            if k_new < 2:
                alive[t] = False
                continue
            if not np.isfinite(float(min_d_np[t])):
                log.warning("no valid merge pair at K=%d (tenant %s); "
                            "stopping that tenant's sweep", k_new,
                            packed.names[t])
                alive[t] = False
                continue
            if rec.active:
                rec.set_context(tenant=packed.names[t])
                rec.emit("merge", k_active=k_new, next_k=k_new - 1,
                         min_distance=float(min_d_np[t]),
                         pair=[int(pair_np[t][0]), int(pair_np[t][1])])
                rec.metrics.count("merges")
                rec.set_context(tenant=None)
            merge_mask[t] = True
            k_r[t] = k_new - 1
            if k_r[t] < stop_r[t]:
                alive[t] = False
        if merge_mask.any():
            states = _where_lanes(merge_mask, next_states, states)

        if ckpt is not None and alive.any():
            rec.metrics.count("checkpoint_saves") if rec.active else None
            ckpt.save(step, host_payload())
        step += 1

    # --- per-tenant results -----------------------------------------------
    host_best = _host_batched(model, best_states)
    results: List[TenantResult] = []
    for t in range(T):
        if dropped[t]:
            results.append(TenantResult(
                name=packed.names[t], index=packed.group.indices[t],
                group=group_index, result=None,
                error=drop_error[t] or "dropped"))
            continue
        import jax.numpy as jnp

        lane = jax.tree_util.tree_map(
            lambda a, _t=t: jnp.asarray(np.asarray(a)[_t]), host_best)
        compact_state, n_active = compact(lane)
        # Per-tenant training drift envelope (rev v2.4): the tenant's
        # own packed rows through its winning parameters; rides the
        # tenant's GMMResult into summaries and registry exports.
        envelope = None
        if config.envelope:
            envelope = compute_envelope(
                model, compact_state, packed.chunks[t],
                int(packed.n_events[t]), int(n_active))
        results.append(TenantResult(
            name=packed.names[t], index=packed.group.indices[t],
            group=group_index,
            result=GMMResult(
                state=compact_state,
                ideal_num_clusters=int(n_active),
                min_rissanen=float(min_riss_r[t]),
                final_loglik=float(best_ll_r[t]),
                epsilon=float(packed.epsilons[t]),
                num_events=int(packed.n_events[t]),
                num_dimensions=d,
                data_shift=np.asarray(packed.shifts[t]),
                sweep_log=sweep_logs[t],
                profile=None, profile_report=None,
                host_range=(0, int(packed.n_events[t])),
                health=health.health_summary(
                    health_lane[t],
                    io_retries=(ckpt.io_retries if ckpt is not None
                                else 0)),
                envelope=envelope,
                model=model,
            )))
    return results

