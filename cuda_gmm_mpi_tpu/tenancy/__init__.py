"""Multi-tenancy subsystem: fit and serve thousands of independent GMMs
in a handful of dispatches (docs/TENANCY.md).

- :mod:`~cuda_gmm_mpi_tpu.tenancy.packing` -- ragged tenants into pow2
  (event-bucket, cluster-bucket) groups; pure layout, never arithmetic.
- :mod:`~cuda_gmm_mpi_tpu.tenancy.fleet` -- the fleet-fit driver: one
  packed group = one fleet EM dispatch per sweep step, per-tenant
  freeze-out / health rows / checkpoints, bit-identical to solo fits.
- :mod:`~cuda_gmm_mpi_tpu.tenancy.cli` -- the ``gmm fleet`` driver:
  manifest of per-tenant input files -> per-tenant fitted models, with
  bulk registry export.
"""

from .fleet import FleetResult, TenantResult, fit_fleet
from .packing import (
    FleetGroup, PackedGroup, TenantSpec, pack_group, plan_fleet,
    unpack_rows,
)

__all__ = [
    "FleetGroup", "FleetResult", "PackedGroup", "TenantResult",
    "TenantSpec", "fit_fleet", "pack_group", "plan_fleet", "unpack_rows",
]
