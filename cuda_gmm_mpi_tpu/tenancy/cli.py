"""``gmm fleet``: fit a manifest of per-tenant input files in one run.

The CLI face of the fleet driver (docs/TENANCY.md): a manifest names T
tenants -- each with its own input file, starting K, optional target K
and seed -- and one invocation packs them into shape-bucketed groups,
fits every group as batched fleet dispatches, and writes per-tenant
outputs:

- ``<out-dir>/<name>.summary`` per fitted tenant (the reference's model
  format) plus ``<out-dir>/fleet.json``, the machine-readable fleet
  manifest (per-tenant status/score/paths) that ``gmm export --fleet``
  consumes for bulk registry export;
- with ``--registry``, one EXACT registry version per tenant model in
  the same invocation (atomic-npz artifacts; a tenant whose export
  fails is reported and skipped, never run-fatal).

Manifest format -- JSON array or JSONL, one object per tenant::

    {"name": "patient-007", "infile": "p007.csv", "num_clusters": 8,
     "target_num_clusters": 0, "seed": 7}

Exit codes follow the fit CLI's contract (docs/API.md): 0 fitted (even
with some tenants dropped -- per-tenant status is in fleet.json), 70
when EVERY tenant was dropped or an unrecovered numerical fault aborted
the run, 74 unreadable input, 75 preempted (resume with the same
``--checkpoint-dir``), 1/2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def build_fleet_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gmm fleet",
        description="Fit a manifest of independent per-tenant datasets "
        "as packed fleet dispatches (docs/TENANCY.md).")
    p.add_argument("manifest",
                   help="tenant manifest: JSON array or JSONL of "
                   "{name, infile, num_clusters[, target_num_clusters, "
                   "seed]}")
    p.add_argument("--out-dir", default=None, metavar="DIR",
                   help="write <name>.summary per tenant + fleet.json "
                   "(the bulk-export manifest) into DIR")
    p.add_argument("--registry", default=None, metavar="DIR",
                   help="also export each fitted tenant as one EXACT "
                   "registry version (model name = tenant name); "
                   "per-tenant failures are reported, not run-fatal")
    p.add_argument("--device", default=None,
                   help="JAX platform: tpu | cpu | gpu (default: auto)")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"])
    p.add_argument("--chunk-size", type=int, default=65536)
    p.add_argument("--covariance-type", default="full",
                   choices=["full", "diag", "spherical", "tied"])
    p.add_argument("--criterion", default="rissanen",
                   choices=["rissanen", "bic", "aic", "aicc"])
    p.add_argument("--min-iters", type=int, default=100)
    p.add_argument("--max-iters", type=int, default=100)
    p.add_argument("--seed", type=int, default=0,
                   help="default RNG seed (per-tenant manifest seeds "
                   "override)")
    p.add_argument("--seed-method", default="even",
                   choices=["even", "kmeans++"])
    p.add_argument("--mesh", default=None,
                   help="device mesh 'DATA[,CLUSTER]' (single-controller)")
    p.add_argument("--fleet-mode", default="scan",
                   choices=["scan", "vmap"],
                   help="per-group dispatch mode: 'scan' (default) is "
                   "bit-identical to solo fits; 'vmap' batches the "
                   "tenant matmuls for throughput at reduction-order "
                   "tolerance (docs/TENANCY.md)")
    p.add_argument("--fleet-group-size", type=int, default=None,
                   metavar="T",
                   help="max tenants per packed-group dispatch "
                   "(default: whole group)")
    p.add_argument("--recovery", default="retry",
                   choices=["retry", "off"],
                   help="'retry' drops a numerically poisoned tenant "
                   "and keeps its groupmates; 'off' aborts the run "
                   "(exit 70) on the first fatal fault")
    p.add_argument("--checkpoint-dir", default=None,
                   help="per-group sweep checkpoints (resume with the "
                   "same path)")
    p.add_argument("--resume", default="auto", choices=["auto", "never"])
    p.add_argument("--max-runtime", type=float, default=None,
                   metavar="SECONDS",
                   help="wall-clock budget: reaching it drains like "
                   "SIGTERM -- checkpointed stop between sweep steps, "
                   "exit 75")
    p.add_argument("--metrics-file", default=None, metavar="FILE.jsonl",
                   help="fleet telemetry stream (rev v1.8: fleet_start "
                   "/ tenant_done / fleet_summary); render with "
                   "`gmm report`")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="live observability plane (rev v2.1): serve "
                   "Prometheus/OpenMetrics text on "
                   "127.0.0.1:PORT/metrics (0 = OS-assigned), sample "
                   "host RSS + device memory onto heartbeat records, "
                   "and emit fleet/group trace spans (default: off)")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the fleet fit "
                   "into DIR (view with TensorBoard or Perfetto)")
    p.add_argument("--verbose", "-v", action="store_true")
    return p


def _load_manifest(path: str) -> List[dict]:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    text = text.strip()
    if not text:
        raise ValueError("empty manifest")
    if text.startswith("["):
        entries = json.loads(text)
    else:  # JSONL
        entries = [json.loads(line) for line in text.splitlines()
                   if line.strip()]
    if not isinstance(entries, list) or not entries:
        raise ValueError("manifest must be a non-empty list of tenants")
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            raise ValueError(f"manifest entry {i} is not an object")
        for field in ("name", "infile", "num_clusters"):
            if field not in e:
                raise ValueError(
                    f"manifest entry {i} is missing {field!r}")
    return entries


def fleet_main(argv=None) -> int:
    args = build_fleet_parser().parse_args(argv)

    if args.device:
        os.environ["JAX_PLATFORMS"] = args.device
        import jax

        jax.config.update("jax_platforms", args.device)
    if args.dtype == "float64":
        import jax

        jax.config.update("jax_enable_x64", True)

    try:
        entries = _load_manifest(args.manifest)
    except (OSError, ValueError) as e:
        print(f"Cannot read manifest {args.manifest!r}: {e}",
              file=sys.stderr)
        return 1

    from .. import supervisor as supervisor_mod
    from ..cli import _parse_mesh, _read_events_or_none
    from ..config import GMMConfig
    from ..health import NumericalFaultError
    from ..io.readers import read_data
    from ..supervisor import PreemptedError
    from .packing import TenantSpec

    try:
        config = GMMConfig(
            dtype=args.dtype,
            chunk_size=args.chunk_size,
            covariance_type=args.covariance_type,
            criterion=args.criterion,
            min_iters=args.min_iters,
            max_iters=args.max_iters,
            seed=args.seed,
            seed_method=args.seed_method,
            mesh_shape=_parse_mesh(args.mesh),
            device=args.device,
            recovery=args.recovery,
            checkpoint_dir=args.checkpoint_dir,
            resume=args.resume,
            max_runtime_s=args.max_runtime,
            metrics_file=args.metrics_file,
            metrics_port=args.metrics_port,
            fleet_mode=args.fleet_mode,
            fleet_group_size=args.fleet_group_size,
            enable_print=args.verbose,
        )
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1

    tenants: List[TenantSpec] = []
    for e in entries:
        data, rc = _read_events_or_none(read_data, str(e["infile"]))
        if data is None:
            return rc
        try:
            tenants.append(TenantSpec(
                name=str(e["name"]), data=data,
                num_clusters=int(e["num_clusters"]),
                target_num_clusters=int(e.get("target_num_clusters", 0)),
                seed=(int(e["seed"]) if e.get("seed") is not None
                      else None)))
        except ValueError as err:
            print(str(err), file=sys.stderr)
            return 1

    from ..utils.profiling import trace
    from .fleet import fit_fleet

    sup = supervisor_mod.RunSupervisor(max_runtime_s=args.max_runtime)
    try:
        with supervisor_mod.use(sup), trace(args.trace_dir):
            fleet = fit_fleet(tenants, config, verbose=args.verbose)
    except PreemptedError as e:
        print(f"Preempted -- {e}", file=sys.stderr)
        return supervisor_mod.EX_TEMPFAIL
    except NumericalFaultError as e:
        print(f"Numerical fault -- no models written.\n{e}",
              file=sys.stderr)
        return supervisor_mod.EX_SOFTWARE
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1

    rows: List[dict] = []
    if args.out_dir:
        os.makedirs(args.out_dir, exist_ok=True)
    for tr in fleet.tenants:
        row: dict = {"name": tr.name, "dropped": tr.dropped,
                     "group": tr.group}
        if tr.dropped:
            row["error"] = tr.error
        else:
            r = tr.result
            row.update(
                k=int(r.ideal_num_clusters),
                score=(float(r.min_rissanen)
                       if r.min_rissanen == r.min_rissanen else None),
                loglik=float(r.final_loglik),
                criterion=config.criterion,
                covariance_type=config.covariance_type,
                dtype=config.dtype,
            )
            if args.out_dir:
                from ..io import write_summary

                summary_path = os.path.join(args.out_dir,
                                            f"{tr.name}.summary")
                write_summary(summary_path, r, enable_output=True)
                row["summary"] = os.path.abspath(summary_path)
                if getattr(r, "envelope", None) is not None:
                    # Per-tenant training drift envelope (rev v2.4):
                    # `gmm export --fleet` republishes it next to the
                    # tenant's registry version (envelope.json).
                    env_path = os.path.join(
                        args.out_dir, f"{tr.name}.envelope.json")
                    with open(env_path, "w", encoding="utf-8") as f:
                        json.dump(r.envelope, f, sort_keys=True)
                    row["envelope"] = os.path.abspath(env_path)
        rows.append(row)

    exported = 0
    if args.registry:
        from ..serving.registry import ModelRegistry, RegistryError

        reg = ModelRegistry(args.registry)
        for tr, row in zip(fleet.tenants, rows):
            if tr.dropped:
                continue
            try:
                v = reg.save(tr.name, tr.result, config=config,
                             source="fleet")
                row["registry_version"] = int(v)
                exported += 1
            except (RegistryError, OSError) as e:
                # Partial failure stays per-tenant: one unexportable
                # model must not void its siblings' exports.
                row["export_error"] = str(e)
                print(f"export of {tr.name!r} failed: {e}",
                      file=sys.stderr)

    if args.out_dir:
        manifest_out = {
            "schema": 1,
            "mode": fleet.mode,
            "groups": fleet.groups,
            "wall_s": fleet.wall_s,
            "tenants": rows,
        }
        with open(os.path.join(args.out_dir, "fleet.json"), "w",
                  encoding="utf-8") as f:
            json.dump(manifest_out, f, indent=1, sort_keys=True)

    fitted = len(fleet.fitted)
    print(f"fleet: {fitted}/{len(fleet.tenants)} tenants fitted in "
          f"{len(fleet.groups)} group(s), {fleet.wall_s:.2f}s"
          + (f"; {exported} exported to registry" if args.registry
             else ""))
    for row in rows:
        if row["dropped"]:
            print(f"  {row['name']}: DROPPED ({row.get('error')})",
                  file=sys.stderr)
    if fitted == 0:
        from .. import supervisor as supervisor_mod

        return supervisor_mod.EX_SOFTWARE
    return 0
