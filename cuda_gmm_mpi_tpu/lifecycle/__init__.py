"""Closed-loop model lifecycle (rev v2.6; docs/ROBUSTNESS.md).

The reference fits once and exits; our repro already has every piece of
a production ML loop -- stepwise minibatch EM, registry hot-reload,
drift envelopes/alarms -- as disconnected subsystems. This package
closes the loop: a :class:`LifecycleController` consumes ``drift_alarm``
events for a served route and drives retrain -> canary -> promote ->
watch with rollback as a first-class state, never touching the serving
path until a candidate has passed every gate.
"""

from .controller import (LifecycleController, LifecycleError,
                         LifecyclePolicy)

__all__ = ["LifecycleController", "LifecycleError", "LifecyclePolicy"]
