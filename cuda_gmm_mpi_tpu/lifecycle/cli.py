"""``gmm lifecycle``: the closed loop, offline.

Replays the ``drift_alarm`` events of a RECORDED serve stream into a
:class:`LifecycleController` over a registry: debounce, shadow
minibatch-EM retrain (from ``--data`` or the policy's configured
source), canary gates on the holdout slice, and -- when every gate
passes -- an atomic promotion the next serve run's hot-reload adopts.
The duplicate-dispatch shadow window and the post-promotion watch need
live traffic, so offline runs skip straight from a passed canary to
promote + cooldown; rejected candidates are quarantined exactly as in
serve mode. Lifecycle events are appended to ``--out`` (rev v2.6) for
``gmm report`` / ``gmm diff``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Tuple

from .controller import (LifecycleController, LifecycleError,
                         LifecyclePolicy)


def _stream_alarms(path: str) -> List[Tuple[str, int]]:
    """(model, version) per drift_alarm record of a serve stream."""
    alarms: List[Tuple[str, int]] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue  # torn tail line: a live stream's last record
            if r.get("event") == "drift_alarm" and r.get("model"):
                alarms.append((str(r["model"]),
                               int(r.get("version") or 0)))
    return alarms


def lifecycle_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="gmm lifecycle",
        description="Drive the drift->retrain->canary->promote loop "
        "offline from a recorded serve stream (docs/ROBUSTNESS.md "
        "'Model lifecycle').")
    p.add_argument("stream", help="recorded serve stream (*.jsonl) "
                   "whose drift_alarm events trigger the loop")
    p.add_argument("--registry", required=True, metavar="DIR",
                   help="model registry root (gmm export)")
    p.add_argument("--policy", required=True, metavar="POLICY.json",
                   help="lifecycle policy (see docs/API.md)")
    p.add_argument("--data", default=None, metavar="FILE.bin",
                   help="retrain data source (overrides the policy's "
                   "retrain.data)")
    p.add_argument("--out", default=None, metavar="FILE.jsonl",
                   help="write lifecycle telemetry events here")
    p.add_argument("--max-wall-s", type=float, default=300.0,
                   help="bound on the retry/backoff pump (default 300)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable verdict on stdout")
    p.add_argument("--device", default=None,
                   help="JAX platform for scoring/refit: tpu|cpu|gpu")
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    if args.device:
        os.environ["JAX_PLATFORMS"] = args.device

    from .. import telemetry
    from ..serving.registry import ModelRegistry
    from ..telemetry.recorder import RunRecorder

    try:
        policy = LifecyclePolicy.from_file(args.policy)
    except LifecycleError as e:
        print(f"lifecycle: {e}", file=sys.stderr)
        return 2
    if args.data:
        policy.retrain["data"] = args.data
    try:
        alarms = _stream_alarms(args.stream)
    except OSError as e:
        print(f"lifecycle: cannot read stream: {e}", file=sys.stderr)
        return 2

    registry = ModelRegistry(args.registry)
    ctl = LifecycleController(registry, policy)
    rec = RunRecorder(path=args.out)
    with telemetry.use(rec), rec:
        for model, version in alarms:
            ctl.observe_alarm(model, version)
        # Pump the state machine until every route settles (retry
        # backoffs are real waits, bounded by --max-wall-s).
        deadline = time.monotonic() + max(1.0, float(args.max_wall_s))
        while time.monotonic() < deadline:
            ctl.on_tick()
            routes = ctl.stats()["routes"]
            if all(s in ("idle", "cooldown") for s in routes.values()):
                break
            time.sleep(0.02)
    verdict = {
        "alarms": len(alarms),
        "counts": ctl.counts,
        "routes": {name: {"state": state,
                          "live_versions": registry.versions(name)}
                   for name, state in ctl.stats()["routes"].items()},
    }
    if args.json:
        print(json.dumps(verdict, sort_keys=True))
    else:
        print(f"lifecycle: {len(alarms)} alarm(s) -> "
              f"{ctl.counts['retrains']} retrain(s), "
              f"{ctl.counts['promotes']} promotion(s), "
              f"{ctl.counts['quarantines']} quarantine(s)")
        for name, row in verdict["routes"].items():
            print(f"  {name}: live versions {row['live_versions']}")
    return 1 if ctl.counts["quarantines"] else 0
