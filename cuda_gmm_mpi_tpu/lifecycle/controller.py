"""Drift-triggered shadow retrain with canary gates and auto-rollback.

The controller is a per-route state machine driven from the serve tick
loop (``gmm serve --lifecycle policy.json``) or offline against a
recorded stream (``gmm lifecycle``)::

    idle --debounced drift_alarm--> retrain --published--> canary
      ^                               | exhausted            | gates
      |                               v                      v
    cooldown <---- quarantine <-------+            promote --+--> watch
      ^                                                        | trip /
      |                     rollback (re-publish prior) <------+ alarm /
      +------------------------------------+                     regress

Contracts (docs/ROBUSTNESS.md "Model lifecycle"):

- The serving path is NEVER touched by a failed retrain or a rejected
  canary: candidates are published with the registry's ``candidate``
  stage (invisible to enumeration/poll/default-load), shadow scoring
  duplicates live dispatches without altering a single reply byte, and
  the only client-visible transition is the existing hot-reload swap
  after :meth:`ModelRegistry.promote`.
- Retrain failures retry with the checkpoint-retries recipe: jittered
  doubling backoff, scheduled (never slept) on the tick loop;
  exhaustion quarantines the attempt and opens a cooldown.
- Post-promotion probation: a breaker trip, a drift alarm on the new
  version, or a mean-score regression beyond ``health_regression_scale
  x convergence_epsilon`` rolls back to the pinned prior version
  (re-published as newest; bit-identical scoring by the npz
  round-trip), quarantines the bad candidate with a reason file, and
  opens a cooldown.
- Every transition is a ``lifecycle`` telemetry event (rev v2.6) with
  the gate values that drove it.
"""

from __future__ import annotations

import json
import random
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import telemetry
from ..serving.registry import ModelRegistry, RegistryError, ServedModel
from ..telemetry.sketch import SCORE_BOUNDS, StreamSketch, ks, psi
from ..testing import faults


class LifecycleError(RuntimeError):
    """A lifecycle policy or transition is invalid."""


# Policy knob -> default. One flat table so from_dict can reject typos
# loudly (an ignored knob in a promotion policy is a silent outage).
_DEFAULTS: Dict[str, Any] = {
    # Routes to manage; [] = every model the registry serves.
    "models": [],
    # Consecutive drift alarms on a route before a retrain starts.
    "debounce_alarms": 2,
    # Seconds after a quarantine / rollback / watch-pass before the
    # next alarm may start a retrain.
    "cooldown_s": 300.0,
    # Per-model cap on spooled request rows (the fallback data source).
    "spool_rows": 4096,
    # Holdout slice (taken from the tail of the retrain data) for the
    # immediate canary gates.
    "holdout_rows": 256,
    "retrain": {
        # BIN dataset path; null -> refit from spooled request rows.
        "data": None,
        # Stepwise minibatch-EM steps (min_iters == max_iters).
        "steps": 30,
        "minibatch_size": 0,
        "chunk_size": 1024,
        # Rows required before a refit is attempted at all.
        "min_rows": 64,
        # Cap on rows read from the data file.
        "max_rows": 65536,
        # Jittered doubling backoff (checkpoint_retries recipe).
        "retries": 3,
        "backoff_base_s": 0.5,
        "backoff_max_s": 30.0,
    },
    "canary": {
        # Score-distribution gates, candidate vs incumbent on the
        # holdout slice (telemetry/sketch.py ladder).
        "max_psi": 0.5,
        "max_ks": 0.5,
        # Duplicate-dispatch shadow window: live ticks scored by BOTH
        # versions before promotion. 0 = skip (offline mode).
        "shadow_ticks": 3,
        # Mean-score regression tolerance factor: tolerance =
        # health_regression_scale x the refit's convergence epsilon
        # (config.py health_regression_scale semantics).
        "health_regression_scale": 10.0,
    },
    "promote": {
        # Retries for a torn promotion (promote_torn semantics).
        "retries": 3,
    },
    "watch": {
        # Probation: whichever of ticks/seconds elapses LAST closes the
        # window (a quiet route must not pass probation by silence).
        "probation_ticks": 20,
        "probation_s": 600.0,
        # Rows required before the watch score gate is consulted.
        "min_rows": 32,
    },
}


def _merged(defaults: Dict[str, Any], overrides: Dict[str, Any],
            where: str) -> Dict[str, Any]:
    out = dict(defaults)
    for key, val in overrides.items():
        if key not in defaults:
            raise LifecycleError(
                f"unknown lifecycle policy knob {where}{key!r} "
                f"(expected one of {sorted(defaults)})")
        if isinstance(defaults[key], dict):
            if not isinstance(val, dict):
                raise LifecycleError(
                    f"policy knob {where}{key!r} must be an object")
            out[key] = _merged(defaults[key], val, f"{where}{key}.")
        else:
            out[key] = val
    return out


class LifecyclePolicy:
    """Validated lifecycle policy (the ``--lifecycle policy.json``)."""

    def __init__(self, spec: Optional[Dict[str, Any]] = None):
        merged = _merged(_DEFAULTS, spec or {}, "")
        self.models: List[str] = [str(m) for m in merged["models"]]
        self.debounce_alarms = max(1, int(merged["debounce_alarms"]))
        self.cooldown_s = float(merged["cooldown_s"])
        self.spool_rows = max(0, int(merged["spool_rows"]))
        self.holdout_rows = max(1, int(merged["holdout_rows"]))
        self.retrain = merged["retrain"]
        self.canary = merged["canary"]
        self.promote = merged["promote"]
        self.watch = merged["watch"]
        if self.retrain["min_rows"] < 1:
            raise LifecycleError("retrain.min_rows must be >= 1")
        if self.retrain["steps"] < 1:
            raise LifecycleError("retrain.steps must be >= 1")

    @classmethod
    def from_file(cls, path: str) -> "LifecyclePolicy":
        try:
            with open(path, encoding="utf-8") as f:
                spec = json.load(f)
        except (OSError, ValueError) as e:
            raise LifecycleError(
                f"cannot read lifecycle policy {path!r}: {e}") from e
        if not isinstance(spec, dict):
            raise LifecycleError(
                f"lifecycle policy {path!r} must hold a JSON object")
        return cls(spec)


def _jitter(name: str, attempt: int) -> float:
    """+-25% deterministic jitter, the breaker/checkpoint recipe, seeded
    per (route, attempt) so concurrent controllers spread."""
    seed = hash((name, int(attempt))) & 0xFFFFFFFF
    return 0.75 + 0.5 * random.Random(seed).random()


class _Route:
    """Mutable per-model lifecycle state (tick-loop thread only)."""

    __slots__ = ("state", "alarms", "attempt", "next_attempt_t",
                 "cooldown_until", "spool", "spool_count",
                 "candidate_version", "candidate", "tolerance", "gates",
                 "shadow_left", "shadow", "prior_version",
                 "promote_attempts", "watch_deadline", "watch_ticks_left",
                 "baseline_mean", "watch_sum", "watch_count", "violation",
                 "breaker_trips0")

    def __init__(self):
        self.state = "idle"
        self.alarms = 0
        self.attempt = 0
        self.next_attempt_t = 0.0
        self.cooldown_until = 0.0
        self.spool: List[np.ndarray] = []
        self.spool_count = 0
        self._clear_candidate()

    def _clear_candidate(self):
        self.candidate_version = None
        self.candidate = None
        self.tolerance = 0.0
        self.gates = {}
        self.shadow_left = 0
        self.shadow = None
        self.prior_version = None
        self.promote_attempts = 0
        self.watch_deadline = 0.0
        self.watch_ticks_left = 0
        self.baseline_mean = None
        self.watch_sum = 0.0
        self.watch_count = 0
        self.violation = None
        self.breaker_trips0 = None


class LifecycleController:
    """The closed-loop state machine over one registry.

    Serve mode: constructed by ``serve_main --lifecycle`` and bound to
    the :class:`GMMServer`; ``observe_alarm`` is fed by the drift
    flush, ``observe_dispatch`` by every answered coalesced dispatch,
    and ``on_tick`` runs between ticks on the tick-loop thread (so all
    state is single-threaded by construction). Offline mode: no server
    -- alarms come from a recorded stream, shadow windows are skipped
    (``shadow_ticks`` forced to 0), and promotion still flips the
    registry so the NEXT serve run adopts the candidate.
    """

    def __init__(self, registry: ModelRegistry, policy: LifecyclePolicy,
                 *, server=None):
        self._registry = registry
        self._policy = policy
        self._server = server
        self._routes: Dict[str, _Route] = {}
        self._executors: Dict[tuple, Any] = {}
        # Rollup counters (serve_summary / offline verdicts).
        self.counts = {"retrains": 0, "canaries": 0, "promotes": 0,
                       "rollbacks": 0, "quarantines": 0}

    def bind(self, server) -> None:
        self._server = server

    @property
    def policy(self) -> LifecyclePolicy:
        return self._policy

    def manages(self, name: str) -> bool:
        models = self._policy.models
        return not models or name in models

    def stats(self) -> Dict[str, Any]:
        return dict(self.counts,
                    routes={n: r.state for n, r in self._routes.items()})

    # -- inputs (tick-loop thread) ---------------------------------------

    def observe_alarm(self, name: str, version: Optional[int],
                      stats: Optional[Dict[str, Any]] = None,
                      now: Optional[float] = None) -> None:
        """One ``drift_alarm`` for a served route (the drift flush's
        feed). Debounces in idle, is a rollback trigger in watch, and
        is ignored during cooldown/retrain/canary (the loop is already
        reacting)."""
        if not self.manages(name):
            return
        now = time.monotonic() if now is None else now
        r = self._routes.setdefault(name, _Route())
        if r.state == "watch":
            r.violation = r.violation or "drift_alarm"
            return
        if r.state != "idle" or now < r.cooldown_until:
            return
        r.alarms += 1
        if r.alarms >= self._policy.debounce_alarms:
            r.state = "retrain"
            r.attempt = 0
            r.next_attempt_t = now  # first attempt on the next tick
            self._emit("retrain", name, outcome="scheduled",
                       alarms=r.alarms, version=version)

    def observe_dispatch(self, name: str, m: ServedModel, rows, logz
                         ) -> None:
        """One answered coalesced dispatch for route ``(name, None)``.

        ``rows`` are CENTERED by the incumbent's data_shift (the
        executor's input), ``logz`` the per-row scores it returned.
        Feeds the request-row spool, the canary duplicate-dispatch
        shadow window, and the watch score gate. Never mutates its
        inputs -- replies are computed before this hook runs.
        """
        if not self.manages(name):
            return
        r = self._routes.setdefault(name, _Route())
        rows = np.asarray(rows, np.float64)
        logz = np.asarray(logz, np.float64).reshape(-1)
        if rows.size == 0:
            return
        original = rows + np.asarray(m.data_shift, np.float64)
        self._spool(r, original)
        if r.state == "canary" and r.shadow_left > 0 \
                and r.candidate is not None:
            cand_logz = self._score(r.candidate, original)
            sh = r.shadow
            sh["inc_sum"] += float(logz.sum())
            sh["cand_sum"] += float(np.nan_to_num(cand_logz,
                                                  nan=0.0).sum())
            sh["rows"] += int(logz.size)
            sh["nonfinite"] += int(np.count_nonzero(
                ~np.isfinite(cand_logz)))
            r.shadow_left -= 1
        elif r.state == "watch":
            r.watch_sum += float(logz.sum())
            r.watch_count += int(logz.size)
            r.watch_ticks_left = max(0, r.watch_ticks_left - 1)

    # -- the state machine -----------------------------------------------

    def on_tick(self, now: Optional[float] = None) -> None:
        """Advance every route; cheap when nothing is scheduled."""
        now = time.monotonic() if now is None else now
        for name, r in self._routes.items():
            try:
                self._tick_route(name, r, now)
            except RegistryError as e:
                # Registry trouble mid-transition must never take down
                # the tick loop; the route retries or quarantines on a
                # later tick.
                self._emit("retrain" if r.state == "retrain"
                           else r.state, name, outcome="error",
                           reason=str(e)[:200])

    def _tick_route(self, name: str, r: _Route, now: float) -> None:
        if r.state == "cooldown":
            if now >= r.cooldown_until:
                r.state = "idle"
                r.alarms = 0
            return
        if r.state == "retrain" and now >= r.next_attempt_t:
            self._attempt_retrain(name, r, now)
        elif r.state == "canary" and r.shadow_left <= 0:
            self._finish_canary(name, r, now)
        elif r.state == "watch":
            self._tick_watch(name, r, now)

    # -- retrain ---------------------------------------------------------

    def _attempt_retrain(self, name: str, r: _Route, now: float) -> None:
        r.attempt += 1
        try:
            incumbent = self._incumbent(name)
            data = self._training_rows(name, r, incumbent)
            if faults.take("retrain_fail", model=name) is not None:
                raise LifecycleError("injected retrain_fail fault")
            result, epsilon = self._refit(incumbent, data)
            vc = self._registry.save(
                name, result, config=None,
                covariance_type=incumbent.covariance_type,
                source="lifecycle", stage="candidate",
                extra={"retrain_of": int(incumbent.version)})
        except Exception as e:  # noqa: BLE001 -- any refit failure retries
            rt = self._policy.retrain
            if r.attempt > int(rt["retries"]):
                self._quarantine_attempt(name, r, now,
                                         reason="retrain_exhausted",
                                         error=str(e)[:200])
                return
            backoff = min(float(rt["backoff_base_s"])
                          * (2.0 ** (r.attempt - 1)),
                          float(rt["backoff_max_s"]))
            backoff *= _jitter(name, r.attempt)
            r.next_attempt_t = now + backoff
            self._emit("retrain", name, outcome="retry",
                       attempt=r.attempt, reason=str(e)[:200],
                       retry_in_s=round(backoff, 4))
            return
        r.candidate_version = int(vc)
        r.candidate = self._registry.load(name, int(vc))
        r.prior_version = int(incumbent.version)
        cn = self._policy.canary
        r.tolerance = (float(cn["health_regression_scale"])
                       * float(epsilon))
        self.counts["retrains"] += 1
        self._emit("retrain", name, outcome="published",
                   attempt=r.attempt, candidate_version=int(vc),
                   version=int(incumbent.version))
        # Immediate gates on the holdout slice; the shadow window (live
        # traffic) follows only if these pass.
        gates = self._holdout_gates(name, incumbent, r.candidate,
                                    data, r.tolerance)
        r.gates = gates
        self.counts["canaries"] += 1
        if not gates["pass"]:
            self._emit("canary", name, outcome="rejected",
                       candidate_version=int(vc), **gates["fields"])
            self._quarantine_candidate(name, r, now,
                                       reason="canary_gates",
                                       gates=gates["fields"])
            return
        shadow_ticks = (int(cn["shadow_ticks"])
                        if self._server is not None else 0)
        r.shadow_left = shadow_ticks
        r.shadow = {"inc_sum": 0.0, "cand_sum": 0.0, "rows": 0,
                    "nonfinite": 0, "ticks": shadow_ticks}
        r.state = "canary"

    def _training_rows(self, name: str, r: _Route,
                       incumbent: ServedModel) -> np.ndarray:
        rt = self._policy.retrain
        if rt["data"]:
            from ..io.readers import FileSource

            src = FileSource(str(rt["data"]))
            n = min(int(src.shape[0]), int(rt["max_rows"]))
            rows = np.asarray(src.read_range(0, n), np.float64)
        elif r.spool_count:
            rows = np.concatenate(r.spool, axis=0)
        else:
            rows = np.zeros((0, incumbent.d))
        if rows.shape[0] < int(rt["min_rows"]):
            raise LifecycleError(
                f"retrain needs >= {rt['min_rows']} rows, have "
                f"{rows.shape[0]} (configure retrain.data or let the "
                "spool fill)")
        return rows

    def _refit(self, incumbent: ServedModel, rows: np.ndarray):
        """Shadow minibatch-EM refit warm-started from the served state.

        Returns ``(GMMResult, convergence_epsilon)``. The warm start
        hands the incumbent's means back in ORIGINAL data coordinates
        (the served state is centered by its own data_shift).
        """
        from ..config import GMMConfig
        from ..estimator import GaussianMixture

        rt = self._policy.retrain
        n = int(rows.shape[0])
        cfg = GMMConfig(
            stream_events=True,
            em_mode="minibatch",
            minibatch_size=int(rt["minibatch_size"]),
            chunk_size=max(32, min(int(rt["chunk_size"]), n)),
            min_iters=int(rt["steps"]),
            max_iters=int(rt["steps"]),
            dtype=incumbent.dtype,
            covariance_type=incumbent.covariance_type,
        )
        means0 = (np.asarray(incumbent.state.means, np.float64)
                  + np.asarray(incumbent.data_shift, np.float64))
        gm = GaussianMixture(incumbent.k, target_components=incumbent.k,
                             config=cfg, means_init=means0)
        gm.fit(rows)
        return gm.result_, float(gm.result_.epsilon)

    # -- canary ----------------------------------------------------------

    def _holdout_gates(self, name: str, incumbent: ServedModel,
                       candidate: ServedModel, data: np.ndarray,
                       tolerance: float) -> Dict[str, Any]:
        cn = self._policy.canary
        holdout = data[-min(len(data), self._policy.holdout_rows):]
        inc_scores = self._score(incumbent, holdout)
        cand_scores = self._score(candidate, holdout)
        mean_inc = float(np.mean(inc_scores))
        mean_cand = float(np.mean(cand_scores))
        cfg = faults.take("canary_regression", model=name)
        if cfg is not None:
            # Poison the SHADOW score only: the gate must reject with
            # zero client-visible change.
            mean_cand -= float(cfg.get("shift", 100.0 * (tolerance + 1)))
        inc_sk = StreamSketch(SCORE_BOUNDS).update(inc_scores)
        cand_sk = StreamSketch(SCORE_BOUNDS).update(cand_scores)
        g_psi = psi(inc_sk.buckets, cand_sk.buckets)
        g_ks = ks(inc_sk.buckets, cand_sk.buckets)
        regression = mean_inc - mean_cand
        ok = (np.isfinite(mean_cand)
              and g_psi <= float(cn["max_psi"])
              and g_ks <= float(cn["max_ks"])
              and regression <= tolerance)
        fields = {"psi": round(g_psi, 6), "ks": round(g_ks, 6),
                  "mean_incumbent": round(mean_inc, 6),
                  "mean_candidate": round(mean_cand, 6),
                  "regression": round(regression, 6),
                  "tolerance": round(tolerance, 6),
                  "shadow_rows": int(len(holdout))}
        return {"pass": bool(ok), "fields": fields,
                "mean_incumbent": mean_inc}

    def _finish_canary(self, name: str, r: _Route, now: float) -> None:
        sh = r.shadow or {"rows": 0, "ticks": 0, "nonfinite": 0,
                          "inc_sum": 0.0, "cand_sum": 0.0}
        fields = dict(r.gates.get("fields", {}))
        if sh["rows"]:
            mean_inc = sh["inc_sum"] / sh["rows"]
            mean_cand = sh["cand_sum"] / sh["rows"]
            regression = mean_inc - mean_cand
            fields.update(mean_incumbent=round(mean_inc, 6),
                          mean_candidate=round(mean_cand, 6),
                          regression=round(regression, 6),
                          shadow_rows=int(sh["rows"]),
                          shadow_ticks=int(sh["ticks"]))
            if sh["nonfinite"] or regression > r.tolerance:
                self._emit("canary", name, outcome="rejected",
                           candidate_version=r.candidate_version,
                           reason=("shadow_nonfinite" if sh["nonfinite"]
                                   else "shadow_regression"), **fields)
                self._quarantine_candidate(name, r, now,
                                           reason="shadow_window",
                                           gates=fields)
                return
            r.baseline_mean = mean_inc
        else:
            r.baseline_mean = r.gates.get("mean_incumbent")
        self._emit("canary", name, outcome="pass",
                   candidate_version=r.candidate_version, **fields)
        self._promote(name, r, now)

    # -- promote ---------------------------------------------------------

    def _promote(self, name: str, r: _Route, now: float) -> None:
        r.promote_attempts += 1
        try:
            self._registry.promote(name, int(r.candidate_version))
        except RegistryError as e:
            # Torn or failed flip: the candidate is still invisible and
            # the flip retryable; exhaustion quarantines it.
            self._emit("promote", name, outcome="torn",
                       candidate_version=r.candidate_version,
                       attempt=r.promote_attempts,
                       reason=str(e)[:200])
            if r.promote_attempts > int(self._policy.promote["retries"]):
                self._quarantine_candidate(name, r, now,
                                           reason="promote_exhausted")
            return
        self.counts["promotes"] += 1
        self._emit("promote", name, outcome="promoted",
                   from_version=r.prior_version,
                   to_version=r.candidate_version,
                   attempt=r.promote_attempts)
        self._reload()
        if self._server is None:
            # Offline: no live traffic to watch -- the NEXT serve run
            # adopts the promoted version and its own drift plane /
            # breaker provide the probation signals.
            self._cooldown(name, r, now)
            return
        w = self._policy.watch
        r.state = "watch"
        r.violation = None
        r.watch_sum = 0.0
        r.watch_count = 0
        r.watch_ticks_left = int(w["probation_ticks"])
        r.watch_deadline = now + float(w["probation_s"])
        r.alarms = 0
        if self._server is not None:
            r.breaker_trips0 = self._server.breaker.stats()["trips"]

    # -- watch / rollback ------------------------------------------------

    def _tick_watch(self, name: str, r: _Route, now: float) -> None:
        w = self._policy.watch
        if self._server is not None and r.breaker_trips0 is not None:
            if self._server.breaker.stats()["trips"] > r.breaker_trips0:
                r.violation = r.violation or "breaker_trip"
        if (r.violation is None and r.watch_count >= int(w["min_rows"])
                and r.baseline_mean is not None):
            mean_watch = r.watch_sum / r.watch_count
            if (r.baseline_mean - mean_watch) > r.tolerance:
                r.violation = "score_regression"
        if r.violation is not None:
            self._emit("watch", name, outcome="violated",
                       version=r.candidate_version, reason=r.violation)
            self._rollback(name, r, now)
            return
        if r.watch_ticks_left <= 0 and now >= r.watch_deadline:
            self._emit("watch", name, outcome="passed",
                       version=r.candidate_version,
                       shadow_rows=r.watch_count)
            self._cooldown(name, r, now)

    def _rollback(self, name: str, r: _Route, now: float) -> None:
        bad, prior = int(r.candidate_version), int(r.prior_version)
        new_v = self._registry.rollback(
            name, to_version=prior, bad_version=bad,
            reason={"reason": r.violation,
                    "baseline_mean": r.baseline_mean,
                    "watch_mean": (r.watch_sum / r.watch_count
                                   if r.watch_count else None)})
        self.counts["rollbacks"] += 1
        self.counts["quarantines"] += 1
        self._emit("rollback", name, from_version=bad, to_version=new_v,
                   version=prior, reason=r.violation,
                   tolerance=round(r.tolerance, 6))
        self._emit("quarantine", name, version=bad, reason=r.violation)
        self._reload()
        self._cooldown(name, r, now)

    # -- shared helpers --------------------------------------------------

    def _quarantine_attempt(self, name: str, r: _Route, now: float, *,
                            reason: str, error: str) -> None:
        """Retrain exhausted: no artifact exists to quarantine, but the
        ATTEMPT is -- the route stops retrying and cools down, and the
        health-shaped event makes the exhaustion visible."""
        self.counts["quarantines"] += 1
        self._emit("quarantine", name, reason=f"{reason}: {error}",
                   attempt=r.attempt, flag_names=[reason],
                   cooldown_s=self._policy.cooldown_s)
        self._cooldown(name, r, now)

    def _quarantine_candidate(self, name: str, r: _Route, now: float, *,
                              reason: str, gates=None) -> None:
        self._registry.quarantine(
            name, int(r.candidate_version),
            dict({"reason": reason}, **({"gates": gates} if gates
                                        else {})))
        self.counts["quarantines"] += 1
        self._emit("quarantine", name, version=r.candidate_version,
                   reason=reason, cooldown_s=self._policy.cooldown_s)
        self._cooldown(name, r, now)

    def _cooldown(self, name: str, r: _Route, now: float) -> None:
        self._release_candidate(r)
        r._clear_candidate()
        r.state = "cooldown"
        r.alarms = 0
        r.attempt = 0
        r.cooldown_until = now + self._policy.cooldown_s

    def _release_candidate(self, r: _Route) -> None:
        if r.candidate is not None and self._server is not None:
            try:
                self._server._executor_for(r.candidate).release_state(
                    r.candidate.state)
            except Exception:
                pass

    def _incumbent(self, name: str) -> ServedModel:
        if self._server is not None:
            return self._server.resolve(name)
        return self._registry.load(name)

    def _reload(self) -> None:
        """Run the EXISTING hot-reload path (the only client-visible
        swap the lifecycle ever performs)."""
        if self._server is not None:
            self._server.maybe_reload()

    def _score(self, m: ServedModel, rows_original: np.ndarray
               ) -> np.ndarray:
        """Per-row log-likelihood of ``rows_original`` (original data
        coordinates) under ``m`` -- the shadow/gate scoring dispatch.
        Uses the server's executor cache when bound (sharing compiled
        kernels with live traffic), else a private one."""
        rows = (np.asarray(rows_original, np.float64)
                - np.asarray(m.data_shift, np.float64))
        if self._server is not None:
            ex = self._server._executor_for(m)
        else:
            key = (m.dtype, m.diag_only)
            ex = self._executors.get(key)
            if ex is None:
                from ..serving.executor import ScoringExecutor

                ex = ScoringExecutor(dtype=m.dtype,
                                     diag_only=m.diag_only)
                self._executors[key] = ex
        _, logz = ex.infer(m.state, rows, want="proba")
        return np.asarray(logz, np.float64).reshape(-1)

    def _spool(self, r: _Route, original_rows: np.ndarray) -> None:
        cap = self._policy.spool_rows
        if cap <= 0:
            return
        r.spool.append(np.array(original_rows, np.float64, copy=True))
        r.spool_count += int(original_rows.shape[0])
        while r.spool_count > cap and len(r.spool) > 1:
            dropped = r.spool.pop(0)
            r.spool_count -= int(dropped.shape[0])

    def _emit(self, phase: str, name: str, **fields) -> None:
        rec = telemetry.current()
        if not rec.active:
            return
        clean = {k: v for k, v in fields.items() if v is not None}
        rec.emit("lifecycle", model=name, phase=phase, **clean)
        rec.metrics.count(f"lifecycle_{phase}")
