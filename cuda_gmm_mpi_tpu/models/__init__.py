"""Model layer: EM driver and the Rissanen model-order search (SURVEY L4/L5)."""

from .gmm import GMMModel, chunk_events, em_while_loop
from .order_search import (GMMResult, compute_memberships, fit_gmm,
                           iter_memberships)

__all__ = [
    "GMMModel", "chunk_events", "em_while_loop",
    "GMMResult", "compute_memberships", "fit_gmm", "iter_memberships",
]
