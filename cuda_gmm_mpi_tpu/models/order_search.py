"""Rissanen/MDL model-order search: the reference's outer K-sweep.

Orchestrates the L6/L5 control flow of ``main`` (``gaussian.cu:479-960``):
run EM at the current cluster count, score with Rissanen/MDL, save the best
configuration, eliminate empty clusters, merge the closest pair, repeat down to
``target_num_clusters`` (or 1). Per-K work is entirely jitted device code; the
host loop only moves scalars (loglik, active count, rissanen).

Best-model save rule (gaussian.cu:839): keep when it's the first K, or when
rissanen improves and no target K was requested, or when K equals the target.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import functools
import math
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import health, supervisor
from ..config import GMMConfig
from ..parallel import elastic
from ..ops.formulas import convergence_epsilon, model_score
from ..validation import InvalidInputError, validate_finite
from ..ops.merge import eliminate_and_reduce
from ..state import GMMState, bucket_width, clone_state, compact
from .. import telemetry
from ..telemetry import RunRecorder
from ..telemetry import exporter as tl_exporter
from ..telemetry import profiling as tl_profiling
from ..telemetry import sketch as tl_sketch
from ..telemetry import spans as tl_spans
from ..testing import faults
from ..utils.logging_ import get_logger, metrics_line
from ..utils.profiling import PhaseTimer
from .gmm import GMMModel, chunk_events


# Orbax's standard handler holds arrays/numbers only, so config identity
# rides checkpoints as int codes. A checkpoint is only resumable under the
# semantics it was written with: criterion scores live on per-criterion
# scales, and a state evolved under one covariance family must not continue
# under another.
_CRITERION_CODE = {"rissanen": 0, "bic": 1, "aic": 2, "aicc": 3}
_CRITERION_NAME = {v: k for k, v in _CRITERION_CODE.items()}
_COV_CODE = {"full": 0, "diag": 1, "spherical": 2, "tied": 3}
_COV_NAME = {v: k for k, v in _COV_CODE.items()}


def _restored_criterion(restored) -> str:
    return _CRITERION_NAME.get(int(restored.get("criterion_code", 0)),
                               "rissanen")


def _restored_cov(restored, default: str) -> str:
    # Checkpoints predating the covariance_type field carry the writing
    # run's family implicitly; assume the resuming config's (the old
    # behavior) rather than rejecting every legacy checkpoint.
    if "cov_code" not in restored:
        return default
    return _COV_NAME.get(int(restored["cov_code"]), default)


def _resume_mismatch(restored, config, log) -> bool:
    """True (and warns) when a checkpoint's semantics differ from this run's."""
    crit = _restored_criterion(restored)
    cov = _restored_cov(restored, config.covariance_type)
    if ("cov_code" not in restored
            and config.covariance_type in ("spherical", "tied")):
        # Legacy checkpoints predate these families entirely, so the
        # benefit-of-the-doubt default cannot apply to them.
        cov = "pre-covariance_type (full or diag)"
    if crit == config.criterion and cov == config.covariance_type:
        if "cov_code" not in restored and log:
            # The family match above is an assumption, not a verification:
            # legacy checkpoints don't record theirs. Resume proceeds (old
            # behavior) but says so, so a diag checkpoint silently resumed
            # under full (or vice versa) is at least diagnosable.
            log.warning(
                "checkpoint predates the covariance_type field; assuming it "
                "was written under this run's family (%r) -- verify the "
                "original run's config if results look wrong",
                config.covariance_type)
        return False
    if log:
        log.warning(
            "checkpoint was written under criterion=%r covariance_type=%r "
            "but this run uses %r/%r; starting fresh",
            crit, cov, config.criterion, config.covariance_type)
    return True


@contextlib.contextmanager
def _null_phase(_name):
    yield


@functools.lru_cache(maxsize=None)
def _elim_reduce_jit(diag_only: bool):
    """Process-wide jitted eliminate_and_reduce (per diag flag).

    A fresh ``jax.jit`` per fit would recompile the pair-scan program on
    every fit -- and, with bucketed sweeps, once per bucket width INSIDE
    the timed sweep. One shared jit keeps XLA's shape-keyed executable
    cache alive across fits and widths (two entries total; states are
    pytrees of plain arrays, so nothing pins device buffers here).
    """
    return jax.jit(functools.partial(eliminate_and_reduce,
                                     diag_only=diag_only))


def _emit_em_iters(rec, k, ll_log, iters, dt, epsilon, model):
    """Per-iteration ``em_iter`` records from one K's EM run.

    ``ll_log`` is the [max_iters + 1] loglik log (slot 0 = initial E-step;
    NaN beyond the iteration count -- em_while_loop's trajectory contract).
    Wall time per iteration is REAL for host-driven loops that expose
    ``last_iter_seconds`` (streaming), amortized (whole-K wall / iters)
    for single-dispatch EM loops, and says which in ``timing``.
    """
    if not rec.active or ll_log is None or iters <= 0:
        return
    lls = np.asarray(jax.device_get(ll_log), np.float64)
    n = min(iters, lls.shape[0] - 1)
    secs = getattr(model, "last_iter_seconds", None)
    measured = isinstance(secs, list) and len(secs) == iters
    for i in range(n):
        wall = secs[i] if measured else dt / max(iters, 1)
        rec.emit("em_iter", k=int(k), iter=i,
                 loglik=float(lls[i + 1]),
                 delta=float(lls[i + 1] - lls[i]),
                 epsilon=float(epsilon),
                 wall_s=round(float(wall), 6),
                 timing="measured" if measured else "amortized")


def _shutdown_and_raise(sup, rec, log, ckpt, *, step, k=None, em_iter=None,
                        payload=None, checkpointed=None):
    """The cooperative stop's endgame: write the emergency intra-K
    sub-step (when ``payload`` is given), emit the ``shutdown`` telemetry
    record, and raise the stop as PreemptedError / PeerLostError
    (supervisor.raise_stop) for the CLI's exit-75 contract."""
    if payload is not None:
        checkpointed = bool(
            ckpt is not None
            and ckpt.save_substep(int(step), int(em_iter), payload))
    checkpointed = bool(checkpointed)
    if rec.active:
        fields = dict(reason=sup.stop_reason or "unknown",
                      checkpointed=checkpointed)
        if step is not None:
            fields["step"] = int(step)
        if k is not None:
            fields["k"] = int(k)
        if em_iter is not None:
            fields["em_iter"] = int(em_iter)
        rec.emit("shutdown", **fields)
        if checkpointed:
            rec.metrics.count("emergency_checkpoints")
    log.warning(
        "stopping (%s)%s: emergency checkpoint %s", sup.stop_reason,
        (f" at K={k}" + (f" iteration {em_iter}" if em_iter is not None
                         else "")) if k is not None else "",
        "written" if checkpointed else
        ("not needed (sweep position already durable)" if payload is None
         and ckpt is not None else "unavailable"))
    sup.raise_stop(step=step, em_iter=em_iter, checkpointed=checkpointed)


def _reseed_and_refit(model, config, state, chunks, wts, epsilon, k,
                      want_traj, rec, log, primary):
    """Reseed empty clusters from worst-fit events and refit at the same K
    (``recovery_reseed_empty``; bounded by ``max_recovery_attempts``).

    Returns the refit ``(state, loglik, iters, counts, ll_log)`` once the
    empties are gone (or the attempt budget is spent); a refit that goes
    FATAL discards itself and returns the pre-reseed result -- reseeding
    is an improvement pass, never a correctness risk.
    """
    ll_f, iters_i, counts_np, ll_log = primary
    best = (state, ll_f, iters_i, counts_np, ll_log)
    for attempt in range(1, config.max_recovery_attempts + 1):
        state2, n_reseeded = health.reseed_empty_clusters(model, best[0],
                                                         chunks)
        if not n_reseeded:
            break
        out = model.run_em(state2, chunks, wts, epsilon,
                           trajectory=want_traj)
        if want_traj:
            new_state, ll, iters_a, ll_log_a = out
        else:
            (new_state, ll, iters_a), ll_log_a = out, None
        counts_a = np.asarray(jax.device_get(model.last_health), np.int64)
        ll_a = float(jax.device_get(ll))
        fatal_a = health.word_is_fatal(health.pack_word(counts_a))
        outcome = ("fatal" if fatal_a
                   else "recovered" if counts_a[health.EMPTY_CLUSTER] == 0
                   else "retry")
        log.info("reseeded %d empty cluster(s) at K=%d (attempt %d): %s",
                 n_reseeded, int(k), attempt, outcome)
        if rec.active:
            rec.emit("recovery", k=int(k), attempt=attempt,
                     action="reseed_empty", outcome=outcome,
                     flags=int(health.pack_word(counts_a)),
                     flag_names=health.flag_names(
                         health.pack_word(counts_a)))
            rec.metrics.count("reseeds")
        if fatal_a:
            return best
        best = (new_state, ll_a, np.asarray(int(jax.device_get(iters_a))),
                counts_a, ll_log_a)
        if counts_a[health.EMPTY_CLUSTER] == 0:
            break
    return best


def compute_envelope(model, state, chunks, n_valid, k):
    """Training drift envelope (stream rev v2.4; telemetry/sketch.py):
    one streamed pass of the fit data through the FINAL compacted
    parameters, sketching the per-event score distribution and argmax
    responsibility occupancy -- the reference distribution serve-time
    drift (PSI/KS vs this envelope) is measured against.

    ``chunks`` is the device-resident chunked training data in the
    model's centered frame (the serve path shifts requests into the
    same frame, so fit-time and serve-time scores are comparable);
    ``n_valid`` the local un-padded row count. Reuses the
    ``infer_posteriors`` block executable (iter_memberships' pattern)
    -- peak host memory is one [B, K] block. Observational by
    contract: any failure returns None instead of raising, and a lazy
    (pipelined) source is skipped (`gmm drift --rebuild-envelope`
    backfills those). Multi-host runs merge per-rank sketches through
    ``allgather_json`` -- every rank must call this (the collective is
    reached even when the local pass fails).
    """
    log = get_logger()
    local = None
    try:
        block = np.asarray(jax.device_get(chunks))
        d = block.shape[-1]
        rows = block.reshape(-1, d)[:int(n_valid)]
        B = int(getattr(model, "inference_block", 0) or 1)
        k = int(k)
        sk = tl_sketch.StreamSketch()
        occ = np.zeros(k, dtype=np.int64)
        for lo in range(0, rows.shape[0], B):
            xb = rows[lo:lo + B]
            valid = xb.shape[0]
            if valid < B:  # pad the tail to the jitted block shape
                xb = np.concatenate(
                    [xb, np.zeros((B - valid, d), xb.dtype)])
            w, logz = model.infer_posteriors(state, xb)
            w_host = np.asarray(jax.device_get(w))[:valid, :k]
            sk.update(np.asarray(jax.device_get(logz))[:valid])
            occ += np.bincount(np.argmax(w_host, axis=1), minlength=k)
        local = tl_sketch.make_envelope(sk, occ, k=k,
                                        num_events=rows.shape[0])
    except Exception:  # noqa: BLE001 -- observational, never run-fatal
        log.warning("envelope computation failed; fit continues "
                    "without one", exc_info=True)
    if jax.process_count() > 1:
        try:
            from ..parallel.distributed import allgather_json

            return tl_sketch.merge_envelopes(allgather_json(local))
        except Exception:  # noqa: BLE001
            log.warning("envelope allgather failed", exc_info=True)
            return None
    return local


def _emit_run_summary(rec, config, timer, sweep_log, ideal_k, best_score,
                      best_ll, em_walls, buckets=None, health_section=None,
                      em_backend=None, envelope=None):
    """Final ``run_summary`` record: scores, 7-category phase profile,
    compile/execute split, metrics-registry snapshot, and (multi-host)
    every rank's snapshot gathered to the one stream process 0 writes.

    The compile split is MEASURED: ``profile.compile_seconds`` (the
    CompileWatch rollup below) is the wall XLA actually spent building
    executables. The ``compile`` dict keeps the raw first/warm call
    walls for context, but the old derived ``est_compile_s``
    (first - warm) estimate is gone -- ``gmm report`` labels the
    measured source and renders the estimate only for pre-v2.2 streams
    that carry nothing else.

    ``buckets`` (host-driven sweeps) describes the cluster-width bucketing:
    ``{mode, em_widths, em_compiles, rebuckets}`` -- em_compiles is the
    number of DISTINCT padded widths EM ran at, i.e. the number of EM
    executables the sweep compiled.
    """
    if not rec.active:
        return
    first = em_walls[0] if em_walls else None
    warm = min(em_walls[1:]) if len(em_walls) > 1 else None
    elastic_section = elastic.run_summary_section()
    # CompileWatch rollup (rev v2.2): MEASURED compile counts/seconds +
    # cost/memory analyses + HBM watermarks -- since rev v2.5 the ONLY
    # compile-cost source this stream emits (``est_compile_s`` deleted;
    # report still renders it, labeled "(est.)", for old fixtures).
    watch = tl_profiling.active()
    fields = dict(
        **({"profile": watch.snapshot()} if watch is not None else {}),
        **({"buckets": buckets} if buckets is not None else {}),
        **({"health": health_section} if health_section is not None else {}),
        # Elastic recovery rollup (rev v2.0): present only when the run
        # survived at least one shrink.
        **({"elastic": elastic_section} if elastic_section is not None
           else {}),
        # Which E-step backend actually ran (pallas / pallas-interpret /
        # jnp / custom; stream rev v1.5) -- mirrors run_start so a
        # summary-only consumer sees it too.
        **({"em_backend": em_backend} if em_backend is not None else {}),
        # Training drift envelope (rev v2.4): the fit data's score
        # sketch + occupancy, the serve-time drift reference.
        **({"envelope": envelope} if envelope is not None else {}),
        ideal_k=int(ideal_k),
        score=float(best_score),
        criterion=config.criterion,
        final_loglik=float(best_ll),
        total_iters=int(sum(r[3] for r in sweep_log)),
        wall_s=round(float(sum(r[4] for r in sweep_log)), 6),
        phase_profile=(timer.snapshot() if timer is not None
                       else {"seconds": {}, "counts": {}}),
        compile={
            "first_call_s": (round(first, 6) if first is not None else None),
            "warm_call_s": (round(warm, 6) if warm is not None else None),
        },
        metrics=rec.metrics.snapshot(),
        memory_stats=telemetry.memory_stats(),
    )
    if jax.process_count() > 1:
        # Collective: every rank contributes its snapshot (all ranks run
        # this; only process 0 writes the assembled record).
        from ..parallel.distributed import allgather_json

        fields["per_process"] = allgather_json(rec.metrics.snapshot())
    rec.emit("run_summary", **fields)


def _rebuild_result(state: dict) -> "GMMResult":
    """Unpickle hook for GMMResult (model dropped at pickle time)."""
    r = GMMResult.__new__(GMMResult)
    r.__dict__.update(state)
    return r


@dataclasses.dataclass
class GMMResult:
    """Final fit: the best (lowest-Rissanen) configuration across the sweep.

    Mirrors the reference's ``saved_clusters`` + summary scalars
    (gaussian.cu:262-281, 839-854, 961-963). ``state`` is compacted (inactive
    slots dropped) and ``means`` are in the original data coordinates (the
    centering shift applied at fit time is undone).
    """

    state: GMMState
    ideal_num_clusters: int
    min_rissanen: float
    final_loglik: float
    epsilon: float
    num_events: int
    num_dimensions: int
    data_shift: np.ndarray  # [D] centering shift (zeros if centering disabled)
    # per-K trajectory: (num_clusters, loglik, rissanen, em_iters, seconds).
    # ``seconds`` is the wall time until that K's loglik was on host: EM only
    # when profiling is on (or on the final K); EM + the fused order-reduction
    # dispatch/sync otherwise (the default path syncs once per K). Fused
    # sweeps with emission (checkpoint/profile) record each K's whole span
    # (EM + order reduction + emit; first new step includes compile) from
    # emission arrival deltas; emission-free fused sweeps amortize wall/steps.
    sweep_log: list = dataclasses.field(default_factory=list)
    profile: Optional[dict] = None          # seconds per phase (7 categories)
    profile_report: Optional[str] = None    # formatted report
    # [start, stop) of the events THIS host loaded (multi-host runs fit on
    # per-host slices; single-host = (0, num_events)). The output path uses
    # it to recompute exactly this host's memberships.
    host_range: Optional[tuple] = None
    # Numerical-health summary of the run (health.health_summary): packed
    # flag word + per-lane counters aggregated over every K, recovery and
    # checkpoint-retry counts. A clean run reads {"flags": 0, ...}.
    health: Optional[dict] = None
    # Which init won an n_init > 1 fit (0-based restart index; None for
    # single-init fits). The batched and sequential restart paths must
    # agree on this at identical seeds (the winner-parity contract,
    # models/restarts.py).
    init_index: Optional[int] = None
    # Training drift envelope (stream rev v2.4; telemetry/sketch.py
    # make_envelope): the fit data's per-event score sketch + per-
    # cluster responsibility occupancy under the final parameters --
    # persisted as envelope.json on registry export, the reference
    # distribution serve-time drift is measured against. None when
    # envelope computation was disabled, failed, or the source was lazy.
    envelope: Optional[dict] = None
    # The fitted model (jitted executables already built) so the output path
    # reuses compiled posteriors instead of building a fresh GMMModel.
    model: Optional[object] = dataclasses.field(default=None, repr=False)

    def __reduce__(self):
        # Pickling drops the fitted model (jitted executables: unpicklable
        # and process-bound); an unpickled result's output path falls back
        # to the per-config cached model (_fallback_model). In-process
        # copy/deepcopy keep the model (see __copy__/__deepcopy__ below).
        state = dict(self.__dict__)
        state["model"] = None
        return (_rebuild_result, (state,))

    def __copy__(self):
        new = GMMResult.__new__(GMMResult)
        new.__dict__.update(self.__dict__)
        return new

    def __deepcopy__(self, memo):
        import copy

        new = GMMResult.__new__(GMMResult)
        memo[id(self)] = new
        for k, v in self.__dict__.items():
            # The model is shared, not copied: it is an immutable-config
            # compiled-executable holder, and deep-copying it is both
            # impossible (jit closures) and pointless.
            new.__dict__[k] = v if k == "model" else copy.deepcopy(v, memo)
        return new

    @property
    def means(self) -> np.ndarray:
        return np.asarray(self.state.means) + self.data_shift[None, :]

    @property
    def covariances(self) -> np.ndarray:
        return np.asarray(self.state.R)

    @property
    def weights(self) -> np.ndarray:
        return np.asarray(self.state.pi)


def fit_gmm(
    data: np.ndarray,
    num_clusters: int,
    target_num_clusters: int = 0,
    config: GMMConfig = GMMConfig(),
    model: Optional[GMMModel] = None,
    verbose: Optional[bool] = None,
    init_means: Optional[np.ndarray] = None,
    sample_weight: Optional[np.ndarray] = None,
) -> GMMResult:
    """Full GMM fit with model-order search -- the library entry point.

    Args mirror the reference CLI (gaussian.cu:1111-1178): ``num_clusters`` is
    the starting K (1..max_clusters), ``target_num_clusters`` = 0 means search
    all the way down to 1 keeping the best Rissanen score (stop_number logic,
    gaussian.cu:177-181). ``init_means`` ([K, D], original coordinates)
    overrides the seeding policy with user-supplied starting means
    (sklearn's means_init); with ``n_init > 1`` it seeds init 0 and the
    kmeans++ restarts still run. ``sample_weight`` ([N] nonnegative) weights
    every sufficient statistic per event. Weights are event MULTIPLICITIES,
    not probabilities: integer weights reproduce replicated rows exactly
    except for the avgvar diagonal loading (seeded from the UNWEIGHTED data
    variance, which physical replication shifts; set a huge
    ``covariance_dynamic_range`` for exact parity), and the absolute
    empty-cluster thresholds (Nk > 0.5 etc.) operate on weighted counts --
    normalized weights summing to ~1 would make every cluster look empty,
    so a total weight below ``num_clusters`` is rejected. In-memory data
    only; seeding and the epsilon/criterion event counts stay unweighted.
    (Upgrade beyond both the reference and sklearn.)

    With ``config.metrics_file`` set, the whole fit runs under an active
    :class:`~cuda_gmm_mpi_tpu.telemetry.RunRecorder`: every execution path
    (in-memory, streaming, sharded, multi-controller, fused-sweep) emits
    the schema-versioned JSONL event stream described in
    docs/OBSERVABILITY.md. Already-active ambient recorders (library users
    wrapping fits in ``telemetry.use``) are reused, not replaced.
    """
    with contextlib.ExitStack() as stack:
        if config.metrics_file and not telemetry.current().active:
            # One recorder spans the whole fit, restarts included: the
            # recursive n_init sub-fits find the ambient recorder active
            # and ride it instead of truncating the stream per init.
            rec = RunRecorder(config.metrics_file)
            stack.enter_context(telemetry.use(rec))
            stack.enter_context(rec)
        if config.max_runtime_s is not None \
                and not supervisor.current().active:
            # A deadline without an ambient supervisor (library call): run
            # one scoped to this fit. No signal handlers -- hijacking a
            # host application's SIGTERM from a library is the CLI's
            # prerogative (it activates its own supervisor), not ours.
            stack.enter_context(supervisor.use(supervisor.RunSupervisor(
                max_runtime_s=config.max_runtime_s,
                install_signals=False)))
        if config.metrics_port is not None:
            # Live observability plane (--metrics-port; stream rev v2.1):
            # the OpenMetrics exporter + resource sampler run for the
            # fit's duration, and a fit-scoped trace activates -- its id
            # rides every stream record via the context, and the span
            # emission points below light up. None (the default) skips
            # ALL of this, keeping the stream byte-identical to pre-v2.1.
            stack.enter_context(tl_exporter.live_plane(
                config.metrics_port,
                registry_provider=lambda: telemetry.current().metrics,
                gauges_provider=elastic.live_gauges))
            rec = telemetry.current()
            tid = stack.enter_context(tl_spans.trace())
            if rec.active:
                rec.set_context(trace_id=tid)
                stack.callback(rec.set_context, trace_id=None)
            stack.enter_context(tl_spans.span("fit"))
        if telemetry.current().active and tl_profiling.active() is None:
            # Compile & cost introspection (stream rev v2.2): the watch
            # rides every active-recorder fit -- XLA compile listeners,
            # executable-cache cost introspection, and memory watermarks
            # all report through it into ``compile`` events and the
            # ``run_summary.profile`` rollup. With no recorder there is
            # no watch, and every instrumented path dispatches through
            # plain jax.jit -- results stay byte-identical to pre-v2.2.
            stack.enter_context(tl_profiling.watch())
        if config.autotune != "off":
            # Profile-guided knob resolution (tuning/, docs/PERF.md
            # "Autotuning"): runs ONCE per fit, under the ambient
            # recorder so the per-knob `tune` events ride this stream.
            # The resolved config comes back with autotune='off' --
            # restart and elastic re-entries inherit the decisions
            # instead of re-probing (and re-emitting) per sub-fit.
            from ..tuning import resolve_fit_config

            config = resolve_fit_config(config, data, num_clusters,
                                        log=get_logger(config))
        # Elastic retry loop (docs/DISTRIBUTED.md "Elastic recovery"): a
        # peer loss under --elastic shrinks the world via the checkpoint-FS
        # rendezvous and REFITS (resume="auto" restores the newest step)
        # instead of propagating to exit 75. Without --elastic, recovery
        # is None and the first PeerLostError propagates unchanged.
        recovery = None
        while True:
            try:
                return _fit_gmm(data, num_clusters, target_num_clusters,
                                config, model, verbose, init_means,
                                sample_weight)
            except supervisor.PeerLostError as e:
                if recovery is None:
                    recovery = supervisor.ElasticRecovery.maybe(config)
                if recovery is None:
                    raise
                # The model survives the retry: its restart cache is
                # world-keyed (_data_fingerprint), so arrays prepared
                # under the old bounds can never serve the refit, and a
                # live pipelined source re-seeks to the new bounds.
                config = recovery.recover(e, config)


def _fit_gmm(data, num_clusters, target_num_clusters, config, model,
             verbose, init_means, sample_weight) -> GMMResult:
    """fit_gmm's body, run under whatever ambient recorder is active."""
    if not (1 <= num_clusters <= config.max_clusters):
        raise ValueError(
            f"num_clusters must be in [1, {config.max_clusters}], got {num_clusters}"
        )
    if target_num_clusters > num_clusters:
        raise ValueError("target_num_clusters must be <= num_clusters")
    stop_number = target_num_clusters if target_num_clusters > 0 else 1
    verbose = config.enable_print if verbose is None else verbose

    if config.device:
        # The runtime replacement for the reference's compile-time DEVICE
        # (gaussian.h:19) + the north-star --device flag. config.update (not
        # just env) because preloading sitecustomize hooks may have consumed
        # JAX_PLATFORMS already. Must run before ANY device discovery --
        # including _fit_with_restarts' model/mesh construction.
        jax.config.update("jax_platforms", config.device)
    if config.dtype == "float64" and not jax.config.jax_enable_x64:
        # Refuse rather than silently truncating to float32 -- and rather
        # than flipping the PROCESS-GLOBAL x64 flag here, which would make
        # later float32 fits (and the host application's own JAX code)
        # call-order dependent. The CLI sets the flag at process entry.
        raise ValueError(
            "dtype='float64' needs jax_enable_x64; set "
            "jax.config.update('jax_enable_x64', True) at startup (the CLI "
            "does this for --dtype=float64)")
    if config.debug_nans:
        jax.config.update("jax_debug_nans", True)

    if config.n_init > 1:
        return _fit_with_restarts(data, num_clusters, target_num_clusters,
                                  config, model, verbose,
                                  init_means=init_means,
                                  sample_weight=sample_weight)

    log = get_logger(config)
    rec = telemetry.current()
    # An active recorder needs the same per-K host syncs profiling needs
    # (per-iteration walls, the 7-category profile in run_summary), so
    # telemetry runs imply a PhaseTimer; the report still prints only
    # under config.profile, keeping --profile's stderr contract unchanged.
    timer = PhaseTimer() if (config.profile or rec.active) else None
    phase = timer.phase if timer else _null_phase

    nproc = jax.process_count()
    if model is None:
        if config.stream_events:
            from .streaming import StreamingGMMModel

            model = StreamingGMMModel(config)
        elif config.mesh_shape is not None or nproc > 1:
            # Multi-controller runs always need the sharded model (the mesh
            # spans all hosts' devices; default = every device on 'data').
            from ..parallel import ShardedGMMModel

            model = ShardedGMMModel(config)
        else:
            model = GMMModel(config)

    (state, chunks, wts, chunks_np, wts_np, n_events, n_dims, shift,
     host_range) = _prepare_fit(data, num_clusters, config, model, phase, log,
                                init_means=init_means,
                                sample_weight=sample_weight)
    epsilon = convergence_epsilon(n_events, n_dims, config.epsilon_scale)
    if verbose:
        print(f"epsilon = {epsilon}")  # gaussian.cu:462
    log.debug("epsilon=%s n=%d d=%d k=%d", epsilon, n_events, n_dims,
              num_clusters)

    if rec.active:
        # Static tags ride every subsequent record (sharded/multi-host
        # streams stay self-describing: path + mesh + process).
        mesh = getattr(model, "mesh", None)
        rec.set_context(
            path=("streaming" if config.stream_events
                  else "sharded" if mesh is not None else "in-memory"),
            mesh=(list(mesh.shape.values()) if mesh is not None else None),
        )
        rec.emit(
            "run_start",
            platform=jax.devices()[0].platform,
            num_events=int(n_events), num_dimensions=int(n_dims),
            start_k=int(num_clusters), target_k=int(target_num_clusters),
            epsilon=float(epsilon),
            process_count=int(nproc),
            device_count=int(jax.device_count()),
            local_device_count=int(jax.local_device_count()),
            dtype=config.dtype, chunk_size=int(config.chunk_size),
            covariance_type=config.covariance_type,
            criterion=config.criterion,
            fused_sweep=bool(config.fused_sweep),
            stream_events=bool(config.stream_events),
            n_init=int(config.n_init),
            em_backend=getattr(model, "estep_backend", "jnp"),
            em_backend_reason=getattr(model, "estep_backend_reason", None),
            memory_stats=telemetry.memory_stats(),
        )

    ckpt = None
    if config.checkpoint_dir:
        from ..utils.checkpoint import SweepCheckpointer

        # All ranks construct and call the checkpointer; orbax coordinates
        # multi-process saves (primary host writes). Multi-host runs require
        # checkpoint_dir on a filesystem every rank can read (on TPU pods
        # that is GCS/NFS by construction; docs/DISTRIBUTED.md).
        ckpt = SweepCheckpointer(config.checkpoint_dir,
                                 keep=config.checkpoint_keep,
                                 retries=config.checkpoint_retries,
                                 allow_world_change=config.elastic)

    sup = supervisor.current()
    if (sup.active and ckpt is not None and nproc > 1
            and config.peer_timeout_s > 0):
        # Cross-host liveness watchdog: rank heartbeats ride the shared
        # checkpoint filesystem (multi-host runs already require one); a
        # peer stale beyond peer_timeout_s raises PeerLostError with a
        # local emergency checkpoint instead of hanging this rank forever
        # in the next collective (supervisor.LivenessWatchdog). An elastic
        # refit watches only the sealed membership's survivors (original
        # rank ids), never the rank it just shrank away.
        sup.start_watchdog(
            os.path.join(os.path.abspath(config.checkpoint_dir),
                         "heartbeats"),
            rank=elastic.original_rank(), nproc=nproc,
            timeout_s=config.peer_timeout_s,
            peers=elastic.peer_ranks())

    # Health counters observed by a fused sweep that aborted on a fatal
    # word (the host-driven rerun below folds them into its summary).
    fused_fatal_counts = None
    if config.fused_sweep:
        # Checkpointing AND profiling both ride the per-K io_callback
        # emission (plain single-controller models); other combinations
        # fall back to the host-driven sweep.
        want_emit = ckpt is not None or timer is not None
        blockers = []
        maker = getattr(model, "make_fused_sweep", None)
        if maker is None:
            blockers.append("model without fused-sweep support")
        elif want_emit and not getattr(model, "supports_fused_emit", False):
            blockers.append("per-K checkpoint emission on this model"
                            if ckpt is not None else
                            "per-K profile emission on this model")
        if blockers:
            log.warning(
                "fused_sweep disabled (%s requested); using the host-driven "
                "sweep", ", ".join(blockers),
            )
        else:
            kwargs = dict(
                start_k=num_clusters, stop_number=stop_number,
                target_k=target_num_clusters,
                num_events=n_events, num_dimensions=n_dims,
            )
            if want_emit:
                kwargs["with_emit"] = True
                # Profiling-only emission needs just the step scalars.
                kwargs["emit_light"] = ckpt is None
            fused = maker(**kwargs)
            with tl_spans.span("fused_sweep", start_k=int(num_clusters)):
                fused_result = _run_fused_sweep(
                    fused, config, state, chunks, wts, epsilon,
                    num_clusters, stop_number, target_num_clusters,
                    n_events, n_dims, shift, verbose, host_range, model,
                    ckpt=ckpt, log=log, timer=timer,
                )
            if isinstance(fused_result, GMMResult):
                return fused_result
            # A counter vector instead of a result = the device program
            # stopped on a FATAL health word (recovery='retry'): a single
            # device program has no per-K host intervention point, so
            # recovery means rerunning through the host-driven sweep
            # below, whose rollback-and-retry ladder handles the fault
            # per K. (recovery='off' raised instead.) The observed
            # counters fold into the rerun's run_summary.health.
            fused_fatal_counts = np.asarray(fused_result, np.int64)
            log.warning(
                "fused sweep aborted on a fatal numerical fault; "
                "re-running via the host-driven sweep's recovery ladder")

    # One fused dispatch for the whole order-reduction step, so each K costs
    # a single blocking device->host sync (see eliminate_and_reduce).
    elim_reduce_fn = _elim_reduce_jit(config.diag_only)

    # Bucketed cluster-width compaction: single-controller host-driven
    # sweeps shrink the padded width to the active count's power-of-two
    # bucket as merges cross boundaries, so EM at k active clusters pays
    # matmuls at width ~k instead of the starting K0 (~2x sweep-level
    # FLOPs for <= ceil(log2 K0) + 1 compiled widths; docs/PERF.md).
    # Multi-controller sweeps stay fixed-width: the K-state is replicated
    # per host and a per-rebucket cross-host re-placement buys nothing.
    bucketing = (config.sweep_k_buckets == "pow2" and nproc == 1
                 and hasattr(model, "rebucket_state"))
    bucket_mult = int(getattr(model, "bucket_multiple", 1) or 1)
    em_widths = []  # padded width of every EM run; distinct => one compile
    n_rebuckets = 0

    sweep_log = []
    min_rissanen = np.inf
    ideal_k, best_state, best_ll = num_clusters, state, -np.inf
    k = num_clusters
    step = 0

    resume_em = None
    resume_sub_step = None
    if ckpt is not None and config.resume != "never":
        restored = ckpt.restore()
        if restored is not None and "fused_log" in restored:
            log.warning("found a fused-sweep checkpoint; the host-driven "
                        "sweep cannot resume it -- starting fresh")
            restored = None
        if restored is not None and _resume_mismatch(restored, config, log):
            restored = None
        if restored is not None and int(restored["num_clusters"]) == num_clusters:
            state = restored["state"]
            if hasattr(model, "prepare_state"):
                # Place ONLY the restored state on the mesh (the data chunks
                # were already prepared above; re-preparing them would pay a
                # second full host->device upload). Multi-host: every rank
                # restored the identical host-local state (shared checkpoint
                # FS); re-assembly is local.
                state = model.prepare_state(
                    jax.tree_util.tree_map(jnp.asarray, state))
            best_state = restored["best_state"]
            min_rissanen = float(restored["min_rissanen"])
            ideal_k = int(restored["ideal_k"])
            best_ll = float(restored["best_ll"])
            k = int(restored["k"])
            step = int(restored["step"]) + 1
            sweep_log = [tuple(r) for r in np.asarray(
                restored["sweep_log"]).tolist()] if len(
                    restored.get("sweep_log", [])) else []
            log.info("resumed sweep from checkpoint: next K=%d", k)
            rec.metrics.count("resumes") if rec.active else None
        # Intra-K emergency sub-step (a preempted run's mid-EM state): it
        # outranks the full steps -- its step is the IN-FLIGHT one -- so
        # --resume auto restarts inside the interrupted fit rather than
        # at its beginning (supervisor.py / docs/ROBUSTNESS.md).
        sub = ckpt.restore_substep()
        if sub is not None and (
                _resume_mismatch(sub, config, log)
                or int(sub["num_clusters"]) != num_clusters
                or int(sub["step"]) < step):
            sub = None
        if sub is not None:
            state = sub["state"]
            if hasattr(model, "prepare_state"):
                state = model.prepare_state(
                    jax.tree_util.tree_map(jnp.asarray, state))
            best_state = sub["best_state"]
            min_rissanen = float(sub["min_rissanen"])
            ideal_k = int(sub["ideal_k"])
            best_ll = float(sub["best_ll"])
            k = int(sub["k"])
            step = int(sub["step"])
            sweep_log = [tuple(r) for r in np.asarray(
                sub["sweep_log"]).tolist()] if len(
                    sub.get("sweep_log", [])) else []
            resume_sub_step = int(sub["step"])
            resume_em = {"em_iter": int(sub["em_iter"]),
                         "em_lls": np.asarray(sub.get("em_lls", ()),
                                              np.float64)}
            for key in ("stream_pass", "stream_block", "mb_step",
                        "mb_cursor"):
                if key in sub:
                    resume_em[key] = int(sub[key])
            for key in ("stream_acc", "mb_acc"):
                if key in sub:
                    resume_em[key] = sub[key]
            log.info("resuming INSIDE the interrupted fit: K=%d at EM "
                     "iteration %d (intra-K sub-step %d.iter%d)",
                     k, resume_em["em_iter"], step, resume_em["em_iter"])
            rec.metrics.count("resumes") if rec.active else None

    want_traj = rec.active  # per-iteration loglik log rides the EM call
    em_walls = []  # per-K EM wall seconds (first includes compile)
    # Numerical fault containment (health.py): per-K health counters are
    # fetched alongside the sweep's decision scalars; a fatal word rolls
    # back to this K's input state and climbs the escalation ladder
    # (recovery='retry') or raises with a diagnostic bundle ('off').
    recovery_on = config.recovery == "retry"
    health_totals = np.zeros((health.NUM_FLAGS,), np.int64)
    n_recoveries = 0
    if fused_fatal_counts is not None:
        # The aborted fused sweep's observed fault + its host_fallback
        # recovery action (the 'recovery' event was already emitted).
        health_totals += fused_fatal_counts
        n_recoveries += 1
    # Preemption-safe mode: with an active supervisor AND checkpointing,
    # EM runs through the segmented driver so SIGTERM/deadline/peer-loss
    # are observed mid-K and an intra-K emergency sub-step can be written
    # (bit-identical results; supervisor.py). Unsupervised runs keep the
    # zero-sync single-dispatch loop untouched.
    supervised = (sup.active and ckpt is not None
                  and hasattr(model, "run_em_resumable"))
    # Non-lexical sweep span (rev v2.1): begin/end instead of a `with`
    # because the loop raises through _shutdown_and_raise on preemption
    # -- an un-ended span simply never emits, and its completed children
    # (per-K EM, checkpoint saves) orphan-promote in the tree view.
    sweep_span = tl_spans.begin("sweep", start_k=int(k))
    sweep_wm = tl_profiling.wm_begin("sweep")
    while k >= stop_number:
        if sup.active and sup.poll(where="sweep", k=int(k)):
            # Between-K stop: every completed K is already durable (the
            # full-step save at the end of the previous loop iteration),
            # so there is nothing to add -- emit and exit.
            _shutdown_and_raise(sup, rec, log, ckpt,
                                step=step - 1 if step else None, k=int(k),
                                checkpointed=ckpt is not None and step > 0)
        t0 = time.perf_counter()
        last_k = k <= stop_number
        em_widths.append(int(state.num_clusters_padded))
        # Rollback point: run_em(donate=True) consumes the input state's
        # buffers, so recovery needs a clone taken first (async device
        # copy, one parameter-set of HBM).
        rollback = clone_state(state) if recovery_on else None
        # fused E+M loop (m_step/constants folded in); em_k = one K's EM
        with tl_spans.span("em_k", k=int(k)), \
                tl_profiling.watermark("em_k"), phase("e_step"):
            # donate=True: the EM carry is rebound every K, so the input
            # state's buffers are handed to the device for in-place reuse
            # (one state-size less peak HBM + copy traffic per K).
            if supervised or resume_em is not None:
                (state, ll, iters, ll_log, em_stopped,
                 stop_extra) = model.run_em_resumable(
                    state, chunks, wts, epsilon,
                    poll_iters=config.preempt_poll_iters,
                    should_stop=(
                        (lambda done, _k=int(k): sup.poll(
                            where="em", k=_k, em_iter=done))
                        if sup.active else None),
                    block_stop=(
                        (lambda p, b, _k=int(k): sup.poll_block(
                            k=_k, em_iter=p, block=b))
                        if sup.active else None),
                    resume=resume_em, donate=True)
                resume_em = None
                hw = model.last_health
                if em_stopped:
                    done = int(iters)
                    host_state = _host_state(state, model)
                    # Before any K completed, best_state still aliases the
                    # (donated, now-deleted) seed state; the mid-EM state
                    # stands in -- the resumed first K re-runs the best-save
                    # rule (k == num_clusters always saves) anyway.
                    host_best = (_host_state(best_state, model)
                                 if np.isfinite(best_ll) else host_state)
                    payload = {
                        "state": host_state,
                        "best_state": host_best,
                        "min_rissanen": float(min_rissanen),
                        "ideal_k": int(ideal_k),
                        "best_ll": float(best_ll),
                        "k": int(k),
                        "num_clusters": int(num_clusters),
                        "criterion_code": _CRITERION_CODE[config.criterion],
                        "cov_code": _COV_CODE[config.covariance_type],
                        "sweep_log": np.asarray(sweep_log, np.float64),
                        # The fit-time centering shift rides every
                        # checkpoint so `gmm export --checkpoint` can
                        # rebuild original-coordinate scoring
                        # (serving/registry.py).
                        "data_shift": np.asarray(shift, np.float64),
                    }
                    payload.update(stop_extra)
                    _shutdown_and_raise(sup, rec, log, ckpt, step=step,
                                        k=int(k), em_iter=done,
                                        payload=payload)
                if resume_sub_step is not None and ckpt is not None:
                    # The interrupted K just completed: its emergency
                    # sub-step is superseded. The save paths prune too,
                    # but the sweep's FINAL K never saves a full step, so
                    # discard explicitly here.
                    ckpt.discard_substeps(resume_sub_step)
                    resume_sub_step = None
                if not want_traj:
                    ll_log = None
            elif want_traj:
                state, ll, iters, ll_log = model.run_em(
                    state, chunks, wts, epsilon, trajectory=True,
                    donate=True)
            else:
                ll_log = None
                state, ll, iters = model.run_em(state, chunks, wts, epsilon,
                                                donate=True)
            hw = model.last_health
            if timer or last_k:
                # Block on EM here so the e_step phase (and sweep_log's
                # seconds) measure EM alone. Profiling trades away the
                # fused single-sync optimization below for attribution.
                ll_f, iters_i, counts_i = map(
                    np.asarray, jax.device_get((ll, iters, hw)))
                dt = time.perf_counter() - t0  # EM-only (synced above)
        if not last_k:
            # Order reduction (gaussian.cu:857-952): dispatch the fused
            # eliminate+scan+merge step immediately, then fetch ALL per-K
            # decision scalars in one blocking sync (each blocking transfer
            # is a full round trip on a remote-TPU link).
            with phase("reduce"):
                next_state, k_active, min_d, pair = elim_reduce_fn(state)
                if timer:
                    k_active_i, min_d_f, pair_i = map(
                        np.asarray, jax.device_get((k_active, min_d, pair))
                    )
                else:
                    (ll_f, iters_i, counts_i, k_active_i, min_d_f,
                     pair_i) = map(
                        np.asarray,
                        jax.device_get((ll, iters, hw, k_active, min_d,
                                        pair)),
                    )
        ll_f = float(ll_f)
        counts_np = np.asarray(counts_i, np.int64)
        if health.word_is_fatal(health.pack_word(counts_np)):
            # The observed fault goes into the totals and the event stream
            # BEFORE recovery overwrites counts_np with the retried run's
            # (usually clean) counters: run_summary.health must record
            # what was seen, recoveries how it was handled.
            health_totals += counts_np
            fatal_word = health.pack_word(counts_np)
            if rec.active:
                rec.emit("health", k=int(k), where="em",
                         flags=int(fatal_word),
                         flag_names=health.flag_names(fatal_word),
                         counters=health.counts_dict(counts_np))
                rec.metrics.count("health_events")
            # Fatal fault: roll back and retry up the escalation ladder
            # (raises NumericalFaultError when recovery is off or the
            # ladder is exhausted). The rung's model is adopted for the
            # rest of the sweep (sticky escalation); the already-dispatched
            # order reduction ran on the poisoned state, so redo it.
            with tl_spans.span("recovery", k=int(k)):
                model, state, ll_f, iters_i, counts_np, ll_log = \
                    health.recover_em(
                        model, config, rollback, chunks, wts, epsilon, k,
                        trajectory=want_traj, rec=rec, log=log,
                        faulty_counts=counts_np)
            n_recoveries += 1
            iters_i = np.asarray(iters_i)
            dt = time.perf_counter() - t0
            if not last_k:
                with phase("reduce"):
                    next_state, k_active, min_d, pair = elim_reduce_fn(state)
                    k_active_i, min_d_f, pair_i = map(
                        np.asarray, jax.device_get((k_active, min_d, pair)))
        if (last_k and config.recovery_reseed_empty and target_num_clusters
                and counts_np[health.EMPTY_CLUSTER] > 0):
            # Target-K fit ended with empty clusters: reseed them from the
            # worst-fit events and refit instead of letting elimination
            # shrink the model below the requested K (opt-in; the
            # reference-style default just eliminates, gaussian.cu:865-874).
            state, ll_f, iters_i, counts_np, ll_log = _reseed_and_refit(
                model, config, state, chunks, wts, epsilon, k,
                want_traj, rec, log,
                (ll_f, iters_i, counts_np, ll_log))
            dt = time.perf_counter() - t0
        health_totals += counts_np
        word = health.pack_word(counts_np)
        if word and rec.active:
            rec.emit("health", k=int(k), where="em", flags=int(word),
                     flag_names=health.flag_names(word),
                     counters=health.counts_dict(counts_np))
            rec.metrics.count("health_events")
        riss = model_score(ll_f, k, n_events, n_dims,
                           criterion=config.criterion,
                           covariance_type=config.covariance_type)
        score_ok = math.isfinite(riss)
        if not score_ok:
            # NaN compares false both ways: an unguarded NaN score could
            # capture the best-model slot at the first K and then never be
            # displaced. Skip the save and record the skip.
            health_totals[health.NONFINITE_SCORE] += 1
            log.warning("non-finite %s score at K=%d; excluded from "
                        "best-model selection", config.criterion, k)
            if rec.active:
                rec.emit("health", k=int(k), where="score",
                         flags=1 << health.NONFINITE_SCORE,
                         flag_names=[
                             health.FLAG_NAMES[health.NONFINITE_SCORE]],
                         counters={health.FLAG_NAMES[
                             health.NONFINITE_SCORE]: 1})
                rec.metrics.count("health_events")
        if not (timer or last_k):  # fused path: EM + reduce until ll on host
            dt = time.perf_counter() - t0
        if timer:
            timer.counts["e_step"] += int(iters_i) - 1  # per-iter averages
        sweep_log.append((k, ll_f, riss, int(iters_i), dt))
        em_walls.append(dt)
        if verbose:
            print(f"K={k}: loglik={ll_f:.6e} {config.criterion}={riss:.6e} "
                  f"iters={int(iters_i)} ({dt:.2f}s)")
        metrics_line("em_done", k=k, loglik=ll_f, score=riss,
                     criterion=config.criterion,
                     iters=int(iters_i), seconds=round(dt, 4)) if (
                         config.enable_debug) else None
        if rec.active:
            rec.metrics.count("em_iters", int(iters_i))
            rec.metrics.gauge("active_k", int(k))
            rec.metrics.series("active_k", int(k))
            _emit_em_iters(rec, k, ll_log, int(iters_i), dt, epsilon, model)
            rec.emit("em_done", k=int(k), loglik=ll_f, score=float(riss),
                     criterion=config.criterion, iters=int(iters_i),
                     seconds=round(dt, 6))
            rec.heartbeat("sweep", k=int(k))

        if score_ok and (
            k == num_clusters
            or (riss < min_rissanen and target_num_clusters == 0)
            or k == target_num_clusters
        ):  # gaussian.cu:839, NaN-score-guarded (health.NONFINITE_SCORE)
            min_rissanen, ideal_k = riss, k
            best_state, best_ll = state, ll_f

        if last_k:
            break
        k = int(k_active_i)
        if k < 2:
            break
        if verbose:
            print(f"non-empty clusters: {k}; merging closest pair")
        if not np.isfinite(float(min_d_f)):
            # No valid merge pair (degenerate covariances everywhere); stop
            # the sweep rather than corrupt the state.
            log.warning("no valid merge pair at K=%d; stopping sweep", k)
            break
        if rec.active:
            # ``pair``: the merged clusters' positions in the compacted
            # (post-elimination) ordering -- stable across rebucketing,
            # unlike raw padded-slot indices (eliminate_and_reduce).
            rec.emit("merge", k_active=int(k), next_k=int(k) - 1,
                     min_distance=float(min_d_f),
                     pair=[int(pair_i[0]), int(pair_i[1])])
            rec.metrics.count("merges")
        state = next_state
        k -= 1

        if bucketing:
            cur_w = int(state.num_clusters_padded)
            target = bucket_width(k, cur_w, multiple=bucket_mult)
            if target < cur_w:
                # Crossed a bucket boundary: rebuild the state at the
                # narrower padded width on device (state.compact_to). The
                # next EM call compiles once per NEW width and every K
                # inside the bucket reuses it.
                with phase("memcpy"):
                    state = model.rebucket_state(state, target)
                n_rebuckets += 1
                log.debug("rebucket: k=%d width %d -> %d", k, cur_w,
                          int(state.num_clusters_padded))
                if rec.active:
                    rec.metrics.count("rebuckets")
                    rec.emit("rebucket", k_active=int(k), from_width=cur_w,
                             to_width=int(state.num_clusters_padded))

        if ckpt is not None:
            rec.metrics.count("checkpoint_saves") if rec.active else None
            with tl_spans.span("checkpoint", step=int(step)), phase("cpu"):
                ckpt.save(step, {
                    "state": _host_state(state, model),
                    "best_state": _host_state(best_state, model),
                    "min_rissanen": float(min_rissanen),
                    "ideal_k": int(ideal_k),
                    "best_ll": float(best_ll),
                    "k": int(k),
                    "num_clusters": int(num_clusters),
                    "criterion_code": _CRITERION_CODE[config.criterion],
                    "cov_code": _COV_CODE[config.covariance_type],
                    "sweep_log": np.asarray(sweep_log, np.float64),
                    # Centering shift for `gmm export --checkpoint`
                    # (serving/registry.py).
                    "data_shift": np.asarray(shift, np.float64),
                })
        step += 1

    tl_spans.end(sweep_span)
    tl_profiling.wm_end(sweep_wm)
    with phase("memcpy"):
        compact_state, n_active = compact(best_state)
    if verbose:
        # Exact reference wording for the default criterion (gaussian.cu:962).
        print(f"Final {config.criterion} score was: {min_rissanen}, "
              f"with {ideal_k} clusters.")

    health_section = health.health_summary(
        health_totals, recoveries=n_recoveries,
        io_retries=(ckpt.io_retries if ckpt is not None else 0))
    # Training drift envelope (rev v2.4): one extra scoring pass over
    # the device-resident chunks through the final parameters. Lazy
    # (pipelined) sources are skipped -- their chunks are a consumed
    # stream, not a resident array (backfill: gmm drift
    # --rebuild-envelope).
    envelope = None
    if config.envelope and not hasattr(chunks, "close"):
        n_local = (host_range[1] - host_range[0] if host_range
                   else n_events)
        envelope = compute_envelope(model, compact_state, chunks,
                                    n_local, n_active)
    _emit_run_summary(
        rec, config, timer, sweep_log, n_active,
        float(min_rissanen), float(best_ll), em_walls,
        em_backend=getattr(model, "estep_backend", None),
        buckets=dict(
            mode=(config.sweep_k_buckets if bucketing else "off"),
            em_widths=sorted(set(em_widths), reverse=True),
            em_compiles=len(set(em_widths)),
            rebuckets=n_rebuckets,
        ),
        health_section=health_section,
        envelope=envelope)
    if hasattr(chunks, "close") and getattr(model, "_restart_cache",
                                            None) is None:
        # Pipelined ingestion owner: stop the prefetch worker and emit
        # ingest_summary. Under restarts the cache (and close) belongs to
        # _fit_with_restarts, which reuses the source across inits.
        chunks.close()
    return GMMResult(
        state=compact_state,
        ideal_num_clusters=n_active,
        min_rissanen=float(min_rissanen),
        final_loglik=best_ll,
        epsilon=epsilon,
        num_events=n_events,
        num_dimensions=n_dims,
        data_shift=np.asarray(shift),
        sweep_log=sweep_log,
        profile=timer.as_dict() if timer else None,
        profile_report=timer.report() if timer else None,
        host_range=host_range,
        health=health_section,
        envelope=envelope,
        model=model,
    )


def _host_state(state, model):
    """Fully host-local numpy copy of a (possibly multi-host global) state.

    Under a multi-controller runtime the EM state is a global sharded array
    (replicated across the data axis, cluster axis within one host), which
    ``jax.device_get`` cannot fetch directly; convert each host's view to a
    host-local array first. Already-host trees (a restored checkpoint) pass
    through untouched.
    """
    leaves = jax.tree_util.tree_leaves(state)
    needs_convert = jax.process_count() > 1 and any(
        isinstance(l, jax.Array) and not l.is_fully_addressable
        for l in leaves
    )
    if needs_convert:
        from jax.experimental import multihost_utils

        from ..parallel.mesh import state_pspecs

        state = multihost_utils.global_array_to_host_local_array(
            state, model.mesh, state_pspecs()
        )
    return jax.device_get(state)


def _seed_rows(data, source, num_clusters, n_dims, n_events, dtype, *,
               seed_method, seed, init_means=None):
    """One restart's K seed rows in ORIGINAL data coordinates.

    The single row recipe behind every init: ``init_means`` verbatim, the
    kmeans++ D^2-weighted draw (deterministic per ``seed``), or the
    reference's evenly-spaced rows. Shared by ``_prepare_fit`` and the
    batched restart driver (models/restarts.py) so the batched path's
    per-restart seeds are bit-identical to the sequential path's by
    construction, never by parallel maintenance.
    """
    from ..ops.seeding import (
        kmeanspp_from_pool, kmeanspp_pool, seed_means_indices,
    )

    if init_means is not None:
        rows = np.asarray(init_means, dtype)
        if rows.shape != (num_clusters, n_dims):
            raise ValueError(
                f"init_means must be [{num_clusters}, {n_dims}], got "
                f"{rows.shape}")
        return rows
    if seed_method == "kmeans++":
        pool, rng = kmeanspp_pool(n_events, seed=seed)
        x_pool = np.asarray(
            source.read_rows(pool) if source is not None else data[pool]
        )
        return x_pool[kmeanspp_from_pool(x_pool, num_clusters, rng)]
    # 'even': float32 index math of gaussian.cu:110-121
    idx = np.asarray(seed_means_indices(n_events, num_clusters))
    return np.asarray(
        source.read_rows(idx) if source is not None else data[idx]
    )


def _data_fingerprint(data, source, sample_weight):
    """Identity key guarding the restart cache against stale device arrays.

    The cache hangs off the MODEL, so a model reused across fits with
    different data must never be served the previous fit's uploaded
    chunks: the fingerprint ties the cached upload to the input object
    (id), its shape, and its dtype, plus the sample_weight's identity.
    (id() alone can be recycled after gc -- shape/dtype narrow that hole
    to byte-compatible arrays, and the restart cache is fit-scoped in
    normal use; the guard is for models shared across fits.)
    """
    obj = source if source is not None else data
    shape = tuple(obj.shape)
    dtype = str(getattr(obj, "dtype", ""))
    w = (None if sample_weight is None
         else (id(sample_weight), tuple(np.asarray(sample_weight).shape)))
    # The effective world is part of the data identity: an elastic shrink
    # changes every survivor's host_chunk_bounds slice, so device arrays
    # uploaded under the old world must never serve the refit.
    return (id(obj), shape, dtype, w, elastic.world())


def _prepare_fit(data, num_clusters, config, model, phase, log,
                 init_means=None, sample_weight=None, skip_seeding=False):
    """Load, center, seed, chunk, and place the data -- one path for all
    four cases (ndarray or FileSource input x single- or multi-process run).

    ``init_means`` ([K, D], original data coordinates) overrides the seeding
    policy with user-supplied starting means (sklearn's means_init; composes
    with ``GaussianMixture.from_summary`` to refine a saved model with more
    EM). Covariances/weights still start from the reference's seed recipe
    (identity-scale R, uniform pi).

    Multi-process (the reference's MPI world, gaussian.cu:128-207): each host
    reads ONLY its chunk-aligned slice (``host_chunk_bounds``), global moments
    come from a chunk-ordered cross-host reduction (bit-identical for every
    process count), seed rows are fetched identically everywhere, and the
    global sharded arrays are assembled with zero cross-host data movement
    (``prepare(host_local=True)``) -- replacing the reference's
    read-on-rank-0 + MPI_Bcast-the-whole-dataset (gaussian.cu:186-207).
    """
    from ..ops.seeding import seed_state_from_parts
    from ..parallel.distributed import global_moments, host_chunk_bounds

    # The EFFECTIVE world: the elastic overlay when a shrink was sealed
    # (survivor index / survivor count -- host_chunk_bounds then re-shards
    # the full event range over the survivors), the launch runtime
    # otherwise. Collectives must agree with it (elastic.py).
    elastic.assert_world_coherent()
    pid, nproc = elastic.world()
    source = data if hasattr(data, "read_range") else None
    dtype = np.dtype(config.dtype)
    if nproc > 1 and not hasattr(model, "prepare"):
        raise ValueError(
            "multi-controller runs require a sharded model (a mesh over all "
            "hosts' devices); pass mesh_shape or let fit_gmm default it"
        )

    if sample_weight is not None and source is not None:
        raise ValueError(
            "sample_weight requires in-memory event data (FileSource/"
            "streamed inputs carry no weight column)")

    pipelined = config.stream_events and config.ingest == "pipelined"
    if pipelined and source is None:
        raise ValueError(
            "ingest='pipelined' reads per-block byte ranges from a file "
            "source; an in-memory array is already resident -- pass a "
            "path/FileSource or keep ingest='resident'")

    # n_init > 1 restarts fit the SAME data repeatedly: _fit_with_restarts
    # hangs a one-fit-scoped cache off the shared model so the load,
    # validation, moments, chunk build, and -- the expensive part -- the
    # host->device upload all happen once, and restarts 1..n-1 reuse the
    # device-resident chunk arrays. Only the seeding (seed-dependent) and
    # the per-restart state placement run again.
    cache = getattr(model, "_restart_cache", None)
    fingerprint = _data_fingerprint(data, source, sample_weight)
    prepared = cache.get("prepared") if cache is not None else None
    if prepared is not None and cache.get("fingerprint") != fingerprint:
        # The model was reused with DIFFERENT data while its restart
        # cache was live: serving the previous fit's device arrays would
        # silently fit the wrong dataset. Drop the stale entry.
        prepared = None
        cache.pop("prepared", None)
    lazy_source = None
    if prepared is not None:
        (chunks, wts, chunks_np, wts_np, n_events, n_dims, shift,
         start, stop, var_mean) = prepared
    elif pipelined:
        # Out-of-core prologue (io/pipeline.py): never materialize the
        # host slice. One pass of per-chunk range reads builds the SAME
        # per-chunk moments partials and the SAME single collective
        # validation decision as the resident path below, then the lazy
        # block source replaces the chunk arrays -- peak host memory is
        # O(queue_depth x block) for the whole fit.
        with phase("cpu"):
            n_events, n_dims = source.shape
            data_axis = getattr(model, "data_size", 1)
            start, stop, num_chunks = host_chunk_bounds(
                n_events, config.chunk_size, data_axis, pid, nproc
            )
        from ..io.pipeline import PipelinedBlockSource, streamed_moments

        with phase("mpi"):
            mean64, var64 = streamed_moments(
                source, start, stop, config.chunk_size, num_chunks,
                validate=config.validate_input,
                collective=nproc > 1, dtype=dtype)
        with phase("cpu"):
            if config.center_data:
                shift = mean64.astype(dtype)
            else:
                shift = np.zeros((n_dims,), dtype)
            var_mean = float(var64.mean())
            s_local = (getattr(model, "_local_data_size", 1)
                       if getattr(model, "mesh", None) is not None else 1)
            chunks_np = wts_np = None
            prior = cache.get("lazy_source") if cache is not None else None
            if (prior is not None and prior.source is source
                    and not prior._closed
                    and prior.chunk_size == config.chunk_size):
                # An elastic refit over the same file: re-seek the live
                # source to the survivor's new host_chunk_bounds range
                # (readers' metadata cache and file handle survive)
                # instead of reopening it.
                prior.reseek(start=start, stop=stop,
                             num_chunks=num_chunks,
                             local_data_size=s_local)
                lazy_source = prior
            else:
                lazy_source = PipelinedBlockSource(
                    source, start=start, stop=stop,
                    chunk_size=config.chunk_size, num_chunks=num_chunks,
                    local_data_size=s_local,
                    shift=(shift if config.center_data else None),
                    dtype=dtype, queue_depth=config.ingest_queue_depth)
            if cache is not None:
                cache["lazy_source"] = lazy_source
    else:
        with phase("cpu"):
            if source is not None:
                n_events, n_dims = source.shape
            else:
                data = np.ascontiguousarray(data)
                n_events, n_dims = data.shape
            data_axis = getattr(model, "data_size", 1)
            start, stop, num_chunks = host_chunk_bounds(
                n_events, config.chunk_size, data_axis, pid, nproc
            )
            local = (source.read_range(start, stop) if source is not None
                     else data[start:stop])
            local = np.ascontiguousarray(local)
            local_weight = None
            if sample_weight is not None:
                sample_weight = np.asarray(sample_weight, np.float64)
                if sample_weight.shape != (n_events,):
                    raise ValueError(
                        f"sample_weight must be [{n_events}], got "
                        f"{sample_weight.shape}")
                if (not np.isfinite(sample_weight).all()
                        or (sample_weight < 0).any()):
                    raise InvalidInputError(
                        "sample_weight must be finite and nonnegative")
                total_w = float(sample_weight.sum())
                if total_w < num_clusters:
                    # Weights are event multiplicities; the absolute Nk
                    # thresholds (> 0.5 / >= 1, reference semantics) would
                    # classify every cluster as empty and return a silently
                    # degenerate model. (Every rank sees the full weight
                    # array, so this decision is identical without a
                    # collective.)
                    raise InvalidInputError(
                        f"sample_weight sums to {total_w:.4g} < num_clusters="
                        f"{num_clusters}: weights are event multiplicities, "
                        "not probabilities -- scale them up (e.g. multiply "
                        "normalized weights by the event count)")
                local_weight = sample_weight[start:stop]
        # Before ANY arithmetic touches the data (the moments would just
        # launder NaNs into the shift): reject rows non-finite now or after
        # the cast to the compute dtype.
        if config.validate_input:
            validate_finite(local, start, collective=nproc > 1, dtype=dtype)

        with phase("mpi"):  # cross-host allgather of tiny per-chunk partials
            mean64, var64 = global_moments(local, config.chunk_size,
                                           num_chunks)

        with phase("cpu"):
            # Global centering keeps the expanded quadratic form
            # well-conditioned (shift-equivariant: EM on x-c equals EM on x,
            # means shifted by c).
            if config.center_data:
                shift = mean64.astype(dtype)
            else:
                shift = np.zeros((n_dims,), dtype)
            local = local.astype(dtype, copy=False)
            if config.center_data:
                local = local - shift[None, :]
            var_mean = float(var64.mean())
            chunks_np, wts_np = chunk_events(
                local, config.chunk_size, num_chunks=num_chunks,
                sample_weight=(None if local_weight is None
                               else local_weight.astype(local.dtype)),
            )

    state = None
    if not skip_seeding:
        with phase("cpu"):
            # Seed rows fetched in ORIGINAL coordinates, identically on
            # every host (net reference semantics: device seeding
            # overwritten by the host full-data reseed, gaussian.cu:
            # 108-123). Per restart (the seed changes); everything above
            # this point is restart-invariant. The batched restart driver
            # passes skip_seeding=True and runs this same recipe itself,
            # once per restart lane (models/restarts.py).
            rows = _seed_rows(data, source, num_clusters, n_dims, n_events,
                              dtype, seed_method=config.seed_method,
                              seed=config.seed, init_means=init_means)
            state = seed_state_from_parts(
                np.asarray(rows, dtype) - np.asarray(shift, dtype)[None, :],
                n_events, var_mean, num_clusters,
                covariance_dynamic_range=config.covariance_dynamic_range,
                dtype=dtype,
            )
            # Deterministic singular-covariance injection (testing.faults):
            # applied to the host state BEFORE mesh placement, so every
            # execution path sees the identical poisoned seed.
            state = faults.maybe_poison_state(state)

    rec = telemetry.current()
    if lazy_source is not None:
        lazy_source.emit_start(rec, em_mode=config.em_mode)
    with phase("memcpy"):
        if prepared is not None:
            # Restart: the chunk arrays are already device-resident (or
            # host-prepared, streaming); only the fresh seed state needs
            # placement. Every model with a prepare() also has
            # prepare_state() (the checkpoint-restore contract).
            if state is not None and hasattr(model, "prepare_state"):
                state = model.prepare_state(
                    jax.tree_util.tree_map(jnp.asarray, state))
        elif hasattr(model, "prepare"):  # sharded path: pad K, place on mesh
            place = state
            if place is None:
                # skip_seeding (batched restarts): the data still needs
                # its mesh placement; a throwaway zero state stands in
                # for prepare()'s state argument and is discarded.
                from ..state import zeros_state

                place = zeros_state(num_clusters, n_dims, dtype)
            placed, chunks, wts = model.prepare(
                place,
                (lazy_source if lazy_source is not None else chunks_np),
                wts_np, host_local=(nproc > 1)
            )
            state = placed if state is not None else None
        else:
            chunks, wts = jnp.asarray(chunks_np), jnp.asarray(wts_np)
    if prepared is None:
        if rec.active and not config.stream_events:
            # Streaming keeps the chunks host-side and accounts its
            # transfers per flushed block instead
            # (StreamingGMMModel._estep_all).
            rec.metrics.count("h2d_bytes", int(np.asarray(chunks_np).nbytes)
                              + int(np.asarray(wts_np).nbytes))
        if cache is not None:
            cache["prepared"] = (
                chunks, wts, chunks_np, wts_np, n_events, n_dims,
                np.asarray(shift), start, stop, var_mean)
            cache["fingerprint"] = fingerprint
    return (state, chunks, wts, chunks_np, wts_np, n_events, n_dims,
            np.asarray(shift), (start, stop))


def _fit_with_restarts(data, num_clusters, target_num_clusters, config,
                       model, verbose, init_means=None, sample_weight=None):
    """n_init independent fits, keep the best Rissanen (capability upgrade;
    the reference's single deterministic init showed local-optima misses).

    Init 0 runs with the user's ``seed_method`` (so the deterministic
    reference init stays in the candidate pool and n_init strictly dominates
    a single-init run); restarts 1..n-1 vary the kmeans++ seed (restarting
    the deterministic 'even' seeding would repeat init 0). The same model
    instance is reused across restarts so compiled executables are shared.
    """
    log = get_logger(config)
    if config.seed_method != "kmeans++":
        log.info("n_init=%d: init 0 uses seed_method=%r, restarts use "
                 "'kmeans++'", config.n_init, config.seed_method)
    if model is None:  # one model => executables shared across restarts
        if config.stream_events:
            from .streaming import StreamingGMMModel

            model = StreamingGMMModel(config)
        elif config.mesh_shape is not None or jax.process_count() > 1:
            from ..parallel import ShardedGMMModel

            model = ShardedGMMModel(config)
        else:
            model = GMMModel(config)

    from .restarts import fit_restarts_batched, resolve_restart_batch_size

    batch_size = resolve_restart_batch_size(config, model, data,
                                            num_clusters, log=log)
    if batch_size > 1:
        # Single-dispatch batched restarts: vmapped seeding + EM over the
        # n_init axis (models/restarts.py). restart_batch_size=1 (or an
        # unsupported path) keeps the sequential loop below -- the
        # degenerate case the batched driver is winner-parity-tested
        # against.
        return fit_restarts_batched(
            data, num_clusters, target_num_clusters, config, model,
            verbose, init_means=init_means, sample_weight=sample_weight,
            batch_size=batch_size)

    best = None
    best_i = None
    init_scores = []  # per-init best criterion score (restart_select)
    rec = telemetry.current()
    # One fit-scoped data cache on the shared model: init 0 prepares (and
    # uploads) the chunked events once, restarts reuse the device-resident
    # arrays (_prepare_fit). try/finally so an aborted restart can never
    # leak a stale cache into a later fit with different data.
    model._restart_cache = {}
    try:
        for i in range(config.n_init):
            if rec.active:
                # The restart index tags every record of this init's
                # sub-fit; all inits share one stream (and one run_id).
                rec.set_context(init=i)
                rec.metrics.count("restarts") if i else None
            sub = dataclasses.replace(
                config, n_init=1,
                seed_method=(config.seed_method if i == 0 else "kmeans++"),
                seed=config.seed + i,
                checkpoint_dir=(os.path.join(config.checkpoint_dir,
                                             f"init{i}")
                                if config.checkpoint_dir else None),
            )
            r = fit_gmm(data, num_clusters, target_num_clusters, config=sub,
                        model=model, verbose=verbose,
                        init_means=(init_means if i == 0 else None),
                        sample_weight=sample_weight)
            if verbose:
                print(f"init {i}: {config.criterion}={r.min_rissanen:.6e} "
                      f"K={r.ideal_num_clusters}")
            init_scores.append(float(r.min_rissanen))
            # NaN-safe best pick: a degenerate init (NaN rissanen) must
            # never shadow later finite restarts ('finite < NaN' is False).
            if (best is None or math.isnan(best.min_rissanen)
                    or r.min_rissanen < best.min_rissanen):
                best, best_i = r, i
    finally:
        cached = (model._restart_cache or {}).get("prepared")
        if cached is not None and hasattr(cached[0], "close"):
            # Pipelined ingestion: the lazy block source outlived the
            # per-init fits by design (all inits stream the same file);
            # close it with the cache.
            cached[0].close()
        model._restart_cache = None
    best.init_index = best_i
    if rec.active:
        rec.set_context(init=None)  # clear the tag for any later records
        rec.emit("restart_select", winner=int(best_i),
                 scores=[s if math.isfinite(s) else None
                         for s in init_scores],
                 criterion=config.criterion,
                 mode="sequential", batch_size=1)
    if verbose:
        print(f"best of {config.n_init} inits: "
              f"{config.criterion}={best.min_rissanen:.6e} "
              f"K={best.ideal_num_clusters}")
    return best


def _run_fused_sweep(fused, config, state, chunks, wts, epsilon,
                     num_clusters, stop_number, target_num_clusters,
                     n_events, n_dims, shift, verbose,
                     host_range=None, model=None, ckpt=None, log=None,
                     timer=None):
    """Whole-sweep-on-device path (models/fused_sweep.py): one dispatch,
    one sync. ``fused`` comes from the model's ``make_fused_sweep`` (cached
    there, so passing the same ``model=`` to fit_gmm reuses the executable).
    Reconstructs the host sweep_log from the device log afterward (per-K
    ``seconds`` are the amortized wall time -- individual K timings do not
    exist off-device by design).

    With ``ckpt`` set, each completed K emits its sweep position to the host
    through the fused program's ordered ``io_callback`` hook and is saved as
    a checkpoint; a surviving checkpoint resumes mid-sweep with dynamic
    resume args (same compiled executable shape)."""
    dtype = chunks.dtype

    resume = None
    if ckpt is not None:
        restored = ckpt.restore() if config.resume != "never" else None
        if restored is not None and _resume_mismatch(restored, config, log):
            restored = None
        if (restored is not None
                and int(restored.get("num_clusters", -1)) == num_clusters):
            if "fused_log" not in restored:
                if log:
                    log.warning("found a host-sweep checkpoint; the fused "
                                "sweep cannot resume it -- starting fresh")
            else:
                state = restored["state"]
                best_state_r = restored["best_state"]
                if hasattr(model, "prepare_state"):
                    # Sharded model: pad K to the cluster axis and place the
                    # restored (host-local, replicated-on-every-rank) states
                    # on the mesh; the data chunks were prepared already.
                    state = model.prepare_state(
                        jax.tree_util.tree_map(jnp.asarray, state))
                    best_state_r = model.prepare_state(
                        jax.tree_util.tree_map(jnp.asarray, best_state_r))
                fused_log = np.asarray(restored["fused_log"])
                if fused_log.shape[1] == 4:
                    # Pre-containment checkpoints carry 4-column logs (no
                    # per-K health word); pad so the compiled 5-column
                    # program accepts them (restored Ks read as clean).
                    fused_log = np.concatenate(
                        [fused_log,
                         np.zeros((fused_log.shape[0], 1),
                                  fused_log.dtype)], axis=1)
                resume = dict(
                    best_state=best_state_r,
                    k=int(restored["k"]),
                    step=int(restored["step"]) + 1,
                    best_ll=float(restored["best_ll"]),
                    best_riss=float(restored["best_riss"]),
                    log=fused_log,
                )
                if log:
                    log.info("resumed fused sweep from checkpoint: next "
                             "K=%d (step %d)", resume["k"], resume["step"])
                if verbose:
                    print(f"resumed fused sweep at K={resume['k']}")

    with_emit = ckpt is not None or timer is not None
    emit_times = {}
    if with_emit:
        import threading

        emit_lock = threading.Lock()

        def emit(payload):
            # Arrival time of each per-K emission: real per-K wall seconds
            # for the sweep log / profile (the emission-free fused path can
            # only amortize; individual K timings don't exist off-device).
            # First arrival per step wins: on a sharded model the callback
            # fires once per LOCAL device shard with identical payloads
            # (cluster shards pre-gathered), and since each device's stream
            # is ordered, first arrivals are monotonic in step -- so this
            # dedupe also keeps checkpoint saves in step order and saves
            # exactly once per step per process (orbax coordinates the
            # per-process saves on multi-controller runs).
            step = int(payload["step"])
            with emit_lock:
                # Atomic test-and-set: arrivals from different local
                # devices run on separate callback threads, and two of
                # them racing past an unlocked check would both save.
                if step in emit_times:
                    return
                emit_times[step] = time.perf_counter()
            if ckpt is None or bool(payload["done"]):
                return  # a finished run returns its result right after
            # save_local, NOT save: this runs inside the ordered io_callback
            # while the device program is blocked on its completion -- the
            # collective orbax save would deadlock the job (checkpoint.py
            # module docstring).
            ckpt.save_local(step, {
                "state": payload["state"],
                "best_state": payload["best_state"],
                "k": int(payload["next_k"]),
                "best_ll": float(payload["best_ll"]),
                "best_riss": float(payload["best_riss"]),
                "fused_log": np.asarray(payload["log"]),
                "num_clusters": int(num_clusters),
                "criterion_code": _CRITERION_CODE[config.criterion],
                "cov_code": _COV_CODE[config.covariance_type],
                # Centering shift for `gmm export --checkpoint`
                # (serving/registry.py).
                "data_shift": np.asarray(shift, np.float64),
            })
            sup = supervisor.current()
            if sup.active and sup.stop_requested:
                # The fused program's only host intervention point is this
                # per-K emission: with this step's checkpoint durable,
                # aborting the device program here is the graceful exit
                # (per-K granularity -- a single device program has no
                # mid-EM poll). The raise surfaces at the fused() call
                # below, where it is converted to the preemption exit.
                sup._emit_preempt(where="fused_emit", k=None,
                                  em_iter=None)
                raise supervisor.PreemptedError(
                    "fused sweep stopped at per-K emission",
                    reason=sup.stop_reason or "unknown", step=step,
                    checkpointed=True)

        model._emit_target = emit

    t0 = time.perf_counter()
    args = [
        state, chunks, wts,
        jnp.asarray(epsilon, dtype),
        jnp.asarray(config.min_iters, jnp.int32),
        jnp.asarray(config.max_iters, jnp.int32),
    ]
    if with_emit:
        args.append(resume)
    try:
        (best_state, best_ll, best_riss, log_rows, steps,
         health_counts) = fused(*args)
        (best_state, best_ll, best_riss, log_rows, steps,
         health_counts) = jax.device_get(
            (best_state, best_ll, best_riss, log_rows, steps, health_counts)
        )
    except Exception as e:
        # A cooperative stop raised inside the emission callback aborts
        # the device program; the runtime may surface it as its own error
        # type, so re-derive the preemption from the supervisor state.
        sup = supervisor.current()
        if sup.active and sup.stop_requested:
            try:
                # Drain the aborted program's effect tokens now (they hold
                # the callback's exception) so interpreter exit does not
                # trip over them in jax's atexit hook.
                jax.effects_barrier()
            except Exception:
                pass
            rec_ = telemetry.current()
            if rec_.active:
                rec_.emit("shutdown", reason=sup.stop_reason or "unknown",
                          checkpointed=bool(ckpt is not None and emit_times))
            sup.raise_stop(
                step=(max(emit_times) if emit_times else None),
                checkpointed=bool(ckpt is not None and emit_times))
        raise
    finally:
        if with_emit:
            model._emit_target = None
    wall = time.perf_counter() - t0
    health_counts = np.asarray(health_counts, np.int64)

    steps = int(steps)
    rec = telemetry.current()
    word = health.pack_word(health_counts)
    if health.word_is_fatal(word):
        rows_f = np.asarray(log_rows)[:steps]
        k_fatal = int(rows_f[-1][0]) if steps else int(num_clusters)
        if rec.active:
            rec.emit("health", k=k_fatal, where="fused_sweep",
                     flags=int(word), flag_names=health.flag_names(word),
                     counters=health.counts_dict(health_counts))
            rec.metrics.count("health_events")
        if config.recovery != "retry":
            raise health.NumericalFaultError(
                f"numerical fault in the fused sweep at K={k_fatal} "
                f"(flags={health.flag_names(word)}) and recovery is "
                f"{config.recovery!r}",
                health.fault_bundle(health_counts, k=k_fatal,
                                    where="fused_sweep", config=config))
        if rec.active:
            rec.emit("recovery", k=k_fatal, attempt=1,
                     action="host_fallback", outcome="rerun",
                     flags=int(word),
                     flag_names=health.flag_names(word))
            rec.metrics.count("recovery_attempts")
        if log is not None:
            log.warning("fused sweep hit %s at K=%d",
                        health.flag_names(word), k_fatal)
        # Hand the observed counters back: the caller falls back to the
        # host-driven sweep and folds them into its run_summary.health.
        return health_counts
    per_k = wall / max(steps, 1)
    # With emission on, each step's host arrival time gives REAL per-K
    # seconds (delta from the previous emission; the first new step is
    # measured from dispatch, which includes any compile). Restored steps
    # keep the amortized per_k.
    step_secs = {}
    if with_emit:
        # Drain the ordered io_callback queue before reading emit_times:
        # device_get blocks on the ARRAYS, not on host-callback completion.
        jax.effects_barrier()
        prev = t0
        for s in sorted(emit_times):
            step_secs[s] = emit_times[s] - prev
            prev = emit_times[s]
    sweep_log = [
        (int(row[0]), float(row[1]), float(row[2]), int(row[3]),
         step_secs.get(i, per_k))
        for i, row in enumerate(np.asarray(log_rows)[:steps])
    ]
    if verbose:
        for k_, ll_, riss_, it_, _ in sweep_log:
            print(f"K={k_}: loglik={ll_:.6e} {config.criterion}={riss_:.6e} "
                  f"iters={it_} (fused)")
    compact_state, n_active = compact(best_state)
    if verbose:
        print(f"Final rissanen score was: {float(best_riss)}, "
              f"with {n_active} clusters.")  # gaussian.cu:962

    profile = profile_report = None
    if timer is not None:
        # Fused attribution: each K's whole span (EM + its order-reduction)
        # lands in e_step; the finer 7-category split needs host-observed
        # phase boundaries, which a single device program doesn't have.
        rows = np.asarray(log_rows)
        for i, dt in sorted(step_secs.items()):
            timer.add("e_step", dt, count=int(rows[i][3]))
        profile = timer.as_dict()
        profile_report = (
            timer.report()
            + "\n  (fused sweep: whole-K spans attributed to e_step)"
        )

    health_section = health.health_summary(health_counts)
    envelope = None
    if config.envelope and not hasattr(chunks, "close"):
        n_local = (host_range[1] - host_range[0] if host_range
                   else n_events)
        envelope = compute_envelope(model, compact_state, chunks,
                                    n_local, n_active)
    if rec.active:
        # The fused device program exposes per-K granularity only (its EM
        # iterations never touch the host), so the stream carries em_done
        # records -- with REAL per-K seconds from the emission arrivals --
        # but no em_iter rows; docs/OBSERVABILITY.md documents the gap.
        # Each K's packed health word rides the device log (column 4);
        # nonzero words become health records here.
        per_k_words = [int(row[4]) for row in np.asarray(log_rows)[:steps]]
        for (k_, ll_, riss_, it_, secs_), word_k in zip(sweep_log,
                                                        per_k_words):
            rec.metrics.count("em_iters", int(it_))
            rec.metrics.series("active_k", int(k_))
            rec.emit("em_done", k=int(k_), loglik=float(ll_),
                     score=float(riss_), criterion=config.criterion,
                     iters=int(it_), seconds=round(float(secs_), 6))
            if word_k:
                rec.emit("health", k=int(k_), where="em", flags=word_k,
                         flag_names=health.flag_names(word_k))
                rec.metrics.count("health_events")
        _emit_run_summary(rec, config, timer, sweep_log, n_active,
                          float(best_riss), float(best_ll),
                          [s for _, s in sorted(step_secs.items())],
                          health_section=health_section,
                          em_backend=getattr(model, "estep_backend", None),
                          envelope=envelope)

    return GMMResult(
        state=compact_state,
        ideal_num_clusters=n_active,
        min_rissanen=float(best_riss),
        final_loglik=float(best_ll),
        epsilon=epsilon,
        num_events=n_events,
        num_dimensions=n_dims,
        data_shift=np.asarray(shift),
        sweep_log=sweep_log,
        profile=profile,
        profile_report=profile_report,
        host_range=host_range,
        health=health_section,
        envelope=envelope,
        model=model,
    )


_fallback_model_cache: "collections.OrderedDict" = collections.OrderedDict()


def _fallback_model(config: GMMConfig) -> GMMModel:
    """Per-config LRU cache (8 slots) for the bare-``config`` output path, so
    a result that carries no fitted model (e.g. unpickled) pays the
    posteriors jit once per config instead of once per ``iter_memberships``
    call -- bounded so a config sweep cannot pin executables forever."""
    cache = _fallback_model_cache
    model = cache.get(config)
    if model is None:
        model = cache[config] = GMMModel(config)
        while len(cache) > 8:
            cache.popitem(last=False)
    else:
        cache.move_to_end(config)
    return model


def iter_memberships(
    result: GMMResult, data: np.ndarray, config: GMMConfig = GMMConfig(),
    model: Optional[GMMModel] = None,
):
    """Yield ``(data_block, posteriors_block)`` per chunk, original coords.

    The streaming producer behind the ``.results`` output path: each block is
    sliced, shifted, and padded individually, and its posteriors recomputed
    from the final parameters -- peak host memory is one block's [B, D] +
    [B, K] regardless of N (SURVEY.md SS7 "memberships at scale": the
    reference gathers the whole N x K matrix to rank 0, gaussian.cu:783-823).

    Reuses the fitted model carried on ``result`` (already-compiled
    posteriors executable) when no ``model`` is passed; a result from a
    foreign source gets a per-config cached fallback model.
    """
    model = model or getattr(result, "model", None) or _fallback_model(config)
    dtype = np.dtype(config.dtype)
    n, d = data.shape
    # Sharded models process one chunk PER LOCAL DEVICE per dispatch.
    B = getattr(model, "inference_block", config.chunk_size)
    shift = np.asarray(result.data_shift, dtype)[None, :]
    state = result.state
    for lo in range(0, n, B):
        block = data[lo:lo + B]
        valid = block.shape[0]
        xb = block.astype(dtype, copy=False) - shift
        if valid < B:  # pad the tail block to the jitted block shape
            xb = np.concatenate([xb, np.zeros((B - valid, d), dtype)])
        # Pass the host block straight through: infer_posteriors does its own
        # placement (a sharded model device_puts with the data-axis sharding;
        # an eager jnp.asarray here would commit to one device first and pay
        # a second device->device reshard).
        w, _ = model.infer_posteriors(state, xb)
        w_host = np.asarray(jax.device_get(w))[:valid]
        rec = telemetry.current()
        if rec.active:
            rec.metrics.count("d2h_bytes", int(w_host.nbytes))
        yield block, w_host


def compute_memberships(
    result: GMMResult, data: np.ndarray, config: GMMConfig = GMMConfig(),
    model: Optional[GMMModel] = None,
) -> np.ndarray:
    """Posteriors [N, K_final] for output -- recomputed from the saved params.

    Bit-equivalent to the reference's saved memberships (the EM loop ends on an
    E-step, so the stored memberships ARE the posteriors of the final params;
    gaussian.cu:713-714, 768). Materialized variant of ``iter_memberships``.
    """
    model = model or getattr(result, "model", None) or _fallback_model(config)
    blocks = [w for _, w in iter_memberships(result, data, config, model)]
    if not blocks:
        return np.zeros((0, result.state.num_clusters_padded),
                        np.dtype(config.dtype))
    return np.concatenate(blocks, axis=0)
