"""GMM-EM model: the jitted EM loop for a fixed (masked) cluster count.

TPU-native collapse of the reference's L4 layer (the EM while-loop,
``gaussian.cu:479-755``): where the reference crosses the device<->host boundary
~10x and the network 4x per iteration (SURVEY.md SS3.2), here the ENTIRE loop --
initial E-step, M-step, constants, E-step, convergence test -- is one
``lax.while_loop`` inside one jit compilation, with zero host round-trips for a
full K's worth of EM. Sufficient statistics are reduced across devices by a
caller-supplied ``reduce_stats`` hook (``jax.lax.psum`` under ``shard_map``; the
TPU-native replacement of the reference's OpenMP+MPI_Allreduce staging,
``gaussian.cu:550-659``).

Loop semantics match ``gaussian.cu:525-755`` exactly:
  change = 2*epsilon initially (:525)
  while iters < MIN_ITERS or (|change| > epsilon and iters < MAX_ITERS): (:532)
      params  <- M-step(stats)  + constants                (:541-701)
      stats   <- fused E-step(params); loglik = stats.loglik (:713-741)
      change  = loglik - old_loglik                         (:748)
The returned state's N/pi come from the final M-step and the returned loglik
from the final E-step, exactly like the reference's post-loop device copy
(:759-768).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

from .. import health
from ..config import GMMConfig
from ..ops.mstep import SuffStats, accumulate_stats, apply_mstep
from ..ops.estep import posteriors
from ..telemetry import profiling as tl_profiling
from ..testing import faults


ReduceFn = Callable[[SuffStats], SuffStats]


def cached_fused_sweep(model, static: dict, build: Callable):
    """Per-model memoization of the jitted whole-sweep executable (a fresh
    jax.jit closure per fit would retrace+recompile every call)."""
    cache = model.__dict__.setdefault("_fused_sweep_cache", {})
    key = tuple(sorted(static.items()))
    fn = cache.get(key)
    if fn is None:
        fn = cache[key] = build()
    return fn


def resolve_iters(config: GMMConfig, min_iters: Optional[int],
                  max_iters: Optional[int]):
    """Iteration bounds as dynamic int32 args (no recompile on change)."""
    return (
        jnp.asarray(config.min_iters if min_iters is None else min_iters,
                    jnp.int32),
        jnp.asarray(config.max_iters if max_iters is None else max_iters,
                    jnp.int32),
    )


def resolve_iters_batched(config: GMMConfig, num_restarts: int,
                          min_iters, max_iters):
    """Per-restart iteration bounds as dynamic int32 [R] vectors.

    Scalars (or None -> the config's values) broadcast to every restart;
    per-restart vectors pass through. A restart whose ``max_iters`` is 0
    runs zero EM iterations -- the batched drivers' freeze-out handle for
    converged / dropped restarts (the loop condition is false from the
    start, so its lane's carry passes through untouched).
    """
    lo = config.min_iters if min_iters is None else min_iters
    hi = config.max_iters if max_iters is None else max_iters
    lo = jnp.broadcast_to(jnp.asarray(lo, jnp.int32), (num_restarts,))
    hi = jnp.broadcast_to(jnp.asarray(hi, jnp.int32), (num_restarts,))
    return jnp.minimum(lo, hi), hi


def chunk_events(
    data: np.ndarray, chunk_size: int, num_shards: int = 1,
    num_chunks: Optional[int] = None,
    sample_weight: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad and reshape events to [num_chunks, chunk_size, D] plus a weight row.

    The reference splits events into 16-aligned ranges per thread block
    (gaussian_kernel.cu:367-381) and pushes the remainder onto the last block;
    on TPU we need fully static shapes, so we pad to a whole number of chunks
    (x num_shards) and mask the tail instead.

    ``num_chunks`` forces the exact padded chunk count -- multi-host loading
    uses it so every host produces the same-shaped chunk array regardless of
    how the event remainder fell across hosts
    (``parallel.distributed.host_chunk_bounds``).

    ``sample_weight`` ([n] nonnegative) replaces the 0/1 validity mask with
    per-event weights (padding rows stay 0). The fused E+M pass multiplies
    responsibilities and log-evidence by this row, which makes every
    sufficient statistic exactly weighted -- an integer weight w is
    identical to replicating the event w times.
    """
    n, d = data.shape
    if sample_weight is not None and np.asarray(sample_weight).shape != (n,):
        raise ValueError(
            f"sample_weight must be [{n}], got "
            f"{np.asarray(sample_weight).shape}")
    if num_chunks is not None:
        total = num_chunks * chunk_size
        if total < n:
            raise ValueError(
                f"num_chunks={num_chunks} x chunk_size={chunk_size} < {n} events"
            )
        if num_chunks % max(num_shards, 1):
            raise ValueError(
                f"num_chunks={num_chunks} not divisible by num_shards={num_shards}"
            )
    else:
        step = chunk_size * num_shards
        total = n + ((-n) % step)
    padded = np.zeros((total, d), dtype=data.dtype)
    padded[:n] = data
    wts = np.zeros((total,), dtype=data.dtype)
    wts[:n] = 1.0 if sample_weight is None else sample_weight
    num_chunks = total // chunk_size
    return padded.reshape(num_chunks, chunk_size, d), wts.reshape(num_chunks, chunk_size)


class GMMModel:
    """EM for a Gaussian mixture with fixed padded K; active clusters masked.

    All jit-compiled entry points are built once per (shape, config) and reused
    across the whole model-order sweep -- changing the active mask does NOT
    recompile (the mask is a traced array), which is the main idiomatic
    departure from the reference's realloc/compact design (SURVEY.md SS7.3).
    """

    # The plain model's fused sweep supports per-K host emission (the
    # io_callback checkpoint hook); the sharded model's does not (callbacks
    # under shard_map observe per-device shards).
    supports_fused_emit = True
    # Bucket widths must be a multiple of this (the cluster-mesh axis
    # extent on sharded models; 1 = any width).
    bucket_multiple = 1
    # Batched n_init restarts (models/restarts.py): the EM loop vmaps
    # over a leading restart axis. Streaming overrides this off (its EM
    # is a host-driven per-block loop with no single program to vmap).
    supports_batched_restarts = True

    def __init__(self, config: GMMConfig = GMMConfig(),
                 reduce_stats: Optional[ReduceFn] = None,
                 stats_fn: Optional[Callable] = None):
        self.config = config
        self.reduce_stats = reduce_stats
        self._emit_target = None  # host sink for fused-sweep per-K emission
        # Health counters of the most recent run_em (device int32
        # [health.NUM_FLAGS]): the EM loop computes them in-carry and
        # run_em stashes them here, keeping the (state, loglik, iters)
        # return contract intact for existing callers.
        self.last_health = None

        kw = dict(
            diag_only=config.diag_only,
            quad_mode=config.quad_mode,
            matmul_precision=config.matmul_precision,
        )
        self._kw = kw

        if stats_fn is None:
            from ..ops.pallas import (
                make_batched_stats_fn, make_mstep_fn, make_stats_fn,
                resolve_estep_backend,
            )

            # Resolved E-step backend + reason: what actually runs (the
            # telemetry stream's em_backend field -- a silent jnp fallback
            # away from a requested kernel is observable, not invisible).
            self.estep_backend, self.estep_backend_reason = \
                resolve_estep_backend(config)
            stats_fn = make_stats_fn(config)
            # Batched (leading restart axis) kernel + fused M-step
            # epilogue hooks; None routes through vmap / apply_mstep.
            self.batched_stats_fn = make_batched_stats_fn(config)
            self._mstep_fn = make_mstep_fn(config)
            self._mstep_fn_batched = make_mstep_fn(config, batched=True)
        else:
            self.estep_backend = "custom"
            self.estep_backend_reason = "caller-supplied stats_fn"
            self.batched_stats_fn = None
            self._mstep_fn = self._mstep_fn_batched = None
        self.stats_fn = stats_fn

        # EM executables are memoized per (trajectory_len, donate) variant
        # (cached_fused_sweep-style); within one variant jax.jit's own
        # shape-keyed cache memoizes per padded width, so a bucketed sweep
        # compiles one EM program per distinct bucket and reuses it for
        # every K inside that bucket.
        self._em_exec_cache: dict = {}
        self._em_run = self._em_executable(0, False)
        self._estep_stats = jax.jit(
            functools.partial(self._estep_stats_impl, reduce_stats=reduce_stats,
                              stats_fn=stats_fn, **kw)
        )
        self._posteriors = jax.jit(
            functools.partial(
                posteriors,
                diag_only=kw["diag_only"],
                quad_mode=kw["quad_mode"],
                matmul_precision=kw["matmul_precision"],
            )
        )

    @staticmethod
    def _estep_stats_impl(state, data_chunks, wts_chunks, *, reduce_stats=None,
                          stats_fn=None, **kw):
        if stats_fn is not None:
            stats = stats_fn(state, data_chunks, wts_chunks)
        else:
            stats = accumulate_stats(state, data_chunks, wts_chunks, **kw)
        return reduce_stats(stats) if reduce_stats else stats

    def _em_executable(self, trajectory_len: int, donate: bool):
        """Memoized jitted EM loop for one (trajectory, donation) variant."""
        key = (trajectory_len, donate)
        fn = self._em_exec_cache.get(key)
        if fn is None:
            # ProfiledExecutable (rev v2.2): a transparent proxy -- plain
            # jit dispatch with no CompileWatch active, explicit timed
            # AOT lower+compile (cost/memory introspection) under one.
            fn = self._em_exec_cache[key] = tl_profiling.ProfiledExecutable(
                jax.jit(
                    functools.partial(
                        em_while_loop, reduce_stats=self.reduce_stats,
                        stats_fn=self.stats_fn, mstep_fn=self._mstep_fn,
                        covariance_type=self.config.covariance_type,
                        precompute_features=self.config.precompute_features,
                        trajectory_len=trajectory_len,
                        dynamic_range=self.config.covariance_dynamic_range,
                        regression_scale=self.config.health_regression_scale,
                        **self._kw),
                    donate_argnums=(0,) if donate else (),
                ),
                site="em")
        return fn

    def run_em(self, state, data_chunks, wts_chunks, epsilon: float,
               min_iters: Optional[int] = None, max_iters: Optional[int] = None,
               *, trajectory: bool = False, donate: bool = False):
        """Full EM at the current active-K. Returns (state, loglik, iters).

        ``min_iters``/``max_iters`` override the config's values without
        recompiling (they are dynamic args of the jitted loop) -- e.g. a
        1-iteration warmup call on the same executable the real run uses.

        ``trajectory=True`` (telemetry paths) uses a separately compiled
        variant that also returns the device-captured per-iteration loglik
        log (``em_while_loop`` ``trajectory_len`` contract, sized to the
        config's ``max_iters``): return becomes (state, loglik, iters,
        ll_log).

        ``donate=True`` donates the INPUT state's buffers to the call
        (``donate_argnums``): the EM carry reuses them in place, cutting
        peak HBM and copy traffic by one state-size. The caller must not
        touch the input state afterwards (it is deleted on backends that
        support donation) -- the model-order sweep opts in because its
        carry is rebound every K; default off so library callers keep the
        safe aliasing-free semantics.

        The run's health counters (non-finite loglik/params, regressions,
        sanitized lanes...; health.py lane table) land on
        ``self.last_health`` as a device int32 vector -- the return tuple
        keeps its historical shape.
        """
        lo, hi = resolve_iters(self.config, min_iters, max_iters)
        run = self._em_executable(
            int(self.config.max_iters) if trajectory else 0, donate)
        out = run(
            state, data_chunks, wts_chunks,
            jnp.asarray(epsilon, data_chunks.dtype), lo, hi,
        )
        self.last_health = out[-1]
        return out[:-1]

    def run_em_resumable(self, state, data_chunks, wts_chunks, epsilon,
                         min_iters: Optional[int] = None,
                         max_iters: Optional[int] = None, *,
                         poll_iters: int = 25,
                         should_stop: Optional[Callable[[int], bool]] = None,
                         block_stop: Optional[Callable] = None,
                         resume: Optional[dict] = None,
                         donate: bool = False):
        """Reference EM semantics in host-polled segments (supervisor.py).

        The single-dispatch ``run_em`` gives the host no intervention point
        for 100 iterations; here the SAME compiled executable runs in
        segments of ``poll_iters`` iterations (``min_iters``/``max_iters``
        are dynamic args, so no recompile), and between segments the host
        polls ``should_stop(done_iters)`` -- the supervisor's cooperative
        stop flag -- and applies the loop's own NaN-safe continuation
        predicate. Each boundary re-runs one E-step on the carried state
        (estep of an unchanged state is deterministic, so the iteration
        sequence -- and the final model -- is bit-identical to the
        single-dispatch loop; the ~1/poll_iters extra E-steps are the price
        of preemptibility). ``resume={"em_iter": i, "em_lls": [...]}``
        restarts at iteration ``i`` from a restored mid-EM state.

        Returns ``(state, loglik, iters, ll_log, stopped, extra)``:
        ``ll_log`` follows ``em_while_loop``'s trajectory contract
        ([config.max_iters + 1], NaN-padded); ``stopped`` is True when
        ``should_stop`` tripped (the state is the segment-boundary state to
        checkpoint); ``extra`` carries path-specific resume payload keys
        (empty here; the streaming override adds its block accumulator).
        Health counters accumulate across segments onto ``last_health``
        (boundary re-E-steps recount state-derived lanes, so non-fatal
        counters can read slightly higher than a single-dispatch run's;
        fatal semantics are identical). ``block_stop`` is accepted for
        interface parity with the streaming override and ignored.
        """
        lo, hi = resolve_iters(self.config, min_iters, max_iters)
        lo, hi = int(lo), int(hi)
        eps_f = abs(float(epsilon))
        inj = faults.peek("preempt")
        inj_iter = None
        if inj is not None and "iter" in inj \
                and int(inj.get("block", -1)) == -1:
            inj_iter = int(inj["iter"])

        done = 0
        lls: list = []
        if resume:
            done = int(resume.get("em_iter", 0))
            lls = [float(x) for x in
                   np.asarray(resume.get("em_lls", ())).reshape(-1)]
        counts_total = np.zeros((health.NUM_FLAGS,), np.int64)
        stopped = False
        while True:
            if lls:  # boundary continuation test == the device cond
                if done >= hi:
                    break
                if done >= lo and len(lls) >= 2 \
                        and abs(lls[-1] - lls[-2]) <= eps_f:
                    break
            seg_end = min(done + max(int(poll_iters), 1), hi)
            if inj_iter is not None and done < inj_iter < seg_end:
                # Clamp the segment so a poll lands exactly on the armed
                # preempt iteration (deterministic injection contract).
                seg_end = inj_iter
            seg_max = seg_end - done
            seg_min = min(max(lo - done, 0), seg_max)
            state, ll, iters, ll_log = self.run_em(
                state, data_chunks, wts_chunks, epsilon,
                min_iters=seg_min, max_iters=seg_max,
                trajectory=True, donate=donate)
            seg_iters = int(jax.device_get(iters))
            seg_lls = np.asarray(jax.device_get(ll_log), np.float64)
            counts_seg = np.asarray(jax.device_get(self.last_health),
                                    np.int64)
            counts_total += counts_seg
            if lls:
                # Slot 0 re-derives the previous segment's final loglik
                # (the boundary E-step); keep only the new iterations.
                lls.extend(float(x) for x in seg_lls[1:seg_iters + 1])
            else:
                lls.extend(float(x) for x in seg_lls[:seg_iters + 1])
            done += seg_iters
            if health.word_is_fatal(health.pack_word(counts_seg)):
                break  # the caller's recovery ladder takes it from here
            if should_stop is not None and should_stop(done):
                stopped = True
                break
            if seg_iters < seg_max or seg_max == 0:
                break  # device exited early: converged inside the segment
        self.last_health = jnp.asarray(
            np.minimum(counts_total, np.iinfo(np.int32).max), jnp.int32)
        buf = np.full((int(self.config.max_iters) + 1,), np.nan, np.float64)
        n = min(len(lls), buf.shape[0])
        buf[:n] = lls[:n]
        ll_out = lls[-1] if lls else float("nan")
        # The exact-length trajectory rides the stop payload so the
        # emergency checkpoint stores precisely the completed iterations.
        extra = {"em_lls": np.asarray(lls, np.float64)} if stopped else {}
        return state, ll_out, done, buf, stopped, extra

    def _em_batched_executable(self, trajectory_len: int, donate: bool):
        """Memoized jitted BATCHED EM loop: ``em_while_loop`` vmapped over
        a leading restart axis (state + per-restart iteration bounds
        batched; the chunked data, weights, and epsilon are shared --
        closure-captured by the vmapped function, so XLA computes every
        data-derived value once, not per restart).

        ``lax.while_loop``'s batching rule is the masked freeze-out: the
        loop runs until EVERY restart's condition is false, and finished
        restarts' carries are frozen via ``select`` -- a converged (or
        fatal, or ``max_iters=0``-frozen) restart stops updating while its
        siblings keep iterating. One executable serves every restart batch
        of equal shape (jit's shape-keyed cache, same contract as the
        per-K executables).

        With the Pallas backend (``batched_stats_fn`` set) the vmap is
        replaced by ``em_while_loop_batched``: the SAME freeze-out
        semantics, but each iteration's statistics for ALL R restarts are
        one batched kernel launch (grid restarts x event tiles) and the
        M-step update runs in the fused epilogue kernel -- one kernel
        round-trip per iteration for the whole batch."""
        key = ("batched", trajectory_len, donate)
        fn = self._em_exec_cache.get(key)
        if fn is None:
            if self.batched_stats_fn is not None:
                fn = tl_profiling.ProfiledExecutable(jax.jit(
                    functools.partial(
                        em_while_loop_batched,
                        batched_stats_fn=self.batched_stats_fn,
                        mstep_fn=self._mstep_fn_batched,
                        reduce_stats=self.reduce_stats,
                        covariance_type=self.config.covariance_type,
                        trajectory_len=trajectory_len,
                        dynamic_range=self.config.covariance_dynamic_range,
                        regression_scale=(
                            self.config.health_regression_scale),
                        **self._kw),
                    donate_argnums=(0,) if donate else ()),
                    site="em_batched")
                self._em_exec_cache[key] = fn
                return fn
            em_fn = functools.partial(
                em_while_loop, reduce_stats=self.reduce_stats,
                stats_fn=self.stats_fn,
                covariance_type=self.config.covariance_type,
                precompute_features=self.config.precompute_features,
                trajectory_len=trajectory_len,
                dynamic_range=self.config.covariance_dynamic_range,
                regression_scale=self.config.health_regression_scale,
                **self._kw)

            def batched(states, rids, data_chunks, wts_chunks, epsilon,
                        lo_r, hi_r):
                run_one = lambda s, rid, lo, hi: em_fn(
                    s, data_chunks, wts_chunks, epsilon, lo, hi,
                    restart_id=rid)
                return jax.vmap(run_one, in_axes=(0, 0, 0, 0))(
                    states, rids, lo_r, hi_r)

            fn = self._em_exec_cache[key] = tl_profiling.ProfiledExecutable(
                jax.jit(batched, donate_argnums=(0,) if donate else ()),
                site="em_batched")
        return fn

    def run_em_batched(self, states, data_chunks, wts_chunks, epsilon: float,
                       min_iters=None, max_iters=None, *,
                       trajectory: bool = False, donate: bool = False,
                       r_bucket: Optional[int] = None):
        """Full EM for a BATCH of restarts in one dispatch.

        ``states`` is a GMMState whose every leaf carries a leading
        restart axis R (models/restarts.py builds it from the vmapped
        seeding). ``min_iters``/``max_iters`` accept scalars or [R]
        vectors -- a restart with ``max_iters=0`` is frozen (zero
        iterations, state passed through bit-identically), which is how
        the drivers keep finished restarts inert inside a live batch.

        ``r_bucket`` pads the batch UP to that many lanes with frozen
        duplicates of lane 0 (``max_iters=0``: zero iterations, outputs
        sliced back to R) so a ragged tail batch reuses the full-size
        batch's compiled executable instead of tracing a second one --
        the R-bucket half of the batched-executable memoization
        (K/D/dtype/precision are keyed by jit's shape cache and the
        kernel's static args). Live lanes' iteration sequences are
        unaffected: a frozen pad lane never holds the while-loop open.

        Returns ``(states, loglik [R], iters [R])`` (+ ``ll_log [R,
        max_iters+1]`` with ``trajectory=True``); per-restart health
        counters land on ``last_health`` as int32 [R, NUM_FLAGS] -- one
        poisoned restart flags its own row only, so the restart driver
        can drop it and keep the survivors (health.py drop-one contract).
        """
        R = int(states.N.shape[0])
        lo_r, hi_r = resolve_iters_batched(self.config, R, min_iters,
                                           max_iters)
        pad = 0
        if r_bucket is not None and int(r_bucket) > R:
            pad = int(r_bucket) - R
            states = jax.tree_util.tree_map(
                lambda a: jnp.concatenate(
                    [a, jnp.broadcast_to(a[:1], (pad,) + a.shape[1:])]),
                states)
            frozen = jnp.zeros((pad,), jnp.int32)
            lo_r = jnp.concatenate([lo_r, frozen])
            hi_r = jnp.concatenate([hi_r, frozen])
        run = self._em_batched_executable(
            int(self.config.max_iters) if trajectory else 0, donate)
        out = run(states, jnp.arange(R + pad, dtype=jnp.int32),
                  data_chunks, wts_chunks,
                  jnp.asarray(epsilon, data_chunks.dtype), lo_r, hi_r)
        if pad:
            out = jax.tree_util.tree_map(lambda a: a[:R], out)
        self.last_health = out[-1]
        return out[:-1]

    def run_em_batched_resumable(self, states, data_chunks, wts_chunks,
                                 epsilon, min_iters: Optional[int] = None,
                                 max_iters: Optional[int] = None, *,
                                 poll_iters: int = 25,
                                 should_stop: Optional[Callable[[int],
                                                               bool]] = None,
                                 freeze=None,
                                 resume: Optional[dict] = None,
                                 donate: bool = False,
                                 r_bucket: Optional[int] = None):
        """Batched sibling of :meth:`run_em_resumable`: the SAME batched
        executable runs in host-polled segments so SIGTERM / deadline are
        observed mid-batch and the emergency checkpoint carries ALL R
        trajectories (supervisor.py contract).

        Per-restart freeze-out spans segments: a restart that converges
        (or goes fatal) inside a segment is frozen for every later one by
        setting its segment ``max_iters`` to 0, so the iteration sequence
        of every restart is bit-identical to the single-dispatch batched
        loop (each boundary re-runs one deterministic E-step, exactly the
        scalar driver's trade). ``freeze`` ([R] bool) pre-freezes lanes
        the caller already finished (the restart sweep's done restarts).

        Returns ``(states, loglik [R], iters [R], ll_logs
        [R, config.max_iters + 1], stopped, extra)``; ``extra`` (on a
        stop) carries the resume payload: NaN-padded per-restart loglik
        rows ``em_lls`` [R, L] with lengths ``em_lens``, plus the
        ``em_frozen`` / ``em_fatal`` masks. Health counters accumulate on
        ``last_health`` as [R, NUM_FLAGS], counting each restart only
        while it was live (frozen lanes' boundary re-E-steps are not
        charged to them).
        """
        R = int(states.N.shape[0])
        lo, hi = resolve_iters(self.config, min_iters, max_iters)
        lo, hi = int(lo), int(hi)
        eps_f = abs(float(epsilon))
        inj = faults.peek("preempt")
        inj_iter = None
        if inj is not None and "iter" in inj \
                and int(inj.get("block", -1)) == -1:
            inj_iter = int(inj["iter"])

        frozen = (np.zeros((R,), bool) if freeze is None
                  else np.asarray(freeze, bool).copy())
        fatal = np.zeros((R,), bool)
        done = 0
        lls: list = [[] for _ in range(R)]
        if resume:
            done = int(resume.get("em_iter", 0))
            rows = np.asarray(resume.get("em_lls", np.zeros((R, 0))),
                              np.float64).reshape(R, -1)
            lens = np.asarray(resume.get("em_lens",
                                         [rows.shape[1]] * R), np.int64)
            lls = [[float(x) for x in rows[r][:int(lens[r])]]
                   for r in range(R)]
            if "em_frozen" in resume:
                frozen |= np.asarray(resume["em_frozen"], bool)
            if "em_fatal" in resume:
                fatal |= np.asarray(resume["em_fatal"], bool)
        counts_total = np.zeros((R, health.NUM_FLAGS), np.int64)
        stopped = False
        while True:
            if any(lls[r] for r in range(R)):
                # Boundary continuation test == the device cond, applied
                # per restart: converged lanes freeze for good.
                for r in range(R):
                    if frozen[r] or not lls[r]:
                        continue
                    if done >= lo and len(lls[r]) >= 2 \
                            and abs(lls[r][-1] - lls[r][-2]) <= eps_f:
                        frozen[r] = True
                if done >= hi or bool(frozen.all()):
                    break
            seg_end = min(done + max(int(poll_iters), 1), hi)
            if inj_iter is not None and done < inj_iter < seg_end:
                # Clamp so a poll lands exactly on the armed preempt
                # iteration (deterministic injection contract).
                seg_end = inj_iter
            seg_max = seg_end - done
            seg_min = min(max(lo - done, 0), seg_max)
            live = ~frozen
            lo_r = np.where(live, seg_min, 0).astype(np.int32)
            hi_r = np.where(live, seg_max, 0).astype(np.int32)
            states, ll_d, iters_d, ll_log_d = self.run_em_batched(
                states, data_chunks, wts_chunks, epsilon,
                min_iters=lo_r, max_iters=hi_r,
                trajectory=True, donate=donate, r_bucket=r_bucket)
            seg_iters = np.asarray(jax.device_get(iters_d), np.int64)
            seg_lls = np.asarray(jax.device_get(ll_log_d), np.float64)
            counts_seg = np.asarray(jax.device_get(self.last_health),
                                    np.int64)
            counts_total[live] += counts_seg[live]
            all_fatal = bool(live.any())
            for r in range(R):
                if not live[r]:
                    continue
                n_r = int(seg_iters[r])
                if lls[r]:
                    # Slot 0 re-derives the previous segment's final
                    # loglik (the boundary E-step); keep the new ones.
                    lls[r].extend(float(x) for x in seg_lls[r][1:n_r + 1])
                else:
                    lls[r].extend(float(x) for x in seg_lls[r][:n_r + 1])
                if health.word_is_fatal(health.pack_word(counts_seg[r])):
                    fatal[r] = frozen[r] = True
                else:
                    all_fatal = False
            done += seg_max
            if all_fatal:
                break  # every live restart poisoned: caller's ladder
            if should_stop is not None and should_stop(done):
                stopped = True
                break
            if seg_max == 0:
                break  # nothing left to run (all lanes pre-frozen)
        self.last_health = jnp.asarray(
            np.minimum(counts_total, np.iinfo(np.int32).max), jnp.int32)
        T = int(self.config.max_iters) + 1
        bufs = np.full((R, T), np.nan, np.float64)
        iters_out = np.zeros((R,), np.int64)
        ll_out = np.full((R,), np.nan, np.float64)
        for r in range(R):
            n = min(len(lls[r]), T)
            bufs[r, :n] = lls[r][:n]
            iters_out[r] = max(len(lls[r]) - 1, 0)
            if lls[r]:
                ll_out[r] = lls[r][-1]
        extra = {}
        if stopped:
            L = max((len(l) for l in lls), default=0)
            em_lls = np.full((R, max(L, 1)), np.nan, np.float64)
            for r in range(R):
                em_lls[r, :len(lls[r])] = lls[r]
            extra = {
                "em_iter": np.int64(done),
                "em_lls": em_lls,
                "em_lens": np.asarray([len(l) for l in lls], np.int64),
                "em_frozen": frozen.astype(np.int8),
                "em_fatal": fatal.astype(np.int8),
            }
        return states, ll_out, iters_out, bufs, stopped, extra

    # Multi-tenant fleet fits (tenancy/; docs/TENANCY.md): the EM loop
    # generalized over a leading DATASET axis -- per-tenant data, weights,
    # epsilon, and iteration bounds instead of the restart axis's shared
    # data. Streaming overrides this off (no single EM program to map).
    supports_fleet = True

    def _em_fleet_executable(self, trajectory_len: int, donate: bool,
                             mode: str):
        """Memoized jitted FLEET EM loop: ``em_while_loop`` mapped over a
        leading tenant axis with PER-TENANT data/weights/epsilon/bounds
        (the dataset-axis generalization of ``_em_batched_executable``,
        whose restart lanes share one dataset).

        ``mode='scan'`` maps lanes with ``lax.map``: one compiled dispatch
        per group whose per-lane arithmetic is the exact HLO of a solo
        ``run_em`` -- tenant results stay BIT-IDENTICAL to solo fits (the
        packed padding is algebraically inert: zero-weight event rows and
        inactive cluster slots contribute exact zeros). ``mode='vmap'``
        batches the lanes instead ([T, B, K] matmuls -- the restart-
        batching throughput shape) at reduction-order tolerance: a batched
        dot_general associates differently than T solo matmuls, so vmap
        trades bit-parity for MXU feed (config.fleet_mode documents the
        trade). Both modes freeze finished lanes -- scan lanes run their
        own while_loop trip counts natively; vmap lanes freeze via
        ``lax.while_loop``'s batching-rule select masks.

        The fleet loop always runs the jnp statistics path (stats_fn=None
        -- the Pallas kernels batch the restart axis over SHARED event
        tiles, which a per-tenant data axis defeats; fit_fleet rejects
        pallas-pinned configs loudly).
        """
        key = ("fleet", mode, trajectory_len, donate)
        fn = self._em_exec_cache.get(key)
        if fn is None:
            em_fn = functools.partial(
                em_while_loop, reduce_stats=self.reduce_stats,
                stats_fn=None,
                covariance_type=self.config.covariance_type,
                precompute_features=False,
                trajectory_len=trajectory_len,
                dynamic_range=self.config.covariance_dynamic_range,
                regression_scale=self.config.health_regression_scale,
                **self._kw)

            def fleet(states, tids, data_chunks, wts_chunks, eps_t,
                      lo_t, hi_t):
                if mode == "vmap":
                    return jax.vmap(
                        lambda s, tid, c, w, e, lo, hi: em_fn(
                            s, c, w, e, lo, hi, restart_id=tid))(
                        states, tids, data_chunks, wts_chunks, eps_t,
                        lo_t, hi_t)
                return lax.map(
                    lambda args: em_fn(args[0], args[2], args[3], args[4],
                                       args[5], args[6],
                                       restart_id=args[1]),
                    (states, tids, data_chunks, wts_chunks, eps_t,
                     lo_t, hi_t))

            fn = self._em_exec_cache[key] = tl_profiling.ProfiledExecutable(
                jax.jit(fleet, donate_argnums=(0,) if donate else ()),
                site="em_fleet")
        return fn

    def run_em_fleet(self, states, data_chunks, wts_chunks, epsilons,
                     min_iters=None, max_iters=None, *,
                     trajectory: bool = False, donate: bool = False,
                     mode: str = "scan"):
        """Full EM for a FLEET of independent datasets in one dispatch.

        ``states`` carries a leading tenant axis T on every leaf;
        ``data_chunks`` [T, C, B, D] / ``wts_chunks`` [T, C, B] hold each
        tenant's own packed chunk grid (zero-weight pad rows beyond its
        true event count); ``epsilons`` [T] each tenant's convergence
        threshold. ``min_iters``/``max_iters`` accept scalars or [T]
        vectors -- a lane with ``max_iters=0`` is frozen (zero iterations,
        state passed through bit-identically), the drivers' handle for
        tenants whose sweep already finished.

        Returns ``(states, loglik [T], iters [T])`` (+ ``ll_log`` with
        ``trajectory=True``); per-tenant health counter ROWS land on
        ``last_health`` as int32 [T, NUM_FLAGS] -- a poisoned tenant flags
        its own row only, so the fleet driver drops it and keeps the
        survivors (the PR-5 drop_restart containment shape).
        """
        T = int(states.N.shape[0])
        lo_t, hi_t = resolve_iters_batched(self.config, T, min_iters,
                                           max_iters)
        run = self._em_fleet_executable(
            int(self.config.max_iters) if trajectory else 0, donate, mode)
        out = run(states, jnp.arange(T, dtype=jnp.int32), data_chunks,
                  wts_chunks, jnp.asarray(epsilons, data_chunks.dtype),
                  lo_t, hi_t)
        self.last_health = out[-1]
        return out[:-1]

    def prepare_fleet(self, data_chunks, wts_chunks):
        """Place one group's packed [T, C, B, D] chunk grid on device
        (the fleet sibling of the plain jnp.asarray data placement)."""
        return jnp.asarray(data_chunks), jnp.asarray(wts_chunks)

    def rebucket_state(self, state, num_clusters: int):
        """Compact ``state`` to a narrower padded width on device (the
        sweep's bucket recompaction; see state.compact_to). Width is
        rounded up to ``bucket_multiple`` by the caller."""
        from ..state import compact_to

        if num_clusters >= state.num_clusters_padded:
            return state
        return compact_to(state, num_clusters)

    def estep_stats(self, state, data_chunks, wts_chunks) -> SuffStats:
        return self._estep_stats(state, data_chunks, wts_chunks)

    def make_fused_sweep(self, with_emit: bool = False,
                         emit_light: bool = False, **static):
        """Jitted whole-sweep-on-device callable (models/fused_sweep.py),
        cached per static config so repeat fits reuse the executable.

        ``with_emit=True`` compiles in the per-K ordered io_callback; the
        actual host sink is read from ``self._emit_target`` at call time, so
        the cached executable is reused across fits with different
        checkpointers. ``emit_light`` emits only the step scalars
        (profiling-only runs skip the per-K state transfer)."""
        from .fused_sweep import fused_sweep

        emit_cb = None
        if with_emit:
            def emit_cb(payload):
                target = self._emit_target
                if target is not None:
                    target(payload)
                # Completion token: fused_sweep threads it into the carry so
                # the device waits for the emission (checkpoint durability).
                return np.int32(0)

        return cached_fused_sweep(
            self, dict(static, with_emit=with_emit, emit_light=emit_light),
            lambda: jax.jit(
                functools.partial(
                    fused_sweep, stats_fn=self.stats_fn,
                    reduce_stats=self.reduce_stats, emit_cb=emit_cb,
                    emit_light=emit_light,
                    covariance_type=self.config.covariance_type,
                    criterion=self.config.criterion,
                    precompute_features=self.config.precompute_features,
                    **self._kw, **static,
                )
            ))

    @property
    def inference_block(self) -> int:
        """Events per output-path dispatch (uniform interface with the
        sharded model, whose block covers all local devices)."""
        return self.config.chunk_size

    def infer_posteriors(self, state, xb):
        """(w [B, K], logZ [B]) for one [inference_block, D] event block."""
        return self._posteriors(state, jnp.asarray(xb))

    def memberships(self, state, data_chunks, return_logz: bool = False):
        """Materialized posteriors [N_padded, K] -- output path only.

        The reference keeps the N x K memberships resident and gathers them per
        K (gaussian.cu:768-823); we recompute them once from the final
        parameters (bit-identical to the last E-step's output, since the loop
        ends on an E-step) and stream chunks to host memory. Padded tail rows
        are garbage; callers slice to the true event count.

        With ``return_logz`` also returns the per-event log evidence
        [N_padded] (estep2's logZ) as a second array.
        """
        w_out, z_out = [], []
        for i in range(data_chunks.shape[0]):
            w, logz = self._posteriors(state, data_chunks[i])
            w_out.append(np.asarray(jax.device_get(w)))
            if return_logz:
                z_out.append(np.asarray(jax.device_get(logz)))
        w = np.concatenate(w_out, axis=0)
        if return_logz:
            return w, np.concatenate(z_out, axis=0)
        return w


def em_while_loop(
    state,
    data_chunks,
    wts_chunks,
    epsilon,
    min_iters,
    max_iters,
    *,
    reduce_stats: Optional[ReduceFn] = None,
    diag_only: bool = False,
    quad_mode: str = "expanded",
    matmul_precision: str = "highest",
    cluster_axis: str | None = None,
    stats_fn: Optional[Callable] = None,
    mstep_fn: Optional[Callable] = None,
    covariance_type: str | None = None,
    precompute_features: bool = False,
    trajectory_len: int = 0,
    dynamic_range: float = 1e3,
    regression_scale: float = 10.0,
    restart_id=None,
):
    """The whole per-K EM algorithm as one traced program.

    ``stats_fn(state, data_chunks, wts_chunks) -> SuffStats`` overrides the
    jnp fused pass -- the hook through which the Pallas TPU kernel
    (ops/pallas/fused_stats.py) replaces XLA-generated code on the hot path.
    ``mstep_fn(state, stats) -> state`` likewise overrides the jnp
    parameter update (apply_mstep + constants) -- the fused M-step
    epilogue kernel rides this hook, so backend 'pallas' completes a full
    EM iteration without a separate XLA M-step dispatch on the
    statistics. ``covariance_type`` selects the M-step covariance
    constraint (ops/mstep.py apply_mstep); the E-step/statistics path is
    shared.

    ``precompute_features`` hoists the [C, B, F] outer-product features out
    of the EM loop: they depend only on the data, so building them once and
    holding them in HBM replaces every iteration's rebuild (a write of
    N x F per iteration) with a read -- the XLA-path candidate for the
    measured xouter-traffic bottleneck (docs/PERF.md). Costs N*F*4 bytes of
    HBM residency (F = D*D expanded, D(D+1)/2 packed -- 2.3 GB vs 1.2 GB at
    the north-star); full-covariance 'expanded'/'packed' only, and a no-op
    under a custom stats_fn (the kernel builds features in VMEM). Results
    are bit-identical either way within a layout (same values through the
    same matmuls).

    ``trajectory_len > 0`` (static) additionally records the per-iteration
    loglik trajectory on device -- the telemetry subsystem's ``em_iter``
    source for paths whose EM loop is a single dispatch (per-iteration
    logliks are otherwise not host-observable). The return gains a fourth
    element ``ll_log`` of shape [trajectory_len + 1]: slot 0 is the initial
    E-step's loglik, slot i+1 iteration i's; unwritten slots are NaN, and
    iterations beyond the buffer are dropped (not an error), so a dynamic
    ``max_iters`` above the static buffer stays safe.

    **Health containment** (health.py): an int32 [NUM_FLAGS] counter
    vector rides the carry -- non-finite loglik/params, loglik regression
    beyond ``regression_scale * epsilon``, empty clusters, covariance
    dynamic-range violations (``dynamic_range``), and the E-step's
    sanitized-lane count (SuffStats.sanitized). FATAL lanes (non-finite
    loglik or params) short-circuit the while-loop condition: a poisoned
    run stops at the iteration the poison became observable instead of
    "converging" through the NaN-compares-false hole the reference has
    (``|change| > epsilon`` is false for NaN change, gaussian.cu:532).
    The convergence predicate itself is also spelled NaN-safe
    (``~(|change| <= epsilon)`` treats a non-finite change as
    NOT-converged). The counters are appended as the LAST element of the
    return tuple; on a sharded mesh they come out replicated (psum-OR
    aggregation: events over ``data`` through the stats psum, clusters
    over ``cluster`` inside health.state_counts).
    """
    kw = dict(diag_only=diag_only, quad_mode=quad_mode,
              matmul_precision=matmul_precision, cluster_axis=cluster_axis)

    # Deterministic fault injection (testing.faults): consumed at TRACE
    # time, so the armed executable reproduces the fault on every reuse
    # while a rebuilt (recovery-escalated) model traces clean. A
    # ``restart``-keyed plan targets ONE lane of the batched restart loop
    # (``restart_id`` is the vmapped per-restart index there); it never
    # fires in a loop that has no restart axis.
    _inj_nan = faults.peek("nan_loglik")
    if _inj_nan is not None and "restart" in _inj_nan and restart_id is None:
        _inj_nan = None
    else:
        _inj_nan = faults.take("nan_loglik")
    _inj_nan_iter = int(_inj_nan["iter"]) if _inj_nan else None
    _inj_nan_restart = (int(_inj_nan["restart"])
                        if _inj_nan and "restart" in _inj_nan else None)

    feats = None
    if (precompute_features and stats_fn is None and not diag_only
            and quad_mode in ("expanded", "packed")):
        from ..ops.estep import expand_features, pack_features

        # The hoisted layout follows quad_mode: [C, B, D*D] flattened outer
        # products for 'expanded', [C, B, D(D+1)/2] upper-triangle products
        # for 'packed' (~52% of the expanded residency) -- each built by the
        # SAME function the inline path uses, which is what makes the
        # per-layout bit-identity contract hold.
        fe = pack_features if quad_mode == "packed" else expand_features
        feats = jax.vmap(fe)(data_chunks)

    def estep(s) -> SuffStats:
        if stats_fn is not None:
            stats = stats_fn(s, data_chunks, wts_chunks)
        else:
            stats = accumulate_stats(s, data_chunks, wts_chunks,
                                     feats_chunks=feats, **kw)
        return reduce_stats(stats) if reduce_stats else stats

    def health_counts(s, stats, ll, ll_prev=None):
        reg_tol = (regression_scale * jnp.asarray(epsilon)
                   if ll_prev is not None else None)
        return (
            health.em_iter_counts(ll, ll_prev, reg_tol)
            + health.state_counts(s, Nk=stats.Nk,
                                  dynamic_range=dynamic_range,
                                  cluster_axis=cluster_axis)
            + jnp.zeros((health.NUM_FLAGS,), jnp.int32)
                 .at[health.SANITIZED_LANES]
                 .set(stats.sanitized.astype(jnp.int32))
        )

    stats0 = estep(state)  # initial E-step (gaussian.cu:487-516)
    change0 = jnp.asarray(2.0, stats0.loglik.dtype) * epsilon + 1.0  # :525
    if trajectory_len:
        ll_log0 = jnp.full((trajectory_len + 1,), jnp.nan,
                           stats0.loglik.dtype)
        ll_log0 = ll_log0.at[0].set(stats0.loglik)
    else:
        ll_log0 = jnp.zeros((0,), stats0.loglik.dtype)
    h0 = health_counts(state, stats0, stats0.loglik)
    carry0 = (state, stats0, stats0.loglik, change0,
              jnp.asarray(0, jnp.int32), ll_log0, h0)

    def cond(carry):
        _, _, _, change, iters, _, h = carry
        # Fatal health flags short-circuit the loop: iterating on a
        # poisoned carry only launders the NaN deeper into the model.
        # ~(|change| <= eps) is the NaN-safe spelling of |change| > eps: a
        # non-finite change reads as NOT converged (gaussian.cu:532's
        # predicate is false for NaN, which made the reference "converge"
        # on poison at min_iters).
        return (~health.fatal(h)) & (
            (iters < min_iters) | (
                ~(jnp.abs(change) <= epsilon) & (iters < max_iters))
        )

    def body(carry):
        s, stats, ll_old, _, iters, ll_log, h = carry
        if mstep_fn is not None:
            s = mstep_fn(s, stats)  # fused epilogue kernel (:541-701)
        else:
            s = apply_mstep(s, stats, diag_only=diag_only,
                            cluster_axis=cluster_axis,
                            covariance_type=covariance_type)  # :541-701
        stats_new = estep(s)  # :713-741
        ll = stats_new.loglik
        if _inj_nan_iter is not None:
            hit = iters + 1 == _inj_nan_iter
            if _inj_nan_restart is not None and restart_id is not None:
                hit = hit & (restart_id == _inj_nan_restart)
            ll = jnp.where(hit, jnp.asarray(jnp.nan, ll.dtype), ll)
        if trajectory_len:
            # mode='drop': dynamic max_iters can exceed the static buffer.
            ll_log = ll_log.at[iters + 1].set(ll, mode="drop")
        h = h + health_counts(s, stats_new, ll, ll_old)
        return (s, stats_new, ll, ll - ll_old, iters + 1, ll_log,
                h)  # :748-751

    s, _, ll, _, iters, ll_log, h = lax.while_loop(cond, body, carry0)
    if trajectory_len:
        return s, ll, iters, ll_log, h
    return s, ll, iters, h


def em_while_loop_batched(
    states,
    rids,
    data_chunks,
    wts_chunks,
    epsilon,
    min_iters_r,
    max_iters_r,
    *,
    batched_stats_fn: Callable,
    mstep_fn: Optional[Callable] = None,
    reduce_stats: Optional[ReduceFn] = None,
    diag_only: bool = False,
    quad_mode: str = "expanded",
    matmul_precision: str = "highest",
    cluster_axis: str | None = None,
    covariance_type: str | None = None,
    trajectory_len: int = 0,
    dynamic_range: float = 1e3,
    regression_scale: float = 10.0,
):
    """Restart-batched EM as ONE explicit while-loop over the whole batch.

    The hand-written equivalent of ``jax.vmap(em_while_loop)``'s batched
    while-loop (same masked freeze-out: the loop runs until every lane's
    condition is false, finished lanes' carries are frozen via per-lane
    ``where``), restructured so the per-iteration work is BATCHED calls
    instead of a vmapped body:

      - statistics: ``batched_stats_fn(states, chunks, wts, lane_mask)``
        -- the leading-R Pallas kernel (ops/pallas/fused_stats.py), one
        launch covering every restart with the event data read once; the
        per-lane freeze-out mask is folded into the kernel's event mask
        so frozen/fatal lanes contribute exact zeros;
      - M-step: ``mstep_fn(states, stats)`` -- the fused epilogue kernel
        over the restart grid (falls back to vmapped ``apply_mstep`` for
        covariance families the kernel does not cover).

    The iteration semantics (per-lane min/max bounds, NaN-safe
    convergence, fatal-health short-circuit, trajectory capture, fault
    injection by restart index, per-lane [R, NUM_FLAGS] health rows)
    mirror ``em_while_loop`` exactly -- the batched-restart drivers call
    either loop through the same ``run_em_batched`` contract and must not
    be able to tell them apart except by speed.
    """
    R = states.N.shape[0]
    kw = dict(diag_only=diag_only, quad_mode=quad_mode,
              matmul_precision=matmul_precision, cluster_axis=cluster_axis)

    if mstep_fn is None:
        mstep_fn = jax.vmap(functools.partial(
            apply_mstep, diag_only=diag_only, cluster_axis=cluster_axis,
            covariance_type=covariance_type))

    # Deterministic fault injection: the batched loop always has a restart
    # axis, so restart-keyed plans target one lane by index (the mirror of
    # em_while_loop's restart_id contract under vmap).
    _inj_nan = faults.take("nan_loglik")
    _inj_nan_iter = int(_inj_nan["iter"]) if _inj_nan else None
    _inj_nan_restart = (int(_inj_nan["restart"])
                        if _inj_nan and "restart" in _inj_nan else None)

    def estep(ss, lane_mask=None):
        stats = batched_stats_fn(ss, data_chunks, wts_chunks,
                                 lane_mask=lane_mask)
        return reduce_stats(stats) if reduce_stats else stats

    zeros_h = jnp.zeros((health.NUM_FLAGS,), jnp.int32)

    def _h_lane(s, stats_lane, ll, ll_prev, reg_tol):
        return (
            health.em_iter_counts(ll, ll_prev, reg_tol)
            + health.state_counts(s, Nk=stats_lane.Nk,
                                  dynamic_range=dynamic_range,
                                  cluster_axis=cluster_axis)
            + zeros_h.at[health.SANITIZED_LANES]
                     .set(stats_lane.sanitized.astype(jnp.int32))
        )

    h0_fn = jax.vmap(lambda s, st, ll: _h_lane(s, st, ll, None, None))
    reg_tol = regression_scale * jnp.asarray(epsilon)
    hstep_fn = jax.vmap(
        lambda s, st, ll, llp: _h_lane(s, st, ll, llp, reg_tol))

    stats0 = estep(states)  # initial E-step, all lanes live
    ll0 = stats0.loglik                                   # [R]
    change0 = jnp.full((R,), 2.0, ll0.dtype) * epsilon + 1.0  # :525
    if trajectory_len:
        ll_log0 = jnp.full((R, trajectory_len + 1), jnp.nan, ll0.dtype)
        ll_log0 = ll_log0.at[:, 0].set(ll0)
    else:
        ll_log0 = jnp.zeros((R, 0), ll0.dtype)
    h0 = h0_fn(states, stats0, ll0)                       # [R, NUM_FLAGS]
    carry0 = (states, stats0, ll0, change0,
              jnp.zeros((R,), jnp.int32), ll_log0, h0)

    def live_lanes(carry):
        _, _, _, change, iters, _, h = carry
        fatal = jax.vmap(health.fatal)(h)                 # [R] bool
        # The per-lane spelling of em_while_loop's cond (NaN-safe
        # convergence, fatal short-circuit), against per-lane bounds.
        return (~fatal) & (
            (iters < min_iters_r) | (
                ~(jnp.abs(change) <= epsilon) & (iters < max_iters_r))
        )

    def cond(carry):
        return jnp.any(live_lanes(carry))

    def body(carry):
        s, stats, ll_old, _, iters, ll_log, h = carry
        live = live_lanes(carry)
        s_new = mstep_fn(s, stats)                        # :541-701, batched
        stats_new = estep(s_new, lane_mask=live)          # :713-741, batched
        ll = stats_new.loglik
        if _inj_nan_iter is not None:
            hit = iters + 1 == _inj_nan_iter
            if _inj_nan_restart is not None:
                hit = hit & (rids == _inj_nan_restart)
            ll = jnp.where(hit, jnp.asarray(jnp.nan, ll.dtype), ll)
        if trajectory_len:
            # mode='drop': dynamic max_iters can exceed the static buffer.
            ll_log = jax.vmap(
                lambda lg, i, v: lg.at[i + 1].set(v, mode="drop"))(
                    ll_log, iters, ll)
        h = h + hstep_fn(s_new, stats_new, ll, ll_old)
        new = (s_new, stats_new, ll, ll - ll_old, iters + 1, ll_log, h)

        def sel(n, o):
            m = live.reshape((R,) + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)

        # Masked freeze-out: finished lanes keep their carry untouched.
        return jax.tree_util.tree_map(sel, new, carry)

    s, _, ll, _, iters, ll_log, h = lax.while_loop(cond, body, carry0)
    if trajectory_len:
        return s, ll, iters, ll_log, h
    return s, ll, iters, h
