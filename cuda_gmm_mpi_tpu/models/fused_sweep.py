"""The entire model-order search as ONE jitted device program.

The reference's K-sweep is a host loop: per K it runs 100 EM iterations on
the GPUs, copies the model up, scores/saves on the host, scans merge pairs
on the host with an O(D^3) CPU inversion per pair, and broadcasts the merged
model back (``gaussian.cu:479-960``). The host-driven sweep in
``order_search.fit_gmm`` already collapses each of those phases into jitted
calls with one sync per K; this module goes the rest of the way: EM loops,
Rissanen scoring, best-model tracking, empty-cluster elimination, pair
scans, and merges for EVERY K run inside a single ``lax.while_loop`` -- zero
host round-trips between the initial dispatch and the final result. On a
remote-TPU link (or any high-latency dispatch path) this removes the last
per-K latency. Per-K checkpointing and (coarse) profiling compose via the
ordered ``io_callback`` emission hook (``emit_cb``/``resume``, round 3) --
whole-K spans are attributed to e_step, since finer phase boundaries are
not host-observable inside one device program. The telemetry subsystem
rides the same hook: an active RunRecorder turns emission on so the
``em_done`` records carry REAL per-K seconds (emission arrival deltas);
per-iteration ``em_iter`` records do not exist on this path by design --
the EM iterations never touch the host (docs/OBSERVABILITY.md). Opt-in
fast path (``GMMConfig.fused_sweep``); the host loop remains the default.

FIXED-WIDTH BY DESIGN: the fused sweep runs every K at the starting padded
width. Bucket recompaction (``sweep_k_buckets``, order_search's
cluster-width shrinking as K drops) needs shape changes between Ks, which
a single jitted ``lax.while_loop`` cannot express -- so the fused path
trades the ~2x sweep-level FLOP saving for its zero-host-round-trip
dispatch. The right pick is latency-dependent: host-driven + bucketed when
compute dominates (CPU, large N/K), fused when per-K dispatch latency
dominates (remote-TPU links, small per-K work).

Semantics match the host sweep exactly (same save rule gaussian.cu:839, same
termination conditions); parity is asserted in tests/test_fused_sweep.py.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.experimental
import jax.numpy as jnp
from jax import lax

from .. import health
from ..ops.formulas import model_score
from ..ops.merge import eliminate_and_reduce
from .gmm import em_while_loop


def fused_sweep(
    state,
    data_chunks,
    wts_chunks,
    epsilon,
    min_iters,
    max_iters,
    resume=None,
    *,
    start_k: int,
    stop_number: int,
    target_k: int,
    num_events: int,
    num_dimensions: int,
    diag_only: bool = False,
    quad_mode: str = "expanded",
    matmul_precision: str = "highest",
    cluster_axis: str | None = None,
    covariance_type: str | None = None,
    criterion: str = "rissanen",
    stats_fn: Optional[Callable] = None,
    reduce_stats: Optional[Callable] = None,
    reduce_order_fn: Optional[Callable] = None,
    emit_cb: Optional[Callable] = None,
    emit_light: bool = False,
    emit_gather_fn: Optional[Callable] = None,
    precompute_features: bool = False,
    dynamic_range: float = 1e3,
    regression_scale: float = 10.0,
):
    """Run the whole K-sweep on device.

    Returns ``(best_state, best_ll, best_riss, log, steps, health)`` where
    ``log`` is a [start_k, 5] array of per-K rows ``(k, loglik, rissanen,
    em_iters, health_word)`` (rows beyond ``steps`` are zero) and
    ``health`` the sweep's cumulative int32 counter vector (health.py).
    A FATAL per-K health word (non-finite loglik/params -- em_while_loop
    already short-circuited that K's EM) also stops the sweep: iterating
    the order reduction on a poisoned state only spreads the poison, and
    the host driver recovers by falling back to the host-driven sweep's
    rollback-and-retry ladder (a single device program has no per-K host
    intervention point). A non-finite score can never capture the
    best-model slot (NaN compares false both ways, so an unguarded
    step-0 save or a poisoned ``<`` would silently corrupt selection --
    the NONFINITE_SCORE health lane records the skip).

    ``reduce_order_fn(state) -> (new_state, k_active, min_d)`` overrides the
    order-reduction step -- the hook through which the cluster-sharded path
    substitutes an all-gather-then-reslice variant (the pair scan needs the
    full K-state; see parallel/sharded_em.py).

    ``emit_cb(payload)`` is an optional HOST callback invoked (via ordered
    ``io_callback``) once per completed K with the sweep position -- the hook
    through which --fused-sweep composes with per-K checkpointing without
    giving up the one-dispatch design. ``resume`` restores a mid-sweep
    position emitted by a previous run's ``emit_cb``: a dict with
    ``best_state`` (pytree like ``state``), ``k``, ``step``, ``best_ll``,
    ``best_riss``, ``log`` -- all dynamic values, so resuming reuses the
    compiled executable.

    ``emit_gather_fn(state_pytree)`` maps each emitted state to its FULL
    (unsharded) form before the callback -- the hook through which the
    cluster-sharded model all-gathers its K-shards so every host's
    checkpoint payload is the complete model (parallel/sharded_em.py).
    """
    if reduce_order_fn is None:
        reduce_order_fn = lambda s: eliminate_and_reduce(s, diag_only=diag_only)
    dtype = data_chunks.dtype
    # Score/compare in float64 when enabled so model selection matches the
    # host loop exactly (it does this arithmetic in Python float64,
    # order_search.py). Without x64 the comparison is best-effort float32:
    # selection can differ from the host loop only when two Ks' Rissanen
    # scores tie within ~1 ulp.
    score_dtype = jnp.float64 if jax.config.jax_enable_x64 else dtype

    def riss_of(ll, k):
        # model_score is plain arithmetic + a static log: trace-safe.
        return model_score(ll.astype(score_dtype), k.astype(score_dtype),
                           num_events, num_dimensions, criterion=criterion,
                           covariance_type=covariance_type)

    def em(s):
        return em_while_loop(
            s, data_chunks, wts_chunks, epsilon, min_iters, max_iters,
            reduce_stats=reduce_stats, diag_only=diag_only,
            quad_mode=quad_mode, matmul_precision=matmul_precision,
            cluster_axis=cluster_axis, stats_fn=stats_fn,
            covariance_type=covariance_type,
            precompute_features=precompute_features,
            dynamic_range=dynamic_range,
            regression_scale=regression_scale,
        )

    zero = jnp.zeros((), dtype)
    carry0 = dict(
        state=state,
        k=jnp.asarray(start_k, jnp.int32),
        best_state=state,
        best_ll=zero,
        best_riss=jnp.asarray(jnp.inf, score_dtype),
        log=jnp.zeros((start_k, 5), dtype),
        step=jnp.asarray(0, jnp.int32),
        done=jnp.asarray(False),
        health=jnp.zeros((health.NUM_FLAGS,), jnp.int32),
    )
    if resume is not None:
        carry0.update(
            best_state=resume["best_state"],
            k=jnp.asarray(resume["k"], jnp.int32),
            best_ll=jnp.asarray(resume["best_ll"], dtype),
            best_riss=jnp.asarray(resume["best_riss"], score_dtype),
            log=jnp.asarray(resume["log"], dtype),
            step=jnp.asarray(resume["step"], jnp.int32),
        )

    def cond(c):
        return (~c["done"]) & (c["step"] < start_k)

    def body(c):
        k = c["k"]
        s, ll, iters, h_k = em(c["state"])
        riss = riss_of(ll, k)
        # A non-finite score must neither win (NaN < best is false, fine)
        # nor be saved by the unconditional step-0 rule -- flag it instead.
        score_ok = jnp.isfinite(riss)
        h_k = h_k.at[health.NONFINITE_SCORE].add(
            (~score_ok).astype(jnp.int32))
        fatal_k = health.fatal(h_k)

        # Best-model save rule (gaussian.cu:839): first K, or better rissanen
        # with no target, or K equals the target -- and a finite score.
        save = (
            (c["step"] == 0)
            | ((riss < c["best_riss"]) & (target_k == 0))
            | (k == target_k)
        ) & score_ok
        best_state = jax.tree_util.tree_map(
            lambda new, old: jnp.where(save, new, old), s, c["best_state"]
        )
        log = c["log"].at[c["step"]].set(
            jnp.stack([k.astype(dtype), ll.astype(dtype), riss.astype(dtype),
                       iters.astype(dtype),
                       health.pack_word_traced(h_k).astype(dtype)])
        )

        stop_now = k <= stop_number
        # Order reduction (dispatched unconditionally -- cheap relative to
        # EM -- and discarded on the stop path, like the host loop).
        next_state, k_active, min_d, _ = reduce_order_fn(s)
        k_active = k_active.astype(jnp.int32)  # x64 mode promotes the sum
        can_merge = (k_active >= 2) & jnp.isfinite(min_d)
        # The host loop re-checks `k >= stop_number` at the top after
        # merging: if elimination dropped the count below the target there
        # is no EM run at that K. Mirror it here or the fused path would run
        # one extra EM below the target. A fatal health word also ends the
        # sweep (the host driver takes over recovery).
        cont = (~stop_now) & can_merge & (k_active - 1 >= stop_number) \
            & ~fatal_k
        new_state = jax.tree_util.tree_map(
            lambda a, b: jnp.where(cont, a, b), next_state, s
        )
        new_carry = dict(
            state=new_state,
            k=jnp.where(cont, k_active - 1, k),
            best_state=best_state,
            best_ll=jnp.where(save, ll.astype(dtype), c["best_ll"]),
            best_riss=jnp.where(save, riss, c["best_riss"]),
            log=log,
            step=c["step"] + 1,
            done=~cont,
            health=c["health"] + h_k,
        )
        if emit_cb is not None:
            # Per-K host emission (checkpoint payload + log row).
            # ``emit_light`` ships only the scalars (profiling wants just
            # the arrival timestamp -- no per-K state transfer).
            if emit_light:
                payload = dict(step=c["step"], done=new_carry["done"])
            else:
                gather = emit_gather_fn or (lambda t: t)
                payload = dict(
                    step=c["step"], k=k, ll=ll, riss=riss, iters=iters,
                    state=gather(new_carry["state"]),
                    best_state=gather(best_state),
                    best_ll=new_carry["best_ll"],
                    best_riss=new_carry["best_riss"],
                    log=log,
                    next_k=new_carry["k"],
                    done=new_carry["done"],
                    health=h_k,  # this K's health counters ride the emission
                )
            # ``ordered=True`` sequences callbacks but does NOT make the
            # device wait for them -- an enqueued-only emission could drain
            # entirely after the program ends, so a crash would lose every
            # "checkpoint" ever emitted. Returning a token and threading it
            # into the carry (behind an optimization_barrier, or XLA folds
            # the x*0-like dependence away) forces step s's emission to
            # COMPLETE -- checkpoint durable on disk -- before step s+1
            # computes. Costs one host round trip per K, only when emission
            # is enabled; the emission-free path stays zero-roundtrip.
            token = jax.experimental.io_callback(
                emit_cb, jax.ShapeDtypeStruct((), jnp.int32), payload,
                ordered=True)
            new_carry["step"] = lax.optimization_barrier(
                (new_carry["step"], token))[0]
        return new_carry

    out = lax.while_loop(cond, body, carry0)
    return (
        out["best_state"], out["best_ll"], out["best_riss"],
        out["log"], out["step"], out["health"],
    )
