"""Single-dispatch batched restarts: vmapped EM + seeding over n_init.

The n_init restarts of ``order_search._fit_with_restarts`` are independent
fits of the SAME device-resident data -- running them as R sequential
sweeps re-dispatches the same EM while-loop R times, and at K <~ 100 the
per-restart [B, K] E-step matmuls leave the MXU underfed. This driver runs
a whole batch of restarts as ONE compiled program per sweep step:

  - seeding: the per-restart seed ROWS keep the sequential path's host
    recipe bit-identically (``order_search._seed_rows`` -- same kmeans++
    RNG streams at seeds ``seed + i``), and the state build vmaps over the
    restart axis (``ops.seeding.seed_states_batched``);
  - EM: ``GMMModel.run_em_batched`` vmaps ``em_while_loop`` over a leading
    restart axis with masked freeze-out -- ``lax.while_loop``'s batching
    rule runs until EVERY restart converges (or hits max_iters) and
    freezes finished lanes via ``select``, so each lane's iteration
    sequence equals its solo run's;
  - order reduction: ``eliminate_and_reduce`` vmapped, with per-lane merge
    application (finished lanes keep their state via ``where``);
  - health: per-restart counter ROWS ([R, NUM_FLAGS]) -- one poisoned
    restart is DROPPED from the batch (its siblings keep their results)
    and the escalation ladder runs only when every live lane goes fatal;
  - preemption: ``run_em_batched_resumable`` runs the same executable in
    host-polled segments; a SIGTERM mid-batch checkpoints all R
    trajectories in one emergency sub-step and ``--resume auto`` restores
    them bit-identically;
  - sharded models reuse the same batched loop with the restart axis
    replicated and the data axis sharded (shard_map(vmap(...))).

The batched sweep is FIXED-WIDTH (no ``sweep_k_buckets`` recompaction):
lanes reach different active counts at the same step, and one compiled
program must serve the whole batch -- the same trade the fused sweep
makes. ``restart_batch_size=1`` keeps the sequential driver, which is the
degenerate case this one is winner-parity-tested against
(tests/test_batched_restarts.py).

Memory model: the batch size is bounded by the [R, B, K] posterior buffer
(plus the [R, B, F] feature intermediates) of one fused E+M chunk pass.
``resolve_restart_batch_size`` auto-caps R from a psutil-free host-memory
probe (sysconf); GMM_RESTART_MEM_BYTES overrides the budget and
GMM_RESTART_BATCH_SIZE the size itself (docs/PERF.md "Restart batching").
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import health, supervisor, telemetry
from ..ops.formulas import convergence_epsilon, model_score
from ..ops.merge import eliminate_and_reduce
from ..ops.pallas import resolve_estep_backend
from ..ops.seeding import seed_states_batched
from ..state import clone_state, compact
from ..testing import faults
from ..utils.logging_ import get_logger


# ---------------------------------------------------------------------------
# Batch sizing (the tier-1-safe default: auto caps by host memory).
# ---------------------------------------------------------------------------

def _host_memory_bytes() -> Optional[int]:
    """Total host memory via sysconf -- deliberately psutil-free (the
    container bakes no extra deps). None when the platform hides it."""
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page = os.sysconf("SC_PAGE_SIZE")
    except (AttributeError, ValueError, OSError):
        return None
    if pages <= 0 or page <= 0:
        return None
    return int(pages) * int(page)


def restart_batch_auto_cap(config, n_events: int, n_dims: int,
                           num_clusters: int) -> int:
    """Largest restart batch the memory budget admits.

    Per-restart working set of one fused E+M chunk pass: the [B, K]
    posteriors, the [B, F] quadratic-form features (F = D^2 expanded --
    the dominant intermediate), and a few K x D x D statistics buffers,
    with a 3x multiplier for XLA temporaries and double-buffering. The
    budget defaults to 1/4 of host memory (CPU tier-1 runs device = host;
    on real accelerators HBM is the binding constraint and the explicit
    knobs take over): GMM_RESTART_MEM_BYTES overrides it directly.

    When the batched PALLAS path will run, host bytes are not the only
    budget: every restart lane holds its own A/h/g parameter blocks and
    statistics accumulators ([R, F, K]-shaped replication) resident in
    VMEM for the whole grid, while the per-tile event block is shared
    across lanes. R is therefore additionally capped by the VMEM budget
    (~16 MiB/core; GMM_RESTART_VMEM_BYTES overrides) -- without this
    term the host-memory heuristic happily picks an R whose lane blocks
    alone overflow VMEM and the kernel fails to lower.
    """
    env = os.environ.get("GMM_RESTART_MEM_BYTES")
    if env not in (None, ""):
        budget = int(env)
    else:
        host = _host_memory_bytes()
        budget = host // 4 if host else 2 << 30
    itemsize = np.dtype(config.dtype).itemsize
    B = max(1, min(int(config.chunk_size), int(n_events)))
    K, D = int(num_clusters), int(n_dims)
    per_restart = itemsize * (B * (K + D * D + D) * 3 + K * D * D * 4)
    cap = max(1, int(budget // max(per_restart, 1)))
    if resolve_estep_backend(config)[0].startswith("pallas"):
        # Per-lane VMEM residency of the batched kernel (f32 always):
        # A [F, K] + h [D, K] + g [1, K] inputs and the mirrored
        # [K, F]/[K, D]/[1, K] accumulator scratch.
        F = D if config.covariance_type in ("diag", "spherical") else D * D
        per_lane_vmem = 4 * (2 * F * K + 2 * D * K + 2 * K + 2)
        tile = 4 * int(config.pallas_block_b) * (D + 1)
        vmem_env = os.environ.get("GMM_RESTART_VMEM_BYTES")
        vmem_budget = int(vmem_env) if vmem_env not in (None, "") \
            else 16 << 20
        cap = min(cap, max(1, (vmem_budget - tile)
                           // max(per_lane_vmem, 1)))
    return cap


def resolve_restart_batch_size(config, model, data, num_clusters=None,
                               log=None) -> int:
    """The restart batch size this fit will actually run.

    1 (the sequential driver) when restarts cannot batch on this path --
    streaming (no single EM program to vmap), fused sweeps (each init runs
    the whole-sweep device program), or a model without the batched loop.
    Otherwise GMM_RESTART_BATCH_SIZE > config.restart_batch_size > the
    host-memory auto cap, clamped to [1, n_init].
    """
    if config.n_init <= 1:
        return 1
    why = None
    if config.stream_events:
        why = "stream_events has no single EM program to vmap"
    elif config.fused_sweep:
        why = "fused_sweep runs the whole-sweep device program per init"
    elif not getattr(model, "supports_batched_restarts", False):
        why = f"{type(model).__name__} has no batched EM loop"
    if why is not None:
        if log is not None and (config.restart_batch_size or 1) > 1:
            log.info("batched restarts disabled (%s); running the %d "
                     "inits sequentially", why, config.n_init)
        return 1
    env = os.environ.get("GMM_RESTART_BATCH_SIZE")
    if env not in (None, ""):
        requested = int(env)
    elif config.restart_batch_size is not None:
        requested = int(config.restart_batch_size)
    else:
        try:
            n_events, n_dims = data.shape
        except (AttributeError, ValueError):
            return 1
        requested = restart_batch_auto_cap(
            config, int(n_events), int(n_dims),
            int(num_clusters or config.max_clusters))
    return max(1, min(requested, config.n_init))


# ---------------------------------------------------------------------------
# Batched state placement / host copies (plain and sharded models).
# ---------------------------------------------------------------------------

def _place_batched(model, host_states: List):
    """One restart-batched device state from R per-lane host states."""
    if hasattr(model, "prepare_states_batched"):
        return model.prepare_states_batched(host_states)
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *host_states)


def _place_batched_state(model, batched_host):
    """Re-place an already-batched HOST state (checkpoint restore)."""
    R = int(np.asarray(batched_host.N).shape[0])
    lanes = [
        jax.tree_util.tree_map(lambda a: jnp.asarray(np.asarray(a)[r]),
                               batched_host)
        for r in range(R)
    ]
    return _place_batched(model, lanes)


def _host_batched(model, states):
    """Host-local copy of a restart-batched state (checkpoint payloads)."""
    if hasattr(model, "host_batched_state"):
        return model.host_batched_state(states)
    return jax.device_get(states)


@functools.lru_cache(maxsize=None)
def _elim_reduce_batched_jit(diag_only: bool):
    """Process-wide jitted vmapped eliminate_and_reduce (per diag flag) --
    same executable-cache rationale as order_search._elim_reduce_jit."""
    return jax.jit(jax.vmap(
        functools.partial(eliminate_and_reduce, diag_only=diag_only)))


def _where_lanes(mask_np, new_states, old_states):
    """Per-lane select: lanes with ``mask`` take ``new``, others keep
    ``old`` (frozen lanes of a batched sweep step)."""
    mask = jnp.asarray(np.asarray(mask_np, bool))

    def sel(old, new):
        m = mask.reshape((mask.shape[0],) + (1,) * (new.ndim - 1))
        return jnp.where(m, new, old)

    return jax.tree_util.tree_map(sel, old_states, new_states)


def _json_scores(scores) -> list:
    """JSON-safe score list (non-finite -> None) for restart_select."""
    return [float(s) if s is not None and math.isfinite(float(s)) else None
            for s in scores]


# ---------------------------------------------------------------------------
# Batched recovery ladder (every live lane fatal).
# ---------------------------------------------------------------------------

def _recover_batched(model, config, rollback, chunks, wts, epsilon, k_r,
                     live, *, trajectory, rec, log, faulty_counts,
                     batch_indices, r_bucket=None):
    """Climb the escalation ladder for a WHOLE-batch fatal EM step.

    Mirrors ``health.recover_em`` lane-wise: every live lane's rollback
    state is repaired (sanitize + boosted variance floor) and the batch
    retries on the rung's model. The first rung with ANY clean live lane
    wins -- still-fatal lanes are handed back for the drop path (the
    batched containment contract: survivors are never rolled back for a
    sibling). Returns ``(model, states, ll, iters, counts, ll_logs,
    clean_live)``; raises :class:`health.NumericalFaultError` when
    recovery is off or the ladder is exhausted.
    """
    R = int(live.shape[0])
    total = np.asarray(faulty_counts, np.int64)[live].sum(axis=0)
    k_top = int(np.max(np.asarray(k_r)[live]))
    if config.recovery != "retry":
        raise health.NumericalFaultError(
            f"numerical fault in every live restart of the batch at "
            f"K={k_top} (flags={health.flag_names(health.pack_word(total))})"
            f" and recovery is {config.recovery!r}",
            health.fault_bundle(total, k=k_top, where="batched_restarts",
                                config=config))
    ladder = health.escalation_ladder(config)
    host_rb = _host_batched(model, rollback)
    lanes = [
        jax.tree_util.tree_map(lambda a: jnp.asarray(np.asarray(a)[r]),
                               host_rb)
        for r in range(R)
    ]
    attempts: List[dict] = []
    for attempt, rung in enumerate(ladder, start=1):
        m2, cfg2 = health.rung_model(model, config, rung)
        boost = float(config.recovery_boost) ** attempt
        repaired = [
            (health.repair_state(lanes[r], diag_only=cfg2.diag_only,
                                 boost=boost) if live[r] else lanes[r])
            for r in range(R)
        ]
        states2 = _place_batched(m2, repaired)
        lo_r = np.where(live, min(config.min_iters, config.max_iters),
                        0).astype(np.int32)
        hi_r = np.where(live, config.max_iters, 0).astype(np.int32)
        out = m2.run_em_batched(states2, chunks, wts, epsilon,
                                min_iters=lo_r, max_iters=hi_r,
                                trajectory=trajectory, r_bucket=r_bucket)
        if trajectory:
            states2, ll_d, iters_d, ll_logs = out
        else:
            (states2, ll_d, iters_d), ll_logs = out, None
        counts = np.asarray(jax.device_get(m2.last_health), np.int64)
        ll_np = np.asarray(jax.device_get(ll_d), np.float64)
        iters_np = np.asarray(jax.device_get(iters_d), np.int64)
        clean = np.asarray([
            not health.word_is_fatal(health.pack_word(counts[r]))
            for r in range(R)
        ])
        clean_live = live & clean
        record = {"attempt": attempt, "action": rung["action"],
                  "boost": boost,
                  "clean": int(clean_live.sum()), "live": int(live.sum())}
        attempts.append(record)
        if log is not None:
            log.warning("batched recovery attempt %d (%s): %d/%d restarts "
                        "clean", attempt, rung["action"],
                        record["clean"], record["live"])
        if rec is not None and rec.active:
            for r in np.flatnonzero(live):
                word_r = health.pack_word(counts[r])
                rec.set_context(init=int(batch_indices[r]))
                rec.emit("recovery", k=int(k_r[r]), attempt=attempt,
                         action=rung["action"],
                         outcome="recovered" if clean[r] else "fatal",
                         flags=int(word_r),
                         flag_names=health.flag_names(word_r))
                rec.metrics.count("recovery_attempts")
            rec.set_context(init=None)
            if clean_live.any():
                rec.metrics.count("recoveries")
        if clean_live.any():
            return m2, states2, ll_np, iters_np, counts, ll_logs, clean_live
    raise health.NumericalFaultError(
        f"numerical fault in every restart of the batch at K={k_top} not "
        f"recovered after {len(ladder)} escalation attempt(s)",
        health.fault_bundle(total, k=k_top, where="batched_restarts",
                            attempts=attempts, config=config))


# ---------------------------------------------------------------------------
# The batched restart driver.
# ---------------------------------------------------------------------------

def fit_restarts_batched(data, num_clusters, target_num_clusters, config,
                         model, verbose, init_means=None,
                         sample_weight=None, batch_size=2):
    """n_init restarts in memory-bounded batches of one vmapped sweep each.

    The order_search entry point for ``restart_batch_size > 1`` (see the
    module docstring); must select the identical winner as the sequential
    driver at the same seeds (``GMMResult.init_index`` carries the pick).
    """
    from .order_search import (
        GMMResult, _emit_run_summary, _null_phase, _prepare_fit,
        compute_envelope,
    )

    log = get_logger(config)
    rec = telemetry.current()
    stop_number = target_num_clusters if target_num_clusters > 0 else 1
    R_total = config.n_init
    if config.seed_method != "kmeans++":
        log.info("n_init=%d: init 0 uses seed_method=%r, restarts use "
                 "'kmeans++'", R_total, config.seed_method)
    log.info("batched restarts: %d inits in batches of %d", R_total,
             batch_size)

    verbose = config.enable_print if verbose is None else verbose
    source = data if hasattr(data, "read_range") else None

    # One fit-scoped data cache on the model (try/finally so an aborted
    # batch can never leak stale device arrays into a later fit).
    model._restart_cache = {}
    try:
        sub0 = dataclasses.replace(config, n_init=1)
        (_, chunks, wts, _cnp, _wnp, n_events, n_dims, shift,
         host_range) = _prepare_fit(
            data, num_clusters, sub0, model, _null_phase, log,
            init_means=init_means, sample_weight=sample_weight,
            skip_seeding=True)
        var_mean = model._restart_cache["prepared"][9]
        epsilon = convergence_epsilon(n_events, n_dims,
                                      config.epsilon_scale)
        if verbose:
            print(f"epsilon = {epsilon}")  # gaussian.cu:462

        if rec.active:
            mesh = getattr(model, "mesh", None)
            rec.set_context(
                path="sharded" if mesh is not None else "in-memory",
                mesh=(list(mesh.shape.values()) if mesh is not None
                      else None),
            )

        all_scores: list = [None] * R_total
        dropped_inits: list = []
        health_totals = np.zeros((health.NUM_FLAGS,), np.int64)
        n_recoveries = 0
        n_drops = 0
        io_retries = 0
        em_walls: list = []
        winner = None  # the running first-best batch winner's payload
        for b0 in range(0, R_total, batch_size):
            idxs = list(range(b0, min(b0 + batch_size, R_total)))
            if rec.active:
                # The stream keeps the sequential contract -- one
                # run_start (and below, one run_summary) PER INIT, each
                # init-tagged -- so `gmm report` and every existing
                # consumer read a batched fit identically.
                for g in idxs:
                    rec.set_context(init=g)
                    if g:
                        rec.metrics.count("restarts")
                    rec.emit(
                        "run_start",
                        platform=jax.devices()[0].platform,
                        num_events=int(n_events),
                        num_dimensions=int(n_dims),
                        start_k=int(num_clusters),
                        target_k=int(target_num_clusters),
                        epsilon=float(epsilon),
                        process_count=int(jax.process_count()),
                        device_count=int(jax.device_count()),
                        local_device_count=int(jax.local_device_count()),
                        dtype=config.dtype,
                        chunk_size=int(config.chunk_size),
                        covariance_type=config.covariance_type,
                        criterion=config.criterion,
                        fused_sweep=False, stream_events=False,
                        n_init=int(R_total),
                        restart_batch_size=int(batch_size),
                        em_backend=getattr(model, "estep_backend", "jnp"),
                        em_backend_reason=getattr(
                            model, "estep_backend_reason", None),
                        memory_stats=telemetry.memory_stats(),
                    )
                rec.set_context(init=None)
            ckpt = None
            if config.checkpoint_dir:
                from ..utils.checkpoint import SweepCheckpointer

                ckpt = SweepCheckpointer(
                    os.path.join(config.checkpoint_dir, f"batch{b0}"),
                    keep=config.checkpoint_keep,
                    retries=config.checkpoint_retries)
            out = _run_batch(
                model, config, data, source, num_clusters, stop_number,
                target_num_clusters, chunks, wts, n_events, n_dims, shift,
                var_mean, epsilon, idxs, init_means, verbose, rec, log,
                ckpt, r_bucket=batch_size)
            model = out["model"]  # sticky escalation spans batches
            health_totals += out["health_totals"]
            n_recoveries += out["recoveries"]
            n_drops += out["drops"]
            em_walls.extend(out["em_walls"])
            if ckpt is not None:
                io_retries += ckpt.io_retries
            for j, g in enumerate(idxs):
                all_scores[g] = float(out["min_riss"][j])
                if out["dropped"][j]:
                    dropped_inits.append(int(g))
                if rec.active:
                    rec.set_context(init=g)
                    _emit_run_summary(
                        rec, config, None, out["sweep_logs"][j],
                        int(out["n_active"][j]),
                        float(out["min_riss"][j]),
                        float(out["best_ll"][j]),
                        [row[4] for row in out["sweep_logs"][j]],
                        em_backend=getattr(model, "estep_backend", None),
                        buckets=dict(
                            mode="off",
                            em_widths=[int(out["winner"]["width"])],
                            em_compiles=1, rebuckets=0),
                        health_section=health.health_summary(
                            out["health_lane"][j],
                            recoveries=out["recoveries"],
                            restart_drops=int(out["dropped"][j])))
                    rec.set_context(init=None)
                if verbose:
                    print(f"init {g}: {config.criterion}="
                          f"{out['min_riss'][j]:.6e} "
                          f"K={out['n_active'][j]}")
            # The sequential first-best rule, composed across batches:
            # within the batch _run_batch already picked first-best, so
            # comparing batch winners in batch order is equivalent.
            w = out["winner"]
            if (winner is None or math.isnan(winner["min_riss"])
                    or w["min_riss"] < winner["min_riss"]):
                winner = w
    finally:
        model._restart_cache = None

    if rec.active:
        rec.set_context(init=None)
        rec.emit("restart_select", winner=int(winner["init"]),
                 scores=_json_scores(all_scores),
                 criterion=config.criterion, mode="batched",
                 batch_size=int(batch_size),
                 dropped=dropped_inits)
    health_section = health.health_summary(
        health_totals, recoveries=n_recoveries, io_retries=io_retries,
        restart_drops=n_drops)
    if verbose:
        print(f"best of {R_total} inits: "
              f"{config.criterion}={winner['min_riss']:.6e} "
              f"K={winner['n_active']}")
    # Training drift envelope (rev v2.4) for the WINNING init's
    # parameters; lazy sources are skipped (backfill with `gmm drift
    # --rebuild-envelope`).
    envelope = None
    if config.envelope and source is None and not hasattr(chunks, "close"):
        n_local = (host_range[1] - host_range[0] if host_range
                   else n_events)
        envelope = compute_envelope(model, winner["state"], chunks,
                                    n_local, winner["n_active"])
    return GMMResult(
        state=winner["state"],
        ideal_num_clusters=winner["n_active"],
        min_rissanen=float(winner["min_riss"]),
        final_loglik=float(winner["best_ll"]),
        epsilon=epsilon,
        num_events=n_events,
        num_dimensions=n_dims,
        data_shift=np.asarray(shift),
        sweep_log=winner["sweep_log"],
        profile=None,
        profile_report=None,
        host_range=host_range,
        health=health_section,
        envelope=envelope,
        model=model,
        init_index=int(winner["init"]),
    )


def _run_batch(model, config, data, source, num_clusters, stop_number,
               target_num_clusters, chunks, wts, n_events, n_dims, shift,
               var_mean, epsilon, batch_indices, init_means, verbose, rec,
               log, ckpt, r_bucket=None):
    """One batch of restarts through the whole vmapped model-order sweep.

    ``r_bucket`` (the fit's restart batch size) pads a ragged tail batch
    up to the bucket inside ``run_em_batched`` so every batch of the fit
    reuses ONE compiled batched-EM executable (frozen pad lanes, outputs
    sliced back -- see GMMModel.run_em_batched).
    """
    from .order_search import (
        _COV_CODE, _CRITERION_CODE, _emit_em_iters, _resume_mismatch,
        _seed_rows, _shutdown_and_raise,
    )

    sup = supervisor.current()
    R = len(batch_indices)
    dtype = np.dtype(config.dtype)

    # --- vmapped seeding: host rows (sequential-identical RNG), one
    # batched device build ---------------------------------------------
    rows = []
    for g in batch_indices:
        method = config.seed_method if g == 0 else "kmeans++"
        rows.append(np.asarray(_seed_rows(
            data, source, num_clusters, n_dims, n_events, dtype,
            seed_method=method, seed=config.seed + g,
            init_means=(init_means if g == 0 else None)), dtype))
    rows = np.stack(rows) - np.asarray(shift, dtype)[None, None, :]
    host_batched = seed_states_batched(
        rows, n_events, var_mean, num_clusters,
        covariance_dynamic_range=config.covariance_dynamic_range,
        dtype=dtype)
    # Deterministic singular-covariance injection: lane 0 of the batch
    # (the sequential path poisons the first seeded fit).
    pois = faults.take("singular_cov")
    if pois is not None:
        c = int(pois.get("cluster", 0))
        host_batched = host_batched.replace(
            R=host_batched.R.at[0, c].set(0.0),
            Rinv=host_batched.Rinv.at[0, c].set(jnp.inf))
    states = _place_batched_state(model, host_batched)
    width = int(np.asarray(host_batched.N).shape[-1])

    # --- per-restart sweep scalars --------------------------------------
    k_r = np.full((R,), num_clusters, np.int64)
    alive = np.ones((R,), bool)
    dropped = np.zeros((R,), bool)
    min_riss_r = np.full((R,), np.inf)
    ideal_k_r = np.full((R,), num_clusters, np.int64)
    best_ll_r = np.full((R,), -np.inf)
    sweep_logs: List[list] = [[] for _ in range(R)]
    # First EM call donates the seed buffers; best_states must not alias.
    best_states = clone_state(states)

    health_lane = np.zeros((R, health.NUM_FLAGS), np.int64)
    n_recoveries = 0
    n_drops = 0
    em_walls: list = []
    recovery_on = config.recovery == "retry"
    want_traj = rec.active
    supervised = sup.active and ckpt is not None
    elim = _elim_reduce_batched_jit(config.diag_only)

    # --- resume ----------------------------------------------------------
    step = 0
    resume_em = None
    resume_sub_step = None
    if ckpt is not None and config.resume != "never":
        restored = ckpt.restore()
        if restored is not None and (
                "batched" not in restored
                or int(np.asarray(restored["num_clusters"])) != num_clusters
                or int(np.asarray(restored["state"].N).shape[0]) != R
                or _resume_mismatch(restored, config, log)):
            restored = None
        if restored is not None:
            states = _place_batched_state(model, restored["state"])
            best_states = _place_batched_state(model,
                                               restored["best_state"])
            k_r = np.asarray(restored["k"], np.int64).copy()
            alive = np.asarray(restored["alive"], bool).copy()
            dropped = np.asarray(restored["dropped"], bool).copy()
            min_riss_r = np.asarray(restored["min_rissanen"],
                                    np.float64).copy()
            ideal_k_r = np.asarray(restored["ideal_k"], np.int64).copy()
            best_ll_r = np.asarray(restored["best_ll"], np.float64).copy()
            lens = np.asarray(restored["sweep_len"], np.int64)
            rows_log = np.asarray(restored["sweep_log"], np.float64)
            sweep_logs = [
                [tuple(row) for row in rows_log[r][:int(lens[r])]]
                for r in range(R)
            ]
            step = int(np.asarray(restored["step"])) + 1
            log.info("resumed batched restart sweep from checkpoint: "
                     "step %d", step)
            rec.metrics.count("resumes") if rec.active else None
        sub = ckpt.restore_substep()
        if sub is not None and (
                "batched" not in sub
                or int(np.asarray(sub["num_clusters"])) != num_clusters
                or int(np.asarray(sub["state"].N).shape[0]) != R
                or int(np.asarray(sub["step"])) < step
                or _resume_mismatch(sub, config, log)):
            sub = None
        if sub is not None:
            states = _place_batched_state(model, sub["state"])
            best_states = _place_batched_state(model, sub["best_state"])
            k_r = np.asarray(sub["k"], np.int64).copy()
            alive = np.asarray(sub["alive"], bool).copy()
            dropped = np.asarray(sub["dropped"], bool).copy()
            min_riss_r = np.asarray(sub["min_rissanen"],
                                    np.float64).copy()
            ideal_k_r = np.asarray(sub["ideal_k"], np.int64).copy()
            best_ll_r = np.asarray(sub["best_ll"], np.float64).copy()
            lens = np.asarray(sub["sweep_len"], np.int64)
            rows_log = np.asarray(sub["sweep_log"], np.float64)
            sweep_logs = [
                [tuple(row) for row in rows_log[r][:int(lens[r])]]
                for r in range(R)
            ]
            step = int(np.asarray(sub["step"]))
            resume_sub_step = step
            resume_em = {
                "em_iter": int(np.asarray(sub["em_iter"])),
                "em_lls": np.asarray(sub["em_lls"], np.float64),
                "em_lens": np.asarray(sub["em_lens"], np.int64),
                "em_frozen": np.asarray(sub["em_frozen"], np.int8),
                "em_fatal": np.asarray(sub["em_fatal"], np.int8),
            }
            log.info("resuming INSIDE the interrupted batched fit: EM "
                     "iteration %d (sub-step %d)", resume_em["em_iter"],
                     step)
            rec.metrics.count("resumes") if rec.active else None

    def host_payload():
        return {
            "state": _host_batched(model, states),
            "best_state": _host_batched(model, best_states),
            "min_rissanen": np.asarray(min_riss_r, np.float64),
            "ideal_k": np.asarray(ideal_k_r, np.int64),
            "best_ll": np.asarray(best_ll_r, np.float64),
            "k": np.asarray(k_r, np.int64),
            "alive": alive.astype(np.int64),
            "dropped": dropped.astype(np.int64),
            "num_clusters": int(num_clusters),
            "criterion_code": _CRITERION_CODE[config.criterion],
            "cov_code": _COV_CODE[config.covariance_type],
            "batched": 1,
            "batch_indices": np.asarray(batch_indices, np.int64),
            "sweep_log": _pad_sweep_logs(sweep_logs),
            "sweep_len": np.asarray([len(l) for l in sweep_logs],
                                    np.int64),
        }

    # --- the batched sweep ----------------------------------------------
    while alive.any():
        k_top = int(k_r[alive].max())
        if sup.active and sup.poll(where="sweep", k=k_top):
            _shutdown_and_raise(sup, rec, log, ckpt,
                                step=step - 1 if step else None, k=k_top,
                                checkpointed=ckpt is not None and step > 0)
        t0 = time.perf_counter()
        live = alive.copy()
        lo_r = np.where(live, min(config.min_iters, config.max_iters),
                        0).astype(np.int32)
        hi_r = np.where(live, config.max_iters, 0).astype(np.int32)
        rollback = clone_state(states) if recovery_on else None
        ll_logs = None
        if supervised or resume_em is not None:
            (states, ll_d, iters_d, ll_logs, em_stopped,
             stop_extra) = model.run_em_batched_resumable(
                states, chunks, wts, epsilon,
                poll_iters=config.preempt_poll_iters,
                should_stop=(
                    (lambda done, _k=k_top: sup.poll(
                        where="em", k=_k, em_iter=done))
                    if sup.active else None),
                freeze=~live, resume=resume_em, donate=True,
                r_bucket=r_bucket)
            resume_em = None
            if em_stopped:
                payload = host_payload()
                payload.update(stop_extra)
                _shutdown_and_raise(
                    sup, rec, log, ckpt, step=step, k=k_top,
                    em_iter=int(stop_extra.get("em_iter", 0)),
                    payload=payload)
            if resume_sub_step is not None and ckpt is not None:
                ckpt.discard_substeps(resume_sub_step)
                resume_sub_step = None
            if not want_traj:
                ll_logs = None
        elif want_traj:
            states, ll_d, iters_d, ll_logs = model.run_em_batched(
                states, chunks, wts, epsilon, min_iters=lo_r,
                max_iters=hi_r, trajectory=True, donate=True,
                r_bucket=r_bucket)
        else:
            states, ll_d, iters_d = model.run_em_batched(
                states, chunks, wts, epsilon, min_iters=lo_r,
                max_iters=hi_r, donate=True, r_bucket=r_bucket)
        counts = np.asarray(jax.device_get(model.last_health), np.int64)
        counts = counts.reshape(R, health.NUM_FLAGS)

        # Order reduction dispatched for every lane (finished lanes'
        # outputs are ignored), then ONE blocking sync for all decision
        # scalars -- the batched mirror of the sequential fused sync.
        next_states, k_active_d, min_d_d, pair_d = elim(states)
        ll_np, iters_np, k_active_np, min_d_np, pair_np = map(
            np.asarray,
            jax.device_get((ll_d, iters_d, k_active_d, min_d_d, pair_d)))
        dt = time.perf_counter() - t0

        # --- per-restart fault containment ---------------------------
        fatal_r = np.asarray([
            health.word_is_fatal(health.pack_word(counts[r]))
            for r in range(R)
        ]) & live
        if fatal_r.any():
            for r in np.flatnonzero(fatal_r):
                health_lane[r] += counts[r]
                word = health.pack_word(counts[r])
                if rec.active:
                    rec.set_context(init=int(batch_indices[r]))
                    rec.emit("health", k=int(k_r[r]), where="em",
                             flags=int(word),
                             flag_names=health.flag_names(word),
                             counters=health.counts_dict(counts[r]))
                    rec.metrics.count("health_events")
                    rec.set_context(init=None)
            if not (live & ~fatal_r).any():
                # EVERY live restart fatal: only now does the escalation
                # ladder run (rolls the whole batch back).
                (model, states, ll_np, iters_np, counts, ll_logs,
                 clean_live) = _recover_batched(
                    model, config, rollback, chunks, wts, epsilon, k_r,
                    live, trajectory=want_traj, rec=rec, log=log,
                    faulty_counts=counts, batch_indices=batch_indices,
                    r_bucket=r_bucket)
                n_recoveries += 1
                still_fatal = live & ~clean_live
                live = clean_live
                if still_fatal.any():
                    alive &= ~still_fatal
                    dropped |= still_fatal
                    n_drops += int(still_fatal.sum())
                next_states, k_active_d, min_d_d, pair_d = elim(states)
                k_active_np, min_d_np, pair_np = map(
                    np.asarray,
                    jax.device_get((k_active_d, min_d_d, pair_d)))
                dt = time.perf_counter() - t0
            else:
                # Drop-one-keep-survivors: the poisoned lanes leave the
                # batch; their siblings' results this step stand.
                for r in np.flatnonzero(fatal_r):
                    log.warning(
                        "restart %d hit a fatal numerical fault at K=%d; "
                        "dropped from the batch (survivors continue)",
                        int(batch_indices[r]), int(k_r[r]))
                    if rec.active:
                        rec.set_context(init=int(batch_indices[r]))
                        rec.emit("recovery", k=int(k_r[r]), attempt=1,
                                 action="drop_restart", outcome="dropped",
                                 flags=int(health.pack_word(counts[r])),
                                 flag_names=health.flag_names(
                                     health.pack_word(counts[r])))
                        rec.metrics.count("restart_drops")
                        rec.set_context(init=None)
                alive &= ~fatal_r
                dropped |= fatal_r
                n_drops += int(fatal_r.sum())
                live &= ~fatal_r

        # --- scoring + best-model save per live lane ------------------
        improved = np.zeros((R,), bool)
        for r in np.flatnonzero(live):
            g = int(batch_indices[r])
            health_lane[r] += counts[r]
            word = health.pack_word(counts[r])
            ll_f = float(ll_np[r])
            riss = model_score(ll_f, int(k_r[r]), n_events, n_dims,
                               criterion=config.criterion,
                               covariance_type=config.covariance_type)
            score_ok = math.isfinite(riss)
            if not score_ok:
                health_lane[r, health.NONFINITE_SCORE] += 1
                log.warning("non-finite %s score at K=%d (init %d); "
                            "excluded from best-model selection",
                            config.criterion, int(k_r[r]), g)
            sweep_logs[r].append((int(k_r[r]), ll_f, riss,
                                  int(iters_np[r]), dt))
            if rec.active:
                rec.set_context(init=g)
                if word:
                    rec.emit("health", k=int(k_r[r]), where="em",
                             flags=int(word),
                             flag_names=health.flag_names(word),
                             counters=health.counts_dict(counts[r]))
                    rec.metrics.count("health_events")
                if not score_ok:
                    rec.emit(
                        "health", k=int(k_r[r]), where="score",
                        flags=1 << health.NONFINITE_SCORE,
                        flag_names=[
                            health.FLAG_NAMES[health.NONFINITE_SCORE]],
                        counters={health.FLAG_NAMES[
                            health.NONFINITE_SCORE]: 1})
                    rec.metrics.count("health_events")
                rec.metrics.count("em_iters", int(iters_np[r]))
                rec.metrics.series("active_k", int(k_r[r]))
                if ll_logs is not None:
                    # Wall seconds are the whole batched step's, amortized
                    # per iteration inside (_emit_em_iters's contract).
                    _emit_em_iters(rec, int(k_r[r]), ll_logs[r],
                                   int(iters_np[r]), dt, epsilon, model)
                rec.emit("em_done", k=int(k_r[r]), loglik=ll_f,
                         score=float(riss), criterion=config.criterion,
                         iters=int(iters_np[r]), seconds=round(dt, 6))
                rec.set_context(init=None)
            if verbose:
                print(f"init {g} K={int(k_r[r])}: loglik={ll_f:.6e} "
                      f"{config.criterion}={riss:.6e} "
                      f"iters={int(iters_np[r])} ({dt:.2f}s)")
            if score_ok and (
                k_r[r] == num_clusters
                or (riss < min_riss_r[r] and target_num_clusters == 0)
                or k_r[r] == target_num_clusters
            ):  # gaussian.cu:839, per lane, NaN-score-guarded
                improved[r] = True
                min_riss_r[r] = riss
                ideal_k_r[r] = k_r[r]
                best_ll_r[r] = ll_f
        em_walls.append(dt)
        if rec.active:
            rec.heartbeat("sweep", k=k_top)
        if improved.any():
            best_states = _where_lanes(improved, states, best_states)

        # --- sweep advance per lane ----------------------------------
        finished = live & (k_r <= stop_number)
        alive &= ~finished
        live &= ~finished
        if not alive.any():
            break
        merge_mask = np.zeros((R,), bool)
        for r in np.flatnonzero(live):
            k_new = int(k_active_np[r])
            if k_new < 2:
                alive[r] = False
                continue
            if not np.isfinite(float(min_d_np[r])):
                log.warning("no valid merge pair at K=%d (init %d); "
                            "stopping that restart's sweep", k_new,
                            int(batch_indices[r]))
                alive[r] = False
                continue
            if rec.active:
                rec.set_context(init=int(batch_indices[r]))
                rec.emit("merge", k_active=k_new, next_k=k_new - 1,
                         min_distance=float(min_d_np[r]),
                         pair=[int(pair_np[r][0]), int(pair_np[r][1])])
                rec.metrics.count("merges")
                rec.set_context(init=None)
            merge_mask[r] = True
            k_r[r] = k_new - 1
            if k_r[r] < stop_number:
                alive[r] = False
        if merge_mask.any():
            states = _where_lanes(merge_mask, next_states, states)

        if ckpt is not None and alive.any():
            rec.metrics.count("checkpoint_saves") if rec.active else None
            ckpt.save(step, host_payload())
        step += 1

    # --- batch winner (the sequential first-best rule, in lane order) ---
    widx = 0
    for r in range(1, R):
        if math.isnan(min_riss_r[widx]) or min_riss_r[r] < min_riss_r[widx]:
            widx = r
    host_best = _host_batched(model, best_states)
    lane = jax.tree_util.tree_map(
        lambda a: jnp.asarray(np.asarray(a)[widx]), host_best)
    compact_state, n_active_w = compact(lane)
    n_active = np.zeros((R,), np.int64)
    for r in range(R):
        if r == widx:
            n_active[r] = n_active_w
        else:
            n_active[r] = int(ideal_k_r[r])
    return {
        "model": model,
        "min_riss": min_riss_r,
        "best_ll": best_ll_r,
        "ideal_k": ideal_k_r,
        "n_active": n_active,
        "dropped": dropped,
        "sweep_logs": sweep_logs,
        "health_lane": health_lane,
        "health_totals": health_lane.sum(axis=0),
        "recoveries": n_recoveries,
        "drops": n_drops,
        "em_walls": em_walls,
        "winner": {
            "init": int(batch_indices[widx]),
            "min_riss": float(min_riss_r[widx]),
            "best_ll": float(best_ll_r[widx]),
            "state": compact_state,
            "n_active": int(n_active_w),
            "sweep_log": sweep_logs[widx],
            "width": width,
        },
    }


def _pad_sweep_logs(sweep_logs: List[list]) -> np.ndarray:
    """[R, S, 5] NaN-padded per-restart sweep rows (checkpoint payload)."""
    R = len(sweep_logs)
    S = max((len(l) for l in sweep_logs), default=0)
    out = np.full((R, max(S, 1), 5), np.nan, np.float64)
    for r, rows in enumerate(sweep_logs):
        for i, row in enumerate(rows):
            out[r, i, :] = np.asarray(row, np.float64)
    return out
