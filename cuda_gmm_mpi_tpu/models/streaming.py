"""Out-of-core EM: events stream host->device per chunk, per iteration.

Scale upgrade past both the reference and the in-memory path: the reference
holds every GPU's event shard resident in device memory for the whole run
(``gaussian.cu:347-377``), and ``GMMModel`` likewise uploads all chunks to
HBM once. Here the chunk array STAYS IN HOST MEMORY; each EM iteration
streams chunks through a jitted fused E+M pass and accumulates sufficient
statistics on device -- the device working set is one chunk plus the
[K, D, D]-sized statistics, so N is bounded by host RAM, not HBM (e.g.
400M x 24 float32 events = 38 GB host is fine on a 16 GB chip).

The price is the single-jit EM loop: iteration control returns to the host
(num_chunks dispatches per iteration instead of zero). Use it only when the
data genuinely exceeds device memory; the in-memory model is strictly faster
otherwise. Loop semantics (estep0; while cond: mstep; estep) and all guards
are shared with ``em_while_loop`` via the same ops and the same
chunk-sequential accumulation order, so trajectories match the in-memory
path to summation-order noise (the CLI outputs are byte-identical).

Single-process, single-device by design: multi-host runs already shard the
data N-ways (per-host slices), which is the first remedy for N too big for
one chip. A ``GMMModel`` subclass, so ``fit_gmm``, the model-order search,
and the whole inference/output surface drive it unchanged; the fused
whole-sweep path is disabled (it needs device-resident data) and falls back
to the host-driven sweep.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import GMMConfig
from ..ops.mstep import apply_mstep, chunk_stats
from .gmm import GMMModel, resolve_iters


class StreamingGMMModel(GMMModel):
    """GMMModel with host-resident chunks and a host-driven EM loop."""

    supports_fused_emit = False
    make_fused_sweep = None  # no fused sweep: data is not on device

    def __init__(self, config: GMMConfig = GMMConfig()):
        if config.mesh_shape is not None:
            raise ValueError(
                "stream_events is single-device; for data too large for one "
                "chip ALSO consider multi-host sharding (each host streams "
                "its slice)")
        if config.use_pallas == "always":
            raise ValueError(
                "stream_events streams per-chunk through the jnp path; "
                "use_pallas='always' (a hard kernel override) cannot be "
                "honored -- drop one of the two flags")
        super().__init__(config)  # inference surface + _posteriors

        kw = dict(self._kw)

        @jax.jit
        def _stats(state, x, wts):
            return chunk_stats(state, x, wts, **kw)

        @jax.jit
        def _add(a, b):
            return a + b  # SuffStats.__add__

        @jax.jit
        def _mstep(state, stats):
            return apply_mstep(state, stats, diag_only=config.diag_only,
                               covariance_type=config.covariance_type)

        self._chunk_stats_jit = _stats
        self._add = _add
        self._mstep = _mstep

    def prepare(self, state, chunks_np, wts_np, host_local: bool = False):
        """Keep the chunk arrays HOST-side; only the state goes on device."""
        del host_local  # single-process
        return (jax.tree_util.tree_map(jnp.asarray, state),
                np.asarray(chunks_np), np.asarray(wts_np))

    def prepare_state(self, state):
        return jax.tree_util.tree_map(jnp.asarray, state)

    def _estep_all(self, state, chunks, wts):
        """One full-data fused E+M pass, streaming chunk by chunk."""
        acc = None
        for i in range(chunks.shape[0]):
            s = self._chunk_stats_jit(state, jnp.asarray(chunks[i]),
                                      jnp.asarray(wts[i]))
            acc = s if acc is None else self._add(acc, s)
        return acc

    def run_em(self, state, chunks, wts, epsilon,
               min_iters: Optional[int] = None,
               max_iters: Optional[int] = None):
        """Reference loop semantics (gaussian.cu:525-755), host-driven."""
        lo, hi = resolve_iters(self.config, min_iters, max_iters)
        lo, hi = int(lo), int(hi)
        stats = self._estep_all(state, chunks, wts)
        ll_old = float(stats.loglik)
        change = abs(2.0 * float(epsilon)) + 1.0  # gaussian.cu:525
        iters = 0
        while iters < lo or (abs(change) > epsilon and iters < hi):
            state = self._mstep(state, stats)
            stats = self._estep_all(state, chunks, wts)
            ll = float(stats.loglik)
            change, ll_old = ll - ll_old, ll
            iters += 1
        return state, jnp.asarray(ll_old, chunks.dtype), jnp.asarray(iters)
