"""Out-of-core EM: events stream host->device per chunk, per iteration.

Scale upgrade past both the reference and the in-memory path: the reference
holds every GPU's event shard resident in device memory for the whole run
(``gaussian.cu:347-377``), and ``GMMModel`` likewise uploads all chunks to
HBM once. Here the chunk array STAYS IN HOST MEMORY; each EM iteration
streams chunks through a jitted fused E+M pass and accumulates sufficient
statistics on device -- the device working set is one chunk plus the
[K, D, D]-sized statistics, so N is bounded by host RAM, not HBM (e.g.
400M x 24 float32 events = 38 GB host is fine on a 16 GB chip).

Two engineering properties matter at that scale:

- **All local devices stay busy** (``mesh_shape=(S, 1)``): each streamed
  block is S chunks placed sharded over the ``data`` mesh axis, every
  device computes its chunk's statistics in parallel, and one psum merges
  them at the end of the pass -- the reference's analog kept every GPU fed
  from host-staged shards (``gaussian.cu:347-377``); a single-device stream
  on an 8-chip host would idle 7/8 of the machine.
- **Transfer/compute overlap**: the NEXT block's host->device copy is
  enqueued before this block's compute is dispatched (double-buffering), so
  the PCIe/ICI copy of block j+1 rides under the device compute of block j
  instead of serializing with it.

The price is the single-jit EM loop: iteration control returns to the host
(num_chunks dispatches per iteration instead of zero). Use it only when the
data genuinely exceeds device memory; the in-memory model is strictly faster
otherwise. Loop semantics (estep0; while cond: mstep; estep) and all guards
are shared with ``em_while_loop`` via the same ops and the same
chunk-sequential accumulation order, so trajectories match the in-memory
path to summation-order noise (the CLI outputs are byte-identical). On a
mesh, chunk j of shard d is the in-memory sharded model's chunk ``d*Cl + j``
and the final cross-shard merge is the same psum collective, so the sharded
trajectories line up the same way.

Multi-host composes too (round 4): each rank streams ITS host slice (the
range readers already bound per-host host RAM) block-by-block over its
local data shards, and the end-of-pass psum spans the global mesh -- the
same collective the in-memory multi-controller path uses. So N is bounded
by the CLUSTER's host RAM, with every chip of every host busy. The cluster
mesh axis must be 1 (events are what overflow memory, not K).
A ``GMMModel`` subclass, so ``fit_gmm``, the model-order search, and the
whole inference/output surface drive it unchanged; the fused whole-sweep
path is disabled (it needs device-resident data) and falls back to the
host-driven sweep.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import health
from ..config import GMMConfig
from ..ops.mstep import SuffStats, apply_mstep, chunk_stats
from ..telemetry import current as current_recorder
from ..testing import faults
from .gmm import GMMModel, resolve_iters


class _StreamPreempt(Exception):
    """Internal: a mid-pass cooperative stop. Carries the partially
    reduced block accumulator (device SuffStats, pre-psum), the next
    unprocessed block, and the pass index -- the streaming path's
    emergency-checkpoint payload (supervisor.py / docs/ROBUSTNESS.md)."""

    def __init__(self, acc, next_block: int, pass_idx: int):
        super().__init__(f"stream pass {pass_idx} stopped before "
                         f"block {next_block}")
        self.acc = acc
        self.next_block = next_block
        self.pass_idx = pass_idx


class StreamingGMMModel(GMMModel):
    """GMMModel with host-resident chunks and a host-driven EM loop."""

    supports_fused_emit = False
    make_fused_sweep = None  # no fused sweep: data is not on device
    # No batched restarts: the streaming EM "loop" is a host-driven
    # per-block dispatch sequence, not one program a restart axis can
    # vmap over (restarts fall back to the sequential driver).
    supports_batched_restarts = False
    # No fleet fits either, for the same reason (tenancy/fleet.py).
    supports_fleet = False
    data_size = 1  # overridden per-instance when a mesh is configured
    cluster_size = 1  # events-only sharding (prepare_inference contract)

    def __init__(self, config: GMMConfig = GMMConfig()):
        self.mesh = None
        if config.mesh_shape is not None or jax.process_count() > 1:
            from ..parallel.mesh import CLUSTER_AXIS, DATA_AXIS, make_mesh

            # Multi-controller defaults to every device of every host on
            # the data axis (the ShardedGMMModel default): the psum must
            # span the whole job.
            mesh = make_mesh(config.mesh_shape)
            if mesh.shape[CLUSTER_AXIS] != 1:
                # Config.__post_init__ enforces this too; keep the direct
                # construction path honest.
                raise ValueError(
                    "stream_events shards events only; the cluster mesh "
                    "axis must be 1")
            self.mesh = mesh
            self.data_size = mesh.shape[DATA_AXIS]
            self._local_data_size = mesh.local_mesh.shape[DATA_AXIS]
        if config.use_pallas == "always":
            raise ValueError(
                "stream_events streams per-chunk through the jnp path; "
                "use_pallas='always' (a hard kernel override) cannot be "
                "honored -- drop one of the two flags")
        super().__init__(config)  # inference surface + _posteriors

        kw = dict(self._kw)

        @jax.jit
        def _stats(state, x, wts):
            return chunk_stats(state, x, wts, **kw)

        # The streaming reduce: donate the running accumulator so every
        # per-block merge updates the SuffStats buffers in place instead of
        # allocating a fresh set per block (the accumulator is loop-local
        # in _estep_all and never read after the add).
        @functools.partial(jax.jit, donate_argnums=(0,))
        def _add(a, b):
            return a + b  # SuffStats.__add__

        @jax.jit
        def _mstep(state, stats):
            return apply_mstep(state, stats, diag_only=config.diag_only,
                               covariance_type=config.covariance_type)

        # Stepwise (minibatch) EM's decayed running estimate (Cappe &
        # Moulines 2009): S <- (1-gamma) S + gamma * scale * s_batch, with
        # ``scale`` rescaling the batch statistics to full-data size so the
        # absolute Nk thresholds (empty-cluster semantics, gaussian.cu)
        # keep their reference meaning. gamma/scale are cast INSIDE the jit
        # to the accumulator dtype so Python-float weak types can never
        # promote the statistics. ``sanitized`` is an integer event count,
        # not a statistic -- it rides through unblended (counted host-side
        # per batch).
        @functools.partial(jax.jit, donate_argnums=(0,))
        def _decay_stats(a, b, gamma, scale):
            g = jnp.asarray(gamma, a.Nk.dtype)
            sc = jnp.asarray(scale, a.Nk.dtype)

            def blend(x, y):
                return (1.0 - g) * x + g * (sc * y)

            return SuffStats(blend(a.loglik, b.loglik), blend(a.Nk, b.Nk),
                             blend(a.M1, b.M1), blend(a.M2, b.M2),
                             b.sanitized)

        @jax.jit
        def _scale_stats(b, scale):
            sc = jnp.asarray(scale, b.Nk.dtype)
            return SuffStats(sc * b.loglik, sc * b.Nk, sc * b.M1,
                             sc * b.M2, b.sanitized)

        self._chunk_stats_jit = _stats
        self._add = _add
        self._mstep = _mstep
        self._decay_stats = _decay_stats
        self._scale_stats = _scale_stats

        if self.mesh is not None:
            from ..ops.estep import posteriors
            from ..parallel.mesh import (
                CLUSTER_AXIS, DATA_AXIS, state_pspecs,
            )
            from ..parallel.sharded_em import shard_map

            self._data_axis = DATA_AXIS
            self._x_sharding_stream = NamedSharding(
                self.mesh, P(DATA_AXIS, None, None))
            self._w_sharding_stream = NamedSharding(
                self.mesh, P(DATA_AXIS, None))

            @jax.jit
            def _stats_block(state, xb, wb):
                # [S, B, D] block sharded on the leading (shard) axis; the
                # vmap keeps every shard's statistics independent, so XLA
                # partitions this with zero communication.
                return jax.vmap(
                    lambda x, w: chunk_stats(state, x, w, **kw))(xb, wb)

            self._stats_block = _stats_block
            self._reduce_fn = None  # built lazily (leaf ranks known then)

            # Output/inference pass over ALL local devices (mirrors
            # ShardedGMMModel: the reference computed final memberships on
            # every GPU, gaussian.cu:768-823) -- streaming's whole point is
            # huge N, which makes a single-device output pass the next
            # bottleneck. Multi-host uses the host-local submesh so each
            # host's output pass is collective-free.
            self._inference_mesh = (
                self.mesh if jax.process_count() == 1
                else self.mesh.local_mesh
            )
            self._inference_data_size = (
                self._inference_mesh.shape[DATA_AXIS])
            post_fn = functools.partial(posteriors, cluster_axis=None, **kw)
            sspec = state_pspecs()
            self._post_sharded = jax.jit(
                shard_map(
                    lambda s, x: post_fn(s, x),
                    mesh=self._inference_mesh,
                    in_specs=(sspec, P(DATA_AXIS, None)),
                    out_specs=(P(DATA_AXIS, CLUSTER_AXIS), P(DATA_AXIS)),
                    check_vma=False,
                )
            )
            self._x_sharding = NamedSharding(
                self._inference_mesh, P(DATA_AXIS, None))
            self._inference_cache = None  # one-slot (state -> placed)
        self._block_major = False  # set by prepare()'s mesh layout pass
        self._counts_checked = None  # one-slot cross-host count check cache
        self._pass_index = 0  # full-data E+M passes within the current run_em
        # Real per-iteration wall seconds of the latest run_em (host-driven
        # loop, so these are measured, not amortized); the telemetry layer
        # reads them for the em_iter records.
        self.last_iter_seconds: list = []
        self.last_health = None  # health counters of the latest run_em

        dyn_range = config.covariance_dynamic_range

        @jax.jit
        def _state_health(state, Nk):
            return health.state_counts(state, Nk=Nk,
                                       dynamic_range=dyn_range)

        self._state_health = _state_health

    def prepare(self, state, chunks_np, wts_np, host_local: bool = False):
        """Keep the chunk arrays HOST-side; only the state goes on device.

        On a mesh this also (a) pads the chunk count to a multiple of the
        LOCAL data-axis extent with zero-weight chunks (zero weight = zero
        contribution to every statistic, the same contract chunk padding
        already uses), and (b) reorders chunks block-major -- block j
        holding local shard d's chunk ``d*blocks + j`` contiguously -- so
        the per-pass strided gather in ``_put_block`` becomes a free
        contiguous view instead of a full extra host copy of the dataset
        every EM iteration.

        Multi-controller: ``chunks_np`` must be THIS host's slice
        (``host_local=True``, same contract as ShardedGMMModel.prepare);
        each host streams its slice over its local shards and the
        end-of-pass psum spans the global mesh.

        Lazy mode (out-of-core ingestion, io/pipeline.py): ``chunks_np``
        may be a block source exposing ``get_block(j)`` instead of an
        ndarray. Nothing is materialized here -- the source already owns
        the block-major layout and the zero-weight padding contract, and
        it is host-local by construction (each rank's source covers only
        its own ``host_chunk_bounds`` row range)."""
        from ..parallel import elastic

        # Elastic worlds: fail loudly here rather than hang in the first
        # end-of-pass psum if a sealed shrink diverged from the live
        # multi-controller runtime (the runtime cannot drop ranks in
        # process; docs/DISTRIBUTED.md "Elastic recovery").
        elastic.assert_world_coherent()
        if hasattr(chunks_np, "get_block"):
            if self.mesh is not None and (
                    chunks_np.local_data_size != self._local_data_size):
                raise ValueError(
                    f"block source was built for local data extent "
                    f"{chunks_np.local_data_size}, mesh has "
                    f"{self._local_data_size}")
            self._block_major = True
            return self.prepare_state(state), chunks_np, wts_np
        if jax.process_count() > 1:
            from ..parallel.distributed import require_host_local_chunks

            require_host_local_chunks(
                host_local, np.asarray(chunks_np).shape,
                "stream every event process_count times")
        chunks_np, wts_np = np.asarray(chunks_np), np.asarray(wts_np)
        if self.mesh is not None:
            S = self._local_data_size
            n = chunks_np.shape[0]
            pad = (-n) % S
            if pad:
                chunks_np = np.concatenate(
                    [chunks_np, np.zeros((pad,) + chunks_np.shape[1:],
                                         chunks_np.dtype)])
                wts_np = np.concatenate(
                    [wts_np, np.zeros((pad,) + wts_np.shape[1:],
                                      wts_np.dtype)])
                n += pad
            blocks = n // S
            order = (np.arange(n).reshape(S, blocks).T).ravel()
            chunks_np = np.ascontiguousarray(chunks_np[order])
            wts_np = np.ascontiguousarray(wts_np[order])
            self._block_major = True
        return self.prepare_state(state), chunks_np, wts_np

    def prepare_state(self, state):
        state = jax.tree_util.tree_map(jnp.asarray, state)
        if self.mesh is not None and jax.process_count() > 1:
            # Multi-controller: the state must be a GLOBAL (replicated)
            # array so the SPMD stats/mstep jits accept it alongside the
            # globally sharded blocks (every rank holds the identical
            # replicated value; same contract as ShardedGMMModel).
            from jax.experimental import multihost_utils

            return multihost_utils.host_local_array_to_global_array(
                state, self.mesh,
                jax.tree_util.tree_map(lambda _: P(), state))
        return state

    def _make_reduce(self, acc):
        """psum the per-shard statistics over the data axis -- the SAME
        collective the in-memory sharded model ends its pass with, so the
        merged values match it bitwise, not just to reduction-order noise."""
        from ..parallel.sharded_em import shard_map  # version-guarded import

        axis = self._data_axis
        in_specs = (jax.tree_util.tree_map(
            lambda a: P(axis, *([None] * (a.ndim - 1))), acc),)
        out_specs = jax.tree_util.tree_map(lambda a: P(), acc)

        def body(t):
            return jax.tree_util.tree_map(
                lambda a: lax.psum(a[0], axis), t)

        return jax.jit(shard_map(body, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))

    def _put_block(self, chunks, wts, j: int, blocks: int):
        """Enqueue block j's host->device copy (async; double-buffered by
        the caller). On a mesh the block is S chunks -- shard d gets chunk
        ``d*blocks + j`` of the original grid, the exact chunk the
        in-memory sharded model assigns it -- placed sharded over the data
        axis. ``prepare`` lays the chunks out block-major, so the block is
        a contiguous zero-copy view; un-prepared arrays fall back to the
        strided gather. A lazy block source (io/pipeline.py) produces the
        block on demand instead -- its prefetch worker has usually already
        read it, so this is a queue pop, not a disk read."""
        lazy = hasattr(chunks, "get_block")
        if self.mesh is None:
            if lazy:
                chunk, wrow = chunks.get_block(j)
            else:
                chunk, wrow = chunks[j], wts[j]
            chunk, wrow = faults.maybe_poison_block(chunk, wrow, j)
            return (jnp.asarray(chunk), jnp.asarray(wrow))
        S = self._local_data_size
        if lazy:
            sel_c, sel_w = chunks.get_block(j)
        elif self._block_major:
            sel_c, sel_w = chunks[j * S:(j + 1) * S], wts[j * S:(j + 1) * S]
        else:
            sel_c = np.ascontiguousarray(chunks[j::blocks])
            sel_w = np.ascontiguousarray(wts[j::blocks])
        sel_c, sel_w = faults.maybe_poison_block(sel_c, sel_w, j)
        if jax.process_count() > 1:
            # Each host contributes its local S chunks; the assembled
            # global block is [S_global, B, D] sharded over the data axis.
            from jax.experimental import multihost_utils

            return (
                multihost_utils.host_local_array_to_global_array(
                    np.ascontiguousarray(sel_c), self.mesh,
                    P(self._data_axis, None, None)),
                multihost_utils.host_local_array_to_global_array(
                    np.ascontiguousarray(sel_w), self.mesh,
                    P(self._data_axis, None)),
            )
        return (jax.device_put(sel_c, self._x_sharding_stream),
                jax.device_put(sel_w, self._w_sharding_stream))

    def _estep_all(self, state, chunks, wts, *, stop_check=None,
                   start_block: int = 0, acc0=None):
        """One full-data fused E+M pass, streaming block by block.

        ``stop_check(pass_idx, block)`` (supervised runs, single-device
        only) is polled after each non-final block; a truthy return raises
        :class:`_StreamPreempt` carrying the partial accumulator so the
        emergency checkpoint loses at most one block of compute.
        ``start_block``/``acc0`` resume such an interrupted pass: blocks
        before ``start_block`` are represented by the restored accumulator,
        so the block-sequential addition order -- and the reduced
        statistics -- stay bit-identical to an uninterrupted pass.
        """
        n = chunks.shape[0]
        if self.mesh is None:
            blocks, stats_fn = n, self._chunk_stats_jit
        else:
            if jax.process_count() > 1 and self._counts_checked != id(chunks):
                # Direct run_em callers may bypass prepare(): verify the
                # cross-host chunk counts COLLECTIVELY before anything can
                # raise locally, so a mismatch fails identically on every
                # rank instead of one rank erroring while the others hang
                # in the psum. One allgather per chunk array, not per pass.
                from jax.experimental import multihost_utils

                multihost_utils.assert_equal(
                    np.asarray(chunks.shape),
                    "per-host chunk array shapes differ across hosts; "
                    "derive slices with "
                    "parallel.distributed.host_chunk_bounds")
                self._counts_checked = id(chunks)
            if n % self._local_data_size:
                # After the collective check the counts are equal
                # everywhere, so this raises on every rank or none.
                raise ValueError(
                    f"local chunk count {n} is not a multiple of the local "
                    f"data mesh extent {self._local_data_size}; pass the "
                    "chunk arrays through prepare() (it pads with "
                    "zero-weight chunks)")
            blocks, stats_fn = n // self._local_data_size, self._stats_block
        rec = current_recorder()
        emit = rec.active
        pass_idx, self._pass_index = self._pass_index, self._pass_index + 1
        chunks_per_block = 1 if self.mesh is None else self._local_data_size
        lazy = hasattr(chunks, "get_block")
        acc = acc0
        nxt = self._put_block(chunks, wts, start_block, blocks)
        # Per-block walls (schema rev v1.9): the put of block j records how
        # long the host BLOCKED on ingestion (0.0 resident -- the array is
        # already there); the wait is carried alongside the double-buffered
        # block so block j's record reports block j's wait even though
        # block j+1's put runs first.
        wait_nxt = chunks.last_wait_s if lazy else 0.0
        for j in range(start_block, blocks):
            cur, wait_cur = nxt, wait_nxt
            if j + 1 < blocks:
                # Double-buffer: enqueue block j+1's copy BEFORE dispatching
                # block j's compute, so the transfer overlaps the compute
                # instead of serializing behind it.
                nxt = self._put_block(chunks, wts, j + 1, blocks)
                wait_nxt = chunks.last_wait_s if lazy else 0.0
            t0 = time.perf_counter()
            s = stats_fn(state, *cur)
            acc = s if acc is None else self._add(acc, s)
            compute_s = time.perf_counter() - t0
            if emit:
                # One record per streamed block flush ("iter" is the pass
                # index: 0 = the initial E-step, i+1 = EM iteration i).
                # prefetch_wait_s/compute_s split the block's host wall:
                # time blocked on ingestion vs. time in the statistics
                # dispatch (including any device-queue backpressure).
                nbytes = int(cur[0].nbytes) + int(cur[1].nbytes)
                rec.metrics.count("h2d_bytes", nbytes)
                rec.emit("chunk_flush", iter=pass_idx, block=j,
                         chunks=chunks_per_block, bytes=nbytes,
                         prefetch_wait_s=round(wait_cur, 6),
                         compute_s=round(compute_s, 6))
                rec.heartbeat("stream")
            if (stop_check is not None and j + 1 < blocks
                    and stop_check(pass_idx, j)):
                # Mid-pass cooperative stop (never on the final block --
                # a finished pass is worth more than one block's latency).
                raise _StreamPreempt(acc, j + 1, pass_idx)
        if self.mesh is not None:
            if self._reduce_fn is None:
                self._reduce_fn = self._make_reduce(acc)
            acc = self._reduce_fn(acc)
        return acc

    def _minibatch_setup(self, chunks, wts):
        """(blocks_total, mb_blocks, W_total) for the stepwise-EM driver.

        ``W_total`` is the GLOBAL event weight (cross-host allgather on a
        multi-controller run, deterministic so a resumed run recomputes the
        identical value); ``mb_blocks`` how many streamed blocks one step
        consumes to cover ``minibatch_size`` events.
        """
        lazy = hasattr(chunks, "get_block")
        n = chunks.shape[0]
        S = 1 if self.mesh is None else self._local_data_size
        blocks = n // S
        events_per_block = self.config.chunk_size * (
            self.data_size if self.mesh is not None else 1)
        mb = int(self.config.minibatch_size)
        mb_blocks = max(1, -(-mb // events_per_block)) if mb > 0 else 1
        mb_blocks = min(mb_blocks, blocks)
        if lazy:
            w_local = float(chunks.total_weight)
        else:
            w_local = float(np.asarray(wts, np.float64).sum())
        if jax.process_count() > 1:
            from ..parallel.distributed import allgather_host

            w_local = float(allgather_host(
                np.asarray([w_local], np.float64)).sum())
        return blocks, mb_blocks, w_local

    def _minibatch_stats(self, state, chunks, wts, cursor, mb_blocks,
                         blocks, emit_iter):
        """One minibatch's reduced SuffStats: ``mb_blocks`` streamed blocks
        from ``cursor`` (wrapping), merged with the same per-block ``_add``
        the full pass uses, psum-reduced on a mesh. Returns
        ``(s_batch, next_cursor)``."""
        stats_fn = (self._chunk_stats_jit if self.mesh is None
                    else self._stats_block)
        rec = current_recorder()
        emit = rec.active
        chunks_per_block = 1 if self.mesh is None else self._local_data_size
        lazy = hasattr(chunks, "get_block")
        acc = None
        j = cursor
        for _ in range(mb_blocks):
            cur = self._put_block(chunks, wts, j, blocks)
            wait = chunks.last_wait_s if lazy else 0.0
            t0 = time.perf_counter()
            s = stats_fn(state, *cur)
            acc = s if acc is None else self._add(acc, s)
            compute_s = time.perf_counter() - t0
            if emit:
                nbytes = int(cur[0].nbytes) + int(cur[1].nbytes)
                rec.metrics.count("h2d_bytes", nbytes)
                rec.emit("chunk_flush", iter=emit_iter, block=j,
                         chunks=chunks_per_block, bytes=nbytes,
                         prefetch_wait_s=round(wait, 6),
                         compute_s=round(compute_s, 6))
                rec.heartbeat("stream")
            j = (j + 1) % blocks
        if self.mesh is not None:
            if self._reduce_fn is None:
                self._reduce_fn = self._make_reduce(acc)
            acc = self._reduce_fn(acc)
        return acc, j

    def _minibatch_core(self, state, chunks, wts, epsilon, lo, hi, *,
                        should_stop=None, resume=None):
        """The stepwise-EM loop (``em_mode='minibatch'``).

        Each step streams one minibatch, folds its statistics into the
        decayed running estimate ``S <- (1-gamma_t) S + gamma_t scale s``
        with ``gamma_t = (t + t0)^-alpha`` (Cappe & Moulines; ``scale``
        rescales the batch to full-data size), and M-steps off the running
        estimate -- so convergence no longer costs a full data pass per
        iteration. min/max_iters count STEPS; the per-step loglik is the
        full-data-equivalent PROXY ``scale * batch_loglik`` (noisy by
        construction); one final full pass produces the true loglik and
        the exit health check. ``should_stop(t)``/``resume`` carry the
        supervisor contract: a stop's payload is ``{mb_step, mb_cursor,
        mb_acc}`` (the decay state), so a resumed run replays the exact
        step sequence bit-identically.

        Returns ``(state, lls, iters, counts, stopped, extra)``.
        """
        import dataclasses as _dc

        counts = np.zeros((health.NUM_FLAGS,), np.int64)
        reg_tol = float(self.config.health_regression_scale) * float(epsilon)
        eps_f = abs(float(epsilon))
        blocks, mb_blocks, w_total = self._minibatch_setup(chunks, wts)
        t0_decay = float(self.config.minibatch_t0)
        alpha = float(self.config.minibatch_alpha)

        def observe(ll, ll_prev=None):
            if not np.isfinite(ll):
                counts[health.NONFINITE_LOGLIK] += 1
                return True
            if ll_prev is not None and np.isfinite(ll_prev) \
                    and ll < ll_prev - reg_tol:
                counts[health.LOGLIK_REGRESSION] += 1
            return False

        resume = resume or {}
        running = None
        cursor, t = 0, 0
        lls: list = []
        if "mb_step" in resume:
            cursor = int(resume["mb_cursor"])
            t = int(resume["mb_step"])
            lls = [float(x) for x in
                   np.asarray(resume.get("em_lls", ())).reshape(-1)]
            if "mb_acc" in resume:  # absent only for a step-0 stop
                running = SuffStats(**{k: jnp.asarray(v) for k, v in
                                       resume["mb_acc"].items()})
        ll_old = lls[-1] if lls else None
        change = (lls[-1] - lls[-2]) if len(lls) >= 2 \
            else abs(2.0 * eps_f) + 1.0
        fatal = False
        inj = faults.peek("nan_loglik")  # runtime-consumed (host loop)
        while not fatal and (
                t < lo or (not abs(change) <= eps_f and t < hi)):
            if should_stop is not None and should_stop(t):
                extra = {"mb_step": int(t), "mb_cursor": int(cursor)}
                if running is not None:
                    extra["mb_acc"] = {
                        f.name: np.asarray(jax.device_get(
                            getattr(running, f.name)))
                        for f in _dc.fields(running)
                    }
                return state, lls, t, counts, True, extra
            t_wall = time.perf_counter()
            s_batch, cursor = self._minibatch_stats(
                state, chunks, wts, cursor, mb_blocks, blocks, t)
            counts[health.SANITIZED_LANES] += int(s_batch.sanitized)
            w_batch = float(jnp.sum(s_batch.Nk))
            if w_batch <= 0.0:
                # An all-padding minibatch (zero-weight tail blocks):
                # nothing to learn from; advance past it without an update.
                self.last_iter_seconds.append(
                    time.perf_counter() - t_wall)
                t += 1
                continue
            scale = w_total / w_batch
            ll = float(s_batch.loglik) * scale
            if inj is not None and t + 1 == int(inj["iter"]) \
                    and faults.take("nan_loglik") is not None:
                ll = float("nan")
            if running is None:
                running = self._scale_stats(s_batch, scale)
            else:
                gamma = (float(t) + t0_decay) ** (-alpha)
                running = self._decay_stats(running, s_batch, gamma, scale)
            state = self._mstep(state, running)
            fatal = observe(ll, ll_old)
            self.last_iter_seconds.append(time.perf_counter() - t_wall)
            lls.append(ll)
            change = ll - ll_old if ll_old is not None \
                else abs(2.0 * eps_f) + 1.0
            ll_old = ll
            t += 1
        if fatal:
            nk = running.Nk if running is not None else None
            if nk is not None:
                counts[:] += np.asarray(jax.device_get(self._state_health(
                    state, nk)), np.int64)
            return state, lls, t, counts, False, {}
        # True final loglik + exit health check: ONE full pass (the only
        # full-data sweep of the whole fit). Its chunk_flush records carry
        # iter=t, right after step t-1's.
        self._pass_index = t
        stats = self._estep_all(state, chunks, wts)
        ll_final = float(stats.loglik)
        counts[health.SANITIZED_LANES] += int(stats.sanitized)
        if not np.isfinite(ll_final):
            counts[health.NONFINITE_LOGLIK] += 1
        # No regression check proxy-vs-true: the per-step logliks are
        # stochastic estimates; comparing the exact final value against
        # them would flag noise, not faults.
        lls.append(ll_final)
        counts[:] += np.asarray(jax.device_get(self._state_health(
            state, stats.Nk)), np.int64)
        return state, lls, t, counts, False, {}

    @property
    def inference_block(self) -> int:
        """Events per output-path block: one chunk per local data shard on
        a mesh, one chunk otherwise (the inherited single-device pass)."""
        if self.mesh is None:
            return self.config.chunk_size
        return self.config.chunk_size * self._inference_data_size

    def infer_posteriors(self, state, xb):
        """(w [B, K], logZ [B]) for one [inference_block, D] event block --
        on a mesh, computed on all local devices in parallel (the shared
        ShardedGMMModel machinery, incl. localization of multi-controller
        global states)."""
        if self.mesh is None:
            return super().infer_posteriors(state, xb)
        from ..parallel.sharded_em import infer_posteriors_sharded

        return infer_posteriors_sharded(self, state, xb)

    def memberships(self, state, data_chunks, return_logz: bool = False):
        """Output pass over all local devices on a mesh (single-device
        inherited otherwise) -- streaming exists for huge N, where a
        one-device output pass would idle the rest of the host."""
        if self.mesh is None:
            return super().memberships(state, data_chunks, return_logz)
        from ..parallel.sharded_em import memberships_sharded

        return memberships_sharded(self, state, data_chunks, return_logz)

    def run_em(self, state, chunks, wts, epsilon,
               min_iters: Optional[int] = None,
               max_iters: Optional[int] = None, *, trajectory: bool = False,
               donate: bool = False):
        """Reference loop semantics (gaussian.cu:525-755), host-driven.

        ``trajectory=True`` returns (state, loglik, iters, ll_log) like the
        in-memory models' telemetry variant; being host-driven, the logliks
        come for free and ``last_iter_seconds`` carries REAL per-iteration
        wall times (the jitted paths can only amortize).

        ``donate`` is accepted for interface parity with the jitted models;
        the host-driven loop's donation lives in the streaming reduce
        (``_add`` updates the statistics accumulator in place) and applies
        regardless -- the loop carry here is rebound per pass either way.

        Health containment mirrors ``em_while_loop``'s in-carry bitmask,
        host-driven: non-finite loglik stops the loop immediately (fatal),
        the convergence test is NaN-safe (``not |change| <= eps``), the
        per-pass sanitized-lane counts accumulate from the statistics, and
        the final state's parameter/range lanes are checked once at exit.
        Counters land on ``self.last_health``.
        """
        lo, hi = resolve_iters(self.config, min_iters, max_iters)
        lo, hi = int(lo), int(hi)
        self._pass_index = 0
        self.last_iter_seconds = []
        if self.config.em_mode == "minibatch":
            state, lls, iters, counts, _, _ = self._minibatch_core(
                state, chunks, wts, epsilon, lo, hi)
            self.last_health = jnp.asarray(counts, jnp.int32)
            ll_out = lls[-1] if lls else float("nan")
            out = (state, jnp.asarray(ll_out, chunks.dtype),
                   jnp.asarray(iters))
            if trajectory:
                return out + (np.asarray(lls, np.float64),)
            return out
        counts = np.zeros((health.NUM_FLAGS,), np.int64)
        reg_tol = float(self.config.health_regression_scale) * float(epsilon)

        def observe(ll, ll_prev=None):
            """Loglik-lane bookkeeping; returns True when fatal."""
            if not np.isfinite(ll):
                counts[health.NONFINITE_LOGLIK] += 1
                return True
            if ll_prev is not None and np.isfinite(ll_prev) \
                    and ll < ll_prev - reg_tol:
                counts[health.LOGLIK_REGRESSION] += 1
            return False

        stats = self._estep_all(state, chunks, wts)
        ll_old = float(stats.loglik)
        counts[health.SANITIZED_LANES] += int(stats.sanitized)
        fatal = observe(ll_old)
        lls = [ll_old]  # slot 0: initial E-step (em_while_loop's contract)
        change = abs(2.0 * float(epsilon)) + 1.0  # gaussian.cu:525
        iters = 0
        inj = faults.peek("nan_loglik")  # runtime-consumed (host loop)
        while not fatal and (
                iters < lo or (not abs(change) <= epsilon and iters < hi)):
            t0 = time.perf_counter()
            state = self._mstep(state, stats)
            stats = self._estep_all(state, chunks, wts)
            ll = float(stats.loglik)
            if inj is not None and iters + 1 == int(inj["iter"]) \
                    and faults.take("nan_loglik") is not None:
                ll = float("nan")
            counts[health.SANITIZED_LANES] += int(stats.sanitized)
            fatal = observe(ll, ll_old)
            self.last_iter_seconds.append(time.perf_counter() - t0)
            lls.append(ll)
            change, ll_old = ll - ll_old, ll
            iters += 1
        # Parameter/empties/range lanes from the final state (the jitted
        # loop checks every iteration; here one exit check keeps the
        # host-driven path's per-iteration cost unchanged -- any NaN that
        # reached the parameters also took the loglik non-finite above).
        counts += np.asarray(jax.device_get(self._state_health(
            state, stats.Nk)), np.int64)
        self.last_health = jnp.asarray(counts, jnp.int32)
        out = (state, jnp.asarray(ll_old, chunks.dtype), jnp.asarray(iters))
        if trajectory:
            return out + (np.asarray(lls, np.float64),)
        return out

    def run_em_resumable(self, state, chunks, wts, epsilon,
                         min_iters: Optional[int] = None,
                         max_iters: Optional[int] = None, *,
                         poll_iters: int = 25, should_stop=None,
                         block_stop=None, resume: Optional[dict] = None,
                         donate: bool = False):
        """Supervised variant of the streaming loop (supervisor.py).

        The host-driven loop is already a poll point per pass;
        additionally ``block_stop(pass_idx, block)`` is consulted after
        every streamed block (single-device streams only -- on a mesh the
        per-shard accumulator is not host-local, so stops round up to the
        pass boundary), and a mid-pass stop carries the partially reduced
        block accumulator into the emergency checkpoint: a preempted
        400M-event pass loses at most one block of compute, not the pass.
        ``resume`` accepts the in-memory keys (``em_iter``/``em_lls``;
        the boundary re-E-step recomputes the statistics the next M-step
        needs, bit-identically) plus the streaming extras
        (``stream_pass``, ``stream_block``, ``stream_acc``) written by
        the mid-pass stop. ``poll_iters`` is ignored (every pass is a
        host round-trip already). Returns the ``run_em_resumable``
        contract: (state, loglik, iters, ll_log, stopped, extra).
        """
        import dataclasses as _dc

        lo, hi = resolve_iters(self.config, min_iters, max_iters)
        lo, hi = int(lo), int(hi)
        self.last_iter_seconds = []
        if self.config.em_mode == "minibatch":
            # Stepwise EM under supervision: the per-step poll replaces the
            # per-pass/per-block polls (steps are short -- one minibatch),
            # and the stop payload carries the decay state (mb_step /
            # mb_cursor / mb_acc) instead of the pass/block/acc carry.
            self._pass_index = 0
            state, lls, iters, counts, stopped, extra = \
                self._minibatch_core(state, chunks, wts, epsilon, lo, hi,
                                     should_stop=should_stop, resume=resume)
            if stopped:
                extra = dict(extra, em_lls=np.asarray(lls, np.float64))
            self.last_health = jnp.asarray(counts, jnp.int32)
            buf = np.full((int(self.config.max_iters) + 1,), np.nan,
                          np.float64)
            n = min(len(lls), buf.shape[0])
            buf[:n] = lls[:n]
            ll_out = lls[-1] if lls else float("nan")
            return state, ll_out, iters, buf, stopped, extra
        counts = np.zeros((health.NUM_FLAGS,), np.int64)
        reg_tol = float(self.config.health_regression_scale) * float(epsilon)
        eps_f = abs(float(epsilon))

        def observe(ll, ll_prev=None):
            if not np.isfinite(ll):
                counts[health.NONFINITE_LOGLIK] += 1
                return True
            if ll_prev is not None and np.isfinite(ll_prev) \
                    and ll < ll_prev - reg_tol:
                counts[health.LOGLIK_REGRESSION] += 1
            return False

        bstop = block_stop if self.mesh is None else None

        def stop_payload(sp: _StreamPreempt):
            return {
                "stream_pass": int(sp.pass_idx),
                "stream_block": int(sp.next_block),
                "stream_acc": {
                    f.name: np.asarray(jax.device_get(
                        getattr(sp.acc, f.name)))
                    for f in _dc.fields(sp.acc)
                },
            }

        def finish(stopped, extra, lls, iters, stats=None):
            if stats is not None:
                counts[:] += np.asarray(jax.device_get(self._state_health(
                    state, stats.Nk)), np.int64)
            if stopped:
                extra = dict(extra, em_lls=np.asarray(lls, np.float64))
            self.last_health = jnp.asarray(counts, jnp.int32)
            buf = np.full((int(self.config.max_iters) + 1,), np.nan,
                          np.float64)
            n = min(len(lls), buf.shape[0])
            buf[:n] = lls[:n]
            ll_out = lls[-1] if lls else float("nan")
            return state, ll_out, iters, buf, stopped, extra

        # -- establish this position's statistics (fresh, boundary resume,
        # or mid-pass resume with the restored partial accumulator) --
        lls: list = []
        iters = 0
        resume = resume or {}
        try:
            if "stream_acc" in resume:
                p = int(resume["stream_pass"])
                acc0 = SuffStats(**{k: jnp.asarray(v) for k, v in
                                    resume["stream_acc"].items()})
                self._pass_index = p
                lls = [float(x) for x in
                       np.asarray(resume.get("em_lls", ())).reshape(-1)]
                iters = max(p - 1, 0)
                # The saved state is post-M-step of pass p (== the state
                # the interrupted E-step was scanning); continue the pass
                # from the first unprocessed block.
                stats = self._estep_all(
                    state, chunks, wts, stop_check=bstop,
                    start_block=int(resume["stream_block"]), acc0=acc0)
            elif resume:
                # Boundary resume: the saved state is iteration ``done``'s
                # post-E-step state, and an E-step leaves the state
                # untouched -- so one recomputed pass rebuilds exactly the
                # statistics the next M-step consumed in the uninterrupted
                # run (the in-memory segmented driver's estep0 analog).
                iters = int(resume.get("em_iter", 0))
                lls = [float(x) for x in
                       np.asarray(resume.get("em_lls", ())).reshape(-1)]
                self._pass_index = iters
                stats = self._estep_all(state, chunks, wts, stop_check=bstop)
            else:
                self._pass_index = 0
                stats = self._estep_all(state, chunks, wts, stop_check=bstop)
        except _StreamPreempt as sp:
            return finish(True, stop_payload(sp), lls, iters)

        if "stream_acc" in resume and int(resume["stream_pass"]) > 0:
            # The resumed pass WAS iteration p: fold its loglik in now.
            p = int(resume["stream_pass"])
            ll = float(stats.loglik)
            counts[health.SANITIZED_LANES] += int(stats.sanitized)
            fatal = observe(ll, lls[-1] if lls else None)
            lls.append(ll)
            iters = p
        else:
            if not lls:  # fresh run (or mid-pass resume of pass 0)
                ll0 = float(stats.loglik)
                counts[health.SANITIZED_LANES] += int(stats.sanitized)
                fatal = observe(ll0)
                lls = [ll0]
            else:
                # Boundary resume: lls already ends with this pass's
                # loglik (the recompute reproduces it bit-identically).
                counts[health.SANITIZED_LANES] += int(stats.sanitized)
                fatal = observe(lls[-1])
        ll_old = lls[-1]
        change = (lls[-1] - lls[-2]) if len(lls) >= 2 \
            else abs(2.0 * eps_f) + 1.0

        inj = faults.peek("nan_loglik")  # runtime-consumed (host loop)
        while not fatal and (
                iters < lo or (not abs(change) <= eps_f and iters < hi)):
            if should_stop is not None and should_stop(iters):
                return finish(True, {}, lls, iters, stats)
            t0 = time.perf_counter()
            state = self._mstep(state, stats)
            try:
                stats = self._estep_all(state, chunks, wts, stop_check=bstop)
            except _StreamPreempt as sp:
                return finish(True, stop_payload(sp), lls, iters)
            ll = float(stats.loglik)
            if inj is not None and iters + 1 == int(inj["iter"]) \
                    and faults.take("nan_loglik") is not None:
                ll = float("nan")
            counts[health.SANITIZED_LANES] += int(stats.sanitized)
            fatal = observe(ll, ll_old)
            self.last_iter_seconds.append(time.perf_counter() - t0)
            lls.append(ll)
            change, ll_old = ll - ll_old, ll
            iters += 1
        return finish(False, {}, lls, iters, stats)
