"""Numerical fault containment: health bitmask + rollback-and-retry recovery.

The reference is fail-fast-or-silent (SURVEY.md SS5.3): a singular
covariance aborts nothing, a NaN loglik makes the EM loop's
``|change| > epsilon`` predicate false so the sweep "converges" on a
poisoned model, and a NaN Rissanen score corrupts best-K selection without
a trace. This module closes that hole in three layers:

**Device side** -- a health vector of int32 counters (one lane per flag,
below) rides the jitted EM loop's carry (``models.gmm.em_while_loop``):
non-finite loglik/params, loglik regression beyond tolerance, empty
clusters, covariance dynamic-range violations, and the (previously silent)
count of log-sum-exp lanes sanitized in the E-step. Fatal lanes
short-circuit the ``lax.while_loop`` condition, so a poisoned run stops
iterating the moment the poison is observable instead of burning
``max_iters`` on garbage. On a sharded mesh the lanes aggregate with a
psum -- sum-is-OR in the nonzero semiring, and because every shard counts
a disjoint slice (events over ``data``, clusters over ``cluster``) the
summed counts equal the single-device run's exactly (the psum-OR parity
contract, tests/test_health.py).

**Host side** -- the sweep driver packs the counters into a flag word
(:func:`pack_word`), emits ``health`` telemetry for any nonzero word, and
on a fatal word either raises :class:`NumericalFaultError` with a
diagnostic bundle (``recovery="off"``) or rolls back to the K's input
state and retries up the deterministic escalation ladder
(``recovery="retry"``): sanitize + raise the variance floor ->
``quad_mode="centered"`` -> ``matmul_precision="highest"``. A successful
rung's model is adopted for the rest of the sweep (sticky escalation: if
the stabler numerics fixed it once, keep them). Exhaustion raises with
the full attempt history.

**Rehearsal** -- every path is testable on demand through the
deterministic injection points in ``testing.faults``
(docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

# ---------------------------------------------------------------------------
# Flag lanes. The packed word is OR(1 << lane for lanes with count > 0).
# ---------------------------------------------------------------------------

NONFINITE_LOGLIK = 0   # fatal: NaN/Inf log-likelihood observed
NONFINITE_PARAMS = 1   # fatal: NaN/Inf in an active cluster's parameters
LOGLIK_REGRESSION = 2  # loglik dropped more than regression_scale * epsilon
EMPTY_CLUSTER = 3      # active cluster with membership below the 0.5 floor
COV_DYNAMIC_RANGE = 4  # covariance diagonal outside the configured range
SANITIZED_LANES = 5    # non-finite log-sum-exp lanes sanitized in the E-step
NONFINITE_SCORE = 6    # NaN/Inf model-order score (selection guard)
NUM_FLAGS = 7

FLAG_NAMES = (
    "nonfinite_loglik", "nonfinite_params", "loglik_regression",
    "empty_cluster", "cov_dynamic_range", "sanitized_lanes",
    "nonfinite_score",
)

FATAL_MASK = (1 << NONFINITE_LOGLIK) | (1 << NONFINITE_PARAMS)

# Membership floor below which an active cluster counts as empty/collapsed
# (the reference's Nk > 0.5 emptiness threshold, gaussian.cu:865-874).
MEMBERSHIP_FLOOR = 0.5


# ---------------------------------------------------------------------------
# Device-side counters (trace-safe; every function returns an int32
# [NUM_FLAGS] vector that adds across iterations / shards).
# ---------------------------------------------------------------------------

def zero_counts():
    import jax.numpy as jnp

    return jnp.zeros((NUM_FLAGS,), jnp.int32)


def _lane(idx: int, count):
    """An all-zero counter vector with ``count`` in lane ``idx``."""
    import jax.numpy as jnp

    return jnp.zeros((NUM_FLAGS,), jnp.int32).at[idx].set(
        jnp.asarray(count, jnp.int32))


def em_iter_counts(loglik, loglik_prev=None, regression_tol=None):
    """Loglik-derived lanes for one EM iteration (trace-safe).

    ``loglik_prev``/``regression_tol`` arm the regression check (EM's
    loglik is non-decreasing in exact arithmetic; a drop beyond the
    tolerance is a numerical event worth flagging, though not fatal).
    """
    import jax.numpy as jnp

    counts = _lane(NONFINITE_LOGLIK, ~jnp.isfinite(loglik))
    if loglik_prev is not None and regression_tol is not None:
        regressed = (jnp.isfinite(loglik) & jnp.isfinite(loglik_prev)
                     & (loglik < loglik_prev - regression_tol))
        counts = counts + _lane(LOGLIK_REGRESSION, regressed)
    return counts


def state_counts(state, Nk=None, *, dynamic_range: float = 1e3,
                 cluster_axis: Optional[str] = None):
    """Parameter-derived lanes for one state (trace-safe).

    - ``nonfinite_params``: active clusters with any non-finite entry
      across N/pi/constant/avgvar/means/R/Rinv.
    - ``empty_cluster``: active clusters whose soft count (``Nk`` when
      given -- the fresh statistics -- else ``state.N``) is below the
      reference's 0.5 emptiness floor. Informational: the order search
      eliminates empties as a matter of course (gaussian.cu:865-874).
    - ``cov_dynamic_range``: active, non-empty clusters whose covariance
      diagonal is non-positive or spans more than
      ``dynamic_range**2`` max/min -- the runtime echo of the reference's
      COVARIANCE_DYNAMIC_RANGE floor (gaussian.h:12), which bounds exactly
      this ratio at seed time.

    When the cluster axis is sharded each shard checks only its rows;
    the psum over ``cluster_axis`` restores the global counts (each shard
    holds a disjoint slice, so the sum is exact, and the result is
    replicated -- the psum-OR aggregation of the module docstring).
    """
    import jax.numpy as jnp
    from jax import lax

    act = state.active
    nk = state.N if Nk is None else Nk

    row_bad = ~(
        jnp.isfinite(state.N) & jnp.isfinite(state.pi)
        & jnp.isfinite(state.constant) & jnp.isfinite(state.avgvar)
        & jnp.all(jnp.isfinite(state.means), axis=-1)
        & jnp.all(jnp.isfinite(state.R), axis=(-2, -1))
        & jnp.all(jnp.isfinite(state.Rinv), axis=(-2, -1))
    )
    n_nonfinite = jnp.sum(act & row_bad, dtype=jnp.int32)

    n_empty = jnp.sum(act & (nk < MEMBERSHIP_FLOOR), dtype=jnp.int32)

    diag = jnp.diagonal(state.R, axis1=-2, axis2=-1)  # [K, D]
    dmax = jnp.max(diag, axis=-1)
    dmin = jnp.min(diag, axis=-1)
    nonempty = act & (nk >= MEMBERSHIP_FLOOR)
    ratio_bad = (dmin <= 0.0) | (dmax > (dynamic_range ** 2)
                                 * jnp.maximum(dmin, 1e-300))
    # Non-finite diagonals already count under nonfinite_params; keep the
    # two lanes disjoint so their sum is interpretable.
    ratio_bad = ratio_bad & jnp.all(jnp.isfinite(diag), axis=-1)
    n_range = jnp.sum(nonempty & ratio_bad, dtype=jnp.int32)

    counts = (_lane(NONFINITE_PARAMS, n_nonfinite)
              + _lane(EMPTY_CLUSTER, n_empty)
              + _lane(COV_DYNAMIC_RANGE, n_range))
    if cluster_axis is not None:
        counts = lax.psum(counts, cluster_axis)
    return counts


def fatal(counts):
    """Trace-safe scalar bool: any fatal lane nonzero."""
    return (counts[NONFINITE_LOGLIK] > 0) | (counts[NONFINITE_PARAMS] > 0)


def pack_word_traced(counts):
    """Trace-safe sibling of :func:`pack_word`: int32 flag word on device
    (the fused sweep stores one per K in its device log)."""
    import jax.numpy as jnp

    lanes = jnp.asarray([1 << b for b in range(NUM_FLAGS)], jnp.int32)
    return jnp.sum((counts > 0) * lanes, dtype=jnp.int32)


# ---------------------------------------------------------------------------
# Host-side word packing / description.
# ---------------------------------------------------------------------------

def pack_word(counts) -> int:
    """Pack a counter vector into the int flag word (host-side)."""
    c = np.asarray(counts).reshape(-1)
    word = 0
    for lane in range(min(c.shape[0], NUM_FLAGS)):
        if c[lane] > 0:
            word |= 1 << lane
    return word


def word_is_fatal(word: int) -> bool:
    return bool(int(word) & FATAL_MASK)


def flag_names(word: int) -> List[str]:
    return [name for lane, name in enumerate(FLAG_NAMES)
            if int(word) & (1 << lane)]


def counts_dict(counts) -> Dict[str, int]:
    c = np.asarray(counts).reshape(-1)
    return {name: int(c[lane]) for lane, name in enumerate(FLAG_NAMES)
            if lane < c.shape[0] and c[lane]}


def health_summary(total_counts, recoveries: int = 0,
                   io_retries: int = 0,
                   restart_drops: int = 0) -> Dict[str, Any]:
    """The ``run_summary.health`` section / ``GMMResult.health`` payload.

    ``restart_drops`` counts restarts dropped from a batched n_init run
    by the drop-one-keep-survivors containment path (a poisoned restart
    leaves the batch instead of rolling back its siblings;
    models/restarts.py).
    """
    word = pack_word(total_counts)
    out = {
        "flags": int(word),
        "flag_names": flag_names(word),
        "fatal": word_is_fatal(word),
        "counters": counts_dict(total_counts),
        "recoveries": int(recoveries),
        "io_retries": int(io_retries),
    }
    if restart_drops:
        out["restart_drops"] = int(restart_drops)
    return out


class NumericalFaultError(RuntimeError):
    """A numerical fault was detected and could not (or must not) be
    recovered. Carries the diagnostic ``bundle``: the flag word and
    per-lane counters, the sweep position, and -- after an exhausted
    escalation ladder -- the full per-attempt history."""

    def __init__(self, message: str, bundle: Dict[str, Any]):
        self.bundle = bundle
        lines = [message]
        for key in sorted(bundle):
            lines.append(f"  {key}: {bundle[key]}")
        super().__init__("\n".join(lines))


def fault_bundle(counts, *, k=None, where: str = "em",
                 attempts: Optional[list] = None,
                 config=None) -> Dict[str, Any]:
    word = pack_word(counts)
    bundle: Dict[str, Any] = {
        "flags": int(word),
        "flag_names": flag_names(word),
        "counters": counts_dict(counts),
        "where": where,
    }
    if k is not None:
        bundle["k"] = int(k)
    if attempts is not None:
        bundle["attempts"] = attempts
    if config is not None:
        bundle["config"] = {
            "quad_mode": config.quad_mode,
            "matmul_precision": config.matmul_precision,
            "dtype": config.dtype,
            "covariance_type": config.covariance_type,
            "recovery": config.recovery,
        }
    return bundle


# ---------------------------------------------------------------------------
# Rollback-and-retry recovery (host side).
# ---------------------------------------------------------------------------

def escalation_ladder(config) -> List[Dict[str, Any]]:
    """The deterministic recovery ladder, bounded by
    ``max_recovery_attempts``. Every rung first rolls back to the K's
    input state and sanitizes it (non-finite entries cleared, non-PD
    covariances identity-reset, variance floor raised by
    ``recovery_boost`` per attempt); rungs 2/3 additionally rebuild the
    model with progressively stabler numerics."""
    rungs = [
        {"action": "regularize"},
        {"action": "centered", "quad_mode": "centered"},
        {"action": "highest", "quad_mode": "centered",
         "matmul_precision": "highest"},
    ]
    return rungs[:max(0, int(config.max_recovery_attempts))]


def repair_state(state, *, diag_only: bool = False, boost: float = 1.0):
    """Sanitize a (host-local) rollback state for a retry.

    Non-finite entries are cleared, the variance floor (``avgvar``, the
    reference's COVARIANCE_DYNAMIC_RANGE diagonal loading) is raised by
    ``boost``, and ``compute_constants`` re-derives Rinv/constant/pi --
    which also identity-resets any covariance whose factorization fails
    (the reference's empty-cluster reset, gaussian.cu:669-678), i.e. it
    repairs singular covariances in the same move.
    """
    import jax.numpy as jnp

    from .ops.constants import compute_constants

    def fin(a, fill=0.0):
        return jnp.where(jnp.isfinite(a), a, fill)

    st = state.replace(
        N=fin(state.N),
        pi=fin(state.pi, 1e-10),
        avgvar=fin(state.avgvar) * jnp.asarray(boost, state.avgvar.dtype),
        means=fin(state.means),
        R=fin(state.R),
        constant=fin(state.constant),
        Rinv=fin(state.Rinv),
    )
    return compute_constants(st, diag_only=diag_only)


def rung_model(model, config, rung: Dict[str, Any]):
    """The model to run a recovery rung on: the primary model for the
    pure-regularization rung, else a same-class rebuild with the rung's
    numerics overrides (cached per rung on the primary model, so a sweep
    that recovers at the same rung repeatedly compiles once)."""
    overrides: Dict[str, Any] = {}
    if "quad_mode" in rung and config.quad_mode != rung["quad_mode"]:
        overrides["quad_mode"] = rung["quad_mode"]
    if ("matmul_precision" in rung
            and config.matmul_precision != rung["matmul_precision"]):
        overrides["matmul_precision"] = rung["matmul_precision"]
    if not overrides:
        return model, config
    if config.precompute_features and overrides.get("quad_mode") == "centered":
        # 'centered' has no loop-invariant feature matrix to hoist
        # (config validation rejects the combination).
        overrides["precompute_features"] = False
    if config.use_pallas == "always" or config.estep_backend == "pallas":
        # Recovery wants the most-conservative path; the kernel override
        # must not pin the escalated run back onto experimental code.
        # (Both spellings overridden together -- __post_init__ rejects a
        # contradictory pair.)
        overrides["use_pallas"] = "never"
        overrides["estep_backend"] = "jnp"
    cfg2 = dataclasses.replace(config, **overrides)

    cache = model.__dict__.setdefault("_recovery_models", {})
    key = tuple(sorted(overrides.items()))
    m2 = cache.get(key)
    if m2 is None:
        from .models.gmm import GMMModel
        from .models.streaming import StreamingGMMModel

        if isinstance(model, StreamingGMMModel):
            m2 = StreamingGMMModel(cfg2)
        elif isinstance(model, GMMModel):
            m2 = GMMModel(cfg2)
        else:  # ShardedGMMModel: keep the SAME mesh (placed data stays valid)
            m2 = type(model)(cfg2, mesh=model.mesh)
        cache[key] = m2
    return m2, cfg2


def _host_state(state, model):
    """Host-local numpy copy of a possibly mesh-placed / multi-host state."""
    from .models.order_search import _host_state as impl

    return impl(state, model)


def recover_em(model, config, rollback, chunks, wts, epsilon, k, *,
               trajectory: bool, rec, log, faulty_counts):
    """Roll back and retry one K's EM up the escalation ladder.

    Returns ``(model, state, loglik, iters, counts, ll_log)`` from the
    first clean rung; the returned model is the rung's (callers adopt it
    for the rest of the sweep -- sticky escalation). Raises
    :class:`NumericalFaultError` when recovery is off, the ladder is
    empty, or every rung stays fatal.
    """
    import jax
    import jax.numpy as jnp

    word = pack_word(faulty_counts)
    if config.recovery != "retry":
        raise NumericalFaultError(
            f"numerical fault at K={int(k)} "
            f"(flags={flag_names(word)}) and recovery is "
            f"{config.recovery!r}",
            fault_bundle(faulty_counts, k=k, config=config))

    ladder = escalation_ladder(config)
    attempts: List[Dict[str, Any]] = []
    host_rollback = jax.tree_util.tree_map(
        jnp.asarray, _host_state(rollback, model))
    for attempt, rung in enumerate(ladder, start=1):
        m2, cfg2 = rung_model(model, config, rung)
        boost = float(config.recovery_boost) ** attempt
        repaired = repair_state(host_rollback, diag_only=cfg2.diag_only,
                                boost=boost)
        if hasattr(m2, "prepare_state"):
            repaired = m2.prepare_state(repaired)
        out = m2.run_em(repaired, chunks, wts, epsilon,
                        trajectory=trajectory)
        if trajectory:
            state, ll, iters, ll_log = out
        else:
            (state, ll, iters), ll_log = out, None
        counts = np.asarray(jax.device_get(m2.last_health), np.int64)
        ll_f, iters_i = float(jax.device_get(ll)), int(jax.device_get(iters))
        ok = not word_is_fatal(pack_word(counts))
        record = {
            "attempt": attempt, "action": rung["action"], "boost": boost,
            "flags": int(pack_word(counts)),
            "flag_names": flag_names(pack_word(counts)),
            "outcome": "recovered" if ok else "fatal",
            "loglik": ll_f,
        }
        attempts.append(record)
        if log is not None:
            log.warning(
                "recovery attempt %d (%s) at K=%d: %s", attempt,
                rung["action"], int(k), record["outcome"])
        if rec is not None and rec.active:
            rec.emit("recovery", k=int(k), attempt=attempt,
                     action=rung["action"], outcome=record["outcome"],
                     flags=record["flags"],
                     flag_names=record["flag_names"])
            rec.metrics.count("recovery_attempts")
            if ok:
                rec.metrics.count("recoveries")
        if ok:
            return m2, state, ll_f, iters_i, counts, ll_log
    raise NumericalFaultError(
        f"numerical fault at K={int(k)} not recovered after "
        f"{len(ladder)} escalation attempt(s) "
        f"(flags={flag_names(word)})",
        fault_bundle(faulty_counts, k=k, attempts=attempts, config=config))


def reseed_empty_clusters(model, state, chunks, seed: int = 0):
    """Reseed empty active clusters from the worst-fit events.

    The reference ELIMINATES empties (gaussian.cu:865-874) -- that stays
    the default. With ``recovery_reseed_empty`` a target-K fit instead
    relocates each empty cluster's mean onto the events the current model
    explains worst (lowest log-evidence in the probe block), giving EM a
    chance to keep the requested K alive. Deterministic: the probe is the
    first data block and ties resolve by row order. Returns
    ``(new_state, n_reseeded)``.
    """
    import jax
    import jax.numpy as jnp

    host = jax.tree_util.tree_map(jnp.asarray, _host_state(state, model))
    act = np.asarray(host.active)
    nk = np.asarray(host.N)
    empty = np.flatnonzero(act & (nk < MEMBERSHIP_FLOOR))
    if empty.size == 0:
        return state, 0

    block = np.asarray(jax.device_get(chunks))
    block = block.reshape(-1, block.shape[-1])[:model.inference_block]
    _, logz = model.infer_posteriors(host, block)
    logz = np.asarray(jax.device_get(logz))[:block.shape[0]]
    worst = np.argsort(logz, kind="stable")[:empty.size]

    means = np.asarray(host.means).copy()
    R = np.asarray(host.R).copy()
    N = np.asarray(host.N).copy()
    live = np.flatnonzero(act & (nk >= MEMBERSHIP_FLOOR))
    # A fresh covariance for the reseeded slots: the mean live covariance
    # (identity if nothing is live), so the new cluster starts wide enough
    # to capture neighbors of its worst-fit seed event.
    R_seed = (R[live].mean(axis=0) if live.size
              else np.eye(R.shape[-1], dtype=R.dtype))
    for slot, row in zip(empty, worst):
        means[slot] = block[row]
        R[slot] = R_seed
        N[slot] = 1.0
    from .ops.constants import compute_constants

    repaired = host.replace(
        means=jnp.asarray(means), R=jnp.asarray(R), N=jnp.asarray(N))
    repaired = compute_constants(repaired,
                                 diag_only=model.config.diag_only)
    if hasattr(model, "prepare_state"):
        repaired = model.prepare_state(repaired)
    return repaired, int(empty.size)
