"""HTTP front end for the serving plane (stream rev v2.7, stdlib-only).

The reference is a single offline binary; our serving loop (PRs 7/8)
spoke JSONL over stdin or a UNIX socket, capping it at one host and one
client locality. This module puts the SAME micro-batch queue core behind
``POST /v1/models/<name>[@<version>]:<op>`` -- every request still rides
the coalescing tick loop, admission control, deadlines, and circuit
breakers of :class:`~.server.GMMServer`; HTTP is a transport, not a
second serving implementation.

Contract (docs/SERVING.md "HTTP front end"):

* ``POST /v1/models/NAME[@VER]:{predict,predict_proba,score_samples,
  score}`` with a JSON body ``{"x": [[...], ...]}``. The per-request
  budget comes from the ``X-GMM-Deadline-Ms`` header (falling back to a
  ``deadline_ms`` body field); the request's trace identity from
  ``X-GMM-Trace-Id`` (minted when absent) and is echoed back in the
  response header, so ``gmm timeline`` flow arrows join client and
  server across the wire.
* ``GET /healthz`` -- liveness: 200 while the process can answer at all.
* ``GET /readyz`` -- routability: flips to 503 the instant a drain
  begins (SIGTERM / --max-runtime), BEFORE the queue flush, so a load
  balancer stops routing while the flush still answers what it admitted.
* ``GET /metrics`` -- the OpenMetrics exposition, rendered by the same
  :func:`~..telemetry.exporter.render_openmetrics` the --metrics-port
  plane uses.

Failure containment, because the network is where the failures live:
per-connection read deadlines (a slowloris client times out instead of
wedging a handler thread), a bounded request body (413 past it), and a
connection cap that sheds 503 + ``Retry-After`` instead of letting a
connection storm exhaust threads. Protocol error tokens map onto status
codes (overloaded -> 429, shutting_down / circuit_open -> 503 +
``Retry-After``, deadline_expired -> 504, unknown model -> 404,
dispatch/poison failures -> 500, worker loss past the sibling retry ->
502) so a fleet's LB and the :class:`~.client.GMMClient` retry policy
can tell retryable congestion from deterministic client error.
"""

from __future__ import annotations

import collections
import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

from .. import telemetry
from ..telemetry.exporter import render_openmetrics
from . import wire

#: ops accepted in the URL (mirrors server.OPS; ping/shutdown stay
#: JSONL-protocol-only -- an HTTP caller probes /healthz and drains via
#: SIGTERM, not via a scoring endpoint).
HTTP_OPS = ("predict", "predict_proba", "score_samples", "score")

DEFAULT_MAX_BODY_BYTES = 8 << 20
DEFAULT_READ_TIMEOUT_S = 30.0
DEFAULT_MAX_CONNECTIONS = 64

#: Retry-After seconds suggested on 429/503 sheds (coarse by design: the
#: client's jittered backoff is the real pacing; this is the floor).
RETRY_AFTER_S = 1


def parse_model_path(path: str) -> Optional[Tuple[str, Optional[int], str]]:
    """``/v1/models/NAME[@VER]:OP`` -> (name, version, op), or None."""
    prefix = "/v1/models/"
    if not path.startswith(prefix):
        return None
    rest = path[len(prefix):]
    spec, sep, op = rest.rpartition(":")
    if not sep or not spec or op not in HTTP_OPS:
        return None
    name, at, ver = spec.partition("@")
    if not name or (at and not ver):
        return None     # "m@:op" is a malformed pin, not latest
    version: Optional[int] = None
    if ver:
        try:
            version = int(ver)
        except ValueError:
            return None
    return name, version, op


def status_for_error(error: str) -> int:
    """Protocol error token -> HTTP status (the containment taxonomy)."""
    if error == "overloaded":
        return 429
    if error in ("shutting_down", "circuit_open"):
        return 503
    if error in ("deadline_expired", "http_timeout"):
        return 504
    if error == "worker_unavailable":
        return 502
    if error == "non_finite_scores" or error.startswith("dispatch failed"):
        return 500
    if error in ("frame_too_large", "body_too_large", "line_too_long"):
        return 413
    if "unknown model" in error or "registry" in error:
        return 404
    # bad_request / bad_frame / bad_json and every other client-content
    # token: deterministic 400, never retried.
    return 400


class InprocBackend:
    """Single-process backend: HTTP handler threads submit straight onto
    the owning :class:`~.server.GMMServer`'s batching queue (exactly like
    UNIX-socket reader threads do) and block on the reply."""

    def __init__(self, server):
        self._server = server

    def score(self, req: dict,
              trace_id: Optional[str] = None) -> Tuple[dict, Dict[str, Any]]:
        srv = self._server
        done = threading.Event()
        box: Dict[str, dict] = {}

        def reply(resp: dict) -> None:
            box["resp"] = resp
            done.set()

        # admit_request decodes x at admission (bad_request / bad_frame
        # answer synchronously on this thread) and sheds synchronously
        # too, exactly as submit did.
        srv.admit_request(req, reply, trace_id=trace_id)
        # Bound the wait by the request's own budget plus grace for the
        # in-flight dispatch; a budget-less request waits for the loop.
        ms = srv._default_deadline_ms
        raw = req.get("deadline_ms") if isinstance(req, dict) else None
        if isinstance(raw, (int, float)) and not isinstance(raw, bool):
            ms = float(raw)
        timeout = (ms / 1e3 + 10.0) if ms and ms > 0 else None
        if not done.wait(timeout):
            return ({"id": req.get("id"), "ok": False,
                     "error": "http_timeout",
                     "detail": "no reply within the request budget"},
                    {})
        return box["resp"], {}

    def ready(self) -> bool:
        return not self._server.draining

    def gauges(self) -> Dict[str, float]:
        return self._server.live_gauges()

    def http_stats(self) -> Dict[str, int]:
        return {}


class HTTPFrontEnd:
    """The ThreadingHTTPServer wrapper: routing, header contract,
    connection accounting, probes, and the v2.7 http telemetry.

    ``backend`` is duck-typed (:class:`InprocBackend` or the worker
    pool's router): ``score(req, trace_id) -> (response, meta)``,
    ``ready() -> bool``, ``gauges() -> dict``, ``http_stats() -> dict``.
    ``stopping`` (optional callable) joins the ambient supervisor's stop
    flag into /readyz so the probe flips at signal time, before the
    backend notices the drain.
    """

    def __init__(self, backend, *, host: str = "127.0.0.1", port: int = 0,
                 max_body_bytes: int = DEFAULT_MAX_BODY_BYTES,
                 read_timeout_s: float = DEFAULT_READ_TIMEOUT_S,
                 max_connections: int = DEFAULT_MAX_CONNECTIONS,
                 stopping: Optional[Callable[[], bool]] = None):
        self._backend = backend
        self._requested = (host, int(port))
        self._max_body = int(max_body_bytes)
        self._read_timeout_s = float(read_timeout_s)
        self._max_connections = int(max_connections)
        self._stopping = stopping
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._connections = 0
        self._latencies: collections.deque = collections.deque(
            maxlen=100_000)
        self.requests = 0
        self.rows = 0
        self.errors_4xx = 0
        self.errors_5xx = 0
        self.shed_connections = 0

    # -- lifecycle -------------------------------------------------------

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> "HTTPFrontEnd":
        if self._httpd is not None:
            return self
        front = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = "gmm-serve"

            def setup(self):
                super().setup()
                # Slowloris defense: a client that trickles (or never
                # sends) its request times out here instead of parking a
                # handler thread forever.
                self.connection.settimeout(front._read_timeout_s)
                with front._lock:
                    front._connections += 1
                    self._over_cap = (front._connections
                                      > front._max_connections)

            def finish(self):
                with front._lock:
                    front._connections -= 1
                try:
                    super().finish()
                except OSError:
                    pass

            def handle_one_request(self):
                try:
                    super().handle_one_request()
                except (socket.timeout, TimeoutError):
                    self.close_connection = True

            def do_GET(self):  # noqa: N802 (http.server API)
                front._handle_get(self)

            def do_POST(self):  # noqa: N802
                front._handle_post(self)

            def log_message(self, *args):  # keep stderr quiet per request
                pass

        self._httpd = ThreadingHTTPServer(self._requested, _Handler)
        self._httpd.daemon_threads = True
        httpd = self._httpd
        self._thread = threading.Thread(
            target=lambda: httpd.serve_forever(poll_interval=0.02),
            name="gmm-http-front", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- rollup ----------------------------------------------------------

    def live_gauges(self) -> Dict[str, float]:
        gauges = {
            "gmm_http_connections": float(self._connections),
            "gmm_http_requests": float(self.requests),
            "gmm_http_errors_4xx": float(self.errors_4xx),
            "gmm_http_errors_5xx": float(self.errors_5xx),
            "gmm_http_shed_connections": float(self.shed_connections),
        }
        try:
            gauges.update(self._backend.gauges() or {})
        except Exception:
            pass
        return gauges

    def http_rollup(self) -> Dict[str, int]:
        """The ``serve_summary.http`` block: front-end counters plus the
        backend's worker-pool counters (zeros in-process)."""
        rollup = {
            "requests": int(self.requests),
            "errors_4xx": int(self.errors_4xx),
            "errors_5xx": int(self.errors_5xx),
            "shed_connections": int(self.shed_connections),
            "retries": 0, "retries_exhausted": 0, "worker_crashes": 0,
            "worker_respawns": 0, "worker_quarantines": 0, "workers": 0,
        }
        try:
            rollup.update(self._backend.http_stats() or {})
        except Exception:
            pass
        return rollup

    # -- request handling ------------------------------------------------

    def _ready(self) -> bool:
        if self._stopping is not None and self._stopping():
            return False
        try:
            return bool(self._backend.ready())
        except Exception:
            return False

    def _send(self, h, status: int, body: bytes,
              content_type: str = "application/json",
              headers: Optional[Dict[str, str]] = None) -> None:
        try:
            h.send_response(status)
            h.send_header("Content-Type", content_type)
            h.send_header("Content-Length", str(len(body)))
            for key, val in (headers or {}).items():
                h.send_header(key, val)
            h.end_headers()
            h.wfile.write(body)
        except (BrokenPipeError, OSError):
            h.close_connection = True  # client went away mid-reply

    def _send_json(self, h, status: int, obj: dict,
                   headers: Optional[Dict[str, str]] = None) -> None:
        self._send(h, status, (json.dumps(obj) + "\n").encode("utf-8"),
                   headers=headers)

    def latency_summary(self) -> Dict[str, float]:
        """p50/p99/mean/max over the HTTP edge's request latencies (the
        pool parent's serve_summary.latency_ms; in-process mode uses the
        queue core's own summary)."""
        lat = sorted(self._latencies)
        if not lat:
            return {"p50": 0.0, "p99": 0.0, "mean": 0.0, "max": 0.0}

        def pct(q: float) -> float:
            return lat[min(len(lat) - 1, int(q * (len(lat) - 1) + 0.5))]

        return {"p50": round(pct(0.50), 3), "p99": round(pct(0.99), 3),
                "mean": round(sum(lat) / len(lat), 3),
                "max": round(lat[-1], 3)}

    def _count_status(self, status: int, latency_ms: float,
                      n=None) -> None:
        with self._lock:
            self.requests += 1
            self._latencies.append(latency_ms)
            if isinstance(n, int):
                self.rows += n
            if 400 <= status < 500:
                self.errors_4xx += 1
            elif status >= 500:
                self.errors_5xx += 1

    def _emit(self, h, status: int, t0: float, *, model=None, op=None,
              n=None, error=None, worker=None, retried=None,
              trace_id=None) -> None:
        latency_ms = (time.perf_counter() - t0) * 1e3
        self._count_status(status, latency_ms, n)
        rec = telemetry.current()
        if not rec.active:
            return
        rec.emit("http_request", method=h.command,
                 path=h.path.split("?", 1)[0], status=int(status),
                 latency_ms=round(latency_ms, 3),
                 **{k: v for k, v in (
                     ("model", model), ("op", op), ("n", n),
                     ("error", error), ("worker", worker),
                     ("retried", retried), ("trace_id", trace_id),
                 ) if v is not None})
        rec.metrics.count("http_requests")
        rec.metrics.observe("http.latency_ms", latency_ms)
        if status >= 500:
            rec.metrics.count("http_errors_5xx")
        elif status >= 400:
            rec.metrics.count("http_errors_4xx")

    def _shed_connection(self, h, t0: float) -> None:
        with self._lock:
            self.shed_connections += 1
        h.close_connection = True
        self._emit(h, 503, t0, error="connection_cap")
        rec = telemetry.current()
        if rec.active:
            rec.metrics.count("http_shed_connections")
        self._send_json(
            h, 503,
            {"ok": False, "error": "connection_cap",
             "detail": f"connection cap of {self._max_connections} "
             "reached; retry after backoff"},
            headers={"Retry-After": str(RETRY_AFTER_S),
                     "Connection": "close"})

    def _handle_get(self, h) -> None:
        t0 = time.perf_counter()
        path = h.path.split("?", 1)[0]
        if getattr(h, "_over_cap", False):
            self._shed_connection(h, t0)
            return
        if path == "/healthz":
            self._send_json(h, 200, {"ok": True})
            return  # probes stay out of the request counters
        if path == "/readyz":
            if self._ready():
                self._send_json(h, 200, {"ok": True, "ready": True})
            else:
                self._send_json(
                    h, 503, {"ok": False, "ready": False,
                             "error": "draining"},
                    headers={"Retry-After": str(RETRY_AFTER_S)})
            return
        if path in ("/metrics", "/"):
            rec = telemetry.current()
            snapshot, buckets = {}, {}
            pair_fn = getattr(rec.metrics, "snapshot_with_buckets", None)
            if callable(pair_fn):
                snapshot, buckets = pair_fn()
            else:
                snapshot = rec.metrics.snapshot()
            body = render_openmetrics(snapshot, self.live_gauges(),
                                      buckets).encode("utf-8")
            self._send(h, 200, body,
                       content_type="application/openmetrics-text; "
                       "version=1.0.0; charset=utf-8")
            return
        self._emit(h, 404, t0, error="no_such_endpoint")
        self._send_json(h, 404, {"ok": False, "error": "no_such_endpoint",
                                 "detail": f"no endpoint {path!r}"})

    def _handle_post(self, h) -> None:
        t0 = time.perf_counter()
        if getattr(h, "_over_cap", False):
            self._shed_connection(h, t0)
            return
        path = h.path.split("?", 1)[0]
        route = parse_model_path(path)
        if route is None:
            self._emit(h, 404, t0, error="no_such_endpoint")
            self._send_json(
                h, 404,
                {"ok": False, "error": "no_such_endpoint",
                 "detail": "POST /v1/models/NAME[@VER]:OP with OP one "
                 f"of {', '.join(HTTP_OPS)}"})
            return
        name, version, op = route
        length = h.headers.get("Content-Length")
        if length is None:
            self._emit(h, 411, t0, model=name, op=op,
                       error="length_required")
            self._send_json(h, 411, {"ok": False,
                                     "error": "length_required"})
            return
        try:
            n_bytes = int(length)
        except ValueError:
            self._emit(h, 400, t0, model=name, op=op,
                       error="bad_content_length")
            self._send_json(h, 400, {"ok": False,
                                     "error": "bad_content_length"})
            return
        if n_bytes > self._max_body:
            # Reject WITHOUT reading: the bound exists so an oversized
            # body never occupies memory or the read deadline.
            h.close_connection = True
            self._emit(h, 413, t0, model=name, op=op, error="body_too_large")
            self._send_json(
                h, 413,
                {"ok": False, "error": "body_too_large",
                 "detail": f"body of {n_bytes} bytes exceeds the "
                 f"{self._max_body}-byte bound"},
                headers={"Connection": "close"})
            return
        try:
            body = h.rfile.read(n_bytes)
        except (socket.timeout, TimeoutError, OSError):
            h.close_connection = True  # slowloris body: drop the thread
            return
        ctype = (h.headers.get("Content-Type")
                 or "").split(";", 1)[0].strip().lower()
        if ctype == wire.CONTENT_TYPE:
            # Zero-copy binary payload (docs/SERVING.md "Binary
            # payloads"): the entire body is one x-gmm-rows frame;
            # model/op/version ride the URL, the deadline rides the
            # X-GMM-Deadline-Ms header. Decoded via np.frombuffer --
            # no JSON float parsing on the scoring hot path.
            try:
                x: Any = wire.decode_rows(body)
            except wire.WireError as e:
                self._emit(h, 400, t0, model=name, op=op,
                           error="bad_frame")
                self._send_json(h, 400,
                                {"ok": False, "error": "bad_frame",
                                 "detail": str(e)})
                return
            payload: Dict[str, Any] = {}
        else:
            try:
                payload = (json.loads(body.decode("utf-8"))
                           if n_bytes else {})
                if not isinstance(payload, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, UnicodeDecodeError) as e:
                self._emit(h, 400, t0, model=name, op=op,
                           error="bad_json")
                self._send_json(h, 400, {"ok": False, "error": "bad_json",
                                         "detail": str(e)})
                return
            x = payload.get("x")
        req = {"model": name, "op": op, "x": x}
        if version is not None:
            req["version"] = version
        if payload.get("id") is not None:
            req["id"] = payload["id"]
        deadline_hdr = h.headers.get("X-GMM-Deadline-Ms")
        if deadline_hdr is not None:
            try:
                req["deadline_ms"] = float(deadline_hdr)
            except ValueError:
                self._emit(h, 400, t0, model=name, op=op,
                           error="bad_deadline")
                self._send_json(
                    h, 400, {"ok": False, "error": "bad_deadline",
                             "detail": "X-GMM-Deadline-Ms must be a "
                             "number"})
                return
        elif payload.get("deadline_ms") is not None:
            req["deadline_ms"] = payload["deadline_ms"]
        trace_id = h.headers.get("X-GMM-Trace-Id") or None
        try:
            resp, meta = self._backend.score(req, trace_id=trace_id)
        except Exception as e:  # backend must never kill the handler
            self._emit(h, 500, t0, model=name, op=op,
                       error=f"backend error: {e}")
            self._send_json(h, 500, {"ok": False,
                                     "error": f"backend error: {e}"})
            return
        trace_out = resp.get("trace_id") or trace_id
        headers = {}
        if trace_out:
            headers["X-GMM-Trace-Id"] = str(trace_out)
        if resp.get("ok"):
            status = 200
        else:
            status = status_for_error(str(resp.get("error") or ""))
            if status in (429, 503):
                headers["Retry-After"] = str(RETRY_AFTER_S)
        self._emit(h, status, t0, model=name, op=op,
                   n=resp.get("n"),
                   error=None if resp.get("ok") else resp.get("error"),
                   worker=meta.get("worker"), retried=meta.get("retried"),
                   trace_id=trace_out)
        self._send_json(h, status, resp, headers=headers)
