"""Resilient HTTP client for the serving tier (stdlib-only).

:class:`GMMClient` is the reference client for docs/SERVING.md's HTTP
front end, and the load half of ``bench.py --http``. The point is not
the four one-line scoring methods -- it is the retry discipline around
them, because a naive client is how a single slow server becomes a
regional outage:

* **deadline propagation** -- one budget covers the WHOLE call, retries
  included: each attempt's ``X-GMM-Deadline-Ms`` header carries the
  remaining budget, so the server sheds work the client has already
  given up on instead of scoring into the void;
* **bounded jittered-backoff retries** -- only on transport failures and
  explicitly-retryable statuses (429/502/503), never on deterministic
  client errors (4xx) or dispatch failures (500); honors the server's
  ``Retry-After`` when it names a longer wait than the backoff ladder;
* **retry budget** -- a token bucket refilled by SUCCESSFUL requests
  (``retry_budget`` tokens each, spend 1.0 per retry): under a real
  outage the bucket drains and the client fails fast instead of
  multiplying the dead server's load by ``1 + retries`` -- the storm
  amplification cap;
* **latency hedging** (opt-in) -- ``hedge_ms`` launches ONE duplicate of
  a still-unanswered request after that many milliseconds and takes the
  first answer (scoring is idempotent); tail latency hiding for the
  p99, paid for with bounded extra load.

Every knob is deterministic under ``seed`` so tests and the bench can
replay schedules.
"""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
from typing import Any, Dict, List, Optional
from urllib.parse import urlsplit

import numpy as np

from . import wire

RETRYABLE_STATUSES = (429, 502, 503)


class GMMClientError(RuntimeError):
    """Transport/budget failure after the retry policy gave up.
    ``status`` carries the last HTTP status (None = connection error);
    ``body`` the last decoded response body, when one arrived."""

    def __init__(self, msg: str, status: Optional[int] = None,
                 body: Optional[dict] = None):
        super().__init__(msg)
        self.status = status
        self.body = body


class GMMClient:
    """One serving-tier endpoint, with the retry/hedging policy baked in.

    Thread-safe: each request opens its own connection (the resilience
    policy needs per-attempt sockets anyway -- a retry must not reuse
    the pipe its predecessor died on), and the retry-budget bucket is
    the only shared state, guarded by a lock.
    """

    def __init__(self, base_url: str, *, timeout_s: float = 30.0,
                 retries: int = 2, backoff_base_s: float = 0.05,
                 retry_budget: float = 0.2, hedge_ms: Optional[float] = None,
                 encoding: str = "json", seed: int = 0):
        if encoding not in ("json", "binary"):
            raise ValueError(
                f"encoding must be 'json' or 'binary', got {encoding!r}")
        # 'binary' posts each request's rows as ONE x-gmm-rows frame
        # (serving/wire.py) instead of a JSON body: no float
        # stringification client-side, no JSON float parsing
        # server-side, bit-identical responses either way (a JSON body
        # parses to float64 before the executor cast; the binary
        # encoder packs float64 unless handed float32 rows).
        self._encoding = encoding
        parts = urlsplit(base_url if "//" in base_url
                         else f"http://{base_url}")
        self._host = parts.hostname or "127.0.0.1"
        self._port = parts.port or 80
        self._timeout_s = float(timeout_s)
        self._retries = int(retries)
        self._backoff_base_s = float(backoff_base_s)
        self._budget_ratio = float(retry_budget)
        self._hedge_ms = float(hedge_ms) if hedge_ms else None
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # The bucket starts with enough for a few retries so a cold
        # client can survive hitting a mid-respawn pool on request one.
        self._tokens = 2.0
        self._tokens_cap = 10.0
        self.requests = 0
        self.retries = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.budget_denied = 0

    # -- scoring API -----------------------------------------------------

    def predict(self, model: str, x, **kw) -> List[int]:
        return self._call_op(model, "predict", x, **kw)

    def predict_proba(self, model: str, x, **kw) -> List[List[float]]:
        return self._call_op(model, "predict_proba", x, **kw)

    def score_samples(self, model: str, x, **kw) -> List[float]:
        return self._call_op(model, "score_samples", x, **kw)

    def score(self, model: str, x, **kw) -> float:
        return self._call_op(model, "score", x, **kw)

    def _call_op(self, model: str, op: str, x, *,
                 version: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 request_id: Any = None):
        resp = self.request(model, op, x, version=version,
                            deadline_ms=deadline_ms,
                            request_id=request_id)
        return resp["result"]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"requests": self.requests, "retries": self.retries,
                    "hedges": self.hedges, "hedge_wins": self.hedge_wins,
                    "budget_denied": self.budget_denied,
                    "retry_tokens": round(self._tokens, 3)}

    # -- probes ----------------------------------------------------------

    def healthz(self) -> bool:
        return self._probe("/healthz")

    def readyz(self) -> bool:
        return self._probe("/readyz")

    def _probe(self, path: str) -> bool:
        try:
            status, _, _ = self._attempt("GET", path, None, None, None)
            return status == 200
        except OSError:
            return False

    # -- the retry engine ------------------------------------------------

    def request(self, model: str, op: str, x, *,
                version: Optional[int] = None,
                deadline_ms: Optional[float] = None,
                request_id: Any = None,
                encoding: Optional[str] = None) -> dict:
        """One scored request under the full policy. Returns the decoded
        response body of the first 200; raises :class:`GMMClientError`
        otherwise. ``encoding`` overrides the client default per
        request ('binary' sends one x-gmm-rows frame; the request id
        only rides JSON bodies)."""
        enc = encoding or self._encoding
        if enc not in ("json", "binary"):
            raise ValueError(
                f"encoding must be 'json' or 'binary', got {enc!r}")
        spec = model if version is None else f"{model}@{version}"
        path = f"/v1/models/{spec}:{op}"
        if enc == "binary":
            if request_id is not None:
                raise ValueError(
                    "binary encoding has no body field for request_id; "
                    "use encoding='json' when an id must round-trip")
            body = wire.encode_rows(np.asarray(x))
            headers = {"Content-Type": wire.CONTENT_TYPE}
        else:
            body = json.dumps(
                {"x": x, **({"id": request_id} if request_id is not None
                            else {})}).encode("utf-8")
            headers = None
        t_end = (time.perf_counter() + deadline_ms / 1e3
                 if deadline_ms else None)
        with self._lock:
            self.requests += 1
        last_status: Optional[int] = None
        last_body: Optional[dict] = None
        last_err = "no attempt ran"
        for attempt in range(self._retries + 1):
            remaining_ms = None
            if t_end is not None:
                remaining_ms = (t_end - time.perf_counter()) * 1e3
                if remaining_ms <= 0:
                    raise GMMClientError(
                        f"{path}: deadline of {deadline_ms}ms exhausted "
                        f"after {attempt} attempt(s)", last_status,
                        last_body)
            if attempt > 0 and not self._spend_retry_token():
                with self._lock:
                    self.budget_denied += 1
                raise GMMClientError(
                    f"{path}: retry budget exhausted (failing fast "
                    "instead of amplifying load): " + last_err,
                    last_status, last_body)
            try:
                status, resp_headers, decoded = self._attempt_hedged(
                    path, body, remaining_ms, headers)
            except OSError as e:
                last_err = f"connection failed: {e}"
                last_status, last_body = None, None
                self._sleep_backoff(attempt, None, t_end)
                continue
            last_status, last_body = status, decoded
            if status == 200:
                self._refill()
                return decoded or {}
            last_err = (f"HTTP {status}: "
                        f"{(decoded or {}).get('error', '?')}")
            if status not in RETRYABLE_STATUSES:
                raise GMMClientError(f"{path}: {last_err}", status,
                                     decoded)
            self._sleep_backoff(attempt,
                                resp_headers.get("Retry-After"), t_end)
        raise GMMClientError(
            f"{path}: retries exhausted after {self._retries + 1} "
            "attempts: " + last_err, last_status, last_body)

    def _spend_retry_token(self) -> bool:
        with self._lock:
            if self._tokens < 1.0:
                return False
            self._tokens -= 1.0
            self.retries += 1
            return True

    def _refill(self) -> None:
        with self._lock:
            self._tokens = min(self._tokens_cap,
                               self._tokens + self._budget_ratio)

    def _sleep_backoff(self, attempt: int, retry_after: Optional[str],
                       t_end: Optional[float]) -> None:
        """Jittered doubling backoff, raised to the server's Retry-After
        when it asks for more, clipped to the remaining deadline."""
        with self._lock:
            jitter = self._rng.random()
        wait = self._backoff_base_s * (2.0 ** attempt) * (1.0 + jitter)
        if retry_after:
            try:
                wait = max(wait, float(retry_after))
            except ValueError:
                pass
        if t_end is not None:
            wait = min(wait, max(0.0, t_end - time.perf_counter()))
        if wait > 0:
            time.sleep(wait)

    # -- transport -------------------------------------------------------

    def _attempt_hedged(self, path: str, body: bytes,
                        remaining_ms: Optional[float],
                        req_headers: Optional[Dict[str, str]] = None):
        """One POST attempt, optionally racing a single hedge duplicate
        launched after ``hedge_ms`` of silence; first answer wins."""
        if self._hedge_ms is None:
            return self._attempt("POST", path, body, remaining_ms,
                                 req_headers)
        done = threading.Event()
        results: List[tuple] = []
        errors: List[BaseException] = []
        lock = threading.Lock()

        def run(is_hedge: bool):
            try:
                out = self._attempt("POST", path, body, remaining_ms,
                                    req_headers)
                with lock:
                    results.append((is_hedge, out))
            except OSError as e:
                with lock:
                    errors.append(e)
            finally:
                done.set()

        primary = threading.Thread(target=run, args=(False,), daemon=True)
        primary.start()
        hedged = False
        if not done.wait(self._hedge_ms / 1e3):
            hedged = True
            with self._lock:
                self.hedges += 1
            threading.Thread(target=run, args=(True,),
                             daemon=True).start()
        timeout = (remaining_ms / 1e3 + 5.0 if remaining_ms is not None
                   else self._timeout_s + 5.0)
        t_stop = time.perf_counter() + timeout
        while time.perf_counter() < t_stop:
            with lock:
                if results:
                    is_hedge, out = results[0]
                    if is_hedge and hedged:
                        with self._lock:
                            self.hedge_wins += 1
                    return out
                # every launched leg failed -> surface the first error
                if errors and len(errors) >= (2 if hedged else 1):
                    raise errors[0]
            done.wait(0.005)
            done.clear()
        raise TimeoutError(f"{path}: no leg answered in {timeout:.1f}s")

    def _attempt(self, method: str, path: str, body: Optional[bytes],
                 remaining_ms: Optional[float],
                 extra_headers: Optional[Dict[str, str]]):
        """One HTTP round trip. Returns (status, headers, decoded_body);
        raises OSError flavors on transport failure."""
        timeout = self._timeout_s
        if remaining_ms is not None:
            timeout = min(timeout, remaining_ms / 1e3 + 1.0)
        conn = http.client.HTTPConnection(self._host, self._port,
                                          timeout=timeout)
        try:
            headers = {"Content-Type": "application/json"}
            if remaining_ms is not None:
                headers["X-GMM-Deadline-Ms"] = f"{remaining_ms:.1f}"
            headers.update(extra_headers or {})
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            decoded: Optional[dict] = None
            if raw:
                try:
                    decoded = json.loads(raw.decode("utf-8"))
                except ValueError:
                    decoded = None
            return resp.status, dict(resp.getheaders()), decoded
        finally:
            conn.close()
