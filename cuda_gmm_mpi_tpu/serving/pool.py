"""Supervised serving worker pool (stream rev v2.7; docs/ROBUSTNESS.md
"Network failure containment").

``gmm serve --http PORT --workers N`` forks N child processes, each
running the ALREADY-TESTED single-process serve loop (``gmm serve
--socket``) over the shared model registry, and routes HTTP requests to
them over per-worker UNIX sockets. The parent process is a pure router +
supervisor: it never imports an executor or loads a model, so a worker
taking a SIGKILL (OOM, bad node, fault injection) can never take the
front end down with it.

Containment arc, in order:

* **routing affinity** -- (model, version) hashes to a stable worker
  slot (crc32), so each worker's AOT executor cache warms for its own
  slice of the registry instead of every worker compiling everything;
* **sibling retry** -- a request in flight on a crashing worker fails
  its socket, and because scoring is idempotent the router retries it
  ONCE on the next live sibling; the client sees one answer, not an
  error (``retries`` counted; both legs dead -> 502
  ``worker_unavailable`` + ``retries_exhausted``);
* **respawn** -- the supervisor notices the exit (``worker_exit``,
  ``crash: true``), and relaunches with jittered doubling backoff
  (deterministic per slot+generation, so two crashed workers never
  thundering-herd the registry);
* **quarantine** -- a slot that crashes ``quarantine_after`` times in a
  row stops respawning: a reason file lands in the worker directory
  (``worker<i>.quarantine.json``) for the operator, siblings keep
  serving, and /readyz stays green as long as ANY worker lives.

Each spawn also writes ``worker<i>.json`` ({pid, socket, gen}) so tests
and the bench's kill-under-load probe can target a real pid. Children
get ``GMM_SERVE_WORKER`` / ``GMM_SERVE_WORKER_GEN`` stamped into their
env -- the match keys of the ``worker_crash`` fault kind
(testing/faults.py).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry
from . import wire

#: extra seconds of socket patience past a request's own deadline: the
#: worker answers deadline_expired itself; the transport must outlive it.
DEADLINE_GRACE_S = 10.0

#: how long a request parks waiting for ANY live worker before 502:
#: covers the whole-pool-dead respawn window (backoff + process start)
#: so a brief total outage reads as latency, not an error.
NO_WORKER_WAIT_S = 15.0


class _Worker:
    """One supervised slot: the live process (if any) and its crash
    history. All mutation happens under the pool lock."""

    def __init__(self, idx: int, sock: str):
        self.idx = idx
        self.sock = sock
        self.proc: Optional[subprocess.Popen] = None
        self.gen = 0                  # respawn generation (0 = first)
        self.consecutive_crashes = 0
        self.quarantined = False
        self.respawn_at: Optional[float] = None  # backoff deadline
        self.started_at = 0.0
        self.log = None

    @property
    def alive(self) -> bool:
        return (self.proc is not None and self.proc.poll() is None
                and os.path.exists(self.sock))


class WorkerPool:
    """Spawn, route to, and supervise N ``gmm serve --socket`` workers.

    ``command_for(idx, sock_path)`` builds one worker's argv (the serve
    CLI reconstructs it from its own flags minus the pool/http ones).
    """

    def __init__(self, n_workers: int, worker_dir: str, command_for,
                 *, backoff_base_s: float = 0.5,
                 quarantine_after: int = 5,
                 spawn_timeout_s: float = 120.0,
                 request_timeout_s: float = 60.0):
        if n_workers < 1:
            raise ValueError("worker pool needs at least 1 worker")
        self._n = int(n_workers)
        self._dir = worker_dir
        self._command_for = command_for
        self._backoff_base_s = float(backoff_base_s)
        self._quarantine_after = int(quarantine_after)
        self._spawn_timeout_s = float(spawn_timeout_s)
        self._request_timeout_s = float(request_timeout_s)
        self._lock = threading.Lock()
        self._draining = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(worker_dir, exist_ok=True)
        self._workers = [
            _Worker(i, os.path.join(worker_dir, f"worker{i}.sock"))
            for i in range(self._n)]
        self.worker_crashes = 0
        self.worker_respawns = 0
        self.worker_quarantines = 0
        self.retries = 0
        self.retries_exhausted = 0

    # -- lifecycle -------------------------------------------------------

    def _spawn(self, w: _Worker, *, respawn: bool) -> None:
        """Launch one worker process (pool lock held)."""
        if os.path.exists(w.sock):
            os.remove(w.sock)  # a stale socket must not look alive
        env = dict(os.environ,
                   GMM_SERVE_WORKER=str(w.idx),
                   GMM_SERVE_WORKER_GEN=str(w.gen))
        if w.log is None:
            w.log = open(os.path.join(self._dir, f"worker{w.idx}.log"),
                         "ab", buffering=0)
        w.proc = subprocess.Popen(self._command_for(w.idx, w.sock),
                                  stdin=subprocess.DEVNULL,
                                  stdout=w.log, stderr=w.log, env=env)
        w.started_at = time.monotonic()
        w.respawn_at = None
        state = {"worker": w.idx, "pid": w.proc.pid, "socket": w.sock,
                 "gen": w.gen}
        path = os.path.join(self._dir, f"worker{w.idx}.json")
        with open(path + ".tmp", "w", encoding="utf-8") as f:
            json.dump(state, f)
        os.replace(path + ".tmp", path)
        rec = telemetry.current()
        if rec.active:
            rec.emit("worker_spawn", worker=w.idx, pid=w.proc.pid,
                     socket=w.sock, attempt=w.consecutive_crashes,
                     respawn=bool(respawn),
                     **({"backoff_s": round(self._backoff_s(w), 3)}
                        if respawn else {}))
            rec.metrics.count("worker_spawns")

    def start(self) -> "WorkerPool":
        with self._lock:
            for w in self._workers:
                self._spawn(w, respawn=False)
        deadline = time.monotonic() + self._spawn_timeout_s
        for w in self._workers:
            while not os.path.exists(w.sock):
                if w.proc.poll() is not None:
                    raise RuntimeError(
                        f"worker {w.idx} exited with code "
                        f"{w.proc.returncode} before its socket came up "
                        f"(see {self._dir}/worker{w.idx}.log)")
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"worker {w.idx} socket {w.sock} did not appear "
                        f"within {self._spawn_timeout_s:.0f}s")
                time.sleep(0.02)
        self._thread = threading.Thread(target=self._supervise,
                                        name="gmm-worker-supervisor",
                                        daemon=True)
        self._thread.start()
        return self

    def _backoff_s(self, w: _Worker) -> float:
        """Jittered doubling backoff for this slot's next respawn.
        Deterministic per (slot, generation): reproducible in tests, and
        no two slots share a schedule."""
        attempt = max(1, w.consecutive_crashes)
        base = self._backoff_base_s * (2.0 ** (attempt - 1))
        seed = zlib.crc32(f"{w.idx}:{w.gen}".encode()) % 1000
        return base * (1.0 + seed / 2000.0)  # +0..50% jitter

    def _handle_exit(self, w: _Worker) -> None:
        """One observed worker death (pool lock held)."""
        code = w.proc.returncode
        pid = w.proc.pid
        rec = telemetry.current()
        if self._draining.is_set():
            if rec.active:
                rec.emit("worker_exit", worker=w.idx, exitcode=int(code),
                         pid=pid, reason="drain", crash=False)
            w.proc = None
            return
        self.worker_crashes += 1
        w.consecutive_crashes += 1
        quarantine = w.consecutive_crashes >= self._quarantine_after
        if rec.active:
            rec.emit("worker_exit", worker=w.idx, exitcode=int(code),
                     pid=pid, reason="crash", crash=True,
                     quarantined=bool(quarantine))
            rec.metrics.count("worker_crashes")
        try:
            if os.path.exists(w.sock):
                os.remove(w.sock)  # dead socket must stop routing NOW
        except OSError:
            pass
        w.proc = None
        if quarantine:
            self.worker_quarantines += 1
            w.quarantined = True
            reason = {
                "worker": w.idx, "pid": pid, "last_exitcode": int(code),
                "consecutive_crashes": int(w.consecutive_crashes),
                "reason": "crash loop: worker died "
                          f"{w.consecutive_crashes} consecutive times",
                "quarantined_at": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            }
            path = os.path.join(self._dir,
                                f"worker{w.idx}.quarantine.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(reason, f, indent=1)
            if rec.active:
                rec.metrics.count("worker_quarantines")
            return
        w.respawn_at = time.monotonic() + self._backoff_s(w)

    def _supervise(self) -> None:
        """The supervision loop: notice exits, pace respawns, reset the
        crash streak once a respawned worker proves stable."""
        while not self._stop.is_set():
            with self._lock:
                for w in self._workers:
                    if w.proc is not None and w.proc.poll() is not None:
                        self._handle_exit(w)
                    elif (w.proc is None and not w.quarantined
                          and not self._draining.is_set()
                          and w.respawn_at is not None
                          and time.monotonic() >= w.respawn_at):
                        w.gen += 1
                        self.worker_respawns += 1
                        self._spawn(w, respawn=True)
                        rec = telemetry.current()
                        if rec.active:
                            rec.metrics.count("worker_respawns")
                    elif (w.alive and w.consecutive_crashes
                          and time.monotonic() - w.started_at > 30.0):
                        # 30s of life = the crash loop broke; later
                        # crashes restart the backoff ladder from base.
                        w.consecutive_crashes = 0
            self._stop.wait(0.05)

    def begin_drain(self) -> None:
        """SIGTERM every worker: each drains its own queue and exits 75
        (the single-process contract, unchanged)."""
        self._draining.set()
        with self._lock:
            for w in self._workers:
                if w.proc is not None and w.proc.poll() is None:
                    try:
                        w.proc.send_signal(signal.SIGTERM)
                    except OSError:
                        pass

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def wait(self, timeout_s: float = 60.0) -> List[Optional[int]]:
        """Join every worker (SIGKILL stragglers past the timeout);
        returns per-slot exit codes (None = never started)."""
        deadline = time.monotonic() + timeout_s
        codes: List[Optional[int]] = []
        for w in self._workers:
            proc = w.proc
            if proc is None:
                codes.append(None)
                continue
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)
            codes.append(proc.returncode)
        return codes

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            for w in self._workers:
                if w.proc is not None and w.proc.poll() is None:
                    w.proc.kill()
                if w.log is not None:
                    w.log.close()
                    w.log = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- routing + transport (the HTTP backend protocol) -----------------

    def _route_order(self, model: Any, version: Any) -> List[_Worker]:
        """Live workers in routing order: the slot (model, version)
        hashes to first -- executor-cache affinity -- then siblings in
        ring order for failover."""
        start = zlib.crc32(f"{model}@{version}".encode()) % self._n
        with self._lock:
            ring = [self._workers[(start + i) % self._n]
                    for i in range(self._n)]
            return [w for w in ring if w.alive and not w.quarantined]

    def _call(self, w: _Worker, payload: bytes, timeout_s: float) -> dict:
        """One request over one worker's UNIX socket (fresh connection:
        a crashed worker must fail THIS call, not poison a pool)."""
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(timeout_s)
            s.connect(w.sock)
            s.sendall(payload)
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = s.recv(1 << 16)
                if not chunk:
                    raise ConnectionError(
                        f"worker {w.idx} closed mid-reply")
                buf += chunk
        return json.loads(buf)

    def score(self, req: dict,
              trace_id: Optional[str] = None) -> Tuple[dict, Dict[str, Any]]:
        """Route one request; on a transport failure (the worker died
        under it) retry ONCE on the next live sibling -- scoring is
        idempotent, so the client sees an answer, not the crash."""
        del trace_id  # the JSONL protocol mints its own ids worker-side
        x = req.get("x")
        if isinstance(x, np.ndarray):
            # A binary (x-gmm-rows) POST decoded to rows in the router;
            # re-frame instead of JSON-ifying the floats so the zero-copy
            # plane survives the hop to the worker: one header line
            # declaring x_bytes, then the raw frame.
            frame = wire.encode_rows(x)
            head = {k: v for k, v in req.items() if k != "x"}
            head["x_bytes"] = len(frame)
            payload = (json.dumps(head) + "\n").encode("utf-8") + frame
        else:
            payload = (json.dumps(req) + "\n").encode("utf-8")
        timeout_s = self._request_timeout_s
        deadline_ms = req.get("deadline_ms")
        if isinstance(deadline_ms, (int, float)) and deadline_ms > 0:
            timeout_s = float(deadline_ms) / 1e3 + DEADLINE_GRACE_S
        order = self._route_order(req.get("model"), req.get("version"))
        if not order:
            # Whole-pool-dead window (every slot mid-respawn): park the
            # request for the supervisor instead of 502ing instantly --
            # a transient total outage should cost latency, not errors.
            wait_until = time.monotonic() + min(timeout_s,
                                                NO_WORKER_WAIT_S)
            while (not order and time.monotonic() < wait_until
                   and not self._draining.is_set()):
                time.sleep(0.05)
                order = self._route_order(req.get("model"),
                                          req.get("version"))
        retried = False
        for attempt, w in enumerate(order[:2]):
            try:
                resp = self._call(w, payload, timeout_s)
                return resp, {"worker": w.idx, "retried": retried}
            except socket.timeout:
                return ({"id": req.get("id"), "ok": False,
                         "error": "http_timeout",
                         "detail": f"worker {w.idx} gave no reply within "
                         f"{timeout_s:.1f}s"},
                        {"worker": w.idx, "retried": retried})
            except (OSError, ConnectionError, ValueError):
                # Dead socket / torn reply: the worker crashed under us.
                if attempt == 0 and len(order) > 1:
                    retried = True
                    with self._lock:
                        self.retries += 1
                    rec = telemetry.current()
                    if rec.active:
                        rec.metrics.count("http_retries")
                    continue
        with self._lock:
            self.retries_exhausted += 1
        rec = telemetry.current()
        if rec.active:
            rec.metrics.count("http_retries_exhausted")
        return ({"id": req.get("id"), "ok": False,
                 "error": "worker_unavailable",
                 "detail": "no live worker could answer (crash retry "
                 "exhausted)"}, {"retried": retried})

    def ready(self) -> bool:
        if self._draining.is_set():
            return False
        with self._lock:
            return any(w.alive and not w.quarantined
                       for w in self._workers)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            alive = sum(w.alive for w in self._workers)
            quarantined = sum(w.quarantined for w in self._workers)
        return {
            "gmm_http_workers": float(self._n),
            "gmm_http_workers_alive": float(alive),
            "gmm_http_workers_quarantined": float(quarantined),
            "gmm_http_worker_crashes": float(self.worker_crashes),
            "gmm_http_worker_respawns": float(self.worker_respawns),
            "gmm_http_retries": float(self.retries),
            "gmm_http_retries_exhausted": float(self.retries_exhausted),
        }

    def http_stats(self) -> Dict[str, int]:
        """The pool's share of the ``serve_summary.http`` rollup."""
        with self._lock:
            return {
                "retries": int(self.retries),
                "retries_exhausted": int(self.retries_exhausted),
                "worker_crashes": int(self.worker_crashes),
                "worker_respawns": int(self.worker_respawns),
                "worker_quarantines": int(self.worker_quarantines),
                "workers": int(self._n),
            }
